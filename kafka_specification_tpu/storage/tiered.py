"""TieredFpSet: host FpSet bounded by a byte budget, spilling to disk runs.

The host tier is the existing native C++ open-addressing FpSet (the
TLC-FPSet equivalent); this class bounds its residency at `mem_budget`
bytes.  When the hot set outgrows the budget, its fingerprints are dumped,
sorted, and written as one immutable on-disk run (storage/runs), and the
hot set restarts empty.  Membership is: hot set first, then each run's
bloom + interval gate, with a binary search over the run's mmap only on a
probable hit.  Because a fingerprint is inserted exactly once ever (the
novelty decision happens before any spill), runs are pairwise disjoint and
the hot set never overlaps disk — so the tiered set's novelty masks are
bit-identical to one unbounded FpSet's.

When the run count passes `runs_per_merge`, all runs k-way-merge into one
(fewer bloom probes per lookup, one searchsorted instead of k).  Merged
inputs are not deleted until `gc_barrier` newer checkpoint generations
have been saved (`on_checkpoint_saved`), so every retained generation's
manifest still resolves on disk — the deletion barrier is what makes the
disk tier itself the durable state the checkpoint merely *references*.
"""

from __future__ import annotations

import os

import numpy as np

from ..native import FpSet
from .. import durable_io as _dio
from .atomic import sweep_tmp
from .runs import SortedRun, merge_runs, write_run

# ~bytes of host residency per fingerprint: 8 B/slot at <=1/2 open-
# addressing load, i.e. ~16 B per live entry
_BYTES_PER_FP = 16

#: machine-readable ownership contract (docs/analysis.md; docs/storage.md
#: § Background merges as data): the merge worker writes FILES ONLY — its
#: job closure captures immutable SortedRun inputs and never touches the
#: set object, so every attribute is engine-thread-only; adoption of a
#: finished merge (run-list swap, counter retirement, deletion-barrier
#: scheduling) happens on the engine thread in poll_merge.
THREAD_CONTRACT = {
    "schema": "kspec-ownership/1",
    "classes": {
        "DeferredDeleter": {
            "engine_only": ["pending", "barrier"],
        },
        "TieredFpSet": {
            "engine_only": ["hot", "runs", "disk_n", "seq", "spills",
                            "merges", "_merge_job", "_retired_probes",
                            "mem_budget", "deleter"],
            "immutable_after_init": ["dir", "runs_per_merge",
                                     "fault_plan", "verify_on_open",
                                     "merge_worker"],
        },
    },
}


class DeferredDeleter:
    """Deletion barrier keyed to checkpoint saves.

    `schedule(paths)` marks files obsolete; they are unlinked only after
    `barrier` subsequent `on_save()` calls (checkpoint generations), so no
    retained generation can reference a vanished file.  barrier=0 (not
    checkpointing) deletes immediately.  State round-trips through the
    checkpoint manifest so a resumed run keeps honoring in-flight barriers.
    """

    def __init__(self, barrier: int):
        self.barrier = max(0, int(barrier))
        self.pending: list = []  # [remaining_saves, path]

    def schedule(self, paths) -> None:
        if self.barrier == 0:
            for p in paths:
                _unlink_quiet(p)
            return
        self.pending.extend([self.barrier, p] for p in paths)

    def mark(self) -> int:
        """Watermark for :meth:`on_save` — the count of currently pending
        entries.  An ASYNC checkpoint save snapshots its manifest now but
        promotes later; its barrier advance must cover exactly the files
        scheduled before the snapshot (entries appended afterwards belong
        to younger state the write never referenced)."""
        return len(self.pending)

    def on_save(self, upto=None) -> None:
        """Advance the barrier for one durably promoted generation.
        `upto` (a :meth:`mark` watermark) restricts the advance to the
        entries pending at that save's snapshot; None = all (the
        synchronous path, where snapshot and promote coincide)."""
        n = len(self.pending) if upto is None else min(
            int(upto), len(self.pending)
        )
        keep = []
        for i, item in enumerate(self.pending):
            if i < n:
                item[0] -= 1
            if item[0] <= 0:
                _unlink_quiet(item[1])
            else:
                keep.append(item)
        self.pending = keep

    def flush(self) -> int:
        """Delete every pending file NOW.  Legal only when the caller has
        just pruned all checkpoint generations older than the newest one
        (resource reclamation): the files' barrier counts protected
        exactly those generations' manifests."""
        n = len(self.pending)
        for _, p in self.pending:
            _unlink_quiet(p)
        self.pending = []
        return n

    def manifest(self, directory: str) -> list:
        return [[n, os.path.relpath(p, directory)] for n, p in self.pending]

    def restore(self, directory: str, entries) -> None:
        # normpath: entries may point outside `directory` (the engine
        # store routes frontier-segment deletions through the same
        # barrier, serialized as "../frontier/..." relpaths) and sweep
        # code compares dirnames textually
        self.pending = [
            [int(n), os.path.normpath(os.path.join(directory, p))]
            for n, p in entries
        ]


def _unlink_quiet(path: str) -> None:
    for p in (path, path + ".bloom"):
        try:
            _dio.unlink(p)
        except OSError:
            pass


class TieredFpSet:
    """Budget-bounded host FpSet + immutable sorted disk runs.

    Drop-in for the engines' host backend (`insert(u64) -> novelty mask`,
    `contains`, `len`); `native` is False so the engines take the
    row-masking path rather than the fused C arena (the arena's win is
    host-assembly time, irrelevant once the set itself is the bottleneck).
    """

    native = False

    def __init__(
        self,
        directory: str,
        mem_budget: int,
        *,
        runs_per_merge: int = 8,
        gc_barrier: int = 0,
        fault_plan=None,
        verify_on_open: bool = True,
        merge_worker=None,
    ):
        """merge_worker: an :class:`~..overlap.AsyncWorker` — k-way merges
        then run in the background (docs/storage.md § Background merges).
        The worker only writes files (tmp-write + atomic promote, exactly
        the sync merge's crash contract); the run list, gate counters and
        the deletion barrier mutate ONLY on the engine thread when a
        finished merge is *adopted* (poll_merge), so lookups keep serving
        from the immutable inputs the whole time and never block on an
        unfinished merge.  Worker errors — including the injected
        crash@merge:N / enospc@merge:N faults, which fire on the worker —
        re-raise on the engine thread at the next poll/quiesce."""
        # normalized: orphan sweeps and the deletion barrier compare paths
        # textually, and DeferredDeleter.restore normpaths its entries —
        # a dot-prefixed directory ("./ck/spill") must compare equal
        self.dir = os.path.normpath(directory)
        self.mem_budget = int(mem_budget)
        self.runs_per_merge = max(2, int(runs_per_merge))
        self.fault_plan = fault_plan
        self.verify_on_open = verify_on_open
        self.deleter = DeferredDeleter(gc_barrier)
        self.merge_worker = merge_worker
        self._merge_job = None  # (job, inputs, out_path) in flight
        self.hot = FpSet()
        self.runs: list[SortedRun] = []
        self.disk_n = 0
        self.seq = 0  # next run file number (monotonic across merges)
        self.spills = 0
        self.merges = 0
        # bloom-gate traffic accumulated on merged-away runs (their
        # per-run counters die with them; totals must not)
        self._retired_probes = {"probes": 0, "bloom_maybe": 0, "hits": 0}
        os.makedirs(directory, exist_ok=True)
        # startup janitor: a mid-write death leaves a .tmp sibling no
        # manifest references; sweep it before it masquerades as usage
        sweep_tmp(self.dir)

    # --- lifecycle ------------------------------------------------------
    def start_fresh(self) -> None:
        """Wipe the directory (a fresh run owns its namespace — stale runs
        from an abandoned search must not pre-seed the visited set)."""
        self._abandon_merge()
        for name in os.listdir(self.dir):
            _unlink_quiet(os.path.join(self.dir, name))
        self.hot = FpSet()
        self.runs = []
        self.disk_n = 0
        self.seq = 0

    def restore(self, manifest: dict, hot_fps) -> None:
        """Restore this set IN PLACE from a checkpoint manifest: reopen
        (and verify) exactly the referenced runs, re-seed the hot set from
        the checkpointed dump, and sweep orphan files (tmp/run files from
        the crashed post-checkpoint window — the deterministic re-run
        regenerates them identically).  In-place so callers holding a
        reference (the engine's `host_set`) see the restored state."""
        self._abandon_merge()
        directory = self.dir
        self.mem_budget = int(manifest["mem_budget"])
        self.seq = int(manifest["seq"])
        self.spills = int(manifest.get("spills", 0))
        self.merges = int(manifest.get("merges", 0))
        self.runs = [
            SortedRun(directory, m, verify=self.verify_on_open)
            for m in manifest["runs"]
        ]
        self.disk_n = sum(r.count for r in self.runs)
        self.deleter.restore(directory, manifest.get("pending_delete", ()))
        keep = {os.path.join(directory, m["name"]) for m in manifest["runs"]}
        keep |= {p for _, p in self.deleter.pending}
        for name in os.listdir(directory):
            p = os.path.join(directory, name)
            if p not in keep and not p.endswith(".bloom"):
                _unlink_quiet(p)
            elif p.endswith(".bloom") and p[: -len(".bloom")] not in keep:
                _unlink_quiet(p)
        self.hot = FpSet()
        if hot_fps is not None and len(hot_fps):
            self.hot.insert(np.asarray(hot_fps, np.uint64))

    @classmethod
    def from_manifest(
        cls,
        directory: str,
        manifest: dict,
        hot_fps,
        **kwargs,
    ) -> "TieredFpSet":
        s = cls(directory, manifest["mem_budget"], **kwargs)
        s.restore(manifest, hot_fps)
        return s

    def manifest(self) -> dict:
        return {
            "mem_budget": self.mem_budget,
            "seq": self.seq,
            "spills": self.spills,
            "merges": self.merges,
            "runs": [r.meta for r in self.runs],
            "pending_delete": self.deleter.manifest(self.dir),
        }

    def on_checkpoint_saved(self) -> None:
        self.deleter.on_save()

    # --- set interface --------------------------------------------------
    def _disk_contains(self, fps: np.ndarray) -> np.ndarray:
        out = np.zeros(fps.shape[0], bool)
        rem = np.arange(fps.shape[0])
        for r in self.runs:
            if rem.size == 0:
                break
            hit = r.contains(fps[rem])
            out[rem[hit]] = True
            rem = rem[~hit]
        return out

    def insert(self, fps: np.ndarray) -> np.ndarray:
        """Novelty mask, bit-identical to an unbounded FpSet (in-batch
        duplicates report novel exactly once, at first occurrence)."""
        if self._merge_job is not None:
            self.poll_merge()  # adopt a finished background merge (and
            # surface its errors) before probing the run list
        fps = np.ascontiguousarray(fps, np.uint64)
        novel = np.zeros(fps.shape[0], bool)
        fresh = ~self._disk_contains(fps)
        if fresh.any():
            idx = np.nonzero(fresh)[0]
            novel[idx] = self.hot.insert(fps[idx])
            self._maybe_spill()
        return novel

    def insert_level(self, fps: np.ndarray,
                     slice_rows: int = 1 << 18) -> np.ndarray:
        """Once-per-level batched insert (the deferred-probe device
        pipeline's host call): same novelty mask as :meth:`insert`, but
        shaped for ONE call per BFS level instead of one per chunk.

        Two things make the batched form cheaper than a chunk loop of
        :meth:`insert` calls, without changing a single novelty answer:

        - the disk probe runs over the SORTED query batch, once per run
          per LEVEL: each run pays one interval gate, one bloom pass and
          one searchsorted sweep for the whole level (sorted queries
          walk the run's mmap monotonically, so the binary searches
          touch each page once) — the per-chunk loop pays all three per
          run per CHUNK;
        - the hot-tier insert still runs in budget-bounded slices with
          the spill check between them, so residency stays bounded at
          ``mem_budget + slice_rows*16`` bytes exactly like the serial
          path's per-chunk bound — a whole level can be much larger
          than the budget.

        The caller's batch is duplicate-free within the level (the
        device level-new set guarantees it), so slice order cannot
        change any first-occurrence decision; runs stay pairwise
        disjoint because the disk probe still precedes every hot
        insert.  Bit-identity with the per-chunk insert sequence
        follows (tests/test_storage.py pins it)."""
        if self._merge_job is not None:
            self.poll_merge()
        fps = np.ascontiguousarray(fps, np.uint64)
        novel = np.zeros(fps.shape[0], bool)
        if not fps.shape[0]:
            return novel
        order = np.argsort(fps, kind="stable")
        fresh_sorted = ~self._disk_contains(fps[order])
        fresh = np.zeros_like(fresh_sorted)
        fresh[order] = fresh_sorted
        idx = np.nonzero(fresh)[0]
        # hot membership must be resolved BEFORE the sliced inserts: a
        # mid-call spill moves the pre-call hot set to disk, so a later
        # slice's hot.insert would wrongly re-admit a fingerprint the
        # level started with in the hot tier (a double insert breaks
        # the pairwise-disjoint-runs invariant; caught by the twin-set
        # test before it ever shipped)
        if idx.shape[0]:
            idx = idx[~self.hot.contains(fps[idx])]
        novel[idx] = True
        for at in range(0, idx.shape[0], slice_rows):
            sl = idx[at: at + slice_rows]
            self.hot.insert(fps[sl])
            self._maybe_spill()
        return novel

    def contains(self, fps: np.ndarray) -> np.ndarray:
        fps = np.ascontiguousarray(fps, np.uint64)
        out = self.hot.contains(fps)
        miss = ~out
        if miss.any():
            idx = np.nonzero(miss)[0]
            out[idx] = self._disk_contains(fps[idx])
        return out

    def __len__(self) -> int:
        return self.disk_n + len(self.hot)

    def hot_dump(self) -> np.ndarray:
        return self.hot.dump()

    def dump(self) -> np.ndarray:
        """Every fingerprint, hot + disk (tests / tiny sets only — the
        whole point of this class is that this does not fit in RAM)."""
        for r in self.runs:  # read-side CRC: dumps verify like lookups
            if not r._read_verified:
                r._verify_content()
        parts = [self.hot.dump()] + [np.asarray(r.arr) for r in self.runs]
        return np.concatenate(parts) if parts else np.empty(0, np.uint64)

    def stats(self) -> dict:
        return {
            "hot": len(self.hot),
            "disk": self.disk_n,
            "runs": len(self.runs),
            "spills": self.spills,
            "merges": self.merges,
            "disk_bytes": 8 * self.disk_n,
            # bloom-gate accounting per open run (obs: how much disk
            # traffic the per-run gates save — bloom_filtered probes never
            # touched the mmap)
            "run_probes": [
                {
                    "name": r.meta["name"],
                    "probes": r.probes,
                    "bloom_maybe": r.bloom_maybe,
                    "bloom_filtered": r.probes - r.bloom_maybe,
                    "hits": r.hits,
                }
                for r in self.runs
            ],
            # whole-run totals: live runs + everything merged away (the
            # *_total metrics must survive compaction)
            "bloom_totals": {
                k: self._retired_probes[k]
                + sum(getattr(r, a) for r in self.runs)
                for k, a in (
                    ("probes", "probes"),
                    ("bloom_maybe", "bloom_maybe"),
                    ("hits", "hits"),
                )
            },
        }

    # --- spill / merge --------------------------------------------------
    def _hot_bytes(self) -> int:
        return _BYTES_PER_FP * len(self.hot)

    def _maybe_spill(self) -> None:
        if self._hot_bytes() > self.mem_budget:
            self.spill()

    def _run_path(self) -> str:
        path = os.path.join(self.dir, f"run-{self.seq:06d}.fps")
        self.seq += 1
        return path

    def spill(self) -> None:
        """Dump + sort the hot set into a new immutable run; restart the
        hot set empty.  Triggers a k-way merge past `runs_per_merge`."""
        fps = np.sort(self.hot.dump())
        if fps.shape[0] == 0:
            return
        # lazy import: obs <-> storage must stay acyclic at module level
        from ..obs import metrics as _met
        from ..obs import tracer as _obs

        path = self._run_path()
        hook = None
        if self.fault_plan is not None:
            ordinal = self.spills + 1

            def hook():
                # full-disk rehearsal (enospc@spill:N): fires after the
                # tmp write, before the promote — atomic_write cleans up
                # the tmp and the hot set is untouched (it restarts empty
                # only after a successful promote), so the engines'
                # RESOURCE_EXHAUSTED exit leaves a verifiable state
                self.fault_plan.enospc("spill", ordinal)

        with _obs.span("spill-run-write", rows=int(fps.shape[0])):
            meta = write_run(
                path, fps, bloom_path=path + ".bloom", before_replace=hook
            )
        _met.inc("kspec_spill_runs_total")
        if self.fault_plan is not None and self.fault_plan.flip(
            "spill", self.spills + 1
        ):
            # silent on-disk corruption AFTER the atomic promote (the
            # window atomic writes cannot close): caught by the run's
            # read-side CRC on its first lookup (SortedRun.contains),
            # typed INTEGRITY_VIOLATION by the engines
            from ..resilience.faults import corrupt_file

            corrupt_file(path)
        self.runs.append(SortedRun(self.dir, meta, verify=False))
        self.disk_n += fps.shape[0]
        self.spills += 1
        self.hot = FpSet()
        if len(self.runs) > self.runs_per_merge:
            if self.merge_worker is not None:
                self._start_merge()
            else:
                self.merge()

    def merge(self) -> None:
        """K-way merge every run into one.  Crash-safe: the merged output
        is tmp-written then atomically promoted; the inputs stay on disk
        behind the checkpoint-generation deletion barrier, so a crash at
        ANY point (including the injected `crash@merge:N`) leaves a state
        some retained checkpoint manifest fully resolves."""
        self.quiesce()  # a reclaim's eager merge must not race a
        # background promote over the same inputs (PR 10 small fix)
        if len(self.runs) < 2:
            return
        from ..obs import metrics as _met
        from ..obs import tracer as _obs

        self.merges += 1
        path = self._run_path()
        hook = None
        if self.fault_plan is not None:
            ordinal = self.merges

            def hook():
                self.fault_plan.crash("merge", ordinal)
                self.fault_plan.enospc("merge", ordinal)

        with _obs.span(
            "spill-merge",
            runs=len(self.runs),
            rows=int(sum(r.count for r in self.runs)),
        ):
            meta = merge_runs(self.runs, path, crash_hook=hook)
        _met.inc("kspec_spill_merges_total")
        for r in self.runs:  # retire the merged-away runs' gate counters
            self._retired_probes["probes"] += r.probes
            self._retired_probes["bloom_maybe"] += r.bloom_maybe
            self._retired_probes["hits"] += r.hits
        old = [r.path for r in self.runs]
        self.runs = [SortedRun(self.dir, meta, verify=False)]
        self.deleter.schedule(old)

    # --- background merges (KSPEC_OVERLAP; docs/storage.md) -------------
    def _start_merge(self) -> None:
        """Submit a k-way merge of the CURRENT runs to the worker.  At
        most one merge is in flight; if one still is, this spill's runs
        simply ride along until the next trigger (the run list only
        grows between merges, so correctness never depends on merge
        timing — only lookup fan-out does)."""
        self.poll_merge()
        if self._merge_job is not None:
            return  # one merge at a time; adopted at the next poll
        inputs = list(self.runs)
        if len(inputs) < 2:
            return
        self.merges += 1
        ordinal = self.merges
        path = self._run_path()
        fault_plan = self.fault_plan

        def job():
            # worker-side: files only.  The crash/enospc injection points
            # fire HERE (on the worker) and propagate to the engine
            # thread at its next poll/quiesce — same typed exits, same
            # on-disk contract (tmp cleaned, inputs untouched).
            from ..obs import metrics as _met
            from ..obs import tracer as _obs

            hook = None
            if fault_plan is not None:
                def hook():
                    fault_plan.crash("merge", ordinal)
                    fault_plan.enospc("merge", ordinal)

            with _obs.span(
                "spill-merge",
                runs=len(inputs),
                rows=int(sum(r.count for r in inputs)),
                background=True,
            ):
                meta = merge_runs(inputs, path, crash_hook=hook)
            _met.inc("kspec_spill_merges_total")
            return meta

        self._merge_job = (
            self.merge_worker.submit("spill-merge", job), inputs, path
        )

    def poll_merge(self, wait: bool = False) -> None:
        """Engine-thread adoption point: if the in-flight merge finished,
        swap the merged run in for its inputs (newer spills appended
        after submission stay), retire the inputs' gate counters, and
        schedule the input files on the deletion barrier.  Re-raises the
        worker's stored error (typed faults included)."""
        if self._merge_job is None:
            return
        job, inputs, path = self._merge_job
        if not wait and not job.done.is_set():
            return
        try:
            # wait() re-raises THIS job's error (consuming it from the
            # worker's failed queue) — with several tiered sets sharing
            # one worker, a sibling's poll must never launder our error
            # (or vice versa) into the wrong adoption
            meta = self.merge_worker.wait(job)
        except BaseException:
            self._merge_job = None
            raise
        self._merge_job = None
        for r in inputs:
            self._retired_probes["probes"] += r.probes
            self._retired_probes["bloom_maybe"] += r.bloom_maybe
            self._retired_probes["hits"] += r.hits
        self.runs = [SortedRun(self.dir, meta, verify=False)] + [
            r for r in self.runs if r not in inputs
        ]
        self.deleter.schedule([r.path for r in inputs])

    def quiesce(self) -> None:
        """Block until no merge is in flight and adopt its output —
        REQUIRED before any reclamation that sweeps tmp files, flushes
        the deletion barrier, or runs a sync merge (a reclaim racing a
        background promote could unlink the merge's tmp mid-write or
        flush files its manifest still needs)."""
        if self._merge_job is not None:
            self.poll_merge(wait=True)

    def _abandon_merge(self) -> None:
        """Wait out (never adopt) an in-flight merge — fresh-start /
        restore paths: the merged output becomes an unreferenced orphan
        their sweeps remove.  Worker errors are swallowed (the state the
        merge would have produced is being discarded anyway)."""
        if self._merge_job is None:
            return
        job, _inputs, _path = self._merge_job
        self._merge_job = None
        try:
            self.merge_worker.wait(job)  # consumes THIS job's error only
        except BaseException:  # noqa: BLE001 — discarded with the merge
            pass


# KSPEC_TSAN=1 (test-only): assert THREAD_CONTRACT ownership on every
# attribute write (analysis/ownership.py); zero overhead otherwise
from ..analysis.ownership import bind_contract as _bind_contract  # noqa: E402

_bind_contract(globals(), THREAD_CONTRACT)
