"""Disk-spilled frontier queue: chunked segments in discovery order.

A BFS level's next frontier can itself outgrow RAM (the 463.8M-state
product peaked at 3.9M frontier rows; a 5B-state space pushes past 10^8
rows x K lanes).  The writer appends novel rows in discovery order and
cuts an immutable segment file every `seg_rows`; the reader replays them
in the exact same order and chunk boundaries as the in-RAM path, so the
engine's per-chunk computation — and therefore every count and trace — is
bit-identical.

Segment format: `KFRN1\\0` magic, u64 rows, u32 lanes, payload of
rows x lanes u32 LE.  CRC + row counts live in the manifest the engine
checkpoint records ("frontier-segment offsets"); consumed levels'
segments are deleted behind the checkpoint deletion barrier.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from .atomic import atomic_write

_MAGIC = b"KFRN1\x00"
_HEADER = len(_MAGIC) + 8 + 4


class SegmentCorrupt(Exception):
    """A frontier segment failed its manifest verification."""


class FrontierWriter:
    def __init__(self, directory: str, level: int, lanes: int,
                 seg_rows: int = 1 << 18):
        self.dir = directory
        self.level = int(level)
        self.K = int(lanes)
        self.seg_rows = max(1, int(seg_rows))
        self.segments: list[dict] = []
        self._buf: list[np.ndarray] = []
        self._buf_rows = 0
        self.rows = 0
        os.makedirs(directory, exist_ok=True)

    def append(self, rows: np.ndarray) -> None:
        if rows.shape[0] == 0:
            return
        self._buf.append(np.ascontiguousarray(rows, np.uint32))
        self._buf_rows += rows.shape[0]
        self.rows += rows.shape[0]
        while self._buf_rows >= self.seg_rows:
            self._cut(self.seg_rows)

    def _cut(self, n: int) -> None:
        data = np.concatenate(self._buf) if len(self._buf) > 1 else self._buf[0]
        seg, rest = data[:n], data[n:]
        self._buf = [rest] if rest.shape[0] else []
        self._buf_rows = rest.shape[0]
        name = f"frontier-L{self.level:05d}-{len(self.segments):05d}.seg"
        path = os.path.join(self.dir, name)
        payload = seg.tobytes()

        def write(fh):
            fh.write(_MAGIC)
            fh.write(np.uint64(seg.shape[0]).tobytes())
            fh.write(np.uint32(self.K).tobytes())
            fh.write(payload)

        atomic_write(path, write)
        self.segments.append(
            {"name": name, "rows": int(seg.shape[0]), "crc32": zlib.crc32(payload)}
        )

    def finalize(self) -> "FrontierReader":
        if self._buf_rows:
            self._cut(self._buf_rows)
        return FrontierReader(self.dir, self.manifest(), verify=False)

    def manifest(self) -> dict:
        return {
            "level": self.level,
            "lanes": self.K,
            "rows": self.rows,
            "segments": list(self.segments),
        }


class FrontierReader:
    """Replays a level's rows with the same global offsets and chunk
    boundaries the in-RAM `frontier_np[start:start+chunk]` loop produces."""

    def __init__(self, directory: str, manifest: dict, verify: bool = True):
        self.dir = directory
        self.man = manifest
        self.K = int(manifest["lanes"])
        self.rows = int(manifest["rows"])
        self.level = int(manifest["level"])
        self._starts = np.cumsum(
            [0] + [int(s["rows"]) for s in manifest["segments"]]
        )
        if int(self._starts[-1]) != self.rows:
            raise SegmentCorrupt(
                f"level {self.level}: segment rows sum {self._starts[-1]} "
                f"!= manifest rows {self.rows}"
            )
        # segments verify on READ, not just at resume: verify=False (the
        # writer's own freshly-cut reader) defers each segment's content
        # CRC to its first read instead of skipping it, so a bit flipped
        # on disk between the cut and the replay is caught at consumption
        # time (once per segment; replays re-read segments every chunk and
        # must not re-CRC every time)
        self._read_verified: set = set()
        if verify:
            for s in manifest["segments"]:  # eager warm-up verify pass
                self._open(s)

    def _open(self, seg: dict) -> np.ndarray:
        path = os.path.join(self.dir, seg["name"])
        n = int(seg["rows"])
        if not os.path.exists(path) or os.path.getsize(path) != (
            _HEADER + 4 * n * self.K
        ):
            raise SegmentCorrupt(f"{path}: missing or truncated")
        arr = np.memmap(
            path, dtype=np.uint32, mode="r", offset=_HEADER,
            shape=(n, self.K),
        )
        if seg["name"] not in self._read_verified:
            if zlib.crc32(arr.tobytes()) != int(seg["crc32"]):
                raise SegmentCorrupt(f"{path}: content CRC mismatch")
            self._read_verified.add(seg["name"])
        return arr

    def paths(self) -> list:
        return [os.path.join(self.dir, s["name"]) for s in self.man["segments"]]

    def slice(self, start: int, stop: int) -> np.ndarray:
        stop = min(stop, self.rows)
        if start >= stop:
            return np.empty((0, self.K), np.uint32)
        out = np.empty((stop - start, self.K), np.uint32)
        at = 0
        s0 = int(np.searchsorted(self._starts, start, side="right")) - 1
        for i in range(s0, len(self.man["segments"])):
            seg_start = int(self._starts[i])
            if seg_start >= stop:
                break
            arr = self._open(self.man["segments"][i])
            a = max(0, start - seg_start)
            b = min(arr.shape[0], stop - seg_start)
            out[at : at + (b - a)] = arr[a:b]
            at += b - a
        return out

    def iter_chunks(self, chunk: int):
        for start in range(0, self.rows, chunk):
            yield start, self.slice(start, start + chunk)

    def row(self, i: int) -> np.ndarray:
        return self.slice(i, i + 1)[0]

    def read_all(self) -> np.ndarray:
        return self.slice(0, self.rows)
