"""DiskTierStore: the single-device engine's composition of the disk tier.

One object owns the spill directory and the three disk structures —
tiered fingerprint set (`fps/`), spilled frontier segments (`frontier/`),
parent log (`plog/`) — plus the deletion barrier that ties file lifetime
to checkpoint generations.  The engine talks to this object only:

    disk = DiskTierStore(spill_dir, mem_budget, lanes=K, ...)
    disk.start_fresh(init_packed, init_fps)        # or disk.resume(...)
    per level:
        disk.begin_level(next_depth)
        per chunk: disk.append(novel_rows, parents, acts)
        reader = disk.end_level()                  # the next frontier
    checkpoint: manifest = disk.manifest(); ... disk.on_checkpoint_saved()

The checkpoint stores `json.dumps(disk.manifest())` + the (budget-bounded,
hence small) hot fingerprint dump — never the runs, segments, or log: the
disk tier IS the durable state; the checkpoint records how to reference it
(run names/CRCs, frontier segment offsets, parent-log depth).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .atomic import sweep_tmp
from .frontier import FrontierReader, FrontierWriter
from .parent_log import ParentLog
from .tiered import TieredFpSet


class DiskTierStore:
    def __init__(
        self,
        spill_dir: str,
        mem_budget: int,
        *,
        lanes: int,
        gc_barrier: int = 0,
        seg_rows: int = 1 << 18,
        runs_per_merge: int = 8,
        fault_plan=None,
        trace: bool = True,
        merge_worker=None,
    ):
        # normalized for the same reason as TieredFpSet.dir: resume's
        # orphan sweep compares dirnames textually against deleter paths
        self.dir = os.path.normpath(spill_dir)
        spill_dir = self.dir
        self.K = int(lanes)
        self.seg_rows = seg_rows
        os.makedirs(spill_dir, exist_ok=True)
        self.fpset = TieredFpSet(
            os.path.join(spill_dir, "fps"),
            mem_budget,
            runs_per_merge=runs_per_merge,
            gc_barrier=gc_barrier,
            fault_plan=fault_plan,
            merge_worker=merge_worker,
        )
        self.frontier_dir = os.path.join(spill_dir, "frontier")
        sweep_tmp(self.frontier_dir)  # mid-write death janitor
        self.plog = (
            ParentLog(
                os.path.join(spill_dir, "plog"), lanes, fault_plan=fault_plan
            )
            if trace
            else None
        )
        self._writer: Optional[FrontierWriter] = None
        self._reader: Optional[FrontierReader] = None
        # consumed frontier levels ride the same deletion barrier as
        # merged-away runs (older checkpoint generations reference them)
        self._deleter = self.fpset.deleter

    # --- lifecycle ------------------------------------------------------
    def start_fresh(self, init_packed: np.ndarray, init_fps: np.ndarray) -> None:
        for sub in (self.frontier_dir, os.path.join(self.dir, "plog")):
            if os.path.isdir(sub):
                for name in os.listdir(sub):
                    try:
                        os.unlink(os.path.join(sub, name))
                    except OSError:
                        pass
        self.fpset.start_fresh()
        self.fpset.insert(np.asarray(init_fps, np.uint64))
        w = FrontierWriter(self.frontier_dir, 0, self.K, self.seg_rows)
        w.append(init_packed)
        self._reader = w.finalize()
        if self.plog is not None:
            n0 = init_packed.shape[0]
            self.plog.write_level(
                0, init_packed, np.full(n0, -1, np.int64), np.full(n0, -1, np.int32)
            )

    def resume(self, manifest: dict, hot_fps: np.ndarray) -> None:
        """Rebuild from a checkpoint manifest: reopen the referenced runs
        and the pending frontier's segments (CRC-verified), re-seed the
        hot set.  Post-checkpoint orphans are swept; stale parent-log
        segments past the resume depth are left in place — the
        deterministic re-run overwrites them with identical bytes."""
        # in place: the engine's `host_set` aliases self.fpset
        self.fpset.restore(manifest["fpset"], hot_fps)
        self._reader = FrontierReader(
            self.frontier_dir, manifest["frontier"], verify=True
        )
        # sweep frontier segments no generation references
        keep = {s["name"] for s in manifest["frontier"]["segments"]}
        keep |= {
            os.path.basename(p)
            for p in (x[1] for x in self._deleter.pending)
            if os.path.dirname(p) == self.frontier_dir
        }
        if os.path.isdir(self.frontier_dir):
            for name in os.listdir(self.frontier_dir):
                if name not in keep:
                    try:
                        os.unlink(os.path.join(self.frontier_dir, name))
                    except OSError:
                        pass

    def manifest(self) -> dict:
        assert self._reader is not None
        return {
            "fpset": self.fpset.manifest(),
            "frontier": self._reader.man,
        }

    def on_checkpoint_saved(self) -> None:
        self.fpset.on_checkpoint_saved()

    def poll_async(self) -> None:
        """Engine-thread adoption/error point for the background merge
        worker (no-op without one): finished merges swap in, worker
        errors — typed faults included — re-raise here."""
        self.fpset.poll_merge()

    def quiesce(self) -> None:
        """Wait out (and adopt) any in-flight background merge."""
        self.fpset.quiesce()

    def reclaim_merge(self) -> bool:
        """Soft-breach reclamation step: eagerly k-way merge all runs
        (superseded inputs go behind the deletion barrier; the caller's
        fresh checkpoint + generation prune then makes them deletable).
        Quiesces the merge worker first — a reclaim must never race a
        background promote (PR 10 small fix).  Returns whether a merge
        actually ran — the caller skips its fresh checkpoint when
        nothing changed the on-disk state."""
        self.fpset.quiesce()
        if len(self.fpset.runs) < 2:
            return False
        self.fpset.merge()
        return True

    def flush_deleted(self) -> int:
        """Delete every barrier-pending file now — legal only right after
        the caller pruned all generations but the newest (see
        DeferredDeleter.flush).  Quiesces the merge worker first: an
        in-flight merge's inputs must reach the barrier (adoption)
        before a flush can claim the barrier is fully accounted.
        Returns the number of files freed."""
        self.fpset.quiesce()
        return self._deleter.flush()

    def sweep_tmp(self) -> list:
        """Janitor pass over every directory this store writes.  Quiesces
        the merge worker first — the background merge's half-written tmp
        is live work, not a stray (the reclaim-vs-promote race of the
        PR 10 small fix)."""
        self.fpset.quiesce()
        out = sweep_tmp(os.path.join(self.dir, "fps"))
        out += sweep_tmp(self.frontier_dir)
        out += sweep_tmp(os.path.join(self.dir, "plog"))
        return out

    # --- per-level flow -------------------------------------------------
    def pending(self) -> FrontierReader:
        """The frontier the next level expands (discovery order)."""
        assert self._reader is not None
        return self._reader

    def begin_level(self, next_level: int) -> None:
        self._writer = FrontierWriter(
            self.frontier_dir, next_level, self.K, self.seg_rows
        )
        if self.plog is not None:
            self.plog.begin_level(next_level)

    def append(self, rows, parent, act) -> None:
        self._writer.append(rows)
        if self.plog is not None:
            self.plog.append(rows, parent, act)

    def end_level(self) -> FrontierReader:
        """Publish the level: the consumed frontier's segments go behind
        the deletion barrier, the new level becomes pending."""
        consumed = self._reader
        self._reader = self._writer.finalize()
        self._writer = None
        if self.plog is not None:
            self.plog.end_level()
        if consumed is not None:
            self._deleter.schedule(consumed.paths())
        return self._reader

    def abort_level(self) -> None:
        """A verdict cut the level short: drop the partial writer (its
        already-cut segments are harmless orphans, swept on next resume)."""
        self._writer = None

    def has_trace(self, depth: int) -> bool:
        return self.plog is not None and self.plog.has_levels(depth)

    def stats(self) -> dict:
        s = self.fpset.stats()
        if self._reader is not None:
            s["frontier_rows"] = self._reader.rows
            s["frontier_segments"] = len(self._reader.man["segments"])
        return s
