"""Append-only on-disk parent log: counterexample traces without RAM.

The in-RAM trace store keeps every level's (rows, parent, action) triple
alive for the whole run — at 463.8M states that is already ~20 GB, and
checkpointed runs simply dropped it (PR 1's empty-trace-after-resume
limitation).  The parent log moves the triple to disk as one CRC-framed
segment per BFS level, written in discovery order as the level is
assembled; `walk_trace` then reconstructs a violation path by reading
O(depth) single records back through the mmap'd segments instead of
holding parent arrays in RAM.

Because segments for levels <= the checkpointed depth are immutable and
the resumed re-exploration is deterministic (identical discovery order),
a resumed run simply overwrites any partially-written post-checkpoint
segments with identical bytes — so a violation found AFTER a resume still
reports the full root->violation trace.  This retires the empty-trace
limitation for the single-device engine (docs/storage.md).

Segment format (`level-NNNNN.plog`): 256-byte JSON header
{magic, n, lanes, crc_rows, crc_parent, crc_act} padded with spaces, then
rows (n x lanes u32), parent (n i64), act (n i32), each section CRC32'd.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

from .atomic import atomic_write, sweep_tmp

_HDR_LEN = 256
_MAGIC = "KPLG1"


class ParentLogCorrupt(Exception):
    """A parent-log level segment failed verification."""


def _level_name(level: int) -> str:
    return f"level-{level:05d}.plog"


class _LevelView:
    """(rows, parent, act) mmap triple for one level — the same tuple
    shape the in-RAM trace store holds, so `walk_trace` is shared."""

    def __init__(self, path: str):
        try:
            with open(path, "rb") as fh:
                hdr = json.loads(fh.read(_HDR_LEN).decode("ascii").strip())
        except (OSError, ValueError) as e:
            raise ParentLogCorrupt(f"{path}: unreadable header ({e})") from e
        if hdr.get("magic") != _MAGIC:
            raise ParentLogCorrupt(f"{path}: bad magic")
        n, K = int(hdr["n"]), int(hdr["lanes"])
        off = _HDR_LEN
        want = off + 4 * n * K + 8 * n + 4 * n
        if os.path.getsize(path) != want:
            raise ParentLogCorrupt(f"{path}: truncated")
        self.rows = np.memmap(path, np.uint32, "r", offset=off, shape=(n, K))
        off += 4 * n * K
        self.parent = np.memmap(path, np.int64, "r", offset=off, shape=(n,))
        off += 8 * n
        self.act = np.memmap(path, np.int32, "r", offset=off, shape=(n,))
        for name, arr, crc in (
            ("rows", self.rows, hdr["crc_rows"]),
            ("parent", self.parent, hdr["crc_parent"]),
            ("act", self.act, hdr["crc_act"]),
        ):
            if zlib.crc32(arr.tobytes()) != int(crc):
                raise ParentLogCorrupt(f"{path}: {name} CRC mismatch")


class ParentLog:
    def __init__(self, directory: str, lanes: int, fault_plan=None):
        self.dir = directory
        self.K = int(lanes)
        self.fault_plan = fault_plan  # enospc@plog:N injection
        self._parts: list = []  # buffered (rows, parent, act) per append
        self._level = None
        os.makedirs(directory, exist_ok=True)
        sweep_tmp(directory)  # mid-write death janitor (storage/atomic)

    # --- write side -----------------------------------------------------
    def begin_level(self, level: int) -> None:
        self._level = int(level)
        self._parts = []

    def append(self, rows, parent, act) -> None:
        if rows.shape[0] == 0:
            return
        self._parts.append(
            (
                np.ascontiguousarray(rows, np.uint32),
                np.ascontiguousarray(parent, np.int64),
                np.ascontiguousarray(act, np.int32),
            )
        )

    def end_level(self) -> None:
        """Frame + atomically publish the buffered level segment.  A
        pre-existing segment (a resumed run re-exploring) is overwritten —
        deterministic discovery order makes the bytes identical."""
        rows = (
            np.concatenate([p[0] for p in self._parts])
            if self._parts
            else np.empty((0, self.K), np.uint32)
        )
        parent = (
            np.concatenate([p[1] for p in self._parts])
            if self._parts
            else np.empty(0, np.int64)
        )
        act = (
            np.concatenate([p[2] for p in self._parts])
            if self._parts
            else np.empty(0, np.int32)
        )
        hdr = {
            "magic": _MAGIC,
            "n": int(rows.shape[0]),
            "lanes": self.K,
            "crc_rows": zlib.crc32(rows.tobytes()),
            "crc_parent": zlib.crc32(parent.tobytes()),
            "crc_act": zlib.crc32(act.tobytes()),
        }
        blob = json.dumps(hdr).encode("ascii")
        assert len(blob) < _HDR_LEN, "parent-log header overflow"
        path = os.path.join(self.dir, _level_name(self._level))
        hook = None
        if self.fault_plan is not None:
            level = self._level

            def hook():
                # full-disk rehearsal (enospc@plog:N): pre-promote, so the
                # published log still ends at the last complete level
                self.fault_plan.enospc("plog", level)

        def write(fh):
            fh.write(blob.ljust(_HDR_LEN))
            fh.write(rows.tobytes())
            fh.write(parent.tobytes())
            fh.write(act.tobytes())

        atomic_write(path, write, before_replace=hook)
        self._parts = []
        self._level = None

    def write_level(self, level, rows, parent, act) -> None:
        """Convenience: a whole level in one shot (level 0 = inits)."""
        self.begin_level(level)
        self.append(rows, parent, act)
        self.end_level()

    # --- read side ------------------------------------------------------
    def has_levels(self, upto: int) -> bool:
        return all(
            os.path.exists(os.path.join(self.dir, _level_name(d)))
            for d in range(upto + 1)
        )

    def view(self) -> "ParentLog._View":
        return ParentLog._View(self.dir)

    class _View:
        """Indexable like the in-RAM trace store: view[d] -> the level-d
        (rows, parent, act) triple, CRC-verified on open."""

        def __init__(self, directory: str):
            self.dir = directory

        def __getitem__(self, level: int):
            lv = _LevelView(os.path.join(self.dir, _level_name(level)))
            return lv.rows, lv.parent, lv.act
# appended to storage/parent_log.py


class ShardedParentLog:
    """Per-shard parent logs for the sharded engine (+ layout epochs).

    A sharded level's global discovery order is shard-major: shard 0's
    new rows, then shard 1's, ...  Each shard appends its own
    (rows, parent, act) slice as an ordinary ParentLog segment under
    `shard<d>/`, so a multi-host run writes its logs in parallel with no
    cross-host file contention, and a reader re-concatenates the shard
    segments to recover exactly the in-RAM trace store's level layout —
    `walk_trace` is shared unchanged.  Parents are already level-global
    indices (the engine resolves them before appending), so they survive
    the concatenation untouched.

    Elastic resume (docs/resilience.md) changes the shard count mid-log,
    which changes the shard-major order from the resume level on:
    `epochs.json` records `[[start_level, shard_count], ...]`, each level
    is read through the epoch covering it, and `reshard()` rewrites the
    boundary level's segments into the new order (each row keeps its old
    (parent, act) — parents index the previous level, whose layout is
    unchanged), so one trace chain resolves across layouts.  Segments at
    or below a resume's level are immutable; the deterministic re-run
    overwrites later ones byte-identically (same argument as ParentLog).
    """

    def __init__(self, directory: str, lanes: int, shard_count: int,
                 local_shards=None, epoch_writer: bool = True,
                 fault_plan=None):
        self.dir = directory
        self.K = int(lanes)
        self.D = int(shard_count)
        self.fault_plan = fault_plan  # enospc@plog:N (per-shard writers)
        self.local = (
            set(range(self.D))
            if local_shards is None
            else {int(s) for s in local_shards}
        )
        # one writer per job for the (tiny, identical-everywhere) epoch
        # manifest: every process computes the same list in memory
        self.epoch_writer = bool(epoch_writer)
        self.epochs = None  # [[start_level, shard_count], ...]; None=broken
        self._logs: dict = {}
        os.makedirs(directory, exist_ok=True)

    # --- epochs ---------------------------------------------------------
    def _epochs_path(self) -> str:
        return os.path.join(self.dir, "epochs.json")

    def _load_epochs(self):
        try:
            with open(self._epochs_path()) as fh:
                return [[int(a), int(b)] for a, b in json.load(fh)["epochs"]]
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write_epochs(self) -> None:
        if not self.epoch_writer:
            return
        blob = json.dumps({"epochs": self.epochs}).encode("ascii")
        atomic_write(self._epochs_path(), lambda fh: fh.write(blob))

    def _epoch_D(self, level: int):
        D = None
        for start, d in self.epochs or ():
            if start <= level:
                D = d
        return D

    def _log(self, d: int) -> ParentLog:
        if d not in self._logs:
            self._logs[d] = ParentLog(
                os.path.join(self.dir, f"shard{d}"), self.K,
                fault_plan=self.fault_plan,
            )
        return self._logs[d]

    # --- lifecycle ------------------------------------------------------
    def start_fresh(self) -> None:
        """A fresh run owns its namespace: stale segments from an
        abandoned search must never splice into this run's traces.

        Multi-process safe: each process wipes ONLY its own shards' dirs
        (disjoint across processes), and the epoch writer additionally
        clears everything that belongs to no current shard (the old
        epochs.json, stale `shard<k>` dirs from an abandoned bigger
        layout) — so racing peers can never delete each other's (or the
        coordinator's) freshly written files."""
        import shutil

        live = {f"shard{d}" for d in range(self.D)}
        mine = {f"shard{d}" for d in self.local}
        for name in os.listdir(self.dir):
            p = os.path.join(self.dir, name)
            if name in live and name not in mine:
                continue  # another process's current shard dir
            if name in live or self.epoch_writer:
                try:
                    if os.path.isdir(p):
                        shutil.rmtree(p, ignore_errors=True)
                    else:
                        os.unlink(p)
                except OSError:
                    pass
        self.epochs = [[0, self.D]]
        self._write_epochs()

    def resume(self, depth: int) -> bool:
        """Same-layout resume at `depth`: drop epochs past the resume
        level (a crashed run's future) and require the covering layout to
        be ours.  False = no resolvable trace; the engine disables the
        log and falls back to trace-less violations, exactly the pre-PR
        behavior."""
        self.epochs = self._load_epochs()
        if self.epochs is None:
            return False
        self.epochs = [e for e in self.epochs if e[0] <= depth]
        if not self.epochs or self._epoch_D(depth) != self.D:
            self.epochs = None
            return False
        self._write_epochs()
        return True

    def reshard(self, depth: int, per_shard_rows) -> bool:
        """Elastic-resume boundary rewrite: re-emit level `depth` in the
        new shard-major order (`per_shard_rows` = the engine's
        re-bucketed pending frontier), carrying each row's (parent, act)
        over from the old-layout segments.  Rows are unique within a
        level, so the byte-keyed index is a bijection; a missing or
        corrupt old segment disables the log instead of guessing."""
        self.epochs = self._load_epochs()
        if self.epochs is not None:
            self.epochs = [e for e in self.epochs if e[0] <= depth]
        old_D = self._epoch_D(depth) if self.epochs else None
        if old_D is None:
            self.epochs = None
            return False
        try:
            rows_o, parent_o, act_o = self._read_level(depth, old_D)
        except ParentLogCorrupt:
            self.epochs = None
            return False
        index = {
            rows_o[i].tobytes(): i for i in range(rows_o.shape[0])
        }
        per_shard_sel = []
        try:
            for rows_d in per_shard_rows:
                rows_d = np.ascontiguousarray(rows_d, np.uint32)
                per_shard_sel.append(
                    (rows_d,
                     np.asarray([index[r.tobytes()] for r in rows_d],
                                np.int64))
                )
        except KeyError:  # not the same level content: refuse to splice
            self.epochs = None
            return False
        for d, (rows_d, sel) in enumerate(per_shard_sel):
            if d in self.local:
                self._log(d).write_level(
                    depth, rows_d, parent_o[sel], act_o[sel]
                )
        self.epochs = [e for e in self.epochs if e[0] < depth]
        self.epochs.append([depth, len(per_shard_rows)])
        self._write_epochs()
        return True

    # --- write side -----------------------------------------------------
    def write_level(self, level: int, rows_list, parent_list, act_list) -> None:
        """One level, already split per (new-layout) shard; each locally
        hosted shard publishes its slice as a CRC-framed segment."""
        for d in range(len(rows_list)):
            if d in self.local:
                self._log(d).write_level(
                    level,
                    np.ascontiguousarray(rows_list[d], np.uint32),
                    np.ascontiguousarray(parent_list[d], np.int64),
                    np.ascontiguousarray(act_list[d], np.int32),
                )

    # --- read side ------------------------------------------------------
    def _read_level(self, level: int, D_l: int):
        rows, parents, acts = [], [], []
        for d in range(D_l):
            lv = _LevelView(
                os.path.join(self.dir, f"shard{d}", _level_name(level))
            )
            rows.append(np.asarray(lv.rows))
            parents.append(np.asarray(lv.parent))
            acts.append(np.asarray(lv.act))
        return (
            np.concatenate(rows) if rows else np.empty((0, self.K), np.uint32),
            np.concatenate(parents) if parents else np.empty(0, np.int64),
            np.concatenate(acts) if acts else np.empty(0, np.int32),
        )

    def has_levels(self, upto: int) -> bool:
        if self.epochs is None:
            return False
        for level in range(upto + 1):
            D_l = self._epoch_D(level)
            if not D_l:
                return False
            for d in range(D_l):
                if not os.path.exists(
                    os.path.join(self.dir, f"shard{d}", _level_name(level))
                ):
                    return False
        return True

    def view(self) -> "ShardedParentLog._View":
        return ShardedParentLog._View(self)

    class _View:
        """Indexable like the in-RAM trace store: view[d] -> the level-d
        (rows, parent, act) triple, concatenated shard-major through the
        layout epoch that wrote it."""

        def __init__(self, log: "ShardedParentLog"):
            self.log = log

        def __getitem__(self, level: int):
            return self.log._read_level(level, self.log._epoch_D(level))
