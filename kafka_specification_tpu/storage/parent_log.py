"""Append-only on-disk parent log: counterexample traces without RAM.

The in-RAM trace store keeps every level's (rows, parent, action) triple
alive for the whole run — at 463.8M states that is already ~20 GB, and
checkpointed runs simply dropped it (PR 1's empty-trace-after-resume
limitation).  The parent log moves the triple to disk as one CRC-framed
segment per BFS level, written in discovery order as the level is
assembled; `walk_trace` then reconstructs a violation path by reading
O(depth) single records back through the mmap'd segments instead of
holding parent arrays in RAM.

Because segments for levels <= the checkpointed depth are immutable and
the resumed re-exploration is deterministic (identical discovery order),
a resumed run simply overwrites any partially-written post-checkpoint
segments with identical bytes — so a violation found AFTER a resume still
reports the full root->violation trace.  This retires the empty-trace
limitation for the single-device engine (docs/storage.md).

Segment format (`level-NNNNN.plog`): 256-byte JSON header
{magic, n, lanes, crc_rows, crc_parent, crc_act} padded with spaces, then
rows (n x lanes u32), parent (n i64), act (n i32), each section CRC32'd.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

from .atomic import atomic_write

_HDR_LEN = 256
_MAGIC = "KPLG1"


class ParentLogCorrupt(Exception):
    """A parent-log level segment failed verification."""


def _level_name(level: int) -> str:
    return f"level-{level:05d}.plog"


class _LevelView:
    """(rows, parent, act) mmap triple for one level — the same tuple
    shape the in-RAM trace store holds, so `walk_trace` is shared."""

    def __init__(self, path: str):
        try:
            with open(path, "rb") as fh:
                hdr = json.loads(fh.read(_HDR_LEN).decode("ascii").strip())
        except (OSError, ValueError) as e:
            raise ParentLogCorrupt(f"{path}: unreadable header ({e})") from e
        if hdr.get("magic") != _MAGIC:
            raise ParentLogCorrupt(f"{path}: bad magic")
        n, K = int(hdr["n"]), int(hdr["lanes"])
        off = _HDR_LEN
        want = off + 4 * n * K + 8 * n + 4 * n
        if os.path.getsize(path) != want:
            raise ParentLogCorrupt(f"{path}: truncated")
        self.rows = np.memmap(path, np.uint32, "r", offset=off, shape=(n, K))
        off += 4 * n * K
        self.parent = np.memmap(path, np.int64, "r", offset=off, shape=(n,))
        off += 8 * n
        self.act = np.memmap(path, np.int32, "r", offset=off, shape=(n,))
        for name, arr, crc in (
            ("rows", self.rows, hdr["crc_rows"]),
            ("parent", self.parent, hdr["crc_parent"]),
            ("act", self.act, hdr["crc_act"]),
        ):
            if zlib.crc32(arr.tobytes()) != int(crc):
                raise ParentLogCorrupt(f"{path}: {name} CRC mismatch")


class ParentLog:
    def __init__(self, directory: str, lanes: int):
        self.dir = directory
        self.K = int(lanes)
        self._parts: list = []  # buffered (rows, parent, act) per append
        self._level = None
        os.makedirs(directory, exist_ok=True)

    # --- write side -----------------------------------------------------
    def begin_level(self, level: int) -> None:
        self._level = int(level)
        self._parts = []

    def append(self, rows, parent, act) -> None:
        if rows.shape[0] == 0:
            return
        self._parts.append(
            (
                np.ascontiguousarray(rows, np.uint32),
                np.ascontiguousarray(parent, np.int64),
                np.ascontiguousarray(act, np.int32),
            )
        )

    def end_level(self) -> None:
        """Frame + atomically publish the buffered level segment.  A
        pre-existing segment (a resumed run re-exploring) is overwritten —
        deterministic discovery order makes the bytes identical."""
        rows = (
            np.concatenate([p[0] for p in self._parts])
            if self._parts
            else np.empty((0, self.K), np.uint32)
        )
        parent = (
            np.concatenate([p[1] for p in self._parts])
            if self._parts
            else np.empty(0, np.int64)
        )
        act = (
            np.concatenate([p[2] for p in self._parts])
            if self._parts
            else np.empty(0, np.int32)
        )
        hdr = {
            "magic": _MAGIC,
            "n": int(rows.shape[0]),
            "lanes": self.K,
            "crc_rows": zlib.crc32(rows.tobytes()),
            "crc_parent": zlib.crc32(parent.tobytes()),
            "crc_act": zlib.crc32(act.tobytes()),
        }
        blob = json.dumps(hdr).encode("ascii")
        assert len(blob) < _HDR_LEN, "parent-log header overflow"
        path = os.path.join(self.dir, _level_name(self._level))

        def write(fh):
            fh.write(blob.ljust(_HDR_LEN))
            fh.write(rows.tobytes())
            fh.write(parent.tobytes())
            fh.write(act.tobytes())

        atomic_write(path, write)
        self._parts = []
        self._level = None

    def write_level(self, level, rows, parent, act) -> None:
        """Convenience: a whole level in one shot (level 0 = inits)."""
        self.begin_level(level)
        self.append(rows, parent, act)
        self.end_level()

    # --- read side ------------------------------------------------------
    def has_levels(self, upto: int) -> bool:
        return all(
            os.path.exists(os.path.join(self.dir, _level_name(d)))
            for d in range(upto + 1)
        )

    def view(self) -> "ParentLog._View":
        return ParentLog._View(self.dir)

    class _View:
        """Indexable like the in-RAM trace store: view[d] -> the level-d
        (rows, parent, act) triple, CRC-verified on open."""

        def __init__(self, directory: str):
            self.dir = directory

        def __getitem__(self, level: int):
            lv = _LevelView(os.path.join(self.dir, _level_name(level)))
            return lv.rows, lv.parent, lv.act
