"""Sorted fingerprint runs: the on-disk level of the tiered visited set.

A run is an immutable file of strictly increasing uint64 fingerprints —
the LSM-ish shape TLC's DiskFPSet and BLEST's tiered visited set share:
writes are sequential (one sorted dump per spill), membership is a binary
search over an mmap that touches O(log n) pages, and compaction is a
bounded-memory k-way merge of immutable inputs into one new immutable
output (crash mid-merge leaves the inputs untouched).

File format: `KRUN1\\0` magic, u64 count, payload of count u64 LE values.
The content CRC + count + [lo, hi] interval live in the engine checkpoint's
manifest (storage/tiered.py), not in the file — the manifest is what makes
a run *referenced*; unreferenced files are orphans and are swept at open.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from .atomic import atomic_write
from .bloom import DEFAULT_BITS_PER_KEY, BloomFilter

_MAGIC = b"KRUN1\x00"
_HEADER = len(_MAGIC) + 8  # magic + u64 count


class RunCorrupt(Exception):
    """A run file failed its manifest (count/CRC) verification."""


def write_run(path: str, fps: np.ndarray, bloom_path=None,
              before_replace=None) -> dict:
    """Atomically write sorted fingerprints `fps` as a run; -> manifest
    entry {name, count, crc32, lo, hi}.  `fps` must already be sorted and
    duplicate-free (the tiered set guarantees disjoint spills).
    `before_replace` is the pre-promote fault-injection point
    (`KSPEC_FAULT=enospc@spill:N`)."""
    fps = np.ascontiguousarray(fps, np.uint64)
    payload = fps.tobytes()

    def write(fh):
        fh.write(_MAGIC)
        fh.write(np.uint64(fps.shape[0]).tobytes())
        fh.write(payload)

    atomic_write(path, write, before_replace=before_replace)
    if bloom_path is not None:
        BloomFilter.build(fps).save(bloom_path)
    return {
        "name": os.path.basename(path),
        "count": int(fps.shape[0]),
        "crc32": zlib.crc32(payload),
        "lo": int(fps[0]) if fps.shape[0] else 0,
        "hi": int(fps[-1]) if fps.shape[0] else 0,
    }


class SortedRun:
    """An open run: mmap'd values + interval + bloom gate."""

    def __init__(self, directory: str, meta: dict, verify: bool = True):
        self.meta = meta
        self.path = os.path.join(directory, meta["name"])
        self.count = int(meta["count"])
        self.lo = np.uint64(meta["lo"])
        self.hi = np.uint64(meta["hi"])
        if not os.path.exists(self.path):
            raise RunCorrupt(f"{self.path}: missing run file")
        size = os.path.getsize(self.path)
        if size != _HEADER + 8 * self.count:
            raise RunCorrupt(
                f"{self.path}: size {size} != header + 8*{self.count}"
            )
        self.arr = np.memmap(
            self.path, dtype=np.uint64, mode="r", offset=_HEADER,
            shape=(self.count,),
        )
        # verify=False (a run this process just wrote) defers the content
        # CRC to the FIRST lookup instead of skipping it: reads verify,
        # not just writes — a bit flipped on disk between the atomic
        # promote and the first probe (resilience.integrity's flip@spill
        # rehearsal, or real bit rot under a long-lived run) is caught at
        # consumption time, before a wrong membership answer can corrupt
        # the search
        self._read_verified = False
        if verify:
            self._verify_content()
        bloom_path = self.path + ".bloom"
        self.bloom = BloomFilter.load(bloom_path)
        if self.bloom is None:  # missing/rotted sidecar: rebuild, re-save
            self.bloom = BloomFilter.build(np.asarray(self.arr))
            self.bloom.save(bloom_path)
        # bloom-gate accounting (obs metrics: how much disk traffic the
        # per-run gates actually save on a spilled run)
        self.probes = 0  # interval-passing queries
        self.bloom_maybe = 0  # of those, bloom said "maybe" (disk touched)
        self.hits = 0  # of those, actually present

    def _verify_content(self) -> None:
        if zlib.crc32(self.arr.tobytes()) != int(self.meta["crc32"]):
            raise RunCorrupt(f"{self.path}: content CRC mismatch")
        self._read_verified = True

    def contains(self, fps: np.ndarray) -> np.ndarray:
        """Exact membership mask for a (possibly unsorted) query batch."""
        out = np.zeros(fps.shape[0], bool)
        if not self.count:
            return out
        if not self._read_verified:
            # read-side integrity: one full-content CRC at first lookup
            # (unconditional — the bloom/interval gates must not be able
            # to defer detection indefinitely), then mmap reads as usual
            self._verify_content()
        cand = (fps >= self.lo) & (fps <= self.hi)
        if not cand.any():
            return out
        ci = np.nonzero(cand)[0]
        q = fps[ci]
        self.probes += int(ci.shape[0])
        m = self.bloom.maybe(q)  # the disk-touch gate
        self.bloom_maybe += int(m.sum())
        if not m.any():
            return out
        ci, q = ci[m], q[m]
        pos = np.searchsorted(self.arr, q)
        hit = self.arr[np.minimum(pos, self.count - 1)] == q
        self.hits += int(hit.sum())
        out[ci[hit]] = True
        return out


def merge_runs(runs: list, out_path: str, block: int = 1 << 20,
               crash_hook=None) -> dict:
    """Bounded-memory k-way merge of open `SortedRun`s into one new run.

    Per iteration, each live cursor contributes up to `block` values; the
    emit bound is the smallest block-tail across live runs, so everything
    emitted is globally final (all remaining values exceed it).  Inputs
    are disjoint by construction (a fingerprint is spilled exactly once),
    so no dedup pass is needed.  `crash_hook` runs after the tmp write,
    before the atomic promote — the mid-merge torn-write injection point
    (`KSPEC_FAULT=crash@merge:N`).  -> the merged run's manifest entry.
    """
    # every input must pass its content CRC BEFORE its values are
    # streamed: merging an as-yet-unverified corrupt run would launder
    # the corruption into a merged run with a fresh VALID checksum,
    # defeating the read-side verification contract permanently
    for r in runs:
        if not r._read_verified:
            r._verify_content()
    cursors = [0] * len(runs)
    state = {"crc": 0, "total": 0, "lo": None, "hi": None}
    # the filter's bit count is fixed at build time — size it for the final
    # merged count up front, then add each emitted block incrementally
    n_total = sum(r.count for r in runs)
    bloom = BloomFilter(
        np.zeros(_next_pow2_bytes(DEFAULT_BITS_PER_KEY * n_total), np.uint8)
    )

    def write(fh):
        fh.write(_MAGIC)
        fh.write(np.uint64(0).tobytes())  # count patched below
        while True:
            bound = None
            for i, r in enumerate(runs):
                if cursors[i] < r.count:
                    tail = r.arr[min(cursors[i] + block, r.count) - 1]
                    bound = tail if bound is None else min(bound, tail)
            if bound is None:
                break
            parts = []
            for i, r in enumerate(runs):
                if cursors[i] >= r.count:
                    continue
                end = min(cursors[i] + block, r.count)
                seg = np.asarray(r.arr[cursors[i]:end])
                take = int(np.searchsorted(seg, bound, side="right"))
                if take:
                    parts.append(seg[:take])
                    cursors[i] += take
            merged = np.sort(np.concatenate(parts))
            payload = merged.tobytes()
            state["crc"] = zlib.crc32(payload, state["crc"])
            fh.write(payload)
            bloom.add(merged)
            state["total"] += merged.shape[0]
            if state["lo"] is None:
                state["lo"] = int(merged[0])
            state["hi"] = int(merged[-1])
        fh.seek(len(_MAGIC))
        fh.write(np.uint64(state["total"]).tobytes())

    atomic_write(out_path, write, before_replace=crash_hook)
    bloom.save(out_path + ".bloom")
    return {
        "name": os.path.basename(out_path),
        "count": state["total"],
        "crc32": state["crc"],
        "lo": state["lo"] or 0,
        "hi": state["hi"] or 0,
    }


def _next_pow2_bytes(nbits: int) -> int:
    nbits = max(1 << 13, nbits)
    return (1 << max(0, (nbits - 1).bit_length())) // 8
