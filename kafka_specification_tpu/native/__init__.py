"""Native (C++) runtime components, bound via ctypes.

`FpSet` — host-side open-addressing 64-bit fingerprint set (fpset.cpp), the
checker's spill/backstop dedup store (SURVEY.md §2.5): the device-resident
sorted set (ops/dedup.py) is the fast path while fingerprints fit in HBM;
this is the TLC-FPSet-equivalent for runs that outgrow it, and the backend
of engine.check(..., visited_backend="host").

The shared library is compiled on first use with g++ -O2 (cached next to the
source); environments without a toolchain fall back to a numpy-based set
with the same interface.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fpset.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_fpset.so")
_lock = threading.Lock()
_lib = None
_build_error = None


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)) or os.path.getmtime(_SO) < os.path.getmtime(
                _SRC
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            lib.fpset_create.restype = ctypes.c_void_p
            lib.fpset_create.argtypes = [ctypes.c_uint64]
            lib.fpset_destroy.argtypes = [ctypes.c_void_p]
            lib.fpset_count.restype = ctypes.c_uint64
            lib.fpset_count.argtypes = [ctypes.c_void_p]
            lib.fpset_capacity.restype = ctypes.c_uint64
            lib.fpset_capacity.argtypes = [ctypes.c_void_p]
            lib.fpset_insert_batch.restype = ctypes.c_uint64
            lib.fpset_insert_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.fpset_contains_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.fpset_dump.restype = ctypes.c_uint64
            lib.fpset_dump.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
            ]
            _lib = lib
        except Exception as e:  # no toolchain -> numpy fallback
            _build_error = e
        return _lib


def native_available() -> bool:
    return _load() is not None


class FpSet:
    """64-bit fingerprint set. insert(fps) -> bool mask of novel entries."""

    def __init__(self, initial_capacity: int = 1 << 16):
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.fpset_create(initial_capacity)
            if not self._h:
                raise MemoryError("fpset_create failed")
        else:
            self._py = set()

    def insert(self, fps: np.ndarray) -> np.ndarray:
        fps = np.ascontiguousarray(fps, dtype=np.uint64)
        out = np.empty(fps.shape[0], dtype=np.uint8)
        if self._lib is not None:
            rc = self._lib.fpset_insert_batch(
                self._h,
                fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                fps.shape[0],
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
            if rc == np.iinfo(np.uint64).max:
                raise MemoryError("fpset grow failed")
        else:
            for i, fp in enumerate(fps.tolist()):
                new = fp not in self._py
                if new:
                    self._py.add(fp)
                out[i] = new
        return out.astype(bool)

    def contains(self, fps: np.ndarray) -> np.ndarray:
        fps = np.ascontiguousarray(fps, dtype=np.uint64)
        out = np.empty(fps.shape[0], dtype=np.uint8)
        if self._lib is not None:
            self._lib.fpset_contains_batch(
                self._h,
                fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                fps.shape[0],
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        else:
            for i, fp in enumerate(fps.tolist()):
                out[i] = fp in self._py
        return out.astype(bool)

    def __len__(self):
        if self._lib is not None:
            return int(self._lib.fpset_count(self._h))
        return len(self._py)

    def dump(self) -> np.ndarray:
        if self._lib is None:
            return np.fromiter(self._py, dtype=np.uint64, count=len(self._py))
        n = len(self)
        out = np.empty(n, dtype=np.uint64)
        w = self._lib.fpset_dump(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )
        return out[:w]

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.fpset_destroy(h)
            self._h = None
