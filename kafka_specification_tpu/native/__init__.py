"""Native (C++) runtime components, bound via ctypes.

`FpSet` — host-side open-addressing 64-bit fingerprint set (fpset.cpp), the
checker's spill/backstop dedup store (SURVEY.md §2.5): the device-resident
sorted set (ops/dedup.py) is the fast path while fingerprints fit in HBM;
this is the TLC-FPSet-equivalent for runs that outgrow it, and the backend
of engine.check(..., visited_backend="host").

The shared library is compiled on first use with g++ -O2 (cached next to the
source); environments without a toolchain fall back to a numpy-based set
with the same interface.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fpset.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_fpset.so")
_lock = threading.Lock()
_lib = None
_build_error = None


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)) or os.path.getmtime(_SO) < os.path.getmtime(
                _SRC
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            lib.fpset_create.restype = ctypes.c_void_p
            lib.fpset_create.argtypes = [ctypes.c_uint64]
            lib.fpset_destroy.argtypes = [ctypes.c_void_p]
            lib.fpset_count.restype = ctypes.c_uint64
            lib.fpset_count.argtypes = [ctypes.c_void_p]
            lib.fpset_capacity.restype = ctypes.c_uint64
            lib.fpset_capacity.argtypes = [ctypes.c_void_p]
            lib.fpset_insert_batch.restype = ctypes.c_uint64
            lib.fpset_insert_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.fpset_insert_compact.restype = ctypes.c_uint64
            lib.fpset_insert_compact.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.fpset_contains_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.fpset_dump.restype = ctypes.c_uint64
            lib.fpset_dump.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
            ]
            _lib = lib
        except Exception as e:  # no toolchain -> numpy fallback
            _build_error = e
        return _lib


def native_available() -> bool:
    return _load() is not None


class FpSet:
    """64-bit fingerprint set. insert(fps) -> bool mask of novel entries."""

    def __init__(self, initial_capacity: int = 1 << 16):
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.fpset_create(initial_capacity)
            if not self._h:
                raise MemoryError("fpset_create failed")
        else:
            self._py = set()

    def insert(self, fps: np.ndarray) -> np.ndarray:
        fps = np.ascontiguousarray(fps, dtype=np.uint64)
        out = np.empty(fps.shape[0], dtype=np.uint8)
        if self._lib is not None:
            rc = self._lib.fpset_insert_batch(
                self._h,
                fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                fps.shape[0],
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
            if rc == np.iinfo(np.uint64).max:
                raise MemoryError("fpset grow failed")
        else:
            for i, fp in enumerate(fps.tolist()):
                new = fp not in self._py
                if new:
                    self._py.add(fp)
                out[i] = new
        return out.astype(bool)

    @property
    def native(self) -> bool:
        return self._lib is not None

    def insert_compact(
        self,
        hi: np.ndarray,
        lo: np.ndarray,
        rows: np.ndarray,
        parent: np.ndarray,
        parent_base: int,
        act: np.ndarray,
        arena_rows: np.ndarray,
        arena_parent: np.ndarray,
        arena_act: np.ndarray,
    ) -> int:
        """Fused insert + novel-row compaction (engine/bfs host backend).

        Inserts fp = hi<<32|lo per candidate; for novel ones appends
        rows[i] / parent[i]+parent_base / act[i] into the arena slices
        (which must have >= len(hi) rows of headroom).  Returns the number
        of rows appended.  One C pass — no u64 temp, no novelty-mask
        gather, no per-level concatenate.  Requires the native library
        (callers fall back to insert() + masking when `native` is False).
        """
        n = hi.shape[0]
        assert self._lib is not None
        assert rows.flags.c_contiguous and arena_rows.flags.c_contiguous
        # every arena slice needs headroom for the all-novel worst case —
        # the C pass writes unchecked
        assert (
            arena_rows.shape[0] >= n
            and arena_parent.shape[0] >= n
            and arena_act.shape[0] >= n
        )
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        w = self._lib.fpset_insert_compact(
            self._h,
            hi.ctypes.data_as(u32p),
            lo.ctypes.data_as(u32p),
            n,
            rows.ctypes.data_as(u32p),
            rows.shape[1],
            parent.ctypes.data_as(i32p),
            parent_base,
            act.ctypes.data_as(i32p),
            arena_rows.ctypes.data_as(u32p),
            arena_parent.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            arena_act.ctypes.data_as(i32p),
        )
        if w == np.iinfo(np.uint64).max:
            raise MemoryError("fpset grow failed")
        return int(w)

    def contains(self, fps: np.ndarray) -> np.ndarray:
        fps = np.ascontiguousarray(fps, dtype=np.uint64)
        out = np.empty(fps.shape[0], dtype=np.uint8)
        if self._lib is not None:
            self._lib.fpset_contains_batch(
                self._h,
                fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                fps.shape[0],
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        else:
            for i, fp in enumerate(fps.tolist()):
                out[i] = fp in self._py
        return out.astype(bool)

    def __len__(self):
        if self._lib is not None:
            return int(self._lib.fpset_count(self._h))
        return len(self._py)

    def dump(self) -> np.ndarray:
        if self._lib is None:
            return np.fromiter(self._py, dtype=np.uint64, count=len(self._py))
        n = len(self)
        out = np.empty(n, dtype=np.uint64)
        w = self._lib.fpset_dump(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )
        return out[:w]

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.fpset_destroy(h)
            self._h = None
