// Host-side 64-bit fingerprint set: open-addressing, linear probing,
// batch-oriented C ABI for ctypes.
//
// Role (SURVEY.md §2.5): the one native runtime component of the checker.
// The device-resident sorted dedup (ops/dedup.py) is the fast path while the
// visited set fits in HBM; this set is the host spill/backstop — it replaces
// TLC's disk-backed FPSet for runs whose fingerprint set outgrows device
// memory, and serves as the dedup backend of the engine's host mode
// (engine.check(..., visited_backend="host")).
//
// Design: power-of-two capacity, linear probing, empty slot = 0; the
// fingerprint 0 itself is tracked by a dedicated has_zero flag (exact-mode
// fingerprints ARE packed states, so value 0 is a real state and must not
// be conflated with any other). Batch insert returns a novelty mask so one
// FFI crossing handles a whole BFS level.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

struct FpSet {
  uint64_t* slots;
  uint64_t mask;      // capacity - 1
  uint64_t count;
  uint64_t capacity;
  uint8_t has_zero;   // membership of the fingerprint value 0
};

inline uint64_t mix(uint64_t x) {
  // splitmix64 finalizer — decorrelates the probe sequence from the raw fp
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ULL;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebULL;
  x ^= x >> 31;
  return x;
}

bool grow(FpSet* s);

// insert one; returns 1 if newly inserted, 0 if already present
inline int insert_one(FpSet* s, uint64_t fp) {
  if (fp == 0) {
    int is_new = !s->has_zero;
    s->has_zero = 1;
    s->count += static_cast<uint64_t>(is_new);
    return is_new;
  }
  uint64_t i = mix(fp) & s->mask;
  while (true) {
    uint64_t v = s->slots[i];
    if (v == fp) return 0;
    if (v == 0) {
      s->slots[i] = fp;
      s->count++;
      return 1;
    }
    i = (i + 1) & s->mask;
  }
}

bool grow(FpSet* s) {
  uint64_t old_cap = s->capacity;
  uint64_t* old_slots = s->slots;
  uint64_t new_cap = old_cap << 1;
  uint64_t* new_slots = static_cast<uint64_t*>(calloc(new_cap, sizeof(uint64_t)));
  if (!new_slots) return false;
  s->slots = new_slots;
  s->capacity = new_cap;
  s->mask = new_cap - 1;
  s->count = s->has_zero;  // re-count; zero membership carries over
  for (uint64_t i = 0; i < old_cap; i++) {
    if (old_slots[i] != 0) insert_one(s, old_slots[i]);
  }
  free(old_slots);
  return true;
}

}  // namespace

extern "C" {

void* fpset_create(uint64_t initial_capacity) {
  uint64_t cap = 64;
  while (cap < initial_capacity) cap <<= 1;
  FpSet* s = static_cast<FpSet*>(malloc(sizeof(FpSet)));
  if (!s) return nullptr;
  s->slots = static_cast<uint64_t*>(calloc(cap, sizeof(uint64_t)));
  if (!s->slots) {
    free(s);
    return nullptr;
  }
  s->capacity = cap;
  s->mask = cap - 1;
  s->count = 0;
  s->has_zero = 0;
  return s;
}

void fpset_destroy(void* h) {
  FpSet* s = static_cast<FpSet*>(h);
  if (!s) return;
  free(s->slots);
  free(s);
}

uint64_t fpset_count(void* h) { return static_cast<FpSet*>(h)->count; }

uint64_t fpset_capacity(void* h) { return static_cast<FpSet*>(h)->capacity; }

// Insert a batch; out_new[i] = 1 iff fps[i] was not present before this call
// (duplicates *within* the batch: only the first occurrence reports new).
// Returns the number of new fingerprints, or UINT64_MAX on alloc failure.
uint64_t fpset_insert_batch(void* h, const uint64_t* fps, uint64_t n,
                            uint8_t* out_new) {
  FpSet* s = static_cast<FpSet*>(h);
  uint64_t added = 0;
  for (uint64_t i = 0; i < n; i++) {
    // keep load factor under 0.75
    if ((s->count + 1) * 4 > s->capacity * 3) {
      if (!grow(s)) return UINT64_MAX;
    }
    int is_new = insert_one(s, fps[i]);
    if (out_new) out_new[i] = static_cast<uint8_t>(is_new);
    added += static_cast<uint64_t>(is_new);
  }
  return added;
}

// Fused level assembly (engine/bfs host backend): one pass over a chunk's
// candidates that (a) inserts each (hi,lo) fingerprint, and (b) for the
// NEW ones only, appends the packed state row, globalized parent index and
// action id into caller-provided arena slices.  Replaces the Python-side
// u64 packing + novelty-mask gather + per-level concatenate with a single
// cache-friendly pass (the probe is the only random access).  Returns the
// number of rows appended, or UINT64_MAX on alloc failure.
uint64_t fpset_insert_compact(void* h, const uint32_t* hi, const uint32_t* lo,
                              uint64_t n, const uint32_t* rows, uint64_t K,
                              const int32_t* parent_in, int64_t parent_base,
                              const int32_t* act_in, uint32_t* arena_rows,
                              int64_t* parent_out, int32_t* act_out) {
  FpSet* s = static_cast<FpSet*>(h);
  uint64_t w = 0;
  for (uint64_t i = 0; i < n; i++) {
    if ((s->count + 1) * 4 > s->capacity * 3) {
      if (!grow(s)) return UINT64_MAX;
    }
    uint64_t fp = (static_cast<uint64_t>(hi[i]) << 32) |
                  static_cast<uint64_t>(lo[i]);
    if (insert_one(s, fp)) {
      memcpy(arena_rows + w * K, rows + i * K, K * sizeof(uint32_t));
      parent_out[w] = static_cast<int64_t>(parent_in[i]) + parent_base;
      act_out[w] = act_in[i];
      w++;
    }
  }
  return w;
}

// Membership only (no mutation): out_found[i] = 1 iff present.
void fpset_contains_batch(void* h, const uint64_t* fps, uint64_t n,
                          uint8_t* out_found) {
  FpSet* s = static_cast<FpSet*>(h);
  for (uint64_t i = 0; i < n; i++) {
    uint64_t fp = fps[i];
    if (fp == 0) {
      out_found[i] = s->has_zero;
      continue;
    }
    uint64_t j = mix(fp) & s->mask;
    uint8_t found = 0;
    while (true) {
      uint64_t v = s->slots[j];
      if (v == fp) {
        found = 1;
        break;
      }
      if (v == 0) break;
      j = (j + 1) & s->mask;
    }
    out_found[i] = found;
  }
}

// Serialize the live fingerprints into out (caller allocates count slots);
// returns the number written. Order is unspecified.
uint64_t fpset_dump(void* h, uint64_t* out, uint64_t max_n) {
  FpSet* s = static_cast<FpSet*>(h);
  uint64_t w = 0;
  if (s->has_zero && w < max_n) out[w++] = 0;
  for (uint64_t i = 0; i < s->capacity && w < max_n; i++) {
    if (s->slots[i] != 0) out[w++] = s->slots[i];
  }
  return w;
}

}  // extern "C"
