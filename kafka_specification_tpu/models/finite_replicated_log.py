"""FiniteReplicatedLog — standalone bounded per-replica log state machine.

Reference: /root/reference/FiniteReplicatedLog.tla
  State: logs[replica] = [endOffset: 0..LogSize,
                          records: Offsets -> LogRecords \\union {Nil}]  (:41-44)
  Next == \\E replica :                                              (:115-118)
      \\/ \\E record, offset : Append(replica, record, offset)
      \\/ \\E offset : TruncateTo(replica, offset)
      \\/ \\E other # replica : ReplicateTo(replica, other)
  THEOREM Spec => []TypeOk                                           (:122)

Tensor encoding (SURVEY.md §2.2): end[N] in 0..L; rec[N, L] in {-1} + 0..R-1
(Nil = -1).  TruncateTo Nil-fills truncated slots (:108), so the dense array
is canonical by construction and bitwise fingerprinting is sound.

Choice spaces:
  Append      (replica, record): offset is forced to endOffset (:101)
  TruncateTo  (replica, offset): offset in 0..LogSize-1 (Offsets, :37)
  ReplicateTo (from, to): offset/record forced to to's endOffset / from's
              record there (:111-113)
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.packing import Field, StateSpec
from ..oracle.interp import OracleAction, OracleModel
from .base import Action, Invariant, Model

NIL = -1


def make_model(
    n_replicas: int, log_size: int, n_records: int, force_hashed: bool = False
) -> Model:
    N, L, R = n_replicas, log_size, n_records
    spec = StateSpec(
        [
            Field("end", (N,), 0, L),
            Field("rec", (N, L), NIL, R - 1),
        ],
        force_hashed=force_hashed,
    )

    def init():
        # Init == logs = [replica |-> EmptyLog]  (FiniteReplicatedLog.tla:97,43-44)
        return [{"end": [0] * N, "rec": [[NIL] * L for _ in range(N)]}]

    def append(state, choice):
        # Append(replica, record, offset), offset = endOffset, ~IsFull (:99-103)
        r = choice // R
        record = choice % R
        end = state["end"][r]
        enabled = end < L
        off = jnp.minimum(end, L - 1)
        rec = state["rec"].at[r, off].set(jnp.where(enabled, record, state["rec"][r, off]))
        new_end = state["end"].at[r].set(jnp.where(enabled, end + 1, end))
        return enabled, {"end": new_end, "rec": rec}

    def truncate_to(state, choice):
        # TruncateTo(replica, newEndOffset <= endOffset); Nil-fill (:105-109)
        r = choice // L
        new_end = choice % L
        end = state["end"][r]
        enabled = new_end <= end
        offs = jnp.arange(L)
        row = jnp.where(offs < new_end, state["rec"][r], NIL)
        rec = state["rec"].at[r].set(jnp.where(enabled, row, state["rec"][r]))
        ends = state["end"].at[r].set(jnp.where(enabled, new_end, end))
        return enabled, {"end": ends, "rec": rec}

    def replicate_to(state, choice):
        # ReplicateTo(from, to) == \E offset, record : HasEntry(from, record, offset)
        #                          /\ Append(to, record, offset)   (:111-113)
        # offset forced to to's endOffset; record forced to from's entry there.
        src = choice // (N - 1)
        dst_i = choice % (N - 1)
        dst = jnp.where(dst_i >= src, dst_i + 1, dst_i)  # Replicas \ {src}
        off = state["end"][dst]
        enabled = (off < L) & (off < state["end"][src])
        offc = jnp.minimum(off, L - 1)
        record = state["rec"][src, offc]
        rec = state["rec"].at[dst, offc].set(
            jnp.where(enabled, record, state["rec"][dst, offc])
        )
        ends = state["end"].at[dst].set(jnp.where(enabled, off + 1, off))
        return enabled, {"end": ends, "rec": rec}

    def type_ok(state):
        # TypeOk (:90-95): written slots hold records, unwritten slots Nil.
        offs = jnp.arange(L)[None, :]
        written = offs < state["end"][:, None]
        rec = state["rec"]
        ok_written = jnp.all(jnp.where(written, (rec >= 0) & (rec < R), True))
        ok_unwritten = jnp.all(jnp.where(~written, rec == NIL, True))
        ok_end = jnp.all((state["end"] >= 0) & (state["end"] <= L))
        return ok_written & ok_unwritten & ok_end

    def decode(s):
        return tuple(
            tuple(int(x) for x in s["rec"][r][: int(s["end"][r])]) for r in range(N)
        )

    return Model(
        name=f"FiniteReplicatedLog(N={N},L={L},R={R})",
        spec=spec,
        init_states=init,
        actions=[
            Action("Append", N * R, append,
                   writes=frozenset({"end", "rec"})),
            Action("TruncateTo", N * L, truncate_to,
                   writes=frozenset({"end", "rec"})),
            Action("ReplicateTo", N * (N - 1), replicate_to,
                   writes=frozenset({"end", "rec"})),
        ],
        invariants=[Invariant("TypeOk", type_ok)],
        decode=decode,
    )


def make_oracle(n_replicas: int, log_size: int, n_records: int) -> OracleModel:
    """Set-semantics transcription. State = tuple over replicas of the written
    record tuple (endOffset is its length; unwritten slots are implicit Nil,
    canonical per FiniteReplicatedLog.tla:105-109)."""
    N, L, R = n_replicas, log_size, n_records

    def append(s):
        # :99-103
        for r in range(N):
            if len(s[r]) < L:
                for record in range(R):
                    yield s[:r] + (s[r] + (record,),) + s[r + 1 :]

    def truncate(s):
        # :105-109; newEndOffset in Offsets = 0..L-1 (:37,117) and <= endOffset
        for r in range(N):
            for new_end in range(min(len(s[r]), L - 1) + 1):
                yield s[:r] + (s[r][:new_end],) + s[r + 1 :]

    def replicate(s):
        # :111-113, 118
        for src in range(N):
            for dst in range(N):
                if dst == src:
                    continue
                off = len(s[dst])
                if off < L and off < len(s[src]):
                    yield s[:dst] + (s[dst] + (s[src][off],),) + s[dst + 1 :]

    return OracleModel(
        name=f"FiniteReplicatedLog(N={N},L={L},R={R})",
        init_states=lambda: [tuple(() for _ in range(N))],  # :97
        actions=[
            OracleAction("Append", append),
            OracleAction("TruncateTo", truncate),
            OracleAction("ReplicateTo", replicate),
        ],
        # TypeOk (:90-95): endOffset bounded; written slots hold LogRecords
        # (unwritten slots are implicitly Nil in this representation, which is
        # the canonical form TruncateTo maintains, :108)
        invariants=[
            (
                "TypeOk",
                lambda s: all(
                    len(log) <= L and all(0 <= rec < R for rec in log) for log in s
                ),
            )
        ],
    )
