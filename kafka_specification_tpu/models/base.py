"""Model API: a TLA+ spec compiled to tensor form.

A Model is the TPU-native analogue of (TLA+ module + TLC .cfg):

- `spec` defines the canonical tensor encoding of one state,
- each Action is one disjunct of `Next`, compiled to a successor kernel over a
  *fixed* choice space (the bounded existentials of the TLA+ action, e.g.
  `\\E replica \\in Replicas` -> choice = replica index).  The kernel returns
  (enabled?, next_state) for a given (state, choice); the engine vmaps it over
  states x choices and masks disabled combinations — this is how TLC's
  nondeterministic disjunct expansion becomes a dense TPU computation,
- each Invariant is a predicate kernel (True = state OK),
- `constraint`, if set, is TLC's CONSTRAINT: successors violating it are
  pruned (not explored, not counted) — required to bound AsyncIsr, whose
  LeaderWrite has no MaxOffset guard (/root/reference/AsyncIsr.tla:117-119).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..ops.packing import StateSpec

# kernel: (state: dict[str, Array], choice: int32 scalar) -> (enabled: bool, next_state: dict)
SuccessorKernel = Callable
# pred: (state: dict[str, Array]) -> bool  (True = invariant holds)
PredicateKernel = Callable


@dataclass(frozen=True)
class Action:
    name: str
    n_choices: int
    kernel: SuccessorKernel
    # declared write set (TLA+ frame condition: the variables this
    # action's disjunct primes).  None = undeclared (emitted models,
    # ad-hoc test kernels); when declared, the static analyzer's
    # frame-condition pass proves the kernel writes nothing else
    # (analysis/encoding.py; docs/analysis.md)
    writes: Optional[frozenset] = None


@dataclass(frozen=True)
class Invariant:
    name: str
    pred: PredicateKernel


@dataclass
class Model:
    name: str
    spec: StateSpec
    init_states: Callable[[], Sequence[dict]]
    actions: Sequence[Action]
    invariants: Sequence[Invariant]
    constraint: Optional[PredicateKernel] = None
    # canonical Python value for a decoded state; must equal the oracle
    # interpreter's state representation so state *sets* can be compared.
    decode: Optional[Callable[[dict], object]] = None
    meta: dict = field(default_factory=dict)
    # optional fused evaluator: state -> bool[len(invariants)] (column i =
    # invariants[i] holds).  Lets an implementation share work ACROSS
    # invariant predicates within one trace (the emitted models' WeakIsr
    # and StrongIsr share their quantifier core); engines fall back to the
    # per-invariant preds when None (and for single-invariant re-checks).
    invariants_fused: Optional[Callable] = None

    def __post_init__(self):
        # spec-width soundness at EVERY model construction: each declared
        # field range must fit the int32 packed-element dtype and a
        # 32-bit lane (the general form of the AsyncIsr N<=4 cliff; the
        # interval pass over the action kernels runs at the engine/CLI
        # gates — analysis/encoding.py, docs/analysis.md).  jax-free.
        from ..analysis.encoding import check_spec_fields

        check_spec_fields(self.spec.fields, context=self.name)

    @property
    def total_fanout(self) -> int:
        return sum(a.n_choices for a in self.actions)

    def invariant(self, name: str) -> Invariant:
        for inv in self.invariants:
            if inv.name == name:
                return inv
        raise KeyError(name)
