"""IdSequence — monotonically increasing bounded counter.

Reference: /root/reference/IdSequence.tla
  IdSet == 0..MaxId                  (IdSequence.tla:28)
  NextId(id) == id <= MaxId /\\ id = nextId /\\ nextId' = nextId + 1
                                     (IdSequence.tla:30-33)
  Init == nextId = 0                 (IdSequence.tla:37)
  Next == \\E id \\in IdSet : NextId(id)  (IdSequence.tla:39)
  TypeOk == nextId \\in IdSet \\union {MaxId + 1}  (IdSequence.tla:43)

The existential in Next is forced (only id = nextId satisfies the guard), so
the action kernel has a single choice.  Smallest checkable model in the
corpus: MaxId + 2 distinct states in a single chain.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.packing import Field, StateSpec
from ..oracle.interp import OracleAction, OracleModel
from .base import Action, Invariant, Model


def make_model(max_id: int) -> Model:
    spec = StateSpec([Field("nextId", (), 0, max_id + 1)])

    def init():
        return [{"nextId": 0}]

    def next_id(state, choice):
        # NextId guard: id = nextId /\ id <= MaxId (IdSequence.tla:31-32).
        # `id` is forced to nextId, so the only real guard is the bound.
        enabled = state["nextId"] <= max_id
        return enabled, {"nextId": jnp.minimum(state["nextId"] + 1, max_id + 1)}

    def type_ok(state):
        return (state["nextId"] >= 0) & (state["nextId"] <= max_id + 1)

    return Model(
        name=f"IdSequence(MaxId={max_id})",
        spec=spec,
        init_states=init,
        actions=[Action("NextId", 1, next_id,
                        writes=frozenset({"nextId"}))],
        invariants=[Invariant("TypeOk", type_ok)],
        decode=lambda s: int(s["nextId"]),
    )


def make_oracle(max_id: int) -> OracleModel:
    def successors(s):
        if s <= max_id:  # IdSequence.tla:31-33
            yield s + 1

    return OracleModel(
        name=f"IdSequence(MaxId={max_id})",
        init_states=lambda: [0],  # IdSequence.tla:37
        actions=[OracleAction("NextId", successors)],
        invariants=[("TypeOk", lambda s: 0 <= s <= max_id + 1)],  # IdSequence.tla:43
    )
