"""Kip320 — the final, correct fenced replication protocol (the flagship
model), and Kip320FirstTry — the rejected truncate-on-fetch-error design.

References: /root/reference/Kip320.tla and /root/reference/Kip320FirstTry.tla
(both EXTEND Kip279, which supplies FirstNonMatchingOffsetFromTail,
Kip279.tla:39-45).

Kip320's Next (Kip320.tla:150-159) keeps the controller actions, BecomeLeader
and LeaderWrite from the core and replaces the five replica-side actions with
fenced versions (:49-148).  Its four THEOREMs (:168-171) are the corpus's
headline correctness claims: TypeOk / LeaderInIsr / WeakIsr / StrongIsr all
hold (for LeaderInIsr see the literal-vs-intent note in kafka_replication.py).

Kip320FirstTry's Next (Kip320FirstTry.tla:159-169) instead lets followers
fetch immediately and truncate on epoch mismatch at any time (:75-82); it
fails StrongIsr because the leader can advance the HW with a follower on an
older epoch (:27-39).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..oracle.interp import OracleAction, OracleModel
from .base import Action, Model
from . import kafka_replication as kr
from .kafka_replication import NONE, Config, _bit, _member, _forall_isr
from .variants import _invariant_kernels, _invariant_oracles, DEFAULT_INVARIANTS


# --------------------------------------------------------------------------
# Kip320 kernels (Kip320.tla:39-148)
# --------------------------------------------------------------------------


def _following_epoch(s, l, f):
    # IsFollowingLeaderEpoch (Kip320.tla:39-42): leader presumes leadership,
    # follower follows it, and epochs match.
    return (s["ldr"][l] == l) & (s["ldr"][f] == l) & (s["ep"][f] == s["ep"][l])


def fenced_follower_fetch(cfg: Config):
    # FencedFollowerFetch (Kip320.tla:49-56): FollowerReplicate, fenced on
    # the follower having the leader's epoch.
    def kernel(s, c):
        f, l = c // cfg.n, c % cfg.n
        off = s["end"][f]
        enabled = (
            _following_epoch(s, l, f) & (off < cfg.l) & (off < s["end"][l])
        )
        offc = jnp.minimum(off, cfg.l - 1)
        new_hw = jnp.minimum(s["hw"][l], off + 1)
        return enabled, {
            **s,
            "rid": s["rid"].at[f, offc].set(
                jnp.where(enabled, s["rid"][l, offc], s["rid"][f, offc])
            ),
            "repoch": s["repoch"].at[f, offc].set(
                jnp.where(enabled, s["repoch"][l, offc], s["repoch"][f, offc])
            ),
            "end": s["end"].at[f].set(jnp.where(enabled, off + 1, off)),
            "hw": s["hw"].at[f].set(jnp.where(enabled, new_hw, s["hw"][f])),
        }

    return Action("FencedFollowerFetch", cfg.n * cfg.n, kernel,
                  writes=kr._REPLICATE_WRITES)


def fenced_leader_inc_high_watermark(cfg: Config):
    # FencedLeaderIncHighWatermark (Kip320.tla:63-70): every ISR member must
    # be on the leader's epoch and past the HW; the leader itself must hold a
    # record at the HW.  (Quantifies leader over Replicas without a presumes
    # guard of its own — with an empty local ISR the \A is vacuous and only
    # HasOffset(leader, hw) gates; kept literal.)
    def kernel(s, l):
        hw = s["hw"][l]
        has_off = hw < s["end"][l]
        cond = _following_epoch_vec(cfg, s, l) & (s["end"] > hw)
        enabled = has_off & _forall_isr(cfg, s["isr"][l], cond)
        return enabled, {**s, "hw": s["hw"].at[l].set(jnp.minimum(hw + 1, cfg.l))}

    return Action("FencedLeaderIncHighWatermark", cfg.n, kernel,
                  writes=frozenset({"hw"}))


def _following_epoch_vec(cfg, s, l):
    """IsFollowingLeaderEpoch(l, f) for all f as a vector over f."""
    return (s["ldr"][l] == l) & (s["ldr"] == l) & (s["ep"] == s["ep"][l])


def fenced_leader_shrink_isr(cfg: Config):
    # FencedLeaderShrinkIsr (Kip320.tla:78-85): drop an ISR member that is
    # not following the current epoch or whose end offset lags.
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        in_isr = (f != l) & _member(s["isr"][l], f)
        stale = ~_following_epoch(s, l, f) | (s["end"][f] < s["end"][l])
        ok, nxt = kr._quorum_update(s, l, s["isr"][l] & ~_bit(f))
        return in_isr & stale & ok, nxt

    return Action("FencedLeaderShrinkIsr", cfg.n * cfg.n, kernel,
                  writes=kr._QUORUM_WRITES)


def fenced_leader_expand_isr(cfg: Config):
    # FencedLeaderExpandIsr (Kip320.tla:110-117), guarded by
    # HasFollowerReachedHighWatermark (:94-98) and
    # HasHighWatermarkReachedCurrentEpoch (:87-92).
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        outside = ~_member(s["isr"][l], f)
        hw = s["hw"][l]
        follower_at_hw = (hw == 0) | (s["end"][f] >= hw)  # :94-98
        hw_at_epoch = (hw == s["end"][l]) | (
            (hw < s["end"][l])
            & (s["repoch"][l, jnp.minimum(hw, cfg.l - 1)] == s["ep"][l])
        )  # :87-92
        ok, nxt = kr._quorum_update(s, l, s["isr"][l] | _bit(f))
        return (
            outside & _following_epoch(s, l, f) & follower_at_hw & hw_at_epoch & ok
        ), nxt

    return Action("FencedLeaderExpandIsr", cfg.n * cfg.n, kernel,
                  writes=kr._QUORUM_WRITES)


def fenced_become_follower_and_truncate(cfg: Config):
    # FencedBecomeFollowerAndTruncate (Kip320.tla:134-148): truncation is
    # fenced on the target leader being active in the request's epoch
    # (:142-143); truncation point = FirstNonMatchingOffsetFromTail.  The
    # leader = None branch (:138-140) is dead (leader ranges over Replicas).
    trunc = kr.kip279_offset(cfg)

    def kernel(s, c):
        r, e = c // (cfg.e + 1), c % (cfg.e + 1)
        l = s["req_ldr"][e]
        lc = jnp.clip(l, 0, cfg.n - 1)
        enabled = (
            (l >= 0)
            & (lc != r)
            & (e > s["ep"][r])
            & (s["ldr"][lc] == lc)  # ReplicaPresumesLeadership(leader) (:142)
            & (s["ep"][lc] == e)  # leader on the request's epoch (:143)
        )
        toff = trunc(s, lc, r)
        enabled = enabled & (toff <= s["end"][r])
        toff = jnp.clip(toff, 0, cfg.l)
        rid, repoch, end = kr._truncate_log(s, r, toff)
        return enabled, {
            **s,
            "rid": rid,
            "repoch": repoch,
            "end": end,
            "ep": s["ep"].at[r].set(e),
            "ldr": s["ldr"].at[r].set(lc),
            "isr": s["isr"].at[r].set(s["req_isr"][e]),
            "hw": s["hw"].at[r].set(jnp.minimum(toff, s["hw"][r])),  # (:145)
        }

    return Action("FencedBecomeFollowerAndTruncate", cfg.n * (cfg.e + 1),
                  kernel, writes=kr._BECOME_FOLLOWER_WRITES)


# --------------------------------------------------------------------------
# Kip320FirstTry kernels (Kip320FirstTry.tla:49-157)
# --------------------------------------------------------------------------


def _caught_up_to_epoch(cfg, s, l, f, end_offset):
    # IsFollowerCaughtUpToLeaderEpoch (Kip320FirstTry.tla:49-57): presumed
    # leadership + following + the records at endOffset-1 carry the same
    # epoch on both logs (ids need not match).
    base = (s["ldr"][l] == l) & (s["ldr"][f] == l)
    off = jnp.clip(end_offset - 1, 0, cfg.l - 1)
    nonzero = (
        (end_offset > 0)
        & (end_offset <= s["end"][l])
        & (end_offset <= s["end"][f])
        & (s["repoch"][f, off] == s["repoch"][l, off])
    )
    return base & ((end_offset == 0) | nonzero)


def ft_follower_truncate(cfg: Config):
    # FollowerTruncate (Kip320FirstTry.tla:75-82), guarded by
    # FollowerNeedsTruncation (:64-69).
    trunc = kr.kip279_offset(cfg)

    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        base = (s["ldr"][l] == l) & (s["ldr"][f] == l)
        f_end = s["end"][f]
        last = jnp.clip(f_end - 1, 0, cfg.l - 1)
        epoch_mismatch = (
            (f_end > 0)
            & (f_end <= s["end"][l])  # HasOffset(leader, f_end - 1)
            & (s["repoch"][l, last] != s["repoch"][f, last])
        )
        needs = (f_end > s["end"][l]) | epoch_mismatch
        toff = trunc(s, l, f)
        enabled = base & needs & (toff <= f_end)
        toff = jnp.clip(toff, 0, cfg.l)
        rid, repoch, end = kr._truncate_log(s, f, toff)
        return enabled, {
            **s,
            "rid": rid,
            "repoch": repoch,
            "end": end,
            "hw": s["hw"].at[f].set(jnp.minimum(toff, s["hw"][f])),  # (:81)
        }

    return Action("FollowerTruncate", cfg.n * cfg.n, kernel,
                  writes=kr._REPLICATE_WRITES)


def ft_improved_leader_inc_high_watermark(cfg: Config):
    # ImprovedLeaderIncHighWatermark (Kip320FirstTry.tla:90-97): every ISR
    # member caught up (by epoch) to hw+1.
    def kernel(s, l):
        hw = s["hw"][l]
        presumes = s["ldr"][l] == l
        has_entry = hw < s["end"][l]
        off = jnp.minimum(hw, cfg.l - 1)
        cond = (
            (s["ldr"] == l)
            & (hw + 1 <= s["end"][l])
            & (hw + 1 <= s["end"])
            & (s["repoch"][:, off] == s["repoch"][l, off])
        )
        enabled = presumes & has_entry & _forall_isr(cfg, s["isr"][l], cond)
        return enabled, {**s, "hw": s["hw"].at[l].set(jnp.minimum(hw + 1, cfg.l))}

    return Action("ImprovedLeaderIncHighWatermark", cfg.n, kernel,
                  writes=frozenset({"hw"}))


def ft_follower_fetch(cfg: Config):
    # FollowerFetch (Kip320FirstTry.tla:103-111): replicate only when caught
    # up (by epoch) to own end offset.
    def kernel(s, c):
        f, l = c // cfg.n, c % cfg.n
        off = s["end"][f]
        enabled = (
            _caught_up_to_epoch(cfg, s, l, f, off)
            & (off < cfg.l)
            & (off < s["end"][l])
        )
        offc = jnp.minimum(off, cfg.l - 1)
        new_hw = jnp.minimum(s["hw"][l], off + 1)
        return enabled, {
            **s,
            "rid": s["rid"].at[f, offc].set(
                jnp.where(enabled, s["rid"][l, offc], s["rid"][f, offc])
            ),
            "repoch": s["repoch"].at[f, offc].set(
                jnp.where(enabled, s["repoch"][l, offc], s["repoch"][f, offc])
            ),
            "end": s["end"].at[f].set(jnp.where(enabled, off + 1, off)),
            "hw": s["hw"].at[f].set(jnp.where(enabled, new_hw, s["hw"][f])),
        }

    return Action("FollowerFetch", cfg.n * cfg.n, kernel,
                  writes=kr._REPLICATE_WRITES)


def ft_leader_shrink_isr(cfg: Config):
    # LeaderShrinkIsrBetterFencing (Kip320FirstTry.tla:114-120)
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        in_isr = (f != l) & _member(s["isr"][l], f)
        lagging = ~_caught_up_to_epoch(cfg, s, l, f, s["end"][l])
        ok, nxt = kr._quorum_update(s, l, s["isr"][l] & ~_bit(f))
        return in_isr & lagging & ok, nxt

    return Action("LeaderShrinkIsrBetterFencing", cfg.n * cfg.n, kernel,
                  writes=kr._QUORUM_WRITES)


def ft_leader_expand_isr(cfg: Config):
    # LeaderExpandIsrBetterFencing (Kip320FirstTry.tla:134-141), with the
    # HasHighWatermarkReachedCurrentEpoch guard (:122-127).
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        outside = ~_member(s["isr"][l], f)
        hw = s["hw"][l]
        caught = _caught_up_to_epoch(cfg, s, l, f, hw)
        hw_at_epoch = (hw == s["end"][l]) | (
            (hw < s["end"][l])
            & (s["repoch"][l, jnp.minimum(hw, cfg.l - 1)] == s["ep"][l])
        )
        ok, nxt = kr._quorum_update(s, l, s["isr"][l] | _bit(f))
        return outside & caught & hw_at_epoch & ok, nxt

    return Action("LeaderExpandIsrBetterFencing", cfg.n * cfg.n, kernel,
                  writes=kr._QUORUM_WRITES)


def ft_become_follower(cfg: Config):
    # BecomeFollower (Kip320FirstTry.tla:148-157): adopt the request's state,
    # keep the log and hw (no truncation on leader change in this design).
    def kernel(s, c):
        r, e = c // (cfg.e + 1), c % (cfg.e + 1)
        l = s["req_ldr"][e]
        lc = jnp.clip(l, 0, cfg.n - 1)
        enabled = (l >= 0) & (lc != r) & (e > s["ep"][r])
        return enabled, {
            **s,
            "ep": s["ep"].at[r].set(e),
            "ldr": s["ldr"].at[r].set(lc),
            "isr": s["isr"].at[r].set(s["req_isr"][e]),
        }

    return Action("BecomeFollower", cfg.n * (cfg.e + 1), kernel,
                  writes=frozenset({"ep", "ldr", "isr"}))


# --------------------------------------------------------------------------
# model factories
# --------------------------------------------------------------------------


def make_model(cfg: Config, invariants: Sequence[str] = DEFAULT_INVARIANTS) -> Model:
    """Kip320!Next (Kip320.tla:150-159)."""
    actions = [
        kr.controller_elect_leader(cfg),
        kr.controller_shrink_isr(cfg),
        kr.become_leader(cfg),
        fenced_leader_expand_isr(cfg),
        fenced_leader_shrink_isr(cfg),
        kr.leader_write(cfg),
        fenced_leader_inc_high_watermark(cfg),
        fenced_become_follower_and_truncate(cfg),
        fenced_follower_fetch(cfg),
    ]
    return Model(
        name=f"Kip320({cfg.n}r,L{cfg.l},R{cfg.r},E{cfg.e})",
        spec=kr.make_spec(cfg),
        init_states=lambda: [kr.init_state(cfg)],
        actions=actions,
        invariants=_invariant_kernels(cfg, invariants),
        decode=kr.make_decode(cfg),
        meta={"variant": "Kip320", "cfg": cfg},
    )


def make_first_try_model(
    cfg: Config, invariants: Sequence[str] = DEFAULT_INVARIANTS
) -> Model:
    """Kip320FirstTry!Next (Kip320FirstTry.tla:159-169)."""
    actions = [
        kr.controller_elect_leader(cfg),
        kr.controller_shrink_isr(cfg),
        kr.become_leader(cfg),
        ft_leader_expand_isr(cfg),
        ft_leader_shrink_isr(cfg),
        kr.leader_write(cfg),
        ft_improved_leader_inc_high_watermark(cfg),
        ft_become_follower(cfg),
        ft_follower_fetch(cfg),
        ft_follower_truncate(cfg),
    ]
    return Model(
        name=f"Kip320FirstTry({cfg.n}r,L{cfg.l},R{cfg.r},E{cfg.e})",
        spec=kr.make_spec(cfg),
        init_states=lambda: [kr.init_state(cfg)],
        actions=actions,
        invariants=_invariant_kernels(cfg, invariants),
        decode=kr.make_decode(cfg),
        meta={"variant": "Kip320FirstTry", "cfg": cfg},
    )


# ==========================================================================
# oracle transcriptions
# ==========================================================================


def _o_following_epoch(s, l, f):
    # IsFollowingLeaderEpoch (Kip320.tla:39-42)
    _, rstates, *_ = s
    return (
        rstates[l][2] == l and rstates[f][2] == l and rstates[f][1] == rstates[l][1]
    )


def o_fenced_follower_fetch(cfg: Config):
    # Kip320.tla:49-56
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for f in range(cfg.n):
            for l in range(cfg.n):
                if not _o_following_epoch(s, l, f):
                    continue
                off = len(logs[f])
                if off >= cfg.l or off >= len(logs[l]):
                    continue
                new_logs = logs[:f] + (logs[f] + (logs[l][off],),) + logs[f + 1 :]
                hwf = min(rstates[l][0], off + 1)
                _, epf, ldrf, isrf = rstates[f]
                new_rs = rstates[:f] + ((hwf, epf, ldrf, isrf),) + rstates[f + 1 :]
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FencedFollowerFetch", successors)


def o_fenced_leader_inc_hw(cfg: Config):
    # Kip320.tla:63-70
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for l in range(cfg.n):
            hw, ep, ldr, isr = rstates[l]
            if hw >= len(logs[l]):
                continue
            if all(
                _o_following_epoch(s, l, f) and len(logs[f]) > hw for f in isr
            ):
                new_rs = rstates[:l] + ((hw + 1, ep, ldr, isr),) + rstates[l + 1 :]
                yield (logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FencedLeaderIncHighWatermark", successors)


def o_fenced_leader_shrink_isr(cfg: Config):
    # Kip320.tla:78-85
    def successors(s):
        logs, rstates, *_ = s
        for l in range(cfg.n):
            isr = rstates[l][3]
            for f in sorted(isr - {l}):
                if (not _o_following_epoch(s, l, f)) or len(logs[f]) < len(logs[l]):
                    t = kr._o_quorum_update(s, l, isr - {f})
                    if t is not None:
                        yield t

    return OracleAction("FencedLeaderShrinkIsr", successors)


def _o_hw_reached_epoch(s, l):
    # HasHighWatermarkReachedCurrentEpoch (Kip320.tla:87-92)
    logs, rstates, *_ = s
    hw = rstates[l][0]
    if hw == len(logs[l]):
        return True
    return hw < len(logs[l]) and logs[l][hw][1] == rstates[l][1]


def o_fenced_leader_expand_isr(cfg: Config):
    # Kip320.tla:110-117
    def successors(s):
        logs, rstates, *_ = s
        for l in range(cfg.n):
            hw, _, _, isr = rstates[l]
            for f in range(cfg.n):
                if f in isr:
                    continue
                if not _o_following_epoch(s, l, f):
                    continue
                if not (hw == 0 or len(logs[f]) >= hw):  # :94-98
                    continue
                if not _o_hw_reached_epoch(s, l):  # :87-92
                    continue
                t = kr._o_quorum_update(s, l, isr | {f})
                if t is not None:
                    yield t

    return OracleAction("FencedLeaderExpandIsr", successors)


def o_fenced_become_follower_and_truncate(cfg: Config):
    # Kip320.tla:134-148
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for (e, l, risr) in reqs:
            if l == NONE:
                continue
            for r in range(cfg.n):
                if r == l or e <= rstates[r][1]:
                    continue
                if rstates[l][2] != l or rstates[l][1] != e:  # :142-143
                    continue
                toff = kr.o_kip279_offset(cfg, s, l, r)
                if toff > len(logs[r]):
                    continue
                new_hw = min(toff, rstates[r][0])
                new_logs = logs[:r] + (logs[r][:toff],) + logs[r + 1 :]
                new_rs = rstates[:r] + ((new_hw, e, l, risr),) + rstates[r + 1 :]
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FencedBecomeFollowerAndTruncate", successors)


def _o_caught_up_to_epoch(cfg, s, l, f, end_offset):
    # Kip320FirstTry.tla:49-57
    logs, rstates, *_ = s
    if rstates[l][2] != l or rstates[f][2] != l:
        return False
    if end_offset == 0:
        return True
    off = end_offset - 1
    return (
        end_offset <= len(logs[l])
        and end_offset <= len(logs[f])
        and logs[f][off][1] == logs[l][off][1]
    )


def o_ft_follower_truncate(cfg: Config):
    # Kip320FirstTry.tla:64-82
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for l in range(cfg.n):
            for f in range(cfg.n):
                if rstates[l][2] != l or rstates[f][2] != l:
                    continue
                f_end = len(logs[f])
                mismatch = (
                    f_end > 0
                    and f_end <= len(logs[l])
                    and logs[l][f_end - 1][1] != logs[f][f_end - 1][1]
                )
                if not (f_end > len(logs[l]) or mismatch):
                    continue
                toff = kr.o_kip279_offset(cfg, s, l, f)
                if toff > f_end:
                    continue
                new_logs = logs[:f] + (logs[f][:toff],) + logs[f + 1 :]
                hwf, epf, ldrf, isrf = rstates[f]
                new_rs = (
                    rstates[:f] + ((min(toff, hwf), epf, ldrf, isrf),) + rstates[f + 1 :]
                )
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FollowerTruncate", successors)


def o_ft_improved_inc_hw(cfg: Config):
    # Kip320FirstTry.tla:90-97
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for l in range(cfg.n):
            hw, ep, ldr, isr = rstates[l]
            if ldr != l or hw >= len(logs[l]):
                continue
            if all(_o_caught_up_to_epoch(cfg, s, l, f, hw + 1) for f in isr):
                new_rs = rstates[:l] + ((hw + 1, ep, ldr, isr),) + rstates[l + 1 :]
                yield (logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("ImprovedLeaderIncHighWatermark", successors)


def o_ft_follower_fetch(cfg: Config):
    # Kip320FirstTry.tla:103-111
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for f in range(cfg.n):
            for l in range(cfg.n):
                off = len(logs[f])
                if not _o_caught_up_to_epoch(cfg, s, l, f, off):
                    continue
                if off >= cfg.l or off >= len(logs[l]):
                    continue
                new_logs = logs[:f] + (logs[f] + (logs[l][off],),) + logs[f + 1 :]
                hwf = min(rstates[l][0], off + 1)
                _, epf, ldrf, isrf = rstates[f]
                new_rs = rstates[:f] + ((hwf, epf, ldrf, isrf),) + rstates[f + 1 :]
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FollowerFetch", successors)


def o_ft_leader_shrink(cfg: Config):
    # Kip320FirstTry.tla:114-120
    def successors(s):
        logs, rstates, *_ = s
        for l in range(cfg.n):
            isr = rstates[l][3]
            for f in sorted(isr - {l}):
                if not _o_caught_up_to_epoch(cfg, s, l, f, len(logs[l])):
                    t = kr._o_quorum_update(s, l, isr - {f})
                    if t is not None:
                        yield t

    return OracleAction("LeaderShrinkIsrBetterFencing", successors)


def o_ft_leader_expand(cfg: Config):
    # Kip320FirstTry.tla:122-141
    def successors(s):
        logs, rstates, *_ = s
        for l in range(cfg.n):
            hw, _, _, isr = rstates[l]
            for f in range(cfg.n):
                if f in isr:
                    continue
                if not _o_caught_up_to_epoch(cfg, s, l, f, hw):
                    continue
                if not _o_hw_reached_epoch(s, l):
                    continue
                t = kr._o_quorum_update(s, l, isr | {f})
                if t is not None:
                    yield t

    return OracleAction("LeaderExpandIsrBetterFencing", successors)


def o_ft_become_follower(cfg: Config):
    # Kip320FirstTry.tla:148-157
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for (e, l, risr) in reqs:
            if l == NONE:
                continue
            for r in range(cfg.n):
                if r == l or e <= rstates[r][1]:
                    continue
                hwf = rstates[r][0]
                new_rs = rstates[:r] + ((hwf, e, l, risr),) + rstates[r + 1 :]
                yield (logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("BecomeFollower", successors)


def make_oracle(cfg: Config, invariants: Sequence[str] = DEFAULT_INVARIANTS) -> OracleModel:
    actions = [
        kr.o_controller_elect_leader(cfg),
        kr.o_controller_shrink_isr(cfg),
        kr.o_become_leader(cfg),
        o_fenced_leader_expand_isr(cfg),
        o_fenced_leader_shrink_isr(cfg),
        kr.o_leader_write(cfg),
        o_fenced_leader_inc_hw(cfg),
        o_fenced_become_follower_and_truncate(cfg),
        o_fenced_follower_fetch(cfg),
    ]
    return OracleModel(
        name="Kip320-oracle",
        init_states=lambda: [kr.o_init(cfg)],
        actions=actions,
        invariants=_invariant_oracles(cfg, invariants),
        meta={"variant": "Kip320", "cfg": cfg},
    )


def make_first_try_oracle(
    cfg: Config, invariants: Sequence[str] = DEFAULT_INVARIANTS
) -> OracleModel:
    actions = [
        kr.o_controller_elect_leader(cfg),
        kr.o_controller_shrink_isr(cfg),
        kr.o_become_leader(cfg),
        o_ft_leader_expand(cfg),
        o_ft_leader_shrink(cfg),
        kr.o_leader_write(cfg),
        o_ft_improved_inc_hw(cfg),
        o_ft_become_follower(cfg),
        o_ft_follower_fetch(cfg),
        o_ft_follower_truncate(cfg),
    ]
    return OracleModel(
        name="Kip320FirstTry-oracle",
        init_states=lambda: [kr.o_init(cfg)],
        actions=actions,
        invariants=_invariant_oracles(cfg, invariants),
        meta={"variant": "Kip320FirstTry", "cfg": cfg},
    )
