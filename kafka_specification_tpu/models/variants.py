"""L4 spec variants over the KafkaReplication core.

Each variant is a `Next` composition: the 9 disjuncts listed in its reference
module, differing only in the become-follower truncation logic
(KafkaReplication.tla:274-277):

- KafkaTruncateToHighWatermark (KafkaTruncateToHighWatermark.tla:33-42):
  truncate to own HW — known-unsafe pre-KIP-101 behavior (:23-27); expected
  to violate WeakIsr/StrongIsr.
- Kip101 (Kip101.tla:49-58): epoch-based truncation via the
  OffsetsForLeaderEpoch lookup (:27-39); still violates StrongIsr under
  consecutive fast leader changes (Kip279.tla:21-23).
- Kip279 (Kip279.tla:53-62): tail-matching truncation (:27-45); truncation is
  correct but fetch is unfenced, so StrongIsr still fails (Kip320.tla:21-35).

Fairness conjuncts in each Spec (SF/WF) concern liveness only; no liveness
property is stated anywhere in the corpus, so a safety-only BFS checker
ignores them (SURVEY.md §2.4).

Invariant selection mirrors TLC's .cfg INVARIANT list: pass the names to
check (default: all four).
"""

from __future__ import annotations

from typing import Sequence

from ..oracle.interp import OracleModel
from .base import Model
from . import kafka_replication as kr

DEFAULT_INVARIANTS = ("TypeOk", "LeaderInIsr", "WeakIsr", "StrongIsr")


def _invariant_kernels(cfg, names):
    table = {
        "TypeOk": kr.type_ok,
        "LeaderInIsr": kr.leader_in_isr,
        "LeaderInIsrLiteral": kr.leader_in_isr_literal,
        "WeakIsr": kr.weak_isr,
        "StrongIsr": kr.strong_isr,
    }
    return [table[n](cfg) for n in names]


def _invariant_oracles(cfg, names):
    table = {
        "TypeOk": kr.o_type_ok,
        "LeaderInIsr": kr.o_leader_in_isr,
        "LeaderInIsrLiteral": kr.o_leader_in_isr_literal,
        "WeakIsr": kr.o_weak_isr,
        "StrongIsr": kr.o_strong_isr,
    }
    return [table[n](cfg) for n in names]


_VARIANTS = {
    # name -> (kernel truncation offset, oracle truncation offset, citation)
    "KafkaTruncateToHighWatermark": (
        kr.truncate_to_hw_offset,
        lambda cfg: kr.o_truncate_to_hw_offset,
        "BecomeFollowerTruncateToHighWatermark",
    ),
    "Kip101": (kr.kip101_offset, lambda cfg: kr.o_kip101_offset, "BecomeFollowerTruncateKip101"),
    "Kip279": (kr.kip279_offset, lambda cfg: kr.o_kip279_offset, "BecomeFollowerTruncateKip279"),
}


def make_model(
    variant: str, cfg: kr.Config, invariants: Sequence[str] = DEFAULT_INVARIANTS
) -> Model:
    trunc_fn, _, action_name = _VARIANTS[variant]
    spec = kr.make_spec(cfg)
    # Next (KafkaTruncateToHighWatermark.tla:33-42 / Kip101.tla:49-58 /
    # Kip279.tla:53-62): identical 9 disjuncts modulo the truncation action.
    actions = [
        kr.controller_elect_leader(cfg),
        kr.controller_shrink_isr(cfg),
        kr.become_leader(cfg),
        kr.leader_expand_isr(cfg),
        kr.leader_shrink_isr(cfg),
        kr.leader_write(cfg),
        kr.leader_inc_high_watermark(cfg),
        kr.become_follower_and_truncate_to(cfg, action_name, trunc_fn(cfg)),
        kr.follower_replicate(cfg),
    ]
    return Model(
        name=f"{variant}({cfg.n}r,L{cfg.l},R{cfg.r},E{cfg.e})",
        spec=spec,
        init_states=lambda: [kr.init_state(cfg)],
        actions=actions,
        invariants=_invariant_kernels(cfg, invariants),
        decode=kr.make_decode(cfg),
        meta={"variant": variant, "cfg": cfg},
    )


def make_oracle(
    variant: str, cfg: kr.Config, invariants: Sequence[str] = DEFAULT_INVARIANTS
) -> OracleModel:
    _, o_trunc_fn, action_name = _VARIANTS[variant]
    actions = [
        kr.o_controller_elect_leader(cfg),
        kr.o_controller_shrink_isr(cfg),
        kr.o_become_leader(cfg),
        kr.o_leader_expand_isr(cfg),
        kr.o_leader_shrink_isr(cfg),
        kr.o_leader_write(cfg),
        kr.o_leader_inc_high_watermark(cfg),
        kr.o_become_follower_and_truncate_to(cfg, action_name, o_trunc_fn(cfg)),
        kr.o_follower_replicate(cfg),
    ]
    return OracleModel(
        name=f"{variant}-oracle",
        init_states=lambda: [kr.o_init(cfg)],
        actions=actions,
        invariants=_invariant_oracles(cfg, invariants),
        meta={"variant": variant, "cfg": cfg},
    )
