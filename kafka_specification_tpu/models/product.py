"""Product-space combinator: K independent partitions of a base model.

BASELINE.json's stretch workload is "Kip320 at 5 brokers / 3 partitions"; the
reference models a single partition (KafkaReplication.tla:22), so the
framework defines the multi-partition reading explicitly (BASELINE.md note):
the K-partition model is the product state machine of K independent
instances — `Next` is the disjoint union of per-partition actions (one
partition steps at a time, matching how independent single-partition state
machines interleave), invariants are the conjunction over partitions.

The product's reachable space is NOT the K-th power of the base space level
by level (interleaving matters for BFS levels), but its reachable-set size
is |base|^K, which is how the stretch crosses 10^9 states: 737,794^3 at the
bench constants.  Encoding: base fields are replicated with a partition
prefix; kernels are lifted by slicing the partition's sub-state in and out.
"""

from __future__ import annotations

from ..oracle.interp import OracleAction, OracleModel
from ..ops.packing import Field, StateSpec
from .base import Action, Invariant, Model


def product_model(base: Model, k: int, name: str | None = None) -> Model:
    """K independent copies of `base` interleaved as one model."""
    assert k >= 1
    return product_models(
        [base] * k,
        name=name or f"{base.name} x{k}partitions",
        meta={**base.meta, "partitions": k, "base": base.name},
    )


def product_models(bases, name: str | None = None, meta: dict | None = None) -> Model:
    """Product of HETEROGENEOUS independent partitions (round-5 verdict
    item 5: mixed-base exact products like 277^2 x 5,973 need partitions
    with different constants, hence different specs and fanouts).

    Per-partition sub-specs may differ; invariant NAMES must agree across
    bases (the product invariant is the conjunction of each partition's
    same-named predicate over its own sub-state)."""
    assert bases
    specs = [b.spec for b in bases]
    k = len(bases)

    fields = []
    for p, bspec in enumerate(specs):
        for f in bspec.fields:
            fields.append(Field(f"p{p}.{f.name}", f.shape, f.lo, f.hi))
    spec = StateSpec(fields)

    def split(state, p):
        return {f.name: state[f"p{p}.{f.name}"] for f in specs[p].fields}

    def embed(state, p, sub):
        out = dict(state)
        for f in specs[p].fields:
            out[f"p{p}.{f.name}"] = sub[f.name]
        return out

    def init_states():
        # independent instances: the init set is the cross product of the
        # per-partition init sets (every corpus model has one
        # deterministic init, but the combinator must not silently drop
        # mixed-init tuples for bases that don't)
        import itertools

        outs = []
        for combo in itertools.product(*[b.init_states() for b in bases]):
            s = {}
            for p, binit in enumerate(combo):
                for key, v in binit.items():
                    s[f"p{p}.{key}"] = v
            outs.append(s)
        return outs

    actions = []
    for p, b in enumerate(bases):
        for a in b.actions:
            def kernel(state, choice, p=p, a=a):
                ok, nxt = a.kernel(split(state, p), choice)
                return ok, embed(state, p, nxt)

            writes = (
                frozenset(f"p{p}.{w}" for w in a.writes)
                if a.writes is not None else None
            )
            actions.append(
                Action(f"p{p}.{a.name}", a.n_choices, kernel,
                       writes=writes)
            )

    inv_names = [i.name for i in bases[0].invariants]
    for b in bases[1:]:
        assert [i.name for i in b.invariants] == inv_names, (
            "product bases must agree on invariant selection: "
            f"{inv_names} vs {[i.name for i in b.invariants]}"
        )
    invariants = []
    for i_idx, inv_name in enumerate(inv_names):
        def pred(state, i_idx=i_idx):
            ok = None
            for p, b in enumerate(bases):
                r = b.invariants[i_idx].pred(split(state, p))
                ok = r if ok is None else (ok & r)
            return ok

        invariants.append(Invariant(inv_name, pred))

    constraint = None
    if any(b.constraint is not None for b in bases):
        def constraint(state):
            ok = None
            for p, b in enumerate(bases):
                if b.constraint is None:
                    continue
                r = b.constraint(split(state, p))
                ok = r if ok is None else (ok & r)
            return ok

    decode = None
    if all(b.decode is not None for b in bases):
        def decode(s):
            return tuple(
                bases[p].decode(
                    {f.name: s[f"p{p}.{f.name}"] for f in specs[p].fields}
                )
                for p in range(k)
            )

    return Model(
        name=name or " x ".join(b.name for b in bases),
        spec=spec,
        init_states=init_states,
        actions=actions,
        invariants=invariants,
        constraint=constraint,
        decode=decode,
        meta=meta
        or {
            **bases[0].meta,
            "partitions": k,
            "base": [b.name for b in bases],
        },
    )


def product_oracle(base: OracleModel, k: int) -> OracleModel:
    """Oracle twin of product_model: state = k-tuple of base states; each
    action steps one partition.  Canonical form matches product_model's
    decode (a tuple of per-partition decodes)."""
    assert k >= 1

    def init():
        import itertools

        return [tuple(c) for c in itertools.product(base.init_states(), repeat=k)]

    actions = []
    for p in range(k):
        for a in base.actions:
            def succ(s, p=p, a=a):
                for t in a.successors(s[p]):
                    yield s[:p] + (t,) + s[p + 1 :]

            actions.append(OracleAction(f"p{p}.{a.name}", succ))

    invariants = [
        (name, lambda s, pred=pred: all(pred(x) for x in s))
        for name, pred in base.invariants
    ]
    constraint = None
    if base.constraint is not None:
        def constraint(s):
            return all(base.constraint(x) for x in s)

    return OracleModel(
        name=f"{base.name} x{k}partitions",
        init_states=init,
        actions=actions,
        invariants=invariants,
        constraint=constraint,
        meta={**base.meta, "partitions": k, "base": base.name},
    )
