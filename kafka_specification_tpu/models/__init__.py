from .base import Action, Invariant, Model

__all__ = ["Action", "Invariant", "Model"]
