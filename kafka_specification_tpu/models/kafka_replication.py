"""KafkaReplication — the shared protocol core (L3 of SURVEY.md §1).

Reference: /root/reference/KafkaReplication.tla. This module provides, for a
given constant valuation (Replicas=N, LogSize=L, MaxRecords=R,
MaxLeaderEpoch=E):

- the canonical tensor encoding of the 6 state variables (:45-75), per
  SURVEY.md §2.2. The grow-only `leaderAndIsrRequests` message set is encoded
  as an epoch-indexed array: every request is created by ControllerUpdateIsr,
  which consumes a fresh leader epoch (:138-145), so requests are uniquely
  keyed by epoch — append-only and canonical, no set machinery needed.
- vmappable successor kernels for the shared actions (:138-310),
- predicate kernels for TypeOk/WeakIsr/StrongIsr/LeaderInIsr (:101,320,334,345),
- a 1:1 set-semantics oracle transcription of the same definitions, used as
  the golden cross-check (stock TLC is unavailable in this environment),
- `decode` from tensor state to the oracle's canonical Python state, so
  engine and oracle runs can be compared as state *sets*.

Value conventions (shared by tensors and oracle): replicas are 0..N-1,
`None == "NONE"` is -1 (:38), `Nil` is -1 (:39), ISRs are bitmasks in tensor
form and frozensets in oracle form.

Note on LeaderInIsr (:345): taken literally, `quorumState.leader \\in
quorumState.isr` is False whenever leader = None — including the initial
state (:117-119), so the literal invariant is violated at depth 0 despite the
THEOREM at Kip320.tla:169. We expose both the literal predicate
(`LeaderInIsrLiteral`) and the evident intent (`LeaderInIsr`: leader # None
=> leader in ISR), and the known-answer tests pin down both behaviors.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..ops.packing import Field, StateSpec
from ..oracle.interp import OracleAction
from .base import Action, Invariant

NONE = -1  # KafkaReplication.tla:38
NIL = -1  # KafkaReplication.tla:39
ABSENT = -2  # epoch slot with no LeaderAndIsr request yet


@dataclass(frozen=True)
class Config:
    """Constant valuation: Replicas/LogSize/MaxRecords/MaxLeaderEpoch
    (KafkaReplication.tla:32-36)."""

    n_replicas: int
    log_size: int
    max_records: int
    max_leader_epoch: int

    @property
    def n(self):
        return self.n_replicas

    @property
    def l(self):
        return self.log_size

    @property
    def r(self):
        return self.max_records

    @property
    def e(self):
        return self.max_leader_epoch

    @property
    def full_isr(self):
        return (1 << self.n_replicas) - 1


def make_spec(cfg: Config) -> StateSpec:
    """Tensor encoding of the 6 state variables (SURVEY.md §2.2)."""
    N, L, R, E = cfg.n, cfg.l, cfg.r, cfg.e
    return StateSpec(
        [
            # replicaLog (:47; FiniteReplicatedLog.tla:41-44)
            Field("end", (N,), 0, L),
            Field("rid", (N, L), NIL, R - 1),
            Field("repoch", (N, L), NIL, E),
            # replicaState (:49-51, :96-99)
            Field("hw", (N,), 0, L),
            Field("ep", (N,), NIL, E),
            Field("ldr", (N,), NONE, N - 1),
            Field("isr", (N,), 0, cfg.full_isr),
            # id sequences (:55,:59; IdSequence.tla:43)
            Field("nrid", (), 0, R),
            Field("nep", (), 0, E + 1),
            # quorumState (:73, :87-89)
            Field("qep", (), NIL, E),
            Field("qldr", (), NONE, N - 1),
            Field("qisr", (), 0, cfg.full_isr),
            # leaderAndIsrRequests, epoch-indexed (:66, :107; see module doc)
            Field("req_ldr", (E + 1,), ABSENT, N - 1),
            Field("req_isr", (E + 1,), 0, cfg.full_isr),
        ]
    )


def init_state(cfg: Config) -> dict:
    """Init (KafkaReplication.tla:109-120)."""
    N, L, E = cfg.n, cfg.l, cfg.e
    return {
        "end": [0] * N,
        "rid": [[NIL] * L for _ in range(N)],
        "repoch": [[NIL] * L for _ in range(N)],
        "hw": [0] * N,  # ReplicaLog!StartOffset (:113)
        "ep": [NIL] * N,
        "ldr": [NONE] * N,
        "isr": [0] * N,  # local ISR starts empty (:116)
        "nrid": 0,
        "nep": 0,
        "qep": NIL,
        "qldr": NONE,
        "qisr": cfg.full_isr,  # quorum ISR starts as all replicas (:119)
        "req_ldr": [ABSENT] * (E + 1),
        "req_isr": [0] * (E + 1),
    }


# --------------------------------------------------------------------------
# kernel helpers
# --------------------------------------------------------------------------


# declared write sets (frame conditions) for the analyzer's
# frame-condition pass (analysis/encoding.py): the variables each
# action's TLA+ disjunct primes, in tensor-lane terms
_CTRL_WRITES = frozenset({"nep", "qep", "qldr", "qisr", "req_ldr", "req_isr"})
_QUORUM_WRITES = frozenset({"qisr", "isr"})
_BECOME_FOLLOWER_WRITES = frozenset(
    {"rid", "repoch", "end", "ep", "ldr", "isr", "hw"}
)
_REPLICATE_WRITES = frozenset({"rid", "repoch", "end", "hw"})


def _bit(r):
    return jnp.int32(1) << r


def _member(mask, r):
    return ((mask >> r) & 1) == 1


def _is_true_leader(s, l):
    # IsTrueLeader (:128-131)
    return (s["qldr"] == l) & (s["ldr"][l] == l) & (s["ep"][l] == s["qep"])


def _caught_up(s, l, f, end_offset):
    # IsFollowerCaughtUp(leader, follower, endOffset) (:219-225):
    # following /\ (endOffset = 0 \/ (leader has a record at endOffset-1
    # /\ follower HasOffset(endOffset-1)))
    following = s["ldr"][f] == l
    nonzero = (end_offset > 0) & (end_offset <= s["end"][l]) & (s["end"][f] >= end_offset)
    return following & ((end_offset == 0) | nonzero)


def _forall_isr(cfg, isr_mask, cond_vec):
    """\\A follower \\in isr : cond[follower] — masked reduction over N."""
    members = ((isr_mask >> jnp.arange(cfg.n)) & 1) == 1
    return jnp.all(jnp.where(members, cond_vec, True))


def _truncate_log(s, r, new_end):
    """ReplicaLog!TruncateTo Nil-fill (FiniteReplicatedLog.tla:105-109);
    caller must guard new_end <= end[r]."""
    offs = jnp.arange(s["rid"].shape[1])
    keep = offs < new_end
    rid = s["rid"].at[r].set(jnp.where(keep, s["rid"][r], NIL))
    repoch = s["repoch"].at[r].set(jnp.where(keep, s["repoch"][r], NIL))
    end = s["end"].at[r].set(new_end)
    return rid, repoch, end


def _ctrl_update_isr(cfg, s, new_leader, new_isr):
    """ControllerUpdateIsr(newLeader, newIsr) (:138-145): consume a fresh
    epoch via LeaderEpochSeq!NextId (forced existential), write quorumState,
    append the LeaderAndIsr request. Returns (enabled, next_state)."""
    e = s["nep"]
    ok = e <= cfg.e  # IdSequence.tla:31 — disabled once epochs exhausted
    ec = jnp.minimum(e, cfg.e)
    return ok, {
        **s,
        "nep": jnp.minimum(e + 1, cfg.e + 1),
        "qep": ec,
        "qldr": new_leader,
        "qisr": new_isr,
        "req_ldr": s["req_ldr"].at[ec].set(new_leader),
        "req_isr": s["req_isr"].at[ec].set(new_isr),
    }


# --------------------------------------------------------------------------
# shared action kernels (KafkaReplication.tla:138-310)
# --------------------------------------------------------------------------


def controller_shrink_isr(cfg: Config):
    # ControllerShrinkIsr (:158-168), choice = replica
    def kernel(s, r):
        is_ldr = s["qldr"] == r
        sole = s["qisr"] == _bit(r)
        case1 = is_ldr & sole  # leader is the sole ISR member: keep ISR (:159-161)
        case2 = is_ldr & ~sole  # leader leaves: None, ISR - {r} (:162-164)
        case3 = (~is_ldr) & _member(s["qisr"], r)  # follower leaves (:165-167)
        enabled = case1 | case2 | case3
        new_leader = jnp.where(case3, s["qldr"], NONE)
        new_isr = jnp.where(case1, s["qisr"], s["qisr"] & ~_bit(r))
        ok, nxt = _ctrl_update_isr(cfg, s, new_leader, new_isr)
        return enabled & ok, nxt

    return Action("ControllerShrinkIsr", cfg.n, kernel,
                  writes=_CTRL_WRITES)


def controller_elect_leader(cfg: Config):
    # ControllerElectLeader (:176-179), choice = newLeader \in quorum ISR
    def kernel(s, r):
        enabled = _member(s["qisr"], r) & (s["qldr"] != r)
        ok, nxt = _ctrl_update_isr(cfg, s, r, s["qisr"])
        return enabled & ok, nxt

    return Action("ControllerElectLeader", cfg.n, kernel,
                  writes=_CTRL_WRITES)


def become_leader(cfg: Config):
    # BecomeLeader (:186-195), choice = request (keyed by its unique epoch)
    def kernel(s, e):
        l = s["req_ldr"][e]
        lc = jnp.clip(l, 0, cfg.n - 1)
        enabled = (l >= 0) & (e > s["ep"][lc])  # leader # None /\ epoch newer
        return enabled, {
            **s,
            "ep": s["ep"].at[lc].set(e),
            "ldr": s["ldr"].at[lc].set(lc),
            "isr": s["isr"].at[lc].set(s["req_isr"][e]),
            # hw unchanged — the stale-HW subtlety (:183-185, :191)
        }

    return Action("BecomeLeader", cfg.e + 1, kernel,
                  writes=frozenset({"ep", "ldr", "isr"}))


def leader_write(cfg: Config):
    # LeaderWrite (:202-207), choice = replica; id/offset are forced
    def kernel(s, r):
        end = s["end"][r]
        enabled = (s["ldr"][r] == r) & (s["nrid"] < cfg.r) & (end < cfg.l)
        off = jnp.minimum(end, cfg.l - 1)
        return enabled, {
            **s,
            "rid": s["rid"].at[r, off].set(jnp.where(enabled, s["nrid"], s["rid"][r, off])),
            "repoch": s["repoch"].at[r, off].set(
                jnp.where(enabled, s["ep"][r], s["repoch"][r, off])
            ),
            "end": s["end"].at[r].set(jnp.where(enabled, end + 1, end)),
            "nrid": jnp.minimum(s["nrid"] + 1, cfg.r),
        }

    return Action("LeaderWrite", cfg.n, kernel,
                  writes=frozenset({"rid", "repoch", "end", "nrid"}))


def _quorum_update(s, l, new_isr):
    """QuorumUpdateLeaderAndIsr (:213-217): quorum-fenced ISR write; sets the
    quorum ISR and the leader's cached ISR. Returns (enabled, next)."""
    enabled = _is_true_leader(s, l)
    return enabled, {
        **s,
        "qisr": new_isr,
        "isr": s["isr"].at[l].set(new_isr),
    }


def leader_shrink_isr(cfg: Config):
    # LeaderShrinkIsr (:233-239), choice = (leader, replica in isr \ {leader})
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        in_isr = (f != l) & _member(s["isr"][l], f)
        lagging = ~_caught_up(s, l, f, s["end"][l])
        ok, nxt = _quorum_update(s, l, s["isr"][l] & ~_bit(f))
        return in_isr & lagging & ok, nxt

    return Action("LeaderShrinkIsr", cfg.n * cfg.n, kernel,
                  writes=_QUORUM_WRITES)


def leader_expand_isr(cfg: Config):
    # LeaderExpandIsr (:248-254), choice = (leader, replica not in isr)
    def kernel(s, c):
        l, f = c // cfg.n, c % cfg.n
        outside = ~_member(s["isr"][l], f)
        caught = _caught_up(s, l, f, s["hw"][l])
        ok, nxt = _quorum_update(s, l, s["isr"][l] | _bit(f))
        return outside & caught & ok, nxt

    return Action("LeaderExpandIsr", cfg.n * cfg.n, kernel,
                  writes=_QUORUM_WRITES)


def leader_inc_high_watermark(cfg: Config):
    # LeaderIncHighWatermark (:264-271), choice = leader; offset forced = hw.
    # No epoch verification — the pre-KIP-320 hole (:256-263).
    def kernel(s, l):
        hw = s["hw"][l]
        presumes = s["ldr"][l] == l
        in_offsets = hw < cfg.l  # \E offset \in Offsets (:264)
        follows = (s["ldr"] == l) & (s["end"] > hw)  # HasOffset(f, hw) (:267-269)
        all_isr = _forall_isr(cfg, s["isr"][l], follows)
        enabled = presumes & in_offsets & all_isr
        return enabled, {**s, "hw": s["hw"].at[l].set(jnp.minimum(hw + 1, cfg.l))}

    return Action("LeaderIncHighWatermark", cfg.n, kernel,
                  writes=frozenset({"hw"}))


def become_follower_and_truncate_to(cfg: Config, name: str, trunc_offset_fn):
    """BecomeFollowerAndTruncateTo(leader, replica, truncationOffset)
    (:281-294), choice = (replica, request-epoch); leader = request.leader.

    trunc_offset_fn(s, l, r) -> truncation offset computed on the *old* state;
    this is the only thing the historical variants change (:274-277).
    The `leader = None` branch (:285-286) is unreachable from every variant's
    Next (each quantifies leader over Replicas), so leaders here are real
    replicas; requests with leader = None are never consumed.
    """

    def kernel(s, c):
        r, e = c // (cfg.e + 1), c % (cfg.e + 1)
        l = s["req_ldr"][e]
        lc = jnp.clip(l, 0, cfg.n - 1)
        enabled = (l >= 0) & (lc != r) & (e > s["ep"][r])
        toff = trunc_offset_fn(s, lc, r)
        enabled = enabled & (toff <= s["end"][r])  # TruncateTo guard (FRL:106)
        toff = jnp.clip(toff, 0, cfg.l)
        rid, repoch, end = _truncate_log(s, r, toff)
        return enabled, {
            **s,
            "rid": rid,
            "repoch": repoch,
            "end": end,
            "ep": s["ep"].at[r].set(e),
            "ldr": s["ldr"].at[r].set(lc),
            "isr": s["isr"].at[r].set(s["req_isr"][e]),
            "hw": s["hw"].at[r].set(jnp.minimum(toff, s["hw"][r])),  # (:293)
        }

    return Action(name, cfg.n * (cfg.e + 1), kernel,
                  writes=_BECOME_FOLLOWER_WRITES)


def follower_replicate(cfg: Config):
    # FollowerReplicate (:302-310), choice = (follower, leader); the fetched
    # record/offset are forced (ReplicateTo copies the follower's next slot).
    # Unfenced: no epoch check (:297-301).
    def kernel(s, c):
        f, l = c // cfg.n, c % cfg.n
        off = s["end"][f]
        enabled = (
            (s["ldr"][l] == l)
            & (s["ldr"][f] == l)
            & (off < cfg.l)
            & (off < s["end"][l])
        )
        offc = jnp.minimum(off, cfg.l - 1)
        new_hw = jnp.minimum(s["hw"][l], off + 1)  # (:306-309)
        return enabled, {
            **s,
            "rid": s["rid"].at[f, offc].set(
                jnp.where(enabled, s["rid"][l, offc], s["rid"][f, offc])
            ),
            "repoch": s["repoch"].at[f, offc].set(
                jnp.where(enabled, s["repoch"][l, offc], s["repoch"][f, offc])
            ),
            "end": s["end"].at[f].set(jnp.where(enabled, off + 1, off)),
            "hw": s["hw"].at[f].set(jnp.where(enabled, new_hw, s["hw"][f])),
        }

    return Action("FollowerReplicate", cfg.n * cfg.n, kernel,
                  writes=_REPLICATE_WRITES)


# --------------------------------------------------------------------------
# variant truncation offsets (Kip101.tla / Kip279.tla)
# --------------------------------------------------------------------------


def truncate_to_hw_offset(cfg: Config):
    # BecomeFollowerTruncateToHighWatermark: truncate to own HW
    # (KafkaTruncateToHighWatermark.tla:29-31)
    def fn(s, l, r):
        return s["hw"][r]

    return fn


def kip101_offset(cfg: Config):
    """LookupOffsetForEpoch (Kip101.tla:31-39) applied per
    BecomeFollowerTruncateKip101 (Kip101.tla:41-47): empty follower log
    truncates to 0 (disjunct 1); otherwise look up by the epoch of the
    follower's latest record (disjunct 2 — the record is forced)."""

    def fn(s, l, r):
        offs = jnp.arange(cfg.l)
        r_end = s["end"][r]
        epoch = s["repoch"][r, jnp.clip(r_end - 1, 0, cfg.l - 1)]  # latest record's epoch
        l_end = s["end"][l]
        # OffsetsWithLargerEpochs(leader, epoch) (Kip101.tla:27-29)
        larger = (offs < l_end) & (s["repoch"][l] > epoch)
        any_larger = jnp.any(larger)
        min_larger = jnp.min(jnp.where(larger, offs, cfg.l))
        latest_match = s["repoch"][l, jnp.clip(l_end - 1, 0, cfg.l - 1)] == epoch
        lookup = jnp.where(
            l_end == 0,
            s["hw"][r],  # leader empty -> follower hw (Kip101.tla:32-33)
            jnp.where(
                latest_match,
                l_end,  # latest epoch match -> leader end offset (:34-35)
                jnp.where(any_larger, min_larger, s["hw"][r]),  # (:36-39)
            ),
        )
        return jnp.where(r_end == 0, 0, lookup)  # Kip101.tla:42-43

    return fn


def kip279_offset(cfg: Config):
    """FirstNonMatchingOffsetFromTail (Kip279.tla:39-45):
    Max(MatchingOffsets(follower, leader)) + 1, else 0.  MatchingOffsets
    (Kip279.tla:27-30) = offsets whose (id, epoch) entry in the follower's
    log exists identically in the leader's.  The empty-follower disjunct of
    BecomeFollowerTruncateKip279 (Kip279.tla:48-49) yields offset 0, which
    this formula already produces (no matching offsets)."""

    def fn(s, l, r):
        offs = jnp.arange(cfg.l)
        match = (
            (offs < s["end"][r])
            & (offs < s["end"][l])
            & (s["rid"][r] == s["rid"][l])
            & (s["repoch"][r] == s["repoch"][l])
        )
        any_match = jnp.any(match)
        max_match = jnp.max(jnp.where(match, offs, -1))
        return jnp.where(
            (s["end"][l] == 0) | ~any_match, 0, max_match + 1
        )

    return fn


# --------------------------------------------------------------------------
# invariants (KafkaReplication.tla:101-107, 320-345)
# --------------------------------------------------------------------------


def _isr_property(cfg: Config, s, isr_of_r1):
    """Common body of WeakIsr/StrongIsr (:320-340): for every presumed leader
    r1, every member r2 of `isr_of_r1(r1)` has an identical log below r1's hw."""
    N, L = cfg.n, cfg.l
    offs = jnp.arange(L)
    # pair_ok[r1, r2, off]: both logs hold the same record at off
    has1 = offs[None, None, :] < s["end"][:, None, None]  # r1 axis
    has2 = offs[None, None, :] < s["end"][None, :, None]  # r2 axis
    same = (s["rid"][:, None, :] == s["rid"][None, :, :]) & (
        s["repoch"][:, None, :] == s["repoch"][None, :, :]
    )
    pair_ok = has1 & has2 & same
    below_hw = offs[None, None, :] < s["hw"][:, None, None]
    r2_in = ((isr_of_r1 >> jnp.arange(N)[None, :]) & 1) == 1  # [r1, r2]
    relevant = below_hw & r2_in[:, :, None]
    ok_r1 = jnp.all(jnp.where(relevant, pair_ok, True), axis=(1, 2))
    presumes = s["ldr"] == jnp.arange(N)
    return jnp.all(jnp.where(presumes, ok_r1, True))


def weak_isr(cfg: Config):
    # WeakIsr (:320-326): r2 ranges over the presumed leader's *local* ISR
    def pred(s):
        return _isr_property(cfg, s, s["isr"][:, None])

    return Invariant("WeakIsr", pred)


def strong_isr(cfg: Config):
    # StrongIsr (:334-340): r2 ranges over the *quorum* ISR
    def pred(s):
        qisr = jnp.broadcast_to(s["qisr"], (cfg.n,))[:, None]
        return _isr_property(cfg, s, qisr)

    return Invariant("StrongIsr", pred)


def leader_in_isr_literal(cfg: Config):
    # LeaderInIsr (:345) taken literally: False whenever leader = None,
    # including Init (see module docstring).
    def pred(s):
        lc = jnp.clip(s["qldr"], 0, cfg.n - 1)
        return (s["qldr"] >= 0) & _member(s["qisr"], lc)

    return Invariant("LeaderInIsrLiteral", pred)


def leader_in_isr(cfg: Config):
    # Evident intent of (:345): a real leader is always in the quorum ISR.
    def pred(s):
        lc = jnp.clip(s["qldr"], 0, cfg.n - 1)
        return (s["qldr"] < 0) | _member(s["qisr"], lc)

    return Invariant("LeaderInIsr", pred)


def type_ok(cfg: Config):
    """TypeOk (:101-107): sequence bounds, record well-formedness, canonical
    Nil padding (FiniteReplicatedLog.tla:90-95), state ranges."""

    def pred(s):
        offs = jnp.arange(cfg.l)[None, :]
        written = offs < s["end"][:, None]
        recs_ok = jnp.all(
            jnp.where(
                written,
                (s["rid"] >= 0) & (s["rid"] < cfg.r) & (s["repoch"] >= 0) & (s["repoch"] <= cfg.e),
                (s["rid"] == NIL) & (s["repoch"] == NIL),
            )
        )
        seq_ok = (s["nrid"] >= 0) & (s["nrid"] <= cfg.r) & (s["nep"] >= 0) & (s["nep"] <= cfg.e + 1)
        rs_ok = (
            jnp.all((s["hw"] >= 0) & (s["hw"] <= cfg.l))
            & jnp.all((s["ep"] >= NIL) & (s["ep"] <= cfg.e))
            & jnp.all((s["ldr"] >= NONE) & (s["ldr"] < cfg.n))
            & jnp.all((s["isr"] >= 0) & (s["isr"] <= cfg.full_isr))
        )
        q_ok = (
            (s["qep"] >= NIL)
            & (s["qep"] <= cfg.e)
            & (s["qldr"] >= NONE)
            & (s["qldr"] < cfg.n)
            & (s["qisr"] >= 0)
            & (s["qisr"] <= cfg.full_isr)
        )
        return recs_ok & seq_ok & rs_ok & q_ok

    return Invariant("TypeOk", pred)


# --------------------------------------------------------------------------
# decode: tensor state -> canonical oracle state
# --------------------------------------------------------------------------


def make_decode(cfg: Config):
    """Canonical Python state:
    (logs, rstates, nrid, nep, reqs, quorum) with
      logs    = tuple_N of tuple of (id, epoch)
      rstates = tuple_N of (hw, epoch, leader, isr_frozenset)
      reqs    = frozenset of (epoch, leader, isr_frozenset)
      quorum  = (epoch, leader, isr_frozenset)
    """

    def iset(mask):
        return frozenset(r for r in range(cfg.n) if (int(mask) >> r) & 1)

    def decode(s):
        logs = tuple(
            tuple(
                (int(s["rid"][r][o]), int(s["repoch"][r][o]))
                for o in range(int(s["end"][r]))
            )
            for r in range(cfg.n)
        )
        rstates = tuple(
            (int(s["hw"][r]), int(s["ep"][r]), int(s["ldr"][r]), iset(s["isr"][r]))
            for r in range(cfg.n)
        )
        reqs = frozenset(
            (e, int(s["req_ldr"][e]), iset(s["req_isr"][e]))
            for e in range(cfg.e + 1)
            if int(s["req_ldr"][e]) != ABSENT
        )
        quorum = (int(s["qep"]), int(s["qldr"]), iset(s["qisr"]))
        return (logs, rstates, int(s["nrid"]), int(s["nep"]), reqs, quorum)

    return decode


# ==========================================================================
# oracle transcription (independent set semantics; the golden source)
# ==========================================================================
#
# Oracle state mirrors make_decode's canonical form exactly.  Indices below
# cite /root/reference/KafkaReplication.tla.


def o_init(cfg: Config):
    # Init (:109-120)
    logs = tuple(() for _ in range(cfg.n))
    rstates = tuple((0, NIL, NONE, frozenset()) for _ in range(cfg.n))
    quorum = (NIL, NONE, frozenset(range(cfg.n)))
    return (logs, rstates, 0, 0, frozenset(), quorum)


def _o_ctrl_update(cfg, s, new_leader, new_isr):
    # ControllerUpdateIsr (:138-145); None if epochs exhausted
    logs, rstates, nrid, nep, reqs, quorum = s
    if nep > cfg.e:
        return None
    req = (nep, new_leader, frozenset(new_isr))
    return (logs, rstates, nrid, nep + 1, reqs | {req}, req)


def o_controller_shrink_isr(cfg: Config):
    # ControllerShrinkIsr (:158-168)
    def successors(s):
        _, _, _, _, _, (qep, qldr, qisr) = s
        for r in range(cfg.n):
            if qldr == r and qisr == {r}:
                t = _o_ctrl_update(cfg, s, NONE, qisr)
            elif qldr == r and qisr != {r}:
                t = _o_ctrl_update(cfg, s, NONE, qisr - {r})
            elif qldr != r and r in qisr:
                t = _o_ctrl_update(cfg, s, qldr, qisr - {r})
            else:
                continue
            if t is not None:
                yield t

    return OracleAction("ControllerShrinkIsr", successors)


def o_controller_elect_leader(cfg: Config):
    # ControllerElectLeader (:176-179)
    def successors(s):
        _, _, _, _, _, (qep, qldr, qisr) = s
        for n in sorted(qisr):
            if qldr != n:
                t = _o_ctrl_update(cfg, s, n, qisr)
                if t is not None:
                    yield t

    return OracleAction("ControllerElectLeader", successors)


def o_become_leader(cfg: Config):
    # BecomeLeader (:186-195)
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for (e, l, risr) in reqs:
            if l != NONE and e > rstates[l][1]:
                hw = rstates[l][0]
                new_rs = rstates[:l] + ((hw, e, l, risr),) + rstates[l + 1 :]
                yield (logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("BecomeLeader", successors)


def o_leader_write(cfg: Config):
    # LeaderWrite (:202-207): presumed leader appends [id |-> nextRecordId,
    # epoch |-> own epoch]; RecordSeq!NextId bumps the counter.
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        if nrid >= cfg.r:
            return
        for r in range(cfg.n):
            if rstates[r][2] == r and len(logs[r]) < cfg.l:
                rec = (nrid, rstates[r][1])
                new_logs = logs[:r] + (logs[r] + (rec,),) + logs[r + 1 :]
                yield (new_logs, rstates, nrid + 1, nep, reqs, quorum)

    return OracleAction("LeaderWrite", successors)


def _o_is_true_leader(s, l):
    # IsTrueLeader (:128-131)
    _, rstates, _, _, _, (qep, qldr, _) = s
    return qldr == l and rstates[l][2] == l and rstates[l][1] == qep


def _o_quorum_update(s, l, new_isr):
    # QuorumUpdateLeaderAndIsr (:213-217)
    if not _o_is_true_leader(s, l):
        return None
    logs, rstates, nrid, nep, reqs, (qep, qldr, qisr) = s
    fs = frozenset(new_isr)
    hw, ep, ldr, _ = rstates[l]
    new_rs = rstates[:l] + ((hw, ep, ldr, fs),) + rstates[l + 1 :]
    return (logs, new_rs, nrid, nep, reqs, (qep, qldr, fs))


def _o_caught_up(s, l, f, end_offset):
    # IsFollowerCaughtUp (:219-225)
    logs, rstates, _, _, _, _ = s
    if rstates[f][2] != l:
        return False
    if end_offset == 0:
        return True
    return end_offset <= len(logs[l]) and len(logs[f]) >= end_offset


def o_leader_shrink_isr(cfg: Config):
    # LeaderShrinkIsr (:233-239)
    def successors(s):
        _, rstates, _, _, _, _ = s
        logs = s[0]
        for l in range(cfg.n):
            isr = rstates[l][3]
            for f in sorted(isr - {l}):
                if not _o_caught_up(s, l, f, len(logs[l])):
                    t = _o_quorum_update(s, l, isr - {f})
                    if t is not None:
                        yield t

    return OracleAction("LeaderShrinkIsr", successors)


def o_leader_expand_isr(cfg: Config):
    # LeaderExpandIsr (:248-254)
    def successors(s):
        _, rstates, _, _, _, _ = s
        for l in range(cfg.n):
            isr = rstates[l][3]
            hw = rstates[l][0]
            for f in range(cfg.n):
                if f not in isr and _o_caught_up(s, l, f, hw):
                    t = _o_quorum_update(s, l, isr | {f})
                    if t is not None:
                        yield t

    return OracleAction("LeaderExpandIsr", successors)


def o_leader_inc_high_watermark(cfg: Config):
    # LeaderIncHighWatermark (:264-271)
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for l in range(cfg.n):
            hw, ep, ldr, isr = rstates[l]
            if ldr != l or hw >= cfg.l:
                continue
            if all(rstates[f][2] == l and len(logs[f]) > hw for f in isr):
                new_rs = rstates[:l] + ((hw + 1, ep, ldr, isr),) + rstates[l + 1 :]
                yield (logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("LeaderIncHighWatermark", successors)


def o_become_follower_and_truncate_to(cfg: Config, name: str, trunc_offset_fn):
    # BecomeFollowerAndTruncateTo (:281-294) composed per-variant; leader
    # ranges over Replicas in every variant, so the None branch is dead.
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for (e, l, risr) in reqs:
            if l == NONE:
                continue
            for r in range(cfg.n):
                if r == l or e <= rstates[r][1]:
                    continue
                toff = trunc_offset_fn(cfg, s, l, r)
                if toff > len(logs[r]):  # TruncateTo guard (FRL:106)
                    continue
                new_logs = logs[:r] + (logs[r][:toff],) + logs[r + 1 :]
                new_hw = min(toff, rstates[r][0])
                new_rs = rstates[:r] + ((new_hw, e, l, risr),) + rstates[r + 1 :]
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction(name, successors)


def o_follower_replicate(cfg: Config):
    # FollowerReplicate (:302-310)
    def successors(s):
        logs, rstates, nrid, nep, reqs, quorum = s
        for f in range(cfg.n):
            for l in range(cfg.n):
                if rstates[l][2] != l or rstates[f][2] != l:
                    continue
                off = len(logs[f])
                if off >= cfg.l or off >= len(logs[l]):
                    continue
                new_logs = logs[:f] + (logs[f] + (logs[l][off],),) + logs[f + 1 :]
                new_hw = min(rstates[l][0], off + 1)
                hwf, epf, ldrf, isrf = rstates[f]
                new_rs = rstates[:f] + ((new_hw, epf, ldrf, isrf),) + rstates[f + 1 :]
                yield (new_logs, new_rs, nrid, nep, reqs, quorum)

    return OracleAction("FollowerReplicate", successors)


# variant truncation offsets, oracle side ---------------------------------


def o_truncate_to_hw_offset(cfg, s, l, r):
    # KafkaTruncateToHighWatermark.tla:29-31
    return s[1][r][0]


def o_kip101_offset(cfg, s, l, r):
    # Kip101.tla:27-47
    logs, rstates, *_ = s
    if len(logs[r]) == 0:
        return 0
    epoch = logs[r][-1][1]
    if len(logs[l]) == 0:
        return rstates[r][0]
    if logs[l][-1][1] == epoch:
        return len(logs[l])
    larger = [o for o, (_, ep) in enumerate(logs[l]) if ep > epoch]
    return min(larger) if larger else rstates[r][0]


def o_kip279_offset(cfg, s, l, r):
    # Kip279.tla:27-45
    logs = s[0]
    if len(logs[l]) == 0:
        return 0
    matching = [
        o
        for o, rec in enumerate(logs[r])
        if o < len(logs[l]) and logs[l][o] == rec
    ]
    return (max(matching) + 1) if matching else 0


# oracle invariants --------------------------------------------------------


def o_weak_isr(cfg: Config):
    # WeakIsr (:320-326)
    def pred(s):
        logs, rstates, *_ = s
        for r1 in range(cfg.n):
            hw, _, ldr, isr = rstates[r1]
            if ldr != r1:
                continue
            for r2 in isr:
                for off in range(hw):
                    if off >= len(logs[r1]) or off >= len(logs[r2]):
                        return False
                    if logs[r1][off] != logs[r2][off]:
                        return False
        return True

    return ("WeakIsr", pred)


def o_strong_isr(cfg: Config):
    # StrongIsr (:334-340)
    def pred(s):
        logs, rstates, _, _, _, (_, _, qisr) = s
        for r1 in range(cfg.n):
            hw, _, ldr, _ = rstates[r1]
            if ldr != r1:
                continue
            for r2 in qisr:
                for off in range(hw):
                    if off >= len(logs[r1]) or off >= len(logs[r2]):
                        return False
                    if logs[r1][off] != logs[r2][off]:
                        return False
        return True

    return ("StrongIsr", pred)


def o_leader_in_isr_literal(cfg: Config):
    # LeaderInIsr (:345), literal
    def pred(s):
        _, _, _, _, _, (_, qldr, qisr) = s
        return qldr in qisr

    return ("LeaderInIsrLiteral", pred)


def o_leader_in_isr(cfg: Config):
    def pred(s):
        _, _, _, _, _, (_, qldr, qisr) = s
        return qldr == NONE or qldr in qisr

    return ("LeaderInIsr", pred)


def o_type_ok(cfg: Config):
    # TypeOk (:101-107) on the canonical representation
    def pred(s):
        logs, rstates, nrid, nep, reqs, (qep, qldr, qisr) = s
        if not (0 <= nrid <= cfg.r and 0 <= nep <= cfg.e + 1):
            return False
        for log in logs:
            if len(log) > cfg.l:
                return False
            if any(not (0 <= i < cfg.r and 0 <= e <= cfg.e) for i, e in log):
                return False
        for hw, ep, ldr, isr in rstates:
            if not (0 <= hw <= cfg.l and NIL <= ep <= cfg.e and NONE <= ldr < cfg.n):
                return False
            if not isr <= set(range(cfg.n)):
                return False
        return NIL <= qep <= cfg.e and NONE <= qldr < cfg.n

    return ("TypeOk", pred)
