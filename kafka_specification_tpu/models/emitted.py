"""Mechanically emitted models for the full corpus (L3/L4 + AsyncIsr).

Builds checker models for KafkaReplication's five variants and the
standalone AsyncIsr straight from the reference TLA+ text
(/root/reference/<Module>.tla) via the expression front-end (utils/tla_expr
-> utils/tla_emit): module structure and EXTENDS / INSTANCE WITH
substitution from utils/tla_frontend, guards and updates evaluated
symbolically over the SAME tensor encoding the hand-written models use
(kafka_replication.make_spec / async_isr.make_spec, SURVEY.md §2.2) — so
emitted and hand-written models are comparable as exact packed state sets
per BFS level (tests/test_emitted_l3.py).

This is SANY's role (SURVEY.md §2.5 row 1) done end to end: no
hand-translated guard or update anywhere in this path.

Value conventions match the hand models: `None == "NONE"` is pinned to -1
via the consts table (KafkaReplication.tla:38); Nil == -1 inlines from its
own definition (:39); ISRs are bitmasks (SBitset); `leaderAndIsrRequests`
is the epoch-keyed slot array (SKeyedSet) — sound because every request
carries a fresh leaderEpoch (ControllerUpdateIsr, :138-145).
"""

from __future__ import annotations

import os
import sys as _sys
from pathlib import Path

from ..utils.tla_emit import (
    SBitset,
    SFun,
    SInt,
    SKeyedSet,
    SPairSet,
    SRec,
    build_model,
    load_defs,
)
from ..utils.tla_frontend import parse_tla
from .kafka_replication import ABSENT, NIL, NONE, Config, make_spec

# The reference checkout the emitted path parses at runtime (the checker
# consuming the spec corpus exactly as TLC would).  Resolved LAZILY so one
# knob controls both the emitted builders and `cli validate`: the CLI's
# --reference value threads through as `override`, and the env var is read
# at call time, not import time (round-5 advisor item).


def ref_path(override=None) -> Path:
    """Resolve the reference checkout: explicit override > KSPEC_REFERENCE
    env var > /root/reference."""
    return Path(
        override or os.environ.get("KSPEC_REFERENCE", "/root/reference")
    )


def __getattr__(name):  # PEP 562: keep `emitted.REF` importable, but lazy
    if name == "REF":
        return ref_path()
    raise AttributeError(name)

#: the five L4 variant modules (SURVEY.md §2.1) in historical order
VARIANTS = (
    "KafkaTruncateToHighWatermark",
    "Kip101",
    "Kip279",
    "Kip320FirstTry",
    "Kip320",
)


def l3_schemas(cfg: Config) -> dict:
    """TLA VARIABLE -> tensor schema over the hand spec's lanes
    (KafkaReplication.tla:45-75 -> make_spec's fields)."""
    N, L, R, E = cfg.n, cfg.l, cfg.r, cfg.e
    record = SRec(
        {"id": SInt("rid", NIL, R - 1), "epoch": SInt("repoch", NIL, E)}
    )
    return {
        "replicaLog": SFun(
            N,
            SRec(
                {
                    "endOffset": SInt("end", 0, L),
                    "records": SFun(L, record),
                }
            ),
        ),
        "replicaState": SFun(
            N,
            SRec(
                {
                    "hw": SInt("hw", 0, L),
                    "leaderEpoch": SInt("ep", NIL, E),
                    "leader": SInt("ldr", NONE, N - 1),
                    "isr": SBitset("isr", N),
                }
            ),
        ),
        "nextRecordId": SInt("nrid", 0, R),
        "nextLeaderEpoch": SInt("nep", 0, E + 1),
        "quorumState": SRec(
            {
                "leaderEpoch": SInt("qep", NIL, E),
                "leader": SInt("qldr", NONE, N - 1),
                "isr": SBitset("qisr", N),
            }
        ),
        "leaderAndIsrRequests": SKeyedSet(
            size=E + 1,
            key="leaderEpoch",
            fields={
                "leader": SInt("req_ldr", ABSENT, N - 1),
                "isr": SBitset("req_isr", N),
            },
            absent_field="leader",
            absent=ABSENT,
        ),
    }


#: the reference's literal LeaderInIsr (KafkaReplication.tla:345) — the
#: intent rebinding below only applies when the module's definition still
#: IS this literal (known False at Init, :117-119); a future module whose
#: LeaderInIsr genuinely differs keeps its own meaning (round-5 advisor).
_LEADER_IN_ISR_LITERAL = "quorumState.leader \\in quorumState.isr"


def _rebind_if_literal(defs, name, literal_src, intent_src, where):
    """Rebind `name` to the corpus-wide intent reading IFF its definition
    still parses equal to the known reference literal; otherwise keep the
    module's own definition and say so.  The literal stays available as
    `<name>Literal` (PARITY.md)."""
    from ..utils import tla_expr as E

    if defs.get(name) == ((), E.parse_expr(literal_src)):
        defs[f"{name}Literal"] = defs[name]
        defs[name] = ((), E.parse_expr(intent_src))
    elif name in defs:
        print(
            f"[kspec] {where}: {name} differs from the corpus literal — "
            "keeping the module's own definition (no intent rebinding)",
            file=_sys.stderr,
        )


def make_emitted_model(
    module: str,
    cfg: Config,
    invariants=("TypeOk",),
    reference=None,
):
    """Emit the checker model for one variant module from reference text.

    invariants: names resolved in the module's definition namespace
    (TypeOk / WeakIsr / StrongIsr / LeaderInIsr).  `LeaderInIsr` is bound
    to the corpus-wide *intent* reading (leader = None \\/ membership) so
    hand and emitted paths check the same property — but ONLY when the
    module's literal predicate matches the known corpus literal
    (KafkaReplication.tla:345, False at Init); otherwise the module's own
    definition stands.  The literal stays available as
    `LeaderInIsrLiteral` (PARITY.md).
    """
    ref = ref_path(reference)
    defs = load_defs(ref, module)
    _rebind_if_literal(
        defs,
        "LeaderInIsr",
        _LEADER_IN_ISR_LITERAL,
        "(quorumState.leader = None) "
        "\\/ (quorumState.leader \\in quorumState.isr)",
        module,
    )
    mod = parse_tla(ref / f"{module}.tla")
    consts = {
        "Replicas": (0, cfg.n - 1),
        "LogSize": cfg.l,
        "MaxRecords": cfg.r,
        "MaxLeaderEpoch": cfg.e,
        "None": NONE,  # model value "NONE" (KafkaReplication.tla:38)
    }
    built = build_model(
        mod,
        consts,
        l3_schemas(cfg),
        make_spec(cfg),
        invariant_names=invariants,
        name=f"{module}(emitted,{cfg.n}r)",
        defs=defs,
    )
    # emitted and hand models share the same lanes, so the hand decoder and
    # trace-rendering metadata apply verbatim (pretty counterexamples +
    # direct state-set comparison against the oracle)
    from . import kip320 as _kip320
    from . import variants as _variants

    if module == "Kip320":
        hand = _kip320.make_model(cfg, invariants=())
    elif module == "Kip320FirstTry":
        hand = _kip320.make_first_try_model(cfg, invariants=())
    else:
        hand = _variants.make_model(module, cfg, invariants=())
    built.decode = hand.decode
    built.meta = hand.meta
    return built


#: the TLC CONSTRAINT bounding AsyncIsr's unbounded spec (authored — the
#: reference declares MaxOffset but never guards LeaderWrite with it,
#: AsyncIsr.tla:117-119; versions grow without bound).  Same bounds as the
#: hand model's constraint pruning (models/async_isr.py).
ASYNC_ISR_BOUNDED = (
    "/\\ controllerState.version \\leq MaxVersion "
    "/\\ leaderState.version \\leq MaxVersion "
    "/\\ leaderState.offsets[Leader] \\leq MaxOffset"
)


#: the reference's literal TypeOk (AsyncIsr.tla:62-66) — the intent
#: rebinding below only applies while the module's definition IS this
#: literal (False at Init because pendingVersion starts at Nil, :45,:145).
_ASYNC_TYPEOK_LITERAL = (
    "/\\ (controllerState \\in ControllerState) "
    "/\\ (leaderState \\in LeaderState) "
    "/\\ (requests \\subseteq Message) "
    "/\\ (updates \\subseteq Message)"
)


def make_emitted_async_isr(
    cfg,
    invariants=("TypeOk", "ValidHighWatermark"),
    reference=None,
):
    """Emit the standalone AsyncIsr model (AsyncIsr.tla) from reference
    text onto the hand model's lanes (models/async_isr.make_spec).

    cfg: models.async_isr.AsyncIsrConfig.  `updates` is version-keyed
    (controller CAS makes versions unique, :68-70 -> SKeyedSet); `requests`
    may repeat versions (the leader reuses its current version, :88-115) ->
    the per-version subset-lattice bitset (SPairSet).
    """
    from .async_isr import LEADER, make_spec as make_async_spec

    ref = ref_path(reference)
    defs = load_defs(ref, "AsyncIsr")
    # literal TypeOk is False at Init: LeaderState declares
    # `pendingVersion: Nat` (AsyncIsr.tla:45) but Init sets it to Nil = -1
    # (:145).  Bind `TypeOk` to the evident intent (pendingVersion may be
    # Nil) so the .cfg-named invariant passes as the author expected —
    # gated on the definition still being the known literal (round-5
    # advisor): a changed TypeOk keeps its own meaning.
    _rebind_if_literal(
        defs,
        "TypeOk",
        _ASYNC_TYPEOK_LITERAL,
        "/\\ (controllerState \\in ControllerState) "
        "/\\ (leaderState \\in [isr: SUBSET Replicas, version: Nat, "
        "pendingIsr: SUBSET Replicas, pendingVersion: -1 .. MaxVersion, "
        "offsets: [Replicas -> Nat]]) "
        "/\\ (requests \\subseteq Message) "
        "/\\ (updates \\subseteq Message)",
        "AsyncIsr",
    )
    mod = parse_tla(ref / "AsyncIsr.tla")
    N, M, V = cfg.n, cfg.max_offset, cfg.max_version
    schemas = {
        "controllerState": SRec(
            {"isr": SBitset("c_isr", N), "version": SInt("c_ver", 0, V)}
        ),
        "leaderState": SRec(
            {
                "isr": SBitset("l_isr", N),
                "version": SInt("l_ver", 0, V),
                "pendingIsr": SBitset("l_pend", N),
                "pendingVersion": SInt("l_pver", NIL, V),
                "offsets": SFun(N, SInt("offs", 0, M)),
            }
        ),
        "updates": SKeyedSet(
            size=V + 1,
            key="version",
            fields={"isr": SBitset("upd_isr", N)},
            absent_field="isr",
            absent=-1,
        ),
        "requests": SPairSet("req_bits", n_versions=V + 1, n_set=N),
    }
    consts = {
        "Replicas": (0, N - 1),
        "Leader": LEADER,
        "MaxOffset": M,
        "MaxVersion": V,
    }
    return build_model(
        mod,
        consts,
        schemas,
        make_async_spec(cfg),
        invariant_names=invariants,
        name=f"AsyncIsr(emitted,{N}r)",
        defs=defs,
        constraint_src=ASYNC_ISR_BOUNDED,
    )
