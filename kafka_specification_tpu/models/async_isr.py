"""AsyncIsr — the KIP-497-style AlterIsr model (standalone state machine).

Reference: /root/reference/AsyncIsr.tla.  A fixed leader (no elections,
:24-29) proposes ISR changes to the controller asynchronously; the key safety
idea is that the high watermark counts *pending* ISR members too
(`HighWatermark == Min(offsets over isr \\union pendingIsr)`, :58-60), so a
member can be added to the ISR before the controller acknowledges without
exposing unreplicated data.  Invariant: `ValidHighWatermark` (:161-162).

As written the model is infinite-state: `LeaderWrite` has no MaxOffset guard
(:117-119) and controller versions grow without bound, so a TLC run needs a
state CONSTRAINT.  Here the bounds are explicit constants (max_offset,
max_version) enforced as constraint-pruning at successor generation:
out-of-bound successors are discarded — not counted, not invariant-checked —
and the oracle applies the identical rule, so engine and oracle agree exactly.

Encoding notes (SURVEY.md §2.2): every `updates` element is created by
`ControllerWriteIsr`, which CASes version to controllerVersion+1 (:68-70), so
updates are uniquely keyed by version -> version-indexed array.  `requests`
(leader -> controller) reuse the leader's *current* version (:92-99,:107-114),
so several distinct ISRs can share a version -> encoded as a per-version
bitset over ISR subsets (`req_bits[v]` bit s <=> request (isr=s, version=v)
present); N <= 4 keeps the 2^N-bit subset lattice within one signed int32
element (the packing dtype).

WLOG the fixed `Leader` constant is replica 0.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..ops.packing import Field, StateSpec
from ..oracle.interp import OracleAction, OracleModel
from .base import Action, Invariant, Model

NIL = -1  # AsyncIsr.tla:38
LEADER = 0  # WLOG (Leader \in Replicas, :29)


@dataclass(frozen=True)
class AsyncIsrConfig:
    n_replicas: int
    max_offset: int  # CONSTANT MaxOffset (:25) — enforced as a constraint
    max_version: int  # state CONSTRAINT bound on controller/leader versions

    @property
    def n(self):
        return self.n_replicas

    @property
    def full_isr(self):
        return (1 << self.n_replicas) - 1


def check_encoding_bounds(cfg: AsyncIsrConfig) -> None:
    """The N <= 4 encoding cliff, checked wherever a config enters
    (engine spec, model, oracle): the request set is encoded as a
    per-version 2^N-bit ISR-subset bitset (`req_bits`) that must fit ONE
    signed int32 lane — 2^5 = 32 bits already overflows it.

    The DETECTOR is now the general spec-width pass
    (analysis/encoding.spec_fits_errors — every field of every model is
    held to the packed int32 element dtype at Model construction); this
    wrapper keeps the AsyncIsr-specific actionable message, and the
    oracle keeps calling it because a config the engine cannot encode
    must not be silently accepted by the cross-check path either.
    Spreading the bitset over multiple lanes is the documented extension
    path (TODO.md)."""
    from ..analysis.encoding import EncodingUnsound, spec_fits_errors

    # bitset width 2^N, with N capped BEFORE the shift so a wild config
    # (a typo'd N of 10^12) can't make the probe allocate an N-bit
    # integer — past the cap the bound already exceeds the int32 element
    # range by construction, which is all the detector needs
    probe = Field(
        "req_bits", (cfg.max_version + 1,), 0,
        (1 << (1 << min(cfg.n, 6))) - 1,
    )
    findings = spec_fits_errors([probe], context="AsyncIsr")
    if findings:
        raise EncodingUnsound(
            f"AsyncIsr supports at most 4 replicas, got {cfg.n_replicas}: "
            "the request set is encoded as a per-version 2^N-bit subset "
            "bitset (req_bits) that must fit one signed int32 element "
            f"(2^{cfg.n_replicas} bits > 31); "
            "reduce the replica count or extend the encoding to multiple "
            "lanes",
            findings=findings,
        )


def make_spec(cfg: AsyncIsrConfig) -> StateSpec:
    N, M, V = cfg.n, cfg.max_offset, cfg.max_version
    # the per-version request bitset has 2^N bits and lives in int32 fields
    check_encoding_bounds(cfg)
    return StateSpec(
        [
            # controllerState (:48-51)
            Field("c_isr", (), 0, cfg.full_isr),
            Field("c_ver", (), 0, V),
            # leaderState (:40-46)
            Field("l_isr", (), 0, cfg.full_isr),
            Field("l_ver", (), 0, V),
            Field("l_pend", (), 0, cfg.full_isr),
            Field("l_pver", (), NIL, V),
            Field("offs", (N,), 0, M),
            # updates: version-indexed (unique by CAS, :68-70); -1 = absent
            Field("upd_isr", (V + 1,), -1, cfg.full_isr),
            # requests: per-version bitset over ISR subsets (:92-95,:107-110)
            Field("req_bits", (V + 1,), 0, (1 << (1 << N)) - 1),
        ]
    )


def init_state(cfg: AsyncIsrConfig) -> dict:
    # Init (:137-150)
    return {
        "c_isr": cfg.full_isr,
        "c_ver": 0,
        "l_isr": cfg.full_isr,
        "l_ver": 0,
        "l_pend": 0,
        "l_pver": NIL,
        "offs": [0] * cfg.n,
        "upd_isr": [-1] * (cfg.max_version + 1),
        "req_bits": [0] * (cfg.max_version + 1),
    }


def _hw(cfg, s):
    # HighWatermark (:58-60): Min of offsets over isr \union pendingIsr.
    # The union always contains the Leader (shrink never removes it, :73,:89),
    # so it is never empty.
    potential = s["l_isr"] | s["l_pend"]
    members = ((potential >> jnp.arange(cfg.n)) & 1) == 1
    return jnp.min(jnp.where(members, s["offs"], cfg.max_offset + 1))


def _bit(r):
    return jnp.int32(1) << r


def controller_shrink_isr(cfg: AsyncIsrConfig):
    # ControllerShrinkIsr (:72-79); version bound = constraint pruning
    def kernel(s, r):
        enabled = (r != LEADER) & (((s["c_isr"] >> r) & 1) == 1) & (s["c_ver"] < cfg.max_version)
        ver = jnp.minimum(s["c_ver"] + 1, cfg.max_version)
        isr = s["c_isr"] & ~_bit(r)
        return enabled, {
            **s,
            "c_isr": isr,
            "c_ver": ver,
            "upd_isr": s["upd_isr"].at[ver].set(isr),
        }

    return Action("ControllerShrinkIsr", cfg.n, kernel,
                  writes=frozenset({"c_isr", "c_ver", "upd_isr"}))


def controller_handle_request(cfg: AsyncIsrConfig):
    # ControllerHandleRequest (:81-86): pick any pending request whose version
    # CASes against the controller's; choice = the request's ISR subset.
    def kernel(s, subset):
        pending = ((s["req_bits"][s["c_ver"]] >> subset) & 1) == 1
        enabled = pending & (s["c_ver"] < cfg.max_version)
        ver = jnp.minimum(s["c_ver"] + 1, cfg.max_version)
        return enabled, {
            **s,
            "c_isr": subset,
            "c_ver": ver,
            "upd_isr": s["upd_isr"].at[ver].set(subset),
        }

    return Action("ControllerHandleRequest", 1 << cfg.n, kernel,
                  writes=frozenset({"c_isr", "c_ver", "upd_isr"}))


def leader_request_shrink_isr(cfg: AsyncIsrConfig):
    # LeaderRequestShrinkIsr (:88-100): request (isr \ {r}, current version);
    # pendingIsr accumulates by union (:97)
    def kernel(s, r):
        enabled = (r != LEADER) & (((s["l_isr"] >> r) & 1) == 1)
        isr = s["l_isr"] & ~_bit(r)
        return enabled, {
            **s,
            "req_bits": s["req_bits"].at[s["l_ver"]].set(
                s["req_bits"][s["l_ver"]] | (jnp.int32(1) << isr)
            ),
            "l_pend": s["l_pend"] | isr,
            "l_pver": s["l_ver"],
        }

    return Action("LeaderRequestShrinkIsr", cfg.n, kernel,
                  writes=frozenset({"req_bits", "l_pend", "l_pver"}))


def leader_request_expand_isr(cfg: AsyncIsrConfig):
    # LeaderRequestExpandIsr (:102-115): candidate must have reached the HW
    def kernel(s, r):
        enabled = (((s["l_isr"] >> r) & 1) == 0) & (s["offs"][r] >= _hw(cfg, s))
        isr = s["l_isr"] | _bit(r)
        return enabled, {
            **s,
            "req_bits": s["req_bits"].at[s["l_ver"]].set(
                s["req_bits"][s["l_ver"]] | (jnp.int32(1) << isr)
            ),
            "l_pend": s["l_pend"] | isr,
            "l_pver": s["l_ver"],
        }

    return Action("LeaderRequestExpandIsr", cfg.n, kernel,
                  writes=frozenset({"req_bits", "l_pend", "l_pver"}))


def leader_write(cfg: AsyncIsrConfig):
    # LeaderWrite (:117-119); MaxOffset bound = constraint pruning (the TLA+
    # action itself is unguarded — see module docstring)
    def kernel(s, _):
        enabled = s["offs"][LEADER] < cfg.max_offset
        return enabled, {
            **s,
            "offs": s["offs"].at[LEADER].set(
                jnp.minimum(s["offs"][LEADER] + 1, cfg.max_offset)
            ),
        }

    return Action("LeaderWrite", 1, kernel, writes=frozenset({"offs"}))


def leader_handle_update(cfg: AsyncIsrConfig):
    # LeaderHandleUpdate (:121-129): adopt any newer update, clear pending
    def kernel(s, v):
        enabled = (s["upd_isr"][v] >= 0) & (v > s["l_ver"])
        return enabled, {
            **s,
            "l_isr": jnp.maximum(s["upd_isr"][v], 0),
            "l_ver": v,
            "l_pend": jnp.int32(0),
            "l_pver": jnp.int32(NIL),
        }

    return Action("LeaderHandleUpdate", cfg.max_version + 1, kernel,
                  writes=frozenset({"l_isr", "l_ver", "l_pend", "l_pver"}))


def follower_replicate(cfg: AsyncIsrConfig):
    # FollowerReplicate (:131-135)
    def kernel(s, r):
        enabled = (r != LEADER) & (s["offs"][r] < s["offs"][LEADER])
        return enabled, {
            **s,
            "offs": s["offs"].at[r].set(jnp.minimum(s["offs"][r] + 1, cfg.max_offset)),
        }

    return Action("FollowerReplicate", cfg.n, kernel,
                  writes=frozenset({"offs"}))


def valid_high_watermark(cfg: AsyncIsrConfig):
    # ValidHighWatermark (:161-162)
    def pred(s):
        hw = _hw(cfg, s)
        members = ((s["c_isr"] >> jnp.arange(cfg.n)) & 1) == 1
        return jnp.all(jnp.where(members, s["offs"] >= hw, True))

    return Invariant("ValidHighWatermark", pred)


def type_ok(cfg: AsyncIsrConfig):
    # TypeOk (:62-66) within the constraint bounds
    def pred(s):
        return (
            (s["c_ver"] >= 0)
            & (s["c_ver"] <= cfg.max_version)
            & (s["l_ver"] >= 0)
            & (s["l_ver"] <= cfg.max_version)
            & (s["l_pver"] >= NIL)
            & (s["l_pver"] <= cfg.max_version)
            & jnp.all((s["offs"] >= 0) & (s["offs"] <= cfg.max_offset))
        )

    return Invariant("TypeOk", pred)


def make_decode(cfg: AsyncIsrConfig):
    def iset(mask):
        return frozenset(r for r in range(cfg.n) if (int(mask) >> r) & 1)

    def decode(s):
        reqs = frozenset(
            (iset(subset), v)
            for v in range(cfg.max_version + 1)
            for subset in range(1 << cfg.n)
            if (int(s["req_bits"][v]) >> subset) & 1
        )
        upds = frozenset(
            (iset(s["upd_isr"][v]), v)
            for v in range(cfg.max_version + 1)
            if int(s["upd_isr"][v]) >= 0
        )
        return (
            (iset(s["c_isr"]), int(s["c_ver"])),
            (
                iset(s["l_isr"]),
                int(s["l_ver"]),
                iset(s["l_pend"]),
                int(s["l_pver"]),
                tuple(int(x) for x in s["offs"]),
            ),
            reqs,
            upds,
        )

    return decode


def make_model(cfg: AsyncIsrConfig, invariants=("TypeOk", "ValidHighWatermark")) -> Model:
    table = {"TypeOk": type_ok, "ValidHighWatermark": valid_high_watermark}
    return Model(
        name=f"AsyncIsr({cfg.n}r,M{cfg.max_offset},V{cfg.max_version})",
        spec=make_spec(cfg),
        init_states=lambda: [init_state(cfg)],
        actions=[
            controller_shrink_isr(cfg),
            controller_handle_request(cfg),
            leader_request_shrink_isr(cfg),
            leader_request_expand_isr(cfg),
            leader_write(cfg),
            leader_handle_update(cfg),
            follower_replicate(cfg),
        ],
        invariants=[table[n](cfg) for n in invariants],
        decode=make_decode(cfg),
        meta={"variant": "AsyncIsr", "cfg": cfg},
    )


# ==========================================================================
# oracle transcription
# ==========================================================================
# state = ((c_isr, c_ver), (l_isr, l_ver, pend, pver, offs), reqs, upds)
# with isr values as frozensets, reqs/upds as frozensets of (isr, version).


def o_init(cfg: AsyncIsrConfig):
    # Init (:137-150)
    full = frozenset(range(cfg.n))
    return (
        (full, 0),
        (full, 0, frozenset(), NIL, tuple([0] * cfg.n)),
        frozenset(),
        frozenset(),
    )


def _o_hw(s):
    # HighWatermark (:58-60)
    (_, _), (l_isr, _, pend, _, offs), _, _ = s
    return min(offs[r] for r in (l_isr | pend))


def make_oracle(cfg: AsyncIsrConfig, invariants=("TypeOk", "ValidHighWatermark")) -> OracleModel:
    # the oracle itself has no bitset (frozensets), but it exists to
    # cross-check the engine — accepting a config the engine cannot
    # encode would just diverge later, so the cliff check is shared
    check_encoding_bounds(cfg)
    V, M = cfg.max_version, cfg.max_offset

    def ctrl_shrink(s):
        # :72-79 (+ version constraint)
        (c_isr, c_ver), lstate, reqs, upds = s
        if c_ver >= V:
            return
        for r in range(cfg.n):
            if r != LEADER and r in c_isr:
                isr = c_isr - {r}
                yield ((isr, c_ver + 1), lstate, reqs, upds | {(isr, c_ver + 1)})

    def ctrl_handle(s):
        # :81-86 (+ version constraint)
        (c_isr, c_ver), lstate, reqs, upds = s
        if c_ver >= V:
            return
        for (isr, ver) in reqs:
            if ver == c_ver:
                yield ((isr, c_ver + 1), lstate, reqs, upds | {(isr, c_ver + 1)})

    def leader_req_shrink(s):
        # :88-100
        cstate, (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        for r in sorted(l_isr):
            if r == LEADER:
                continue
            isr = l_isr - {r}
            yield (
                cstate,
                (l_isr, l_ver, pend | isr, l_ver, offs),
                reqs | {(isr, l_ver)},
                upds,
            )

    def leader_req_expand(s):
        # :102-115
        cstate, (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        hw = _o_hw(s)
        for r in range(cfg.n):
            if r in l_isr or offs[r] < hw:
                continue
            isr = l_isr | {r}
            yield (
                cstate,
                (l_isr, l_ver, pend | isr, l_ver, offs),
                reqs | {(isr, l_ver)},
                upds,
            )

    def leader_write(s):
        # :117-119 (+ MaxOffset constraint)
        cstate, (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        if offs[LEADER] >= M:
            return
        offs2 = offs[:LEADER] + (offs[LEADER] + 1,) + offs[LEADER + 1 :]
        yield (cstate, (l_isr, l_ver, pend, pver, offs2), reqs, upds)

    def leader_handle_update(s):
        # :121-129
        cstate, (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        for (isr, ver) in upds:
            if ver > l_ver:
                yield (cstate, (isr, ver, frozenset(), NIL, offs), reqs, upds)

    def follower_replicate(s):
        # :131-135
        cstate, (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        for r in range(cfg.n):
            if r != LEADER and offs[r] < offs[LEADER]:
                offs2 = offs[:r] + (offs[r] + 1,) + offs[r + 1 :]
                yield (cstate, (l_isr, l_ver, pend, pver, offs2), reqs, upds)

    def valid_hw(s):
        # :161-162
        (c_isr, _), (_, _, _, _, offs), _, _ = s
        hw = _o_hw(s)
        return all(offs[r] >= hw for r in c_isr)

    def o_type_ok(s):
        (c_isr, c_ver), (l_isr, l_ver, pend, pver, offs), reqs, upds = s
        return (
            0 <= c_ver <= V
            and 0 <= l_ver <= V
            and NIL <= pver <= V
            and all(0 <= o <= M for o in offs)
        )

    table = {"TypeOk": o_type_ok, "ValidHighWatermark": valid_hw}
    return OracleModel(
        name="AsyncIsr-oracle",
        init_states=lambda: [o_init(cfg)],
        actions=[
            OracleAction("ControllerShrinkIsr", ctrl_shrink),
            OracleAction("ControllerHandleRequest", ctrl_handle),
            OracleAction("LeaderRequestShrinkIsr", leader_req_shrink),
            OracleAction("LeaderRequestExpandIsr", leader_req_expand),
            OracleAction("LeaderWrite", leader_write),
            OracleAction("LeaderHandleUpdate", leader_handle_update),
            OracleAction("FollowerReplicate", follower_replicate),
        ],
        invariants=[(n, table[n]) for n in invariants],
        meta={"variant": "AsyncIsr", "cfg": cfg},
    )
