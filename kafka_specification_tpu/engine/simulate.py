"""Random simulation mode (TLC's `-simulate`).

Exhaustive BFS is the framework's main mode; simulation complements it for
state spaces too large to exhaust: random walks from the initial states,
checking invariants at every step, reporting the violating walk as the
counterexample trace.  Deterministic under a seed (numpy Generator drives
all choices), so reported traces replay.

Implementation: per step, the same vmapped action kernels run on a single
state (vmap over the choice lattice only); an enabled successor is drawn
uniformly from the enabled (state-constraint-satisfying) candidates.  The
walk terminates early at deadlocks (no enabled successor).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from .bfs import CheckResult, Violation


def _successor_fn(model: Model):
    """jitted: state dict -> (enabled[C] bool, batched successor struct)."""
    spec = model.spec

    @jax.jit
    def step(state):
        oks, nxts = [], []
        for a in model.actions:
            choices = jnp.arange(a.n_choices, dtype=jnp.int32)
            ok, nxt = jax.vmap(lambda c, a=a: a.kernel(state, c))(choices)
            if model.constraint is not None:
                ok = ok & jax.vmap(model.constraint)(nxt)
            oks.append(ok)
            nxts.append(nxt)
        batched = {
            k: jnp.concatenate([n[k] for n in nxts], axis=0) for k in nxts[0]
        }
        inv_ok = jnp.stack(
            [jnp.all(inv.pred(state)) for inv in model.invariants]
        ) if model.invariants else jnp.ones((1,), bool)
        return jnp.concatenate(oks), batched, inv_ok

    return step


def simulate(
    model: Model,
    num_walks: int = 100,
    max_depth: int = 100,
    seed: int = 0,
    progress=None,
) -> CheckResult:
    """Random-walk checking. Returns a CheckResult whose `total` counts
    visited (not necessarily distinct) states; `violation` carries the full
    violating walk as its trace."""
    rng = np.random.default_rng(seed)
    step = _successor_fn(model)
    # standalone invariant kernel for the walk's final state (the state
    # reached by the max_depth-th transition is never fed back to `step`,
    # but TLC -simulate checks every state on the walk — see below)
    inv_fn = (
        jax.jit(
            lambda s: jnp.stack([jnp.all(inv.pred(s)) for inv in model.invariants])
        )
        if model.invariants
        else None
    )
    act_of = np.concatenate(
        [np.full(a.n_choices, i) for i, a in enumerate(model.actions)]
    )
    t0 = time.perf_counter()
    visited = 0
    violation: Optional[Violation] = None
    inits = model.init_states()

    for walk in range(num_walks):
        state = {
            k: np.asarray(v, np.int32)
            for k, v in inits[rng.integers(len(inits))].items()
        }
        trace = [("<init>", model.decode(state) if model.decode else dict(state))]
        for d in range(max_depth):
            en, batched, inv_ok = step({k: jnp.asarray(v) for k, v in state.items()})
            visited += 1
            inv_ok = np.asarray(inv_ok)
            if model.invariants and not inv_ok.all():
                bad = int(np.argmax(~inv_ok))
                violation = Violation(
                    invariant=model.invariants[bad].name,
                    depth=d,
                    state=trace[-1][1],
                    trace=trace,
                )
                break
            en = np.asarray(en)
            idxs = np.nonzero(en)[0]
            if idxs.size == 0:
                break  # deadlock: the walk ends (matches TLC simulation)
            pick = int(idxs[rng.integers(idxs.size)])
            state = {k: np.asarray(v)[pick] for k, v in batched.items()}
            trace.append(
                (
                    model.actions[int(act_of[pick])].name,
                    model.decode(state) if model.decode else dict(state),
                )
            )
        else:
            # depth limit reached: the last transition's target state has
            # not been invariant-checked yet (violation/deadlock exits have
            # — `step` ran on those states before the break)
            if inv_fn is not None:
                inv_ok = np.asarray(
                    inv_fn({k: jnp.asarray(v) for k, v in state.items()})
                )
                visited += 1
                if not inv_ok.all():
                    bad = int(np.argmax(~inv_ok))
                    violation = Violation(
                        invariant=model.invariants[bad].name,
                        depth=max_depth,
                        state=trace[-1][1],
                        trace=trace,
                    )
        if violation is not None:
            break
        if progress:
            progress(walk + 1, visited)

    dt = time.perf_counter() - t0
    return CheckResult(
        model=model.name,
        levels=[],
        total=visited,
        diameter=0,
        violation=violation,
        seconds=dt,
        states_per_sec=visited / max(dt, 1e-9),
        stats={"mode": "simulate", "walks": num_walks, "max_depth": max_depth, "seed": seed},
    )
