"""Pluggable level-pipeline: the single-device engine's per-chunk stages
behind one interface, with two interchangeable expansion implementations.

Every BFS level runs each frontier chunk through the same five stages
(SURVEY.md §2.3; the sharded engine mirrors them per shard):

  1. expand       — evaluate action guards, produce candidate successors
  2. squeeze      — compact enabled candidates into a dense buffer
  3. fingerprint  — 64-bit fingerprints of the packed candidate rows
  4. dedup        — in-batch + visited-set novelty (backend-specific: the
                    in-jit sort/probe/merge for the device backend, the
                    HBM hash table or native host FpSet outside the jit)
  5. invariants   — predicate kernels over the frontier being expanded
  (6. trace record — host side: parent/action arrays per level, owned by
      :func:`..bfs.check` because it is pure host bookkeeping)

A *pipeline* is the object that owns stages 1-3 (+5) and how they are
fused into jitted programs; :func:`..bfs.check` drives it one chunk at a
time through :meth:`run_chunk`, which returns the same committed-output
contract for every implementation — so the level loop, the visited
backends, checkpointing, resource governance and trace recording are all
pipeline-agnostic.  Two implementations ship:

``legacy`` — the historical per-action path: one monolithic jitted step
  per (bucket, capacity) whose expansion runs one successor-kernel pass
  per action (O(actions) kernel launches per chunk), two-phase compaction
  under :class:`..bfs.AdaptiveCompact`, overflow-retry escalation.

``fused`` — the successor mega-kernel path (the default): per chunk,
  exactly TWO dispatched successor programs —

    launch 1 (``guard matrix``): ONE batched uniform kernel evaluates
      every action guard over the whole padded (frontier x choice)
      lattice — a single predicate matrix [B, C] — plus the frontier
      invariant predicates and deadlock detection (stage 5 rides along
      because it reads the same unpacked states).

    host glue: the predicate matrix is compacted at C speed with
      ``np.flatnonzero`` into ONE shared candidate buffer laid out as
      per-action segments at *data-driven* widths (sized from this
      chunk's exact guard counts + the run's high-water density — the
      update skeleton's shape is data, not code).  Because the exact
      enabled counts are known BEFORE the successor program is
      dispatched, the legacy path's overflow-retry machinery disappears:
      a chunk can never overflow its buffer, widths just grow
      monotonically along a power-of-two ladder.

    launch 2 (``update skeleton``): ONE batched program applies, over
      the one shared buffer, the uniform skeleton
      gather-state -> action update -> CONSTRAINT -> pack -> fingerprint
      (-> sort/probe/merge for the device backend).  Guards are NOT
      re-evaluated (launch 1 already proved every pooled row enabled),
      and the squeeze / pack / fingerprint stages that the legacy path
      ran once per action run exactly once.

  The fused path is bit-identical to the legacy path — same level
  counts, duplicate accounting, first-violation rule, and trace values —
  because the pooled buffer preserves the legacy compact path's
  candidate order (action-major, state-then-choice within an action) and
  all dedup stages consume candidates in that order.  Below the compact
  gate (small buckets, where the legacy path itself runs the full
  uncompacted lattice) the fused pipeline delegates chunks to the legacy
  implementation verbatim, so the whole run stays bit-identical at every
  bucket.  tests/test_pipeline.py pins this across the model matrix.

Plugging a new stage implementation: subclass (or parallel-implement)
a pipeline with the same ``run_chunk`` contract and register it in
:data:`PIPELINES`; the stage helpers in this module (``squeeze_stage``,
``fp_stage``, ``sorted_dedup_stage``, ``invariant_stage``) are the
building blocks both implementations compose, and docs/engine.md walks
through the interface.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dedup
from ..ops.fingerprint import fingerprint_lanes

PIPELINE_ENV = "KSPEC_PIPELINE"
#: registered pipeline names (resolve_pipeline validates against this)
PIPELINES = ("fused", "legacy")


def resolve_pipeline(name: Optional[str]) -> str:
    """CLI/env resolution: explicit arg > $KSPEC_PIPELINE > 'fused'."""
    n = name or os.environ.get(PIPELINE_ENV) or "fused"
    if n not in PIPELINES:
        raise ValueError(
            f"unknown pipeline {n!r} (expected one of {PIPELINES})"
        )
    return n


def key_vcap(key: tuple) -> Optional[int]:
    """The visited-capacity component of a step-cache key, or None for
    programs that don't embed the visited set (guard kernels).  Key
    shapes (engine.bfs._Step.get / FusedPipeline):

      ("step", bucket, vcap, inv_sig, with_merge, compact, sq_full, pallas)
      ("fgd",  bucket, inv_sig)                     — fused launch 1
      ("fsc",  bucket, vcap, widths, with_merge, device_out, pallas)
    """
    tag = key[0]
    if tag in ("step", "fsc"):
        return key[2]
    return None


# --------------------------------------------------------------------------
# shared stage helpers (traced; composed by both pipelines)
# --------------------------------------------------------------------------


def squeeze_stage(cand, parent, actid, valid, width, K):  # kspec: traced
    """Stage 2: compact enabled candidate rows to the front of a `width`
    buffer; overflow=True iff more than `width` rows are enabled."""
    n_en = jnp.sum(valid, dtype=jnp.int32)
    spos = jnp.where(valid, jnp.cumsum(valid) - 1, width)
    out = jnp.zeros((width, K), jnp.uint32).at[spos].set(cand)
    out_parent = jnp.full((width,), -1, jnp.int32).at[spos].set(parent)
    out_act = jnp.full((width,), -1, jnp.int32).at[spos].set(actid)
    rowvalid = jnp.arange(width) < n_en
    return out, out_parent, out_act, rowvalid, n_en, n_en > width


def fp_stage(cand, valid, spec, use_pallas: bool):  # kspec: traced
    """Stage 3: masked (hi, lo) fingerprints (Pallas opt-in or jnp)."""
    sent = jnp.uint32(dedup.SENT)
    if use_pallas:
        import math

        from ..ops.pallas_fingerprint import fingerprint_pallas

        interp = jax.default_backend() == "cpu"
        rows = cand.shape[0]
        block = math.gcd(rows, 1 << 13)
        return fingerprint_pallas(cand, valid, block_rows=block,
                                  interpret=interp)
    hi, lo = fingerprint_lanes(cand, spec.exact64)
    return jnp.where(valid, hi, sent), jnp.where(valid, lo, sent)


def invariant_stage(model, states, fvalid, with_invariants: bool):  # kspec: traced
    """Stage 5: per-invariant (any-violated, first-index) on the frontier
    being expanded (each state checked exactly once, at expansion)."""
    if not (with_invariants and model.invariants):
        return jnp.stack([jnp.bool_(False)]), jnp.stack([jnp.int32(0)])
    if model.invariants_fused is not None:
        ok = jax.vmap(model.invariants_fused)(states)  # [B, n_inv]
        bad = fvalid[:, None] & ~ok
        return jnp.any(bad, axis=0), jnp.argmax(bad, axis=0)
    viol_any, viol_idx = [], []
    for inv in model.invariants:
        ok = jax.vmap(inv.pred)(states)
        bad = fvalid & ~ok
        viol_any.append(jnp.any(bad))
        viol_idx.append(jnp.argmax(bad))
    return jnp.stack(viol_any), jnp.stack(viol_idx)


def sorted_dedup_stage(cand, parent, actid, valid, hi, lo,  # kspec: traced
                       vhi, vlo, vn, vcap, T, K, with_merge: bool):
    """Stage 4 (device backend): minimal-payload lexsort, first-occurrence
    + visited-rank dedup, compaction of the new states to the front, and
    (with_merge) the rank-scatter merge into the sorted visited set.
    Identical primitive sequence to the legacy in-step version — winners
    are decided by the stable sort over the same candidate order, which
    is what keeps the two pipelines trace-bit-identical."""
    sent = jnp.uint32(dedup.SENT)
    order = jnp.lexsort((lo, hi))
    hi_s, lo_s = hi[order], lo[order]
    invalid_s = (hi_s == sent) & (lo_s == sent)
    first = dedup.first_occurrence_mask(hi_s, lo_s, invalid_s)
    seen, rank = dedup.rank_sorted(vhi, vlo, vn, hi_s, lo_s)
    is_new = first & ~seen
    pos = jnp.where(is_new, jnp.cumsum(is_new) - 1, T)
    out = jnp.zeros((T, K), jnp.uint32).at[pos].set(cand[order])
    out_parent = jnp.full((T,), -1, jnp.int32).at[pos].set(parent[order])
    out_act = jnp.full((T,), -1, jnp.int32).at[pos].set(actid[order])
    out_hi = jnp.full((T,), sent).at[pos].set(hi_s)
    out_lo = jnp.full((T,), sent).at[pos].set(lo_s)
    out_rank = jnp.zeros((T,), jnp.int32).at[pos].set(rank)
    new_n = jnp.sum(is_new, dtype=jnp.int32)
    if with_merge:
        vhi, vlo, vn = dedup.merge_ranked(
            vhi, vlo, vn, out_hi, out_lo, out_rank, new_n, vcap
        )
    return out, out_parent, out_act, new_n, out_hi, out_lo, vhi, vlo, vn


# --------------------------------------------------------------------------
# legacy pipeline: the per-action monolithic step + overflow escalation
# --------------------------------------------------------------------------


class LegacyPipeline:
    """The historical per-action expansion behind the pipeline interface:
    one monolithic jitted step per (bucket, vcap) running one successor
    pass per action, with AdaptiveCompact's two-phase compaction and the
    overflow-retry/escalation ladder (moved verbatim from check()'s inner
    loop).  Kernel launches per chunk: O(actions)."""

    name = "legacy"

    def __init__(self, step_builder, model, adapt, chunk_retry, fault,
                 check_invariants: bool, visited_backend: str,
                 on_degrade_chunk):
        self.step = step_builder
        self.model = model
        self.adapt = adapt
        self.chunk_retry = chunk_retry
        self.fault = fault
        self.check_invariants = check_invariants
        self.visited_backend = visited_backend
        self.on_degrade_chunk = on_degrade_chunk
        self.squeeze_full = False  # sticky pre-sort-squeeze overflow relief
        self.compile_fallback = False

    @property
    def launches_per_chunk(self) -> int:
        """Successor-kernel passes dispatched per chunk: one per action
        (the per-action phase-B evaluation; TODO.md's '12 DNF action
        kernels vs hand's 9')."""
        return len(self.model.actions)

    def run_chunk(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        from .bfs import _pad_rows  # cycle-free: bfs imports us lazily

        adapt = self.adapt
        compact_arg = adapt.widths_for(bucket)
        attempt_sq_full = self.squeeze_full
        self.chunk_retry.reset_chunk()
        dispatched = 0  # successor-kernel passes actually dispatched,
        # overflow/retry re-dispatches included
        while True:
            try:
                injected = self.fault.chunk_error(
                    escalated=isinstance(compact_arg, (list, tuple))
                )
                if injected is not None:
                    raise injected
                step = self.step.get(
                    bucket,
                    vcap,
                    self.check_invariants,
                    with_merge=self.visited_backend == "device",
                    compact=compact_arg,
                    squeeze_full=attempt_sq_full,
                )
                (
                    out, out_parent, out_act, new_n, vhi_n, vlo_n, vn_n,
                    viol_any, viol_idx, dl_any, dl_idx, act_en,
                    out_hi, out_lo, overflow, act_guard,
                ) = step(
                    jnp.asarray(_pad_rows(piece, bucket)),
                    jnp.arange(bucket) < fp_n,
                    vhi,
                    vlo,
                    vn,
                )
                dispatched += self.launches_per_chunk
            except Exception as e:  # noqa: BLE001 — XLA compile/run
                # known failure ladder — one policy for both engines
                # (resilience.retry.ChunkRetryHandler); see check()'s
                # docstring for the degradation contract
                action = self.chunk_retry.handle(
                    e,
                    escalated=isinstance(compact_arg, (list, tuple)),
                    depth=depth,
                )
                if action == "retry":
                    continue
                if action == "degrade_chunk":
                    self.on_degrade_chunk()
                compact_arg = adapt.compile_fallback(bucket)
                self.compile_fallback = True
                continue
            ovf = np.asarray(overflow)
            if compact_arg is None or not ovf.any():
                vhi, vlo, vn = vhi_n, vlo_n, vn_n
                break
            # retry this chunk with the offending buffers widened: a
            # per-action compact overflow doubles that action's width
            # (floored for the rest of the run); a squeeze overflow
            # disables the pre-sort width reduction (sticky); a
            # uniform-shift overflow escalates to measured widths
            if ovf[-1]:
                attempt_sq_full = self.squeeze_full = True
            if ovf[:-1].any():
                compact_arg = adapt.escalate(
                    compact_arg,
                    ovf[:-1],
                    bucket,
                    np.asarray(act_guard, np.int64) / max(fp_n, 1),
                )
        # adapt buffer sizing from the committed attempt's PRE-constraint
        # guard counts (what the buffers actually hold; act_en is
        # post-constraint and undercounts on pruning models)
        adapt.observe(np.asarray(act_guard, np.int64) / max(fp_n, 1))
        return (
            out, out_parent, out_act, new_n, vhi, vlo, vn,
            viol_any, viol_idx, dl_any, dl_idx, act_en,
            out_hi, out_lo, act_guard, dispatched,
        )

    def run_chunk_staged(self, piece, fp_n, bucket, depth,
                         vhi, vlo, vn, vcap):
        """Staged form of :meth:`run_chunk` for the overlap driver:
        -> (vhi, vlo, vn, finalize).  The legacy path must read its
        overflow flags before committing (the retry ladder), which
        forces the whole program — so its dispatch is already complete
        and finalize is a no-op closure over the committed tuple.  The
        overlap win for legacy chunks is therefore only the reordering
        of host commits, never deferred device work (docs/engine.md)."""
        outs = self.run_chunk(piece, fp_n, bucket, depth, vhi, vlo, vn,
                              vcap)
        return outs[4], outs[5], outs[6], lambda: outs


# --------------------------------------------------------------------------
# fused pipeline: guard matrix + pooled update skeleton (2 launches)
# --------------------------------------------------------------------------


class PooledWidths:
    """Data-driven sizing of the fused path's shared candidate buffer.

    Each action owns one segment of the pooled buffer; its width rides a
    power-of-two ladder (floor 256 for Pallas block alignment, capped at
    the action's full lattice width) sized from max(this chunk's EXACT
    guard count, the run's high-water per-state density x bucket x 1.35
    headroom).  Exact counts are known before the successor program is
    dispatched (launch 1 already ran), so a chunk can never overflow its
    segment — the ladder only climbs, keeping the set of compiled width
    vectors small and, across runs of the same shape, deterministic
    (warm serving runs replay the same keys; PreparedKernels)."""

    HEADROOM = 1.35

    def __init__(self, actions):
        self.actions = actions
        self.hw = np.zeros(len(actions), np.float64)  # density high-water

    @staticmethod
    def _rung(need: int) -> int:
        """Smallest half-octave rung >= need: {0.75 * 2^k, 2^k} rounded to
        the 256-row fingerprint-block alignment.  Two rungs per octave
        keeps the mean padding ~1.2x (vs ~1.5x for plain pow2) while the
        monotone ladder still bounds the number of compiled width
        vectors per run."""
        from .bfs import _next_pow2, _round256

        p = _next_pow2(need)
        q = _round256((3 * p) >> 2)
        return q if q >= need else _round256(p)

    def widths_for(self, bucket: int, counts: np.ndarray,
                   fp_n: int) -> tuple:
        from .bfs import _round256

        self.hw = np.maximum(self.hw, counts / max(fp_n, 1))
        out = []
        for a, hw, count in zip(self.actions, self.hw, counts):
            cap = _round256(bucket * a.n_choices)
            need = max(256, int(count), int(self.HEADROOM * hw * bucket))
            out.append(min(cap, self._rung(need)))
        return tuple(out)


class FusedPipeline:
    """Successor mega-kernels: 2 dispatched programs per chunk (guard
    matrix -> host flatnonzero compaction -> update skeleton), bit-
    identical to the legacy path (module docstring).  Chunks below the
    compact gate delegate to the legacy pipeline verbatim — the legacy
    path runs the full uncompacted lattice there, and matching it
    instruction-for-instruction is what keeps whole runs bit-identical
    at every bucket."""

    name = "fused"
    launches_per_chunk = 2

    def __init__(self, step_builder, model, adapt, chunk_retry, fault,
                 check_invariants: bool, visited_backend: str,
                 on_degrade_chunk, compact_shift: int, compact_gate: int):
        self.step = step_builder
        self.model = model
        self.spec = model.spec
        self.chunk_retry = chunk_retry
        self.fault = fault
        self.check_invariants = check_invariants
        self.visited_backend = visited_backend
        self.compact_shift = compact_shift
        self.compact_gate = compact_gate
        self.pool = PooledWidths(model.actions)
        self.fallback = False  # sticky: a failed fused compile pins legacy
        self.legacy = LegacyPipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
        )
        self.adapt = adapt
        self._bounds = np.cumsum(
            [0] + [a.n_choices for a in model.actions]
        )

    def _gate(self, bucket: int) -> bool:
        """Fused engages exactly where the legacy path would compact
        (same gate, same shift test) — below it the candidate order is
        the full lattice's state-major order, which only the legacy full
        path produces."""
        return (
            not self.fallback
            and self.compact_shift > 0
            and bucket >= self.compact_gate
            and (bucket >> self.compact_shift) >= 1
        )

    # --- jitted launches (cached on the model's step cache) ---------------
    def guard_step(self, bucket: int):
        """Launch 1: guard predicate matrix + invariants + deadlock.
        The invariant component of the key comes from _Step.inv_sig —
        the SAME source the legacy "step" keys use, so fused and legacy
        programs of one invariant-overlay view stay in lockstep in the
        shared per-base step cache (service/kernel_cache.py)."""
        key = ("fgd", bucket, self.step.inv_sig(self.check_invariants))
        return self.step.cached(
            key, lambda: jax.jit(self._build_guard(bucket)),
            bucket=bucket, program="fused-guards",
        )

    def succ_step(self, bucket: int, widths: tuple, vcap: int):
        """Launch 2: the pooled update skeleton (+ device dedup)."""
        with_merge = self.visited_backend == "device"
        device_out = self.visited_backend != "host"
        key = ("fsc", bucket, vcap, widths, with_merge, device_out,
               self.step.use_pallas)
        return self.step.cached(
            key,
            lambda: jax.jit(self._build_succ(
                bucket, widths, vcap, with_merge, device_out)),
            bucket=bucket, vcap=vcap, widths=repr(widths),
            program="fused-successors",
        )

    def _build_guard(self, bucket: int):
        model, spec = self.model, self.spec
        bounds = self._bounds
        n_actions = len(model.actions)
        check_invariants = self.check_invariants

        def guards_one(state):  # kspec: traced
            parts = []
            for a in model.actions:
                choices = jnp.arange(a.n_choices, dtype=jnp.int32)
                ok = jax.vmap(lambda c, s=state, a=a: a.kernel(s, c)[0])(
                    choices
                )
                parts.append(ok)
            return jnp.concatenate(parts)

        def step(frontier, fvalid):  # kspec: traced
            states = jax.vmap(spec.unpack)(frontier)
            en_pre = jax.vmap(guards_one)(states)  # [B, C] predicate matrix
            ga = en_pre & fvalid[:, None]
            act_guard = jnp.stack(
                [
                    jnp.sum(ga[:, bounds[i]: bounds[i + 1]],
                            dtype=jnp.int32)
                    for i in range(n_actions)
                ]
            )
            deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
            viol_any, viol_idx = invariant_stage(
                model, states, fvalid, check_invariants
            )
            return (ga, act_guard, viol_any, viol_idx,
                    jnp.any(deadlocked), jnp.argmax(deadlocked))

        return step

    def _build_succ(self, bucket: int, widths: tuple, vcap: int,
                    with_merge: bool, device_out: bool):
        model, spec = self.model, self.spec
        K = spec.num_lanes
        offs = np.cumsum([0] + list(widths))
        W = int(offs[-1])
        use_pallas = self.step.use_pallas
        # static action-id column for the pooled layout
        actid_f = jnp.concatenate(
            [
                jnp.full((widths[i],), i, jnp.int32)
                for i in range(len(model.actions))
            ]
        )

        def step(frontier, sidx, chloc, rowvalid, vhi, vlo, vn):  # kspec: traced
            states = jax.vmap(spec.unpack)(frontier)
            gstate = jax.tree.map(lambda x: x[sidx], states)
            cand_parts, ok_parts = [], []
            for i, a in enumerate(model.actions):
                # kspec: allow(host-materialization) offs is the static
                # trace-time width table (np cumsum of Python ints), not
                # a traced value
                sl = slice(int(offs[i]), int(offs[i + 1]))
                ga = jax.tree.map(lambda x: x[sl], gstate)
                # guards are NOT re-evaluated: launch 1 proved every
                # pooled row enabled, so the kernel's own ok bit is
                # redundant here (same pure function, same inputs)
                _, nxt_a = jax.vmap(a.kernel)(ga, chloc[sl])
                ok_a = rowvalid[sl]
                if model.constraint is not None:
                    ok_a = ok_a & jax.vmap(model.constraint)(nxt_a)
                # pack per segment: only the K packed lanes are ever
                # concatenated, never the full unpacked state tree
                cand_parts.append(jax.vmap(spec.pack)(nxt_a))
                ok_parts.append(ok_a)
            ok = jnp.concatenate(ok_parts)
            cand = jnp.concatenate(cand_parts, axis=0)
            if not device_out:
                # host backend: validity is resolved at C speed on the
                # host (run_chunk compacts by the ok mask), so no device
                # squeeze scatter is needed at all
                hi, lo = fp_stage(cand, ok, spec, use_pallas)
                return cand, ok, hi, lo
            act_en = jnp.stack(
                [
                    # kspec: allow(host-materialization) static width table
                    jnp.sum(ok[int(offs[i]): int(offs[i + 1])],
                            dtype=jnp.int32)
                    for i in range(len(model.actions))
                ]
            )
            out, out_parent, out_act, rowvalid2, n_en, _ovf = squeeze_stage(
                cand, sidx, actid_f, ok, W, K
            )
            hi, lo = fp_stage(out, rowvalid2, spec, use_pallas)
            if with_merge:
                (out, out_parent, out_act, new_n, out_hi, out_lo,
                 vhi, vlo, vn) = sorted_dedup_stage(
                    out, out_parent, out_act, rowvalid2, hi, lo,
                    vhi, vlo, vn, vcap, W, K, with_merge,
                )
                return (out, out_parent, out_act, new_n, out_hi, out_lo,
                        vhi, vlo, vn, act_en)
            return (out, out_parent, out_act, n_en, hi, lo,
                    vhi, vlo, vn, act_en)

        return step

    # --- host glue --------------------------------------------------------
    def _compact(self, ga_np: np.ndarray, widths: tuple):
        """Stage 2, host half: C-speed stream compaction of the guard
        matrix into the pooled (state-index, choice) layout — replaces
        the legacy path's O(lattice) in-jit cumsum+scatter (measured
        ~13x cheaper on the flagship chunk) and preserves the legacy
        compact path's candidate order exactly (action-major, row-major
        within an action's [B, n_choices] slice)."""
        bounds = self._bounds
        W = int(sum(widths))
        sidx = np.zeros(W, np.int32)
        chloc = np.zeros(W, np.int32)
        rowvalid = np.zeros(W, bool)
        off = 0
        counts = []
        for i, w in enumerate(widths):
            na = int(bounds[i + 1] - bounds[i])
            idx = np.flatnonzero(
                ga_np[:, bounds[i]: bounds[i + 1]].ravel()
            )
            n = idx.size
            counts.append(n)
            sidx[off: off + n] = idx // na
            chloc[off: off + n] = idx % na
            rowvalid[off: off + n] = True
            off += w
        return sidx, chloc, rowvalid, counts

    # --- the chunk driver -------------------------------------------------
    def run_chunk(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        _h1, _h2, _h3, finalize = self.run_chunk_staged(
            piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
        )
        return finalize()

    def run_chunk_staged(self, piece, fp_n, bucket, depth,
                         vhi, vlo, vn, vcap, reset: bool = True):
        """Dispatch both fused launches; -> (vhi, vlo, vn, finalize).

        The guard matrix is forced here (its counts drive the host
        compaction that shapes launch 2), but launch 2's outputs stay
        in-flight: the overlap driver in check() dispatches chunk k+1's
        programs BEFORE calling chunk k's finalize(), so the host
        compaction/arena assembly of one chunk runs while the other's
        update-skeleton/dedup launch drains on device (the two-slot
        staging queue; docs/engine.md § Async execution).  finalize()
        blocks on the outputs and returns run_chunk's exact tuple —
        with overlap off check() finalizes immediately, which IS the
        historical serial behavior.  The returned visited refs chain the
        next chunk's dispatch on the device backend (functional, still
        in-flight — JAX async dispatch pipelines them)."""
        if not self._gate(bucket):
            return self.legacy.run_chunk_staged(
                piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
            )
        from .bfs import _pad_rows

        if reset:
            self.chunk_retry.reset_chunk()
        dispatched = 0  # successor programs actually dispatched,
        # retries included — what "launches" honestly means
        while True:
            try:
                # escalated=True on BOTH inject and handle: the fused
                # programs are the adaptive (escalated-shape) family, so
                # KSPEC_FAULT=compile_oom rehearses exactly this path's
                # degradation to legacy
                injected = self.fault.chunk_error(escalated=True)
                if injected is not None:
                    raise injected
                frontier = jnp.asarray(_pad_rows(piece, bucket))
                fvalid = jnp.arange(bucket) < fp_n
                (ga, act_guard, viol_any, viol_idx, dl_any, dl_idx
                 ) = self.guard_step(bucket)(frontier, fvalid)
                dispatched += 1  # launch 1: the guard matrix
                act_guard_np = np.asarray(act_guard, np.int64)
                widths = self.pool.widths_for(
                    bucket, act_guard_np.astype(np.float64), fp_n
                )
                sidx, chloc, rowvalid, _counts = self._compact(
                    np.asarray(ga), widths
                )
                outs = self.succ_step(bucket, widths, vcap)(
                    frontier, jnp.asarray(sidx), jnp.asarray(chloc),
                    jnp.asarray(rowvalid), vhi, vlo, vn,
                )
                dispatched += 1  # launch 2: the update skeleton
                if self.visited_backend != "host":
                    (out, out_parent, out_act, new_n, out_hi, out_lo,
                     vhi, vlo, vn, act_en) = outs
            except Exception as e:  # noqa: BLE001 — XLA compile/run
                # escalated=True: the fused programs are the adaptive
                # (escalated-shape) family, so a compile/alloc failure
                # degrades to the always-compilable legacy uniform path
                # for the rest of the run instead of re-raising
                action = self.chunk_retry.handle(
                    e, escalated=True, depth=depth
                )
                if action == "retry":
                    continue
                self.fallback = True
                from ..obs import tracer as _obs

                _obs.event("pipeline-fallback", depth=depth,
                           error=f"{type(e).__name__}: {e}"[:200])
                return self.legacy.run_chunk_staged(
                    piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
                )
            if self.visited_backend == "host":
                # host backend: validity is resolved at C speed on the
                # host — deferred into finalize so the np conversions
                # (the device-wait) land at commit time, off the next
                # chunk's dispatch path
                def finalize(outs=outs, sidx=sidx, widths=widths,
                             vhi=vhi, vlo=vlo, vn=vn,
                             act_guard_np=act_guard_np,
                             verdicts=(viol_any, viol_idx, dl_any,
                                       dl_idx),
                             dispatched=dispatched):
                    cand, ok, hi, lo = outs
                    viol_any, viol_idx, dl_any, dl_idx = verdicts
                    try:
                        # JAX async dispatch defers runtime errors to the
                        # first materialization — which is HERE, outside
                        # the dispatch-time try.  Route them through the
                        # same failure ladder: transients re-run the
                        # whole chunk synchronously; anything else
                        # degrades the run to legacy (the documented
                        # fused failure contract)
                        ok_np = np.asarray(ok)
                    except Exception as e:  # noqa: BLE001 — XLA runtime
                        action = self.chunk_retry.handle(
                            e, escalated=True, depth=depth
                        )
                        if action != "retry":
                            self.fallback = True
                            from ..obs import tracer as _obs

                            _obs.event(
                                "pipeline-fallback", depth=depth,
                                error=f"{type(e).__name__}: {e}"[:200],
                            )
                            return self.legacy.run_chunk(
                                piece, fp_n, bucket, depth,
                                vhi, vlo, vn, vcap,
                            )
                        # re-run the chunk WITHOUT resetting the
                        # per-chunk retry budget: handle() raises once
                        # it is exhausted, so the recursion is bounded
                        _r1, _r2, _r3, fin2 = self.run_chunk_staged(
                            piece, fp_n, bucket, depth, vhi, vlo, vn,
                            vcap, reset=False,
                        )
                        return fin2()
                    nn = int(ok_np.sum())
                    out = np.asarray(cand)[ok_np]
                    out_parent = sidx[ok_np]
                    out_act = self._actid_np(widths)[ok_np]
                    out_hi = np.asarray(hi)[ok_np]
                    out_lo = np.asarray(lo)[ok_np]
                    offs = np.cumsum([0] + list(widths))
                    act_en = np.asarray(
                        [
                            int(ok_np[offs[i]: offs[i + 1]].sum())
                            for i in range(len(widths))
                        ],
                        np.int64,
                    )
                    return (
                        out, out_parent, out_act, nn, vhi, vlo, vn,
                        viol_any, viol_idx, dl_any, dl_idx, act_en,
                        out_hi, out_lo, act_guard_np, dispatched,
                    )

                return vhi, vlo, vn, finalize
            committed = (
                out, out_parent, out_act, new_n, vhi, vlo, vn,
                viol_any, viol_idx, dl_any, dl_idx, act_en,
                out_hi, out_lo, act_guard_np, dispatched,
            )
            return vhi, vlo, vn, lambda: committed

    def _actid_np(self, widths: tuple) -> np.ndarray:
        return np.concatenate(
            [np.full(w, i, np.int32) for i, w in enumerate(widths)]
        )


def make_pipeline(name: str, *, step_builder, model, adapt, chunk_retry,
                  fault, check_invariants, visited_backend,
                  on_degrade_chunk, compact_shift, compact_gate):
    """Pipeline factory (the one interface check() builds against)."""
    if name == "legacy":
        return LegacyPipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
        )
    return FusedPipeline(
        step_builder, model, adapt, chunk_retry, fault,
        check_invariants, visited_backend, on_degrade_chunk,
        compact_shift, compact_gate,
    )


def warm_key(step_builder, model, key: tuple, vcap: int):
    """Re-compile one logged step-cache key at a new visited capacity —
    PreparedKernels.rewarm's per-key worker.  Returns the rebuilt key,
    or None when the key has no capacity component (guard kernels never
    evict on growth)."""
    tag = key[0]
    if tag == "step":
        (_t, bucket, _vcap, inv_sig, with_merge, compact, sq_full,
         _pallas) = key
        if inv_sig and inv_sig != tuple(
            i.name for i in model.invariants
        ):
            return None  # belongs to a sibling invariant overlay
        step = step_builder.get(
            bucket, vcap, bool(inv_sig),
            with_merge=with_merge, compact=compact, squeeze_full=sq_full,
        )
        K = model.spec.num_lanes
        out = step(
            jnp.zeros((bucket, K), jnp.uint32),
            jnp.zeros((bucket,), bool),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)
        return ("step", bucket, vcap, inv_sig, with_merge, compact,
                sq_full, step_builder.use_pallas)
    if tag == "fsc":
        (_t, bucket, _vcap, widths, with_merge, device_out, _pallas) = key
        pipe = FusedPipeline(
            step_builder, model, None, None, None,
            check_invariants=True,
            visited_backend=(
                "device" if with_merge
                else ("device-hash" if device_out else "host")
            ),
            on_degrade_chunk=None, compact_shift=2, compact_gate=4096,
        )
        fn = pipe.succ_step(bucket, widths, vcap)
        W = int(sum(widths))
        K = model.spec.num_lanes
        out = fn(
            jnp.zeros((bucket, K), jnp.uint32),
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), bool),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)
        return ("fsc", bucket, vcap, widths, with_merge, device_out,
                step_builder.use_pallas)
    return None
