"""Pluggable level-pipeline: the single-device engine's per-chunk stages
behind one interface, with two interchangeable expansion implementations.

Every BFS level runs each frontier chunk through the same five stages
(SURVEY.md §2.3; the sharded engine mirrors them per shard):

  1. expand       — evaluate action guards, produce candidate successors
  2. squeeze      — compact enabled candidates into a dense buffer
  3. fingerprint  — 64-bit fingerprints of the packed candidate rows
  4. dedup        — in-batch + visited-set novelty (backend-specific: the
                    in-jit sort/probe/merge for the device backend, the
                    HBM hash table or native host FpSet outside the jit)
  5. invariants   — predicate kernels over the frontier being expanded
  (6. trace record — host side: parent/action arrays per level, owned by
      :func:`..bfs.check` because it is pure host bookkeeping)

A *pipeline* is the object that owns stages 1-3 (+5) and how they are
fused into jitted programs; :func:`..bfs.check` drives it one chunk at a
time through :meth:`run_chunk`, which returns the same committed-output
contract for every implementation — so the level loop, the visited
backends, checkpointing, resource governance and trace recording are all
pipeline-agnostic.  Two implementations ship:

``legacy`` — the historical per-action path: one monolithic jitted step
  per (bucket, capacity) whose expansion runs one successor-kernel pass
  per action (O(actions) kernel launches per chunk), two-phase compaction
  under :class:`..bfs.AdaptiveCompact`, overflow-retry escalation.

``fused`` — the successor mega-kernel path (the default): per chunk,
  exactly TWO dispatched successor programs —

    launch 1 (``guard matrix``): ONE batched uniform kernel evaluates
      every action guard over the whole padded (frontier x choice)
      lattice — a single predicate matrix [B, C] — plus the frontier
      invariant predicates and deadlock detection (stage 5 rides along
      because it reads the same unpacked states).

    host glue: the predicate matrix is compacted at C speed with
      ``np.flatnonzero`` into ONE shared candidate buffer laid out as
      per-action segments at *data-driven* widths (sized from this
      chunk's exact guard counts + the run's high-water density — the
      update skeleton's shape is data, not code).  Because the exact
      enabled counts are known BEFORE the successor program is
      dispatched, the legacy path's overflow-retry machinery disappears:
      a chunk can never overflow its buffer, widths just grow
      monotonically along a power-of-two ladder.

    launch 2 (``update skeleton``): ONE batched program applies, over
      the one shared buffer, the uniform skeleton
      gather-state -> action update -> CONSTRAINT -> pack -> fingerprint
      (-> sort/probe/merge for the device backend).  Guards are NOT
      re-evaluated (launch 1 already proved every pooled row enabled),
      and the squeeze / pack / fingerprint stages that the legacy path
      ran once per action run exactly once.

  The fused path is bit-identical to the legacy path — same level
  counts, duplicate accounting, first-violation rule, and trace values —
  because the pooled buffer preserves the legacy compact path's
  candidate order (action-major, state-then-choice within an action) and
  all dedup stages consume candidates in that order.  Below the compact
  gate (small buckets, where the legacy path itself runs the full
  uncompacted lattice) the fused pipeline delegates chunks to the legacy
  implementation verbatim, so the whole run stays bit-identical at every
  bucket.  tests/test_pipeline.py pins this across the model matrix.

A third implementation collapses the chunk loop itself into the
accelerator:

``device`` — the device-resident level pipeline: a bounded
  ``lax.while_loop`` processes EVERY gated chunk of a level inside ONE
  dispatched program — guard-matrix expansion, in-jit segmented
  compaction (the per-action cumsum/scatter the fused path had moved
  to the host), fingerprints, intra-level dedup against a
  device-resident level-new sorted set, invariant/deadlock verdicts,
  and next-frontier assembly.  On the sorted-set device visited
  backend the program additionally probes the (read-only) visited set
  in-jit, folds the PR 9 (count, xor, sum) digests on device
  (ops/devlevel.py), and defers the O(capacity) visited merge to ONE
  rank-scatter per level instead of one per chunk (the level-new set's
  content equals exactly the states the serial path would have merged
  chunk-by-chunk).  On the HOST visited backend — the C-arena FpSet
  and its disk tier, the production-scale configuration — the device
  holds no visited set at all: the level's novel candidates come back
  in one transfer (rows + fingerprint lanes, chunk-major CANDIDATE
  order — the exact order the serial commit loop feeds the FpSet) and
  the visited probe/insert runs as ONE batched host call per level, so
  host syncs drop from O(chunks) to O(1) per level and the serial
  winner rule is preserved (a cross-chunk intra-level duplicate is
  caught by the level-new set with the earlier chunk winning — the
  same winner the serial per-chunk insert picks).  A level costs <=2
  successor launches TOTAL — one steady-state, two when a
  segment-width overflow forces a re-dispatch at exact measured widths
  — instead of the fused path's 2 per chunk.  Bit-identity with
  ``legacy`` holds chunk for chunk (same candidate order, same
  stable-sort winners, same verdict priority, same digest multisets;
  docs/engine.md § Device-resident level pipeline states the
  argument), and anything the device program cannot serve — the
  device-hash backend, sub-gate chunks, shadow re-execution, kernels
  without analyzer-proven field hulls (analysis.field_hulls), compile
  failure — degrades to ``fused`` via the documented ladder
  (device -> fused -> legacy).

Plugging a new stage implementation: subclass (or parallel-implement)
a pipeline with the same ``run_chunk`` contract and register it in
:data:`PIPELINES` (kafka_specification_tpu/pipeline_registry.py — the
jax-free registry the CLI validates against); the stage helpers in this
module (``squeeze_stage``, ``fp_stage``, ``sorted_dedup_stage``,
``invariant_stage``) are the building blocks the implementations
compose, and docs/engine.md walks through the interface.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dedup, devlevel
from ..ops.fingerprint import fingerprint_lanes
from ..pipeline_registry import (  # noqa: F401 — re-exported API
    PIPELINE_ENV,
    PIPELINE_REGISTRY,
    pipeline_names,
    resolve_pipeline,
)

#: registered pipeline names (resolve_pipeline validates against the
#: jax-free registry; kept as a tuple for the pre-registry callers)
PIPELINES = pipeline_names()


def key_vcap(key: tuple) -> Optional[int]:
    """The visited-capacity component of a step-cache key, or None for
    programs that don't embed the visited set (guard kernels).  Key
    shapes (engine.bfs._Step.get / FusedPipeline / DevicePipeline):

      ("step", bucket, vcap, inv_sig, with_merge, compact, sq_full, pallas)
      ("fgd",  bucket, inv_sig)                     — fused launch 1
      ("fsc",  bucket, vcap, widths, with_merge, device_out, pallas)
      ("dvl",  bucket, vcap, ncp, widths, ln, inv_sig, deadlock, pallas)
      ("dvh",  bucket, ncp, widths, ln, inv_sig, deadlock, pallas)
                 — the host-backend (deferred-probe) level program:
                   no vcap component, the program embeds no visited set
    """
    tag = key[0]
    if tag in ("step", "fsc", "dvl"):
        return key[2]
    return None


def evict_vcap(cache: dict, vcap: int) -> None:
    """Drop every step program compiled at an outgrown visited capacity
    — each is a full compiled program, dead weight in the
    Model-lifetime cache once growth is monotonic past it."""
    for k in [k for k in cache if key_vcap(k) == vcap]:
        del cache[k]


def grow_visited(vhi, vlo, vcap: int, need: int, cache: Optional[dict]
                 = None):
    """Grow the sorted visited pair set to the next power of two >=
    `need` (sentinel-padded) — the ONE growth policy for the per-chunk
    loop (engine/bfs.py) and the device level path.  When `cache` is
    given the outgrown capacity's programs are evicted immediately;
    pass None to defer eviction (the device path evicts only after a
    successful dispatch, so a growth followed by a compile failure
    leaves the per-chunk fallback's programs warm)."""
    from .bfs import _next_pow2

    new_cap = _next_pow2(need)
    pad = jnp.full(new_cap - vcap, 0xFFFFFFFF, jnp.uint32)
    vhi = jnp.concatenate([vhi, pad])
    vlo = jnp.concatenate([vlo, pad])
    if cache is not None:
        evict_vcap(cache, vcap)
    return vhi, vlo, new_cap


# --------------------------------------------------------------------------
# shared stage helpers (traced; composed by both pipelines)
# --------------------------------------------------------------------------


def squeeze_stage(cand, parent, actid, valid, width, K):  # kspec: traced
    """Stage 2: compact enabled candidate rows to the front of a `width`
    buffer; overflow=True iff more than `width` rows are enabled."""
    n_en = jnp.sum(valid, dtype=jnp.int32)
    spos = jnp.where(valid, jnp.cumsum(valid) - 1, width)
    out = jnp.zeros((width, K), jnp.uint32).at[spos].set(cand)
    out_parent = jnp.full((width,), -1, jnp.int32).at[spos].set(parent)
    out_act = jnp.full((width,), -1, jnp.int32).at[spos].set(actid)
    rowvalid = jnp.arange(width) < n_en
    return out, out_parent, out_act, rowvalid, n_en, n_en > width


def fp_stage(cand, valid, spec, use_pallas: bool):  # kspec: traced
    """Stage 3: masked (hi, lo) fingerprints (Pallas opt-in or jnp)."""
    sent = jnp.uint32(dedup.SENT)
    if use_pallas:
        import math

        from ..ops.pallas_fingerprint import fingerprint_pallas

        interp = jax.default_backend() == "cpu"
        rows = cand.shape[0]
        block = math.gcd(rows, 1 << 13)
        return fingerprint_pallas(cand, valid, block_rows=block,
                                  interpret=interp)
    hi, lo = fingerprint_lanes(cand, spec.exact64)
    return jnp.where(valid, hi, sent), jnp.where(valid, lo, sent)


def invariant_stage(model, states, fvalid, with_invariants: bool):  # kspec: traced
    """Stage 5: per-invariant (any-violated, first-index) on the frontier
    being expanded (each state checked exactly once, at expansion)."""
    if not (with_invariants and model.invariants):
        return jnp.stack([jnp.bool_(False)]), jnp.stack([jnp.int32(0)])
    if model.invariants_fused is not None:
        ok = jax.vmap(model.invariants_fused)(states)  # [B, n_inv]
        bad = fvalid[:, None] & ~ok
        return jnp.any(bad, axis=0), jnp.argmax(bad, axis=0)
    viol_any, viol_idx = [], []
    for inv in model.invariants:
        ok = jax.vmap(inv.pred)(states)
        bad = fvalid & ~ok
        viol_any.append(jnp.any(bad))
        viol_idx.append(jnp.argmax(bad))
    return jnp.stack(viol_any), jnp.stack(viol_idx)


def sorted_dedup_stage(cand, parent, actid, valid, hi, lo,  # kspec: traced
                       vhi, vlo, vn, vcap, T, K, with_merge: bool,
                       also_seen_in=None):
    """Stage 4 (device backend): minimal-payload lexsort, first-occurrence
    + visited-rank dedup, compaction of the new states to the front, and
    (with_merge) the rank-scatter merge into the sorted visited set.
    Identical primitive sequence to the legacy in-step version — winners
    are decided by the stable sort over the same candidate order, which
    is what keeps the pipelines trace-bit-identical; this helper is the
    ONE source of that winner-selection sequence (the fused update
    skeleton and the device level program both compose it).

    also_seen_in: optional second sorted pair set (hi, lo, n) that also
    disqualifies candidates from being new — the device pipeline probes
    its read-only visited set here while (vhi, vlo, vn) is the
    device-resident level-new set the compacted rank indexes into.  The
    trailing out_rank return (insertion ranks of the compacted prefix in
    the PRIMARY set) lets with_merge=False callers run their own gated
    merge_ranked."""
    sent = jnp.uint32(dedup.SENT)
    order = jnp.lexsort((lo, hi))
    hi_s, lo_s = hi[order], lo[order]
    invalid_s = (hi_s == sent) & (lo_s == sent)
    first = dedup.first_occurrence_mask(hi_s, lo_s, invalid_s)
    seen, rank = dedup.rank_sorted(vhi, vlo, vn, hi_s, lo_s)
    is_new = first & ~seen
    if also_seen_in is not None:
        a_hi, a_lo, a_n = also_seen_in
        a_seen, _ar = dedup.rank_sorted(a_hi, a_lo, a_n, hi_s, lo_s)
        is_new = is_new & ~a_seen
    pos = jnp.where(is_new, jnp.cumsum(is_new) - 1, T)
    out = jnp.zeros((T, K), jnp.uint32).at[pos].set(cand[order])
    out_parent = jnp.full((T,), -1, jnp.int32).at[pos].set(parent[order])
    out_act = jnp.full((T,), -1, jnp.int32).at[pos].set(actid[order])
    out_hi = jnp.full((T,), sent).at[pos].set(hi_s)
    out_lo = jnp.full((T,), sent).at[pos].set(lo_s)
    out_rank = jnp.zeros((T,), jnp.int32).at[pos].set(rank)
    new_n = jnp.sum(is_new, dtype=jnp.int32)
    if with_merge:
        vhi, vlo, vn = dedup.merge_ranked(
            vhi, vlo, vn, out_hi, out_lo, out_rank, new_n, vcap
        )
    return (out, out_parent, out_act, new_n, out_hi, out_lo,
            vhi, vlo, vn, out_rank)


def candidate_dedup_stage(cand, parent, actid, valid, hi, lo,  # kspec: traced
                          lhi, llo, ln, T, K):
    """Stage 4 for the DEFERRED-probe host backends: intra-level novelty
    with the compacted novel prefix emitted in CANDIDATE order.

    Winners are elected by the SAME stable lexsort sequence as
    :func:`sorted_dedup_stage` (first occurrence among equal
    fingerprints in candidate order — exactly the row the serial host
    commit's first-come FpSet insert keeps), but the novel prefix is
    emitted in CANDIDATE order, because that is the order the serial
    per-chunk host path hands rows to the FpSet: the deferred batched
    probe replays the level in chunk-major candidate order, so the
    committed arena contents — rows, parents, action ids, and hence
    next-level chunk boundaries and trace values — are byte-identical
    to the serial path's.  (lhi, llo, ln) is the device-resident
    level-new sorted set; the sorted view (n_hi/n_lo/n_rank) feeds its
    gated merge exactly as sorted_dedup_stage's outputs do.  States
    already in the VISITED set are deliberately still emitted here —
    the device holds no visited set in this mode; the host's
    once-per-level batched probe filters them, which is the same
    novelty decision the serial per-chunk insert makes, one level
    later in wall time and with O(1) host syncs instead of O(chunks).

    Returns (out, out_parent, out_act, out_hi, out_lo, new_n,
    n_hi, n_lo, n_rank)."""
    sent = jnp.uint32(dedup.SENT)
    order = jnp.lexsort((lo, hi))
    hi_s, lo_s = hi[order], lo[order]
    invalid_s = (hi_s == sent) & (lo_s == sent)
    first = dedup.first_occurrence_mask(hi_s, lo_s, invalid_s)
    seen, rank = dedup.rank_sorted(lhi, llo, ln, hi_s, lo_s)
    is_new = first & ~seen
    # sorted-order compaction: what the level-new merge consumes
    pos_s = jnp.where(is_new, jnp.cumsum(is_new) - 1, T)
    n_hi = jnp.full((T,), sent).at[pos_s].set(hi_s)
    n_lo = jnp.full((T,), sent).at[pos_s].set(lo_s)
    n_rank = jnp.zeros((T,), jnp.int32).at[pos_s].set(rank)
    new_n = jnp.sum(is_new, dtype=jnp.int32)
    # candidate-order compaction: scatter the sorted novelty decisions
    # back to candidate positions, then compact without re-sorting
    isnew_c = jnp.zeros((T,), bool).at[order].set(is_new)
    pos_c = jnp.where(isnew_c, jnp.cumsum(isnew_c) - 1, T)
    out = jnp.zeros((T, K), jnp.uint32).at[pos_c].set(cand)
    out_parent = jnp.full((T,), -1, jnp.int32).at[pos_c].set(parent)
    out_act = jnp.full((T,), -1, jnp.int32).at[pos_c].set(actid)
    out_hi = jnp.full((T,), sent).at[pos_c].set(hi)
    out_lo = jnp.full((T,), sent).at[pos_c].set(lo)
    return (out, out_parent, out_act, out_hi, out_lo, new_n,
            n_hi, n_lo, n_rank)


# --------------------------------------------------------------------------
# legacy pipeline: the per-action monolithic step + overflow escalation
# --------------------------------------------------------------------------


class LegacyPipeline:
    """The historical per-action expansion behind the pipeline interface:
    one monolithic jitted step per (bucket, vcap) running one successor
    pass per action, with AdaptiveCompact's two-phase compaction and the
    overflow-retry/escalation ladder (moved verbatim from check()'s inner
    loop).  Kernel launches per chunk: O(actions)."""

    name = "legacy"

    def __init__(self, step_builder, model, adapt, chunk_retry, fault,
                 check_invariants: bool, visited_backend: str,
                 on_degrade_chunk):
        self.step = step_builder
        self.model = model
        self.adapt = adapt
        self.chunk_retry = chunk_retry
        self.fault = fault
        self.check_invariants = check_invariants
        self.visited_backend = visited_backend
        self.on_degrade_chunk = on_degrade_chunk
        self.squeeze_full = False  # sticky pre-sort-squeeze overflow relief
        self.compile_fallback = False

    @property
    def launches_per_chunk(self) -> int:
        """Successor-kernel passes dispatched per chunk: one per action
        (the per-action phase-B evaluation; TODO.md's '12 DNF action
        kernels vs hand's 9')."""
        return len(self.model.actions)

    def run_chunk(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        from .bfs import _pad_rows  # cycle-free: bfs imports us lazily

        adapt = self.adapt
        compact_arg = adapt.widths_for(bucket)
        attempt_sq_full = self.squeeze_full
        self.chunk_retry.reset_chunk()
        dispatched = 0  # successor-kernel passes actually dispatched,
        # overflow/retry re-dispatches included
        while True:
            try:
                injected = self.fault.chunk_error(
                    escalated=isinstance(compact_arg, (list, tuple))
                )
                if injected is not None:
                    raise injected
                step = self.step.get(
                    bucket,
                    vcap,
                    self.check_invariants,
                    with_merge=self.visited_backend == "device",
                    compact=compact_arg,
                    squeeze_full=attempt_sq_full,
                )
                (
                    out, out_parent, out_act, new_n, vhi_n, vlo_n, vn_n,
                    viol_any, viol_idx, dl_any, dl_idx, act_en,
                    out_hi, out_lo, overflow, act_guard,
                ) = step(
                    jnp.asarray(_pad_rows(piece, bucket)),
                    jnp.arange(bucket) < fp_n,
                    vhi,
                    vlo,
                    vn,
                )
                dispatched += self.launches_per_chunk
            except Exception as e:  # noqa: BLE001 — XLA compile/run
                # known failure ladder — one policy for both engines
                # (resilience.retry.ChunkRetryHandler); see check()'s
                # docstring for the degradation contract
                action = self.chunk_retry.handle(
                    e,
                    escalated=isinstance(compact_arg, (list, tuple)),
                    depth=depth,
                )
                if action == "retry":
                    continue
                if action == "degrade_chunk":
                    self.on_degrade_chunk()
                compact_arg = adapt.compile_fallback(bucket)
                self.compile_fallback = True
                continue
            ovf = np.asarray(overflow)
            if compact_arg is None or not ovf.any():
                vhi, vlo, vn = vhi_n, vlo_n, vn_n
                break
            # retry this chunk with the offending buffers widened: a
            # per-action compact overflow doubles that action's width
            # (floored for the rest of the run); a squeeze overflow
            # disables the pre-sort width reduction (sticky); a
            # uniform-shift overflow escalates to measured widths
            if ovf[-1]:
                attempt_sq_full = self.squeeze_full = True
            if ovf[:-1].any():
                compact_arg = adapt.escalate(
                    compact_arg,
                    ovf[:-1],
                    bucket,
                    np.asarray(act_guard, np.int64) / max(fp_n, 1),
                )
        # adapt buffer sizing from the committed attempt's PRE-constraint
        # guard counts (what the buffers actually hold; act_en is
        # post-constraint and undercounts on pruning models)
        adapt.observe(np.asarray(act_guard, np.int64) / max(fp_n, 1))
        return (
            out, out_parent, out_act, new_n, vhi, vlo, vn,
            viol_any, viol_idx, dl_any, dl_idx, act_en,
            out_hi, out_lo, act_guard, dispatched,
        )

    def run_chunk_staged(self, piece, fp_n, bucket, depth,
                         vhi, vlo, vn, vcap):
        """Staged form of :meth:`run_chunk` for the overlap driver:
        -> (vhi, vlo, vn, finalize).  The legacy path must read its
        overflow flags before committing (the retry ladder), which
        forces the whole program — so its dispatch is already complete
        and finalize is a no-op closure over the committed tuple.  The
        overlap win for legacy chunks is therefore only the reordering
        of host commits, never deferred device work (docs/engine.md)."""
        outs = self.run_chunk(piece, fp_n, bucket, depth, vhi, vlo, vn,
                              vcap)
        return outs[4], outs[5], outs[6], lambda: outs


# --------------------------------------------------------------------------
# fused pipeline: guard matrix + pooled update skeleton (2 launches)
# --------------------------------------------------------------------------


class PooledWidths:
    """Data-driven sizing of the fused path's shared candidate buffer.

    Each action owns one segment of the pooled buffer; its width rides a
    power-of-two ladder (floor 256 for Pallas block alignment, capped at
    the action's full lattice width) sized from max(this chunk's EXACT
    guard count, the run's high-water per-state density x bucket x 1.35
    headroom).  Exact counts are known before the successor program is
    dispatched (launch 1 already ran), so a chunk can never overflow its
    segment — the ladder only climbs, keeping the set of compiled width
    vectors small and, across runs of the same shape, deterministic
    (warm serving runs replay the same keys; PreparedKernels)."""

    HEADROOM = 1.35

    def __init__(self, actions):
        self.actions = actions
        self.hw = np.zeros(len(actions), np.float64)  # density high-water

    @staticmethod
    def _rung(need: int) -> int:
        """Smallest half-octave rung >= need: {0.75 * 2^k, 2^k} rounded to
        the 256-row fingerprint-block alignment.  Two rungs per octave
        keeps the mean padding ~1.2x (vs ~1.5x for plain pow2) while the
        monotone ladder still bounds the number of compiled width
        vectors per run."""
        from .bfs import _next_pow2, _round256

        p = _next_pow2(need)
        q = _round256((3 * p) >> 2)
        return q if q >= need else _round256(p)

    def widths_for(self, bucket: int, counts: np.ndarray,
                   fp_n: int) -> tuple:
        from .bfs import _round256

        self.hw = np.maximum(self.hw, counts / max(fp_n, 1))
        out = []
        for a, hw, count in zip(self.actions, self.hw, counts):
            cap = _round256(bucket * a.n_choices)
            need = max(256, int(count), int(self.HEADROOM * hw * bucket))
            out.append(min(cap, self._rung(need)))
        return tuple(out)


class FusedPipeline:
    """Successor mega-kernels: 2 dispatched programs per chunk (guard
    matrix -> host flatnonzero compaction -> update skeleton), bit-
    identical to the legacy path (module docstring).  Chunks below the
    compact gate delegate to the legacy pipeline verbatim — the legacy
    path runs the full uncompacted lattice there, and matching it
    instruction-for-instruction is what keeps whole runs bit-identical
    at every bucket."""

    name = "fused"
    launches_per_chunk = 2

    def __init__(self, step_builder, model, adapt, chunk_retry, fault,
                 check_invariants: bool, visited_backend: str,
                 on_degrade_chunk, compact_shift: int, compact_gate: int):
        self.step = step_builder
        self.model = model
        self.spec = model.spec
        self.chunk_retry = chunk_retry
        self.fault = fault
        self.check_invariants = check_invariants
        self.visited_backend = visited_backend
        self.compact_shift = compact_shift
        self.compact_gate = compact_gate
        self.pool = PooledWidths(model.actions)
        self.fallback = False  # sticky: a failed fused compile pins legacy
        self.legacy = LegacyPipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
        )
        self.adapt = adapt
        self._bounds = np.cumsum(
            [0] + [a.n_choices for a in model.actions]
        )

    def _gate(self, bucket: int) -> bool:
        """Fused engages exactly where the legacy path would compact
        (same gate, same shift test) — below it the candidate order is
        the full lattice's state-major order, which only the legacy full
        path produces."""
        return (
            not self.fallback
            and self.compact_shift > 0
            and bucket >= self.compact_gate
            and (bucket >> self.compact_shift) >= 1
        )

    # --- jitted launches (cached on the model's step cache) ---------------
    def guard_step(self, bucket: int):
        """Launch 1: guard predicate matrix + invariants + deadlock.
        The invariant component of the key comes from _Step.inv_sig —
        the SAME source the legacy "step" keys use, so fused and legacy
        programs of one invariant-overlay view stay in lockstep in the
        shared per-base step cache (service/kernel_cache.py)."""
        key = ("fgd", bucket, self.step.inv_sig(self.check_invariants))
        return self.step.cached(
            key, lambda: jax.jit(self._build_guard(bucket)),
            bucket=bucket, program="fused-guards",
        )

    def succ_step(self, bucket: int, widths: tuple, vcap: int):
        """Launch 2: the pooled update skeleton (+ device dedup)."""
        with_merge = self.visited_backend == "device"
        device_out = self.visited_backend != "host"
        key = ("fsc", bucket, vcap, widths, with_merge, device_out,
               self.step.use_pallas)
        return self.step.cached(
            key,
            lambda: jax.jit(self._build_succ(
                bucket, widths, vcap, with_merge, device_out)),
            bucket=bucket, vcap=vcap, widths=repr(widths),
            program="fused-successors",
        )

    def _build_guard(self, bucket: int):
        model, spec = self.model, self.spec
        bounds = self._bounds
        n_actions = len(model.actions)
        check_invariants = self.check_invariants

        def guards_one(state):  # kspec: traced
            parts = []
            for a in model.actions:
                choices = jnp.arange(a.n_choices, dtype=jnp.int32)
                ok = jax.vmap(lambda c, s=state, a=a: a.kernel(s, c)[0])(
                    choices
                )
                parts.append(ok)
            return jnp.concatenate(parts)

        def step(frontier, fvalid):  # kspec: traced
            states = jax.vmap(spec.unpack)(frontier)
            en_pre = jax.vmap(guards_one)(states)  # [B, C] predicate matrix
            ga = en_pre & fvalid[:, None]
            act_guard = jnp.stack(
                [
                    jnp.sum(ga[:, bounds[i]: bounds[i + 1]],
                            dtype=jnp.int32)
                    for i in range(n_actions)
                ]
            )
            deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
            viol_any, viol_idx = invariant_stage(
                model, states, fvalid, check_invariants
            )
            return (ga, act_guard, viol_any, viol_idx,
                    jnp.any(deadlocked), jnp.argmax(deadlocked))

        return step

    def _build_succ(self, bucket: int, widths: tuple, vcap: int,
                    with_merge: bool, device_out: bool):
        model, spec = self.model, self.spec
        K = spec.num_lanes
        offs = np.cumsum([0] + list(widths))
        W = int(offs[-1])
        use_pallas = self.step.use_pallas
        # static action-id column for the pooled layout
        actid_f = jnp.concatenate(
            [
                jnp.full((widths[i],), i, jnp.int32)
                for i in range(len(model.actions))
            ]
        )

        def step(frontier, sidx, chloc, rowvalid, vhi, vlo, vn):  # kspec: traced
            states = jax.vmap(spec.unpack)(frontier)
            gstate = jax.tree.map(lambda x: x[sidx], states)
            cand_parts, ok_parts = [], []
            for i, a in enumerate(model.actions):
                # kspec: allow(host-materialization) offs is the static
                # trace-time width table (np cumsum of Python ints), not
                # a traced value
                sl = slice(int(offs[i]), int(offs[i + 1]))
                ga = jax.tree.map(lambda x: x[sl], gstate)
                # guards are NOT re-evaluated: launch 1 proved every
                # pooled row enabled, so the kernel's own ok bit is
                # redundant here (same pure function, same inputs)
                _, nxt_a = jax.vmap(a.kernel)(ga, chloc[sl])
                ok_a = rowvalid[sl]
                if model.constraint is not None:
                    ok_a = ok_a & jax.vmap(model.constraint)(nxt_a)
                # pack per segment: only the K packed lanes are ever
                # concatenated, never the full unpacked state tree
                cand_parts.append(jax.vmap(spec.pack)(nxt_a))
                ok_parts.append(ok_a)
            ok = jnp.concatenate(ok_parts)
            cand = jnp.concatenate(cand_parts, axis=0)
            if not device_out:
                # host backend: validity is resolved at C speed on the
                # host (run_chunk compacts by the ok mask), so no device
                # squeeze scatter is needed at all
                hi, lo = fp_stage(cand, ok, spec, use_pallas)
                return cand, ok, hi, lo
            act_en = jnp.stack(
                [
                    # kspec: allow(host-materialization) static width table
                    jnp.sum(ok[int(offs[i]): int(offs[i + 1])],
                            dtype=jnp.int32)
                    for i in range(len(model.actions))
                ]
            )
            out, out_parent, out_act, rowvalid2, n_en, _ovf = squeeze_stage(
                cand, sidx, actid_f, ok, W, K
            )
            hi, lo = fp_stage(out, rowvalid2, spec, use_pallas)
            if with_merge:
                (out, out_parent, out_act, new_n, out_hi, out_lo,
                 vhi, vlo, vn, _rank) = sorted_dedup_stage(
                    out, out_parent, out_act, rowvalid2, hi, lo,
                    vhi, vlo, vn, vcap, W, K, with_merge,
                )
                return (out, out_parent, out_act, new_n, out_hi, out_lo,
                        vhi, vlo, vn, act_en)
            return (out, out_parent, out_act, n_en, hi, lo,
                    vhi, vlo, vn, act_en)

        return step

    # --- host glue --------------------------------------------------------
    def _compact(self, ga_np: np.ndarray, widths: tuple):
        """Stage 2, host half: C-speed stream compaction of the guard
        matrix into the pooled (state-index, choice) layout — replaces
        the legacy path's O(lattice) in-jit cumsum+scatter (measured
        ~13x cheaper on the flagship chunk) and preserves the legacy
        compact path's candidate order exactly (action-major, row-major
        within an action's [B, n_choices] slice)."""
        bounds = self._bounds
        W = int(sum(widths))
        sidx = np.zeros(W, np.int32)
        chloc = np.zeros(W, np.int32)
        rowvalid = np.zeros(W, bool)
        off = 0
        counts = []
        for i, w in enumerate(widths):
            na = int(bounds[i + 1] - bounds[i])
            idx = np.flatnonzero(
                ga_np[:, bounds[i]: bounds[i + 1]].ravel()
            )
            n = idx.size
            counts.append(n)
            sidx[off: off + n] = idx // na
            chloc[off: off + n] = idx % na
            rowvalid[off: off + n] = True
            off += w
        return sidx, chloc, rowvalid, counts

    # --- the chunk driver -------------------------------------------------
    def run_chunk(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        _h1, _h2, _h3, finalize = self.run_chunk_staged(
            piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
        )
        return finalize()

    def run_chunk_staged(self, piece, fp_n, bucket, depth,
                         vhi, vlo, vn, vcap, reset: bool = True):
        """Dispatch both fused launches; -> (vhi, vlo, vn, finalize).

        The guard matrix is forced here (its counts drive the host
        compaction that shapes launch 2), but launch 2's outputs stay
        in-flight: the overlap driver in check() dispatches chunk k+1's
        programs BEFORE calling chunk k's finalize(), so the host
        compaction/arena assembly of one chunk runs while the other's
        update-skeleton/dedup launch drains on device (the two-slot
        staging queue; docs/engine.md § Async execution).  finalize()
        blocks on the outputs and returns run_chunk's exact tuple —
        with overlap off check() finalizes immediately, which IS the
        historical serial behavior.  The returned visited refs chain the
        next chunk's dispatch on the device backend (functional, still
        in-flight — JAX async dispatch pipelines them)."""
        if not self._gate(bucket):
            return self.legacy.run_chunk_staged(
                piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
            )
        from .bfs import _pad_rows

        if reset:
            self.chunk_retry.reset_chunk()
        dispatched = 0  # successor programs actually dispatched,
        # retries included — what "launches" honestly means
        while True:
            try:
                # escalated=True on BOTH inject and handle: the fused
                # programs are the adaptive (escalated-shape) family, so
                # KSPEC_FAULT=compile_oom rehearses exactly this path's
                # degradation to legacy
                injected = self.fault.chunk_error(escalated=True)
                if injected is not None:
                    raise injected
                frontier = jnp.asarray(_pad_rows(piece, bucket))
                fvalid = jnp.arange(bucket) < fp_n
                (ga, act_guard, viol_any, viol_idx, dl_any, dl_idx
                 ) = self.guard_step(bucket)(frontier, fvalid)
                dispatched += 1  # launch 1: the guard matrix
                act_guard_np = np.asarray(act_guard, np.int64)
                widths = self.pool.widths_for(
                    bucket, act_guard_np.astype(np.float64), fp_n
                )
                sidx, chloc, rowvalid, _counts = self._compact(
                    np.asarray(ga), widths
                )
                outs = self.succ_step(bucket, widths, vcap)(
                    frontier, jnp.asarray(sidx), jnp.asarray(chloc),
                    jnp.asarray(rowvalid), vhi, vlo, vn,
                )
                dispatched += 1  # launch 2: the update skeleton
                if self.visited_backend != "host":
                    (out, out_parent, out_act, new_n, out_hi, out_lo,
                     vhi, vlo, vn, act_en) = outs
            except Exception as e:  # noqa: BLE001 — XLA compile/run
                # escalated=True: the fused programs are the adaptive
                # (escalated-shape) family, so a compile/alloc failure
                # degrades to the always-compilable legacy uniform path
                # for the rest of the run instead of re-raising
                action = self.chunk_retry.handle(
                    e, escalated=True, depth=depth
                )
                if action == "retry":
                    continue
                self.fallback = True
                from ..obs import tracer as _obs

                _obs.event("pipeline-fallback", depth=depth,
                           error=f"{type(e).__name__}: {e}"[:200])
                return self.legacy.run_chunk_staged(
                    piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
                )
            if self.visited_backend == "host":
                # host backend: validity is resolved at C speed on the
                # host — deferred into finalize so the np conversions
                # (the device-wait) land at commit time, off the next
                # chunk's dispatch path
                def finalize(outs=outs, sidx=sidx, widths=widths,
                             vhi=vhi, vlo=vlo, vn=vn,
                             act_guard_np=act_guard_np,
                             verdicts=(viol_any, viol_idx, dl_any,
                                       dl_idx),
                             dispatched=dispatched):
                    cand, ok, hi, lo = outs
                    viol_any, viol_idx, dl_any, dl_idx = verdicts
                    try:
                        # JAX async dispatch defers runtime errors to the
                        # first materialization — which is HERE, outside
                        # the dispatch-time try.  Route them through the
                        # same failure ladder: transients re-run the
                        # whole chunk synchronously; anything else
                        # degrades the run to legacy (the documented
                        # fused failure contract)
                        ok_np = np.asarray(ok)
                    except Exception as e:  # noqa: BLE001 — XLA runtime
                        action = self.chunk_retry.handle(
                            e, escalated=True, depth=depth
                        )
                        if action != "retry":
                            self.fallback = True
                            from ..obs import tracer as _obs

                            _obs.event(
                                "pipeline-fallback", depth=depth,
                                error=f"{type(e).__name__}: {e}"[:200],
                            )
                            return self.legacy.run_chunk(
                                piece, fp_n, bucket, depth,
                                vhi, vlo, vn, vcap,
                            )
                        # re-run the chunk WITHOUT resetting the
                        # per-chunk retry budget: handle() raises once
                        # it is exhausted, so the recursion is bounded
                        _r1, _r2, _r3, fin2 = self.run_chunk_staged(
                            piece, fp_n, bucket, depth, vhi, vlo, vn,
                            vcap, reset=False,
                        )
                        return fin2()
                    nn = int(ok_np.sum())
                    out = np.asarray(cand)[ok_np]
                    out_parent = sidx[ok_np]
                    out_act = self._actid_np(widths)[ok_np]
                    out_hi = np.asarray(hi)[ok_np]
                    out_lo = np.asarray(lo)[ok_np]
                    offs = np.cumsum([0] + list(widths))
                    act_en = np.asarray(
                        [
                            int(ok_np[offs[i]: offs[i + 1]].sum())
                            for i in range(len(widths))
                        ],
                        np.int64,
                    )
                    return (
                        out, out_parent, out_act, nn, vhi, vlo, vn,
                        viol_any, viol_idx, dl_any, dl_idx, act_en,
                        out_hi, out_lo, act_guard_np, dispatched,
                    )

                return vhi, vlo, vn, finalize
            committed = (
                out, out_parent, out_act, new_n, vhi, vlo, vn,
                viol_any, viol_idx, dl_any, dl_idx, act_en,
                out_hi, out_lo, act_guard_np, dispatched,
            )
            return vhi, vlo, vn, lambda: committed

    def _actid_np(self, widths: tuple) -> np.ndarray:
        return np.concatenate(
            [np.full(w, i, np.int32) for i, w in enumerate(widths)]
        )


# --------------------------------------------------------------------------
# device pipeline: the whole level as one dispatched program
# --------------------------------------------------------------------------


def device_hull_fallback(model) -> Optional[str]:
    """The field-hull HARD precondition shared by every device-resident
    level path (single-device DevicePipeline and the sharded per-shard
    variant): every field's proven reachable-value hull must sit inside
    its declared packed range.  Stricter than the engine's KSPEC_ANALYZE
    gate on purpose — the gate can be env-disabled, this cannot: a
    device-resident level has no host visibility between chunks, so the
    pack stage's no-truncation property must be PROVEN, not assumed.
    Returns None when proven, else the human-readable fallback reason."""
    from ..analysis.interval import AnalysisUnsupported

    try:
        from ..analysis import field_hulls

        hulls = field_hulls(model, strict=True)
    except AnalysisUnsupported as e:
        return f"no proven field hulls ({e})"
    except Exception as e:  # noqa: BLE001 — never break checking
        return (
            f"field-hull analysis failed "
            f"({type(e).__name__}: {e})"[:200]
        )
    bad = [
        f.name
        for f in model.spec.fields
        if hulls[f.name][0] < f.lo or hulls[f.name][1] > f.hi
    ]
    if bad:
        return (
            f"field hull escapes the declared packed range for "
            f"{bad} (encoding-unsound model; KSPEC_ANALYZE=0?)"
        )
    return None


class DevicePipeline:
    """Device-resident level pipeline (module docstring): one dispatched
    ``lax.while_loop`` program runs every gated chunk of a BFS level —
    <=2 successor launches per LEVEL.  Two native backends:

    - sorted-set ``device``: in-jit dual-probe dedup (read-only visited
      set + level-new set), the visited-set merge deferred to one
      rank-scatter per level, in-jit digest folds;
    - ``host`` (incl. the disk tier): deferred-probe mode — the device
      holds NO visited set, intra-level novelty is decided against the
      level-new sorted set alone, and the level's novel candidates come
      back (rows + fingerprint lanes, chunk-major CANDIDATE order) for
      ONE batched host FpSet / tiered-run probe per level
      (engine.bfs._commit_device_level) — host syncs drop from
      O(chunks) to O(1) per level on the production backend.

    Both require analyzer-proven per-field value hulls
    (analysis.field_hulls: the in-jit pack stage runs with no host-side
    validation between chunks, so the no-truncation proof is a hard
    precondition here, independent of the KSPEC_ANALYZE build-gate
    toggle); everything else — ``device-hash``, sub-gate chunks, shadow
    re-execution, and any compile/dispatch failure — degrades to the
    ``fused`` per-chunk path, which itself degrades to ``legacy`` (the
    documented ladder)."""

    name = "device"
    launches_per_chunk = 2  # nominal figure when delegating per-chunk

    def __init__(self, step_builder, model, adapt, chunk_retry, fault,
                 check_invariants: bool, visited_backend: str,
                 on_degrade_chunk, compact_shift: int, compact_gate: int,
                 check_deadlock: bool = False):
        self.step = step_builder
        self.model = model
        self.spec = model.spec
        self.chunk_retry = chunk_retry
        self.fault = fault
        self.check_invariants = check_invariants
        self.check_deadlock = check_deadlock
        self.visited_backend = visited_backend
        self.fused = FusedPipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
            compact_shift, compact_gate,
        )
        self.pool = PooledWidths(model.actions)
        self._ln_hw = 0  # per-level new-state high water (LN ladder)
        #: sticky fallback reason; None while the level path is live
        self.device_fallback: Optional[str] = None
        self.device_levels = 0  # levels actually run device-resident
        #: deferred-probe mode (host / disk-tier visited backends): the
        #: level program carries NO visited set — intra-level novelty
        #: against the level-new sorted set only, and the host probes
        #: the level's novel candidates in ONE batched call per level
        #: (engine.bfs._commit_device_level's host branch)
        self.host_mode = visited_backend == "host"
        from ..pipeline_registry import backend_fallback_reason

        # the registry's per-backend support matrix is the ONE source of
        # which backends this pipeline serves natively; unsupported
        # cells degrade with the registry's own (backend-naming) reason
        self.device_fallback = backend_fallback_reason(
            "device", visited_backend
        )
        if self.device_fallback is None:
            self._check_hulls()

    def _check_hulls(self) -> None:
        """The field-hull precondition (:func:`device_hull_fallback` —
        one shared check with the sharded device-resident variant)."""
        self.device_fallback = device_hull_fallback(self.model)

    # --- per-chunk interface: delegate to the fused ladder ----------------
    @property
    def fallback(self) -> bool:
        """fused->legacy degradation flag (stats['pipeline_fallback']
        keeps its historical meaning; the device->fused step is
        reported separately via device_fallback)."""
        return self.fused.fallback

    @property
    def legacy(self):
        return self.fused.legacy

    def _gate(self, bucket: int) -> bool:
        return self.fused._gate(bucket)

    def run_chunk(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        return self.fused.run_chunk(
            piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
        )

    def run_chunk_staged(self, piece, fp_n, bucket, depth,
                         vhi, vlo, vn, vcap):
        return self.fused.run_chunk_staged(
            piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
        )

    # --- the whole-level path ---------------------------------------------
    def plan_level(self, f_total: int, chunk: int, min_bucket: int):
        """-> (bucket, n_chunks, rows_handled) when the device program
        can serve (a prefix of) this level, else None.

        The plan mirrors the serial chunking EXACTLY: full chunks run at
        bucket == chunk; a trailing partial chunk joins the dispatch iff
        the serial loop would have taken the compacted (gated) path for
        it — a sub-gate tail instead runs through the per-chunk ladder
        after the device dispatch, preserving the legacy full-lattice
        candidate order the gate exists to protect (bit-identity)."""
        from .bfs import _next_pow2

        if self.device_fallback is not None or self.fused.fallback:
            return None
        if f_total <= 0:
            return None
        if f_total <= chunk:
            B = _next_pow2(max(f_total, min_bucket))
            return (B, 1, f_total) if self.fused._gate(B) else None
        if not self.fused._gate(chunk):
            return None
        n_full, rem = divmod(f_total, chunk)
        nc, handled = n_full, n_full * chunk
        if rem and self.fused._gate(_next_pow2(max(rem, min_bucket))):
            nc += 1
            handled = f_total
        return (chunk, nc, handled)

    def _level_program(self, B: int, NCp: int, vcap: int, widths: tuple,
                       LN: int):
        if self.host_mode:
            # no vcap component: the program embeds no visited set, so
            # capacity growth can never evict it (key_vcap -> None)
            key = ("dvh", B, NCp, widths, LN,
                   self.step.inv_sig(self.check_invariants),
                   self.check_deadlock, self.step.use_pallas)
            return self.step.cached(
                key,
                lambda: jax.jit(
                    self._build_level_host(B, NCp, widths, LN)
                ),
                bucket=B, chunks=NCp, widths=repr(widths),
                level_new_cap=LN, program="device-level-host",
            )
        key = ("dvl", B, vcap, NCp, widths, LN,
               self.step.inv_sig(self.check_invariants),
               self.check_deadlock, self.step.use_pallas)
        return self.step.cached(
            key,
            lambda: jax.jit(
                self._build_level(B, NCp, vcap, widths, LN)
            ),
            bucket=B, vcap=vcap, chunks=NCp, widths=repr(widths),
            level_new_cap=LN, program="device-level",
        )

    def _build_level(self, B: int, NCp: int, vcap: int, widths: tuple,
                     LN: int):
        """The whole-level program: while_loop over chunk index.

        Bit-identity argument (vs the serial fused/legacy chunk loop):
        every chunk runs the SAME compacted expansion (make_expand's
        per-action in-jit cumsum/scatter — action-major, row-major
        within an action, the exact candidate order the fused host
        compaction preserves), the same squeeze/fingerprint/stable-
        lexsort stages, and novelty against (visited ∪ level-new) ==
        the serial path's chunk-by-chunk merged visited set; winners of
        equal fingerprints are decided by the same stable sort over the
        same candidate order.  Chunks run at the full static bucket
        with padding rows masked — masked rows enable nothing, so the
        enabled-pair sequence (and hence every downstream decision) is
        identical to the serial path's smaller tail bucket.  Verdict
        priority mirrors the serial commit loop: invariants beat
        deadlock within a chunk, earlier chunks beat later ones, and a
        verdict chunk commits nothing.  The visited merge runs ONCE
        after the loop — set-equal to the serial per-chunk merges
        because levels are disjoint from the visited set by
        construction."""
        model, spec = self.model, self.spec
        K = spec.num_lanes
        T = self.step.expand_width(B, widths)
        # LN: the level-new sorted set's capacity — sized by run_level
        # from a high-water ladder (a level's TOTAL new states, usually
        # far below the NCp*T worst case) because the per-chunk merge's
        # cost is O(LN); an overflow re-dispatches once at the safe
        # bound.  OC: the output row buffer gets one chunk of headroom
        # past LN so a full-T append at offset <= LN can never hit the
        # dynamic_update_slice start-index clamp (which would silently
        # overwrite earlier rows instead of failing).
        OC = LN + T
        expand = self.step.make_expand(B, widths)
        check_invariants = self.check_invariants
        check_deadlock = self.check_deadlock
        use_pallas = self.step.use_pallas
        n_actions = len(model.actions)

        def level(fbuf, f_total, n_chunks, vhi, vlo, vn):  # kspec: traced
            sent = jnp.uint32(dedup.SENT)

            def body(carry):  # kspec: traced
                (i, orows, opar, oact, on, lhi, llo, ln,
                 vkind, vinv, vidx, act_en, agmax, dig, ovf) = carry
                start = i * B
                rows = jax.lax.dynamic_slice(fbuf, (start, 0), (B, K))
                fvalid = (
                    start + jnp.arange(B, dtype=jnp.int32)
                ) < f_total
                states = jax.vmap(spec.unpack)(rows)
                (en_pre, cand, valid, parent, actid, a_en, a_guard,
                 exp_ovf) = expand(states, fvalid)
                deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
                viol_any, viol_idx = invariant_stage(
                    model, states, fvalid, check_invariants
                )
                (cand, parent, actid, rowvalid, _n_en,
                 sq_ovf) = squeeze_stage(cand, parent, actid, valid,
                                         T, K)
                hi, lo = fp_stage(cand, rowvalid, spec, use_pallas)
                # the SHARED winner-selection sequence (one source of
                # truth with the fused/legacy paths): primary set =
                # level-new (its ranks drive the gated merge below),
                # also_seen_in = the read-only visited set
                (n_out, n_par, n_act, new_n, n_hi, n_lo, _l1, _l2,
                 _l3, n_rank) = sorted_dedup_stage(
                    cand, parent, actid, rowvalid, hi, lo,
                    lhi, llo, ln, LN, T, K, False,
                    also_seen_in=(vhi, vlo, vn),
                )
                # verdicts, serial-commit priority
                inv_any = jnp.any(viol_any)
                inv_i = jnp.argmax(viol_any).astype(jnp.int32)
                dl_any = jnp.bool_(check_deadlock) & jnp.any(deadlocked)
                kind = jnp.where(
                    inv_any, jnp.int32(1),
                    jnp.where(dl_any, jnp.int32(2), jnp.int32(0)),
                )
                g_idx = jnp.where(
                    inv_any, viol_idx[inv_i],
                    jnp.argmax(deadlocked).astype(jnp.int32),
                ).astype(jnp.int32) + start
                take = (vkind == 0) & (kind != 0)
                commit = kind == 0  # a verdict chunk commits nothing
                # LN overflow: this level's new states outgrew the
                # ladder-sized level-new set — dropped merge scatters
                # would corrupt later chunks' novelty, so stop
                # committing (commit_ok) and flag for the exact-bound
                # re-dispatch.  Width/squeeze overflows flag the same
                # way (the whole level re-runs either way).
                ln_ovf = commit & ((ln + new_n) > LN)
                commit_ok = commit & ~ovf & ~ln_ovf
                app_n = jnp.where(commit_ok, new_n, 0)
                orows = devlevel.append_rows(orows, n_out, on)
                opar = devlevel.append_vec(opar, n_par + start, on)
                oact = devlevel.append_vec(oact, n_act, on)
                lhi, llo, ln = dedup.merge_ranked(
                    lhi, llo, ln, n_hi, n_lo, n_rank, app_n, LN
                )
                dig = devlevel.combine_digest(
                    dig,
                    devlevel.masked_digest(
                        n_hi, n_lo, jnp.arange(T) < app_n
                    ),
                )
                act_en = act_en + jnp.where(commit_ok, a_en, 0)
                agmax = jnp.maximum(agmax, a_guard)
                ovf = ovf | jnp.any(exp_ovf) | sq_ovf | ln_ovf
                return (i + 1, orows, opar, oact, on + app_n,
                        lhi, llo, ln,
                        jnp.where(take, kind, vkind),
                        jnp.where(take, inv_i, vinv),
                        jnp.where(take, g_idx, vidx),
                        act_en, agmax, dig, ovf)

            def cond(carry):  # kspec: traced
                return (carry[0] < n_chunks) & (carry[8] == 0)

            init = (
                jnp.int32(0),
                jnp.zeros((OC, K), jnp.uint32),
                jnp.zeros((OC,), jnp.int32),
                jnp.zeros((OC,), jnp.int32),
                jnp.int32(0),
                jnp.full((LN,), sent),
                jnp.full((LN,), sent),
                jnp.int32(0),
                jnp.int32(0), jnp.int32(0), jnp.int32(0),
                jnp.zeros((n_actions,), jnp.int32),
                jnp.zeros((n_actions,), jnp.int32),
                devlevel.zero_digest(),
                jnp.bool_(False),
            )
            (_i, orows, opar, oact, on, lhi, llo, _ln, vkind, vinv,
             vidx, act_en, agmax, dig, ovf) = jax.lax.while_loop(
                cond, body, init
            )
            # ONE O(capacity) merge per level (the serial path pays one
            # per chunk): every level-new entry is disjoint from the
            # visited set by construction, so the rank-scatter merge of
            # the sorted level-new prefix lands the identical sorted
            # visited array
            _f, rank_v = dedup.rank_sorted(vhi, vlo, vn, lhi, llo)
            vhi, vlo, vn = dedup.merge_ranked(
                vhi, vlo, vn, lhi, llo, rank_v, on, vcap
            )
            return (orows, opar, oact, on, vhi, vlo, vn, vkind, vinv,
                    vidx, act_en, agmax, dig, ovf)

        return level

    def _build_level_host(self, B: int, NCp: int, widths: tuple,
                          LN: int):
        """The whole-level program for the HOST (deferred-probe) visited
        backends — the C-arena FpSet and the disk tier.  Identical chunk
        walk to :meth:`_build_level`, with three deltas:

        - the device holds NO visited set: novelty inside the level is
          decided against the level-new sorted set alone
          (candidate_dedup_stage — same stable-sort winners as the
          device backend, but emitted in CANDIDATE order, the order the
          serial host commit feeds the FpSet), and the host filters
          already-visited states in ONE batched probe per level;
        - the emitted prefix carries its fingerprint lanes out (ohi/olo
          accumulators) so the host probe never recomputes them;
        - no in-jit digest: the multiset the chain folds is only known
          AFTER the probe, so the host folds the surviving fingerprints
          exactly as the serial per-chunk commit does.

        Verdicts derive from the FRONTIER states being expanded — states
        the previous level already probed and committed — so the
        deferred probe cannot change them; the serial priority
        (invariants beat deadlock within a chunk, earlier chunks beat
        later ones, a verdict chunk commits nothing) is mirrored
        unchanged.  docs/engine.md § Device-resident level pipeline
        states the full bit-identity argument."""
        model, spec = self.model, self.spec
        K = spec.num_lanes
        T = self.step.expand_width(B, widths)
        OC = LN + T  # one chunk of append headroom past LN (as _build_level)
        expand = self.step.make_expand(B, widths)
        check_invariants = self.check_invariants
        check_deadlock = self.check_deadlock
        use_pallas = self.step.use_pallas
        n_actions = len(model.actions)

        def level(fbuf, f_total, n_chunks):  # kspec: traced
            sent = jnp.uint32(dedup.SENT)

            def body(carry):  # kspec: traced
                (i, orows, opar, oact, ohi, olo, on, lhi, llo, ln,
                 vkind, vinv, vidx, act_en, agmax, ovf) = carry
                start = i * B
                rows = jax.lax.dynamic_slice(fbuf, (start, 0), (B, K))
                fvalid = (
                    start + jnp.arange(B, dtype=jnp.int32)
                ) < f_total
                states = jax.vmap(spec.unpack)(rows)
                (en_pre, cand, valid, parent, actid, a_en, a_guard,
                 exp_ovf) = expand(states, fvalid)
                deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
                viol_any, viol_idx = invariant_stage(
                    model, states, fvalid, check_invariants
                )
                (cand, parent, actid, rowvalid, _n_en,
                 sq_ovf) = squeeze_stage(cand, parent, actid, valid,
                                         T, K)
                hi, lo = fp_stage(cand, rowvalid, spec, use_pallas)
                (n_out, n_par, n_act, n_ohi, n_olo, new_n,
                 s_hi, s_lo, s_rank) = candidate_dedup_stage(
                    cand, parent, actid, rowvalid, hi, lo,
                    lhi, llo, ln, T, K,
                )
                # verdicts, serial-commit priority (same as _build_level)
                inv_any = jnp.any(viol_any)
                inv_i = jnp.argmax(viol_any).astype(jnp.int32)
                dl_any = jnp.bool_(check_deadlock) & jnp.any(deadlocked)
                kind = jnp.where(
                    inv_any, jnp.int32(1),
                    jnp.where(dl_any, jnp.int32(2), jnp.int32(0)),
                )
                g_idx = jnp.where(
                    inv_any, viol_idx[inv_i],
                    jnp.argmax(deadlocked).astype(jnp.int32),
                ).astype(jnp.int32) + start
                take = (vkind == 0) & (kind != 0)
                commit = kind == 0  # a verdict chunk commits nothing
                ln_ovf = commit & ((ln + new_n) > LN)
                commit_ok = commit & ~ovf & ~ln_ovf
                app_n = jnp.where(commit_ok, new_n, 0)
                orows = devlevel.append_rows(orows, n_out, on)
                opar = devlevel.append_vec(opar, n_par + start, on)
                oact = devlevel.append_vec(oact, n_act, on)
                ohi = devlevel.append_vec(ohi, n_ohi, on)
                olo = devlevel.append_vec(olo, n_olo, on)
                lhi, llo, ln = dedup.merge_ranked(
                    lhi, llo, ln, s_hi, s_lo, s_rank, app_n, LN
                )
                act_en = act_en + jnp.where(commit_ok, a_en, 0)
                agmax = jnp.maximum(agmax, a_guard)
                ovf = ovf | jnp.any(exp_ovf) | sq_ovf | ln_ovf
                return (i + 1, orows, opar, oact, ohi, olo,
                        on + app_n, lhi, llo, ln,
                        jnp.where(take, kind, vkind),
                        jnp.where(take, inv_i, vinv),
                        jnp.where(take, g_idx, vidx),
                        act_en, agmax, ovf)

            def cond(carry):  # kspec: traced
                return (carry[0] < n_chunks) & (carry[10] == 0)

            init = (
                jnp.int32(0),
                jnp.zeros((OC, K), jnp.uint32),
                jnp.full((OC,), -1, jnp.int32),
                jnp.full((OC,), -1, jnp.int32),
                jnp.full((OC,), sent),
                jnp.full((OC,), sent),
                jnp.int32(0),
                jnp.full((LN,), sent),
                jnp.full((LN,), sent),
                jnp.int32(0),
                jnp.int32(0), jnp.int32(0), jnp.int32(0),
                jnp.zeros((n_actions,), jnp.int32),
                jnp.zeros((n_actions,), jnp.int32),
                jnp.bool_(False),
            )
            (_i, orows, opar, oact, ohi, olo, on, _lh, _ll, _ln,
             vkind, vinv, vidx, act_en, agmax, ovf) = jax.lax.while_loop(
                cond, body, init
            )
            return (orows, opar, oact, ohi, olo, on, vkind, vinv,
                    vidx, act_en, agmax, ovf)

        return level

    def run_level(self, frontier_np, f_total: int, depth: int,
                  vhi, vlo, vn, vcap: int, plan):
        """Run the whole-level program (with the <=1 exact-width
        re-dispatch on segment overflow); -> (vhi, vlo, vn, vcap,
        finalize) or None to fall back to the per-chunk ladder.

        The overflow-flag read is the one device sync per level, so
        this call BLOCKS until the level program completes (the
        overlap layer's checkpoint/merge workers are separate threads
        and keep draining while it runs); finalize() only performs the
        host-side output conversions.  The engine accounts the whole
        blocked wall as device-wait on the level's step span — there is
        no in-flight dispatch window to attribute separately, unlike
        the per-chunk staged contract."""
        from .bfs import _next_pow2, _pad_rows

        B, nc, handled = plan
        NCp = _next_pow2(nc)
        self.chunk_retry.reset_chunk()
        n_actions = len(self.model.actions)
        widths = self.step.norm_widths(
            B, self.pool.widths_for(B, np.zeros(n_actions), B)
        )
        T = self.step.expand_width(B, widths)
        # level-new capacity ladder (ops/devlevel.level_new_capacity —
        # ONE sizing policy shared with the sharded device-resident
        # variant): the per-chunk merge costs O(LN), so size LN from the
        # run's measured per-level new-state high water, NOT the NCp*T
        # worst case — an overflow costs exactly one re-dispatch at the
        # safe bound, steady state costs nothing.  This is where the
        # device pipeline's merge win comes from: the serial path
        # scatters O(visited capacity) per CHUNK, this path scatters
        # O(level) per chunk and O(capacity) once.
        LN = devlevel.level_new_capacity(T, self._ln_hw, NCp * T)
        exact = False  # True after an overflow re-dispatch (safe bounds)
        dispatched = 0
        fbuf = None
        outgrown: list = []  # vcaps outgrown this level; evicted on success
        pre_v = (vhi, vlo, vn)  # re-dispatch replays from pre-level state
        # output-tuple indices differ between the two program variants
        # (the host program has no visited set and no digest, but adds
        # the ohi/olo fingerprint accumulators)
        i_vkind, i_agmax, i_ovf = (
            (6, 10, 11) if self.host_mode else (7, 11, 13)
        )
        while True:
            try:
                injected = self.fault.chunk_error(escalated=True)
                if injected is not None:
                    raise injected
                if not self.host_mode:
                    need = int(vn) + min(NCp * T, LN + T)
                    if need > vcap:
                        # eviction of the outgrown capacity's programs
                        # is DEFERRED until this level dispatches
                        # successfully: a growth followed by a device
                        # compile failure must leave the per-chunk
                        # fallback's programs warm
                        outgrown.append(vcap)
                        vhi, vlo, vcap = grow_visited(
                            vhi, vlo, vcap, need
                        )
                        pre_v = (vhi, vlo, vn)
                if fbuf is None:
                    # only the handled prefix rides the device buffer: an
                    # un-gated tail chunk (handled < f_total) runs through
                    # the per-chunk ladder afterwards, and NCp*B can be
                    # smaller than the full frontier in that case
                    fbuf = jnp.asarray(
                        _pad_rows(frontier_np[:handled], NCp * B)
                    )
                fn = self._level_program(B, NCp, vcap, widths, LN)
                if self.host_mode:
                    outs = fn(fbuf, jnp.int32(handled), jnp.int32(nc))
                else:
                    outs = fn(fbuf, jnp.int32(handled), jnp.int32(nc),
                              *pre_v)
                dispatched += 1
                # forces the level program (the ONE device sync/level)
                overflow = bool(outs[i_ovf])
            except Exception as e:  # noqa: BLE001 — XLA compile/run
                action = self.chunk_retry.handle(
                    e, escalated=True, depth=depth
                )
                if action == "retry":
                    continue
                self._mark_fallback(
                    f"{type(e).__name__}: {e}"[:200], depth
                )
                return None
            agmax_np = np.asarray(outs[i_agmax], np.int64)
            if overflow and int(outs[i_vkind]) == 0 and not exact:
                # a segment (or the level-new set) overflowed: outputs
                # are incomplete — discard and re-dispatch ONCE from the
                # pre-level visited state at widths sized from the
                # measured exact per-level max counts and the safe
                # level-new bound (neither can overflow again: <=2
                # launches per level even on growth levels).  A verdict
                # overrides: it derives from frontier states only, so
                # it is exact regardless of successor-buffer overflow.
                widths = self.step.norm_widths(
                    B,
                    self.pool.widths_for(
                        B, agmax_np.astype(np.float64), B
                    ),
                )
                T = self.step.expand_width(B, widths)
                LN = devlevel.level_new_bound(NCp * T)
                exact = True
                continue
            break
        for oc in outgrown:
            evict_vcap(self.step._cache, oc)
        # high waters for the next level's sizing
        np.maximum(
            self.pool.hw, agmax_np.astype(np.float64) / max(B, 1),
            out=self.pool.hw,
        )
        self.device_levels += 1
        if self.host_mode:
            # LN high water tracks the PRE-probe level-new count here
            # (the level-new set is what it sizes, and that set holds
            # the not-yet-probed candidates)
            self._ln_hw = max(self._ln_hw, int(outs[5]))

            def finalize(outs=outs, dispatched=dispatched):
                on = int(outs[5])
                vk = int(outs[6])
                verdict = None
                if vk:
                    verdict = (
                        "invariant" if vk == 1 else "deadlock",
                        int(outs[8]),
                        int(outs[7]),
                    )
                return dict(
                    rows=np.asarray(outs[0][:on]),
                    parent=np.asarray(outs[1][:on], np.int32),
                    act=np.asarray(outs[2][:on], np.int32),
                    hi=np.ascontiguousarray(
                        np.asarray(outs[3][:on]), np.uint32
                    ),
                    lo=np.ascontiguousarray(
                        np.asarray(outs[4][:on]), np.uint32
                    ),
                    new_n=on,
                    verdict=verdict,
                    act_en=np.asarray(outs[9], np.int64),
                    digest=None,  # host folds the probe survivors
                    launches=dispatched,
                )

            # visited refs unchanged: the host set is the visited state
            return vhi, vlo, vn, vcap, finalize
        self._ln_hw = max(self._ln_hw, int(outs[3]))
        new_vhi, new_vlo, new_vn = outs[4], outs[5], outs[6]

        def finalize(outs=outs, dispatched=dispatched):
            on = int(outs[3])
            vk = int(outs[7])
            verdict = None
            if vk:
                verdict = (
                    "invariant" if vk == 1 else "deadlock",
                    int(outs[9]),
                    int(outs[8]),
                )
            return dict(
                rows=np.asarray(outs[0][:on]),
                parent=np.asarray(outs[1][:on], np.int64),
                act=np.asarray(outs[2][:on]),
                new_n=on,
                verdict=verdict,
                act_en=np.asarray(outs[10], np.int64),
                digest=devlevel.digest_ints(outs[12]),
                launches=dispatched,
            )

        return new_vhi, new_vlo, new_vn, vcap, finalize

    def _mark_fallback(self, reason: str, depth: int) -> None:
        self.device_fallback = reason
        from ..obs import tracer as _obs

        _obs.event("pipeline-fallback", depth=depth, pipeline="device",
                   to="fused", error=reason)


def make_pipeline(name: str, *, step_builder, model, adapt, chunk_retry,
                  fault, check_invariants, visited_backend,
                  on_degrade_chunk, compact_shift, compact_gate,
                  check_deadlock: bool = False):
    """Pipeline factory (the one interface check() builds against)."""
    if name == "legacy":
        return LegacyPipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
        )
    if name == "device":
        return DevicePipeline(
            step_builder, model, adapt, chunk_retry, fault,
            check_invariants, visited_backend, on_degrade_chunk,
            compact_shift, compact_gate, check_deadlock=check_deadlock,
        )
    return FusedPipeline(
        step_builder, model, adapt, chunk_retry, fault,
        check_invariants, visited_backend, on_degrade_chunk,
        compact_shift, compact_gate,
    )


def warm_key(step_builder, model, key: tuple, vcap: int):
    """Re-compile one logged step-cache key at a new visited capacity —
    PreparedKernels.rewarm's per-key worker.  Returns the rebuilt key,
    or None when the key has no capacity component (guard kernels never
    evict on growth)."""
    tag = key[0]
    if tag == "step":
        (_t, bucket, _vcap, inv_sig, with_merge, compact, sq_full,
         _pallas) = key
        if inv_sig and inv_sig != tuple(
            i.name for i in model.invariants
        ):
            return None  # belongs to a sibling invariant overlay
        step = step_builder.get(
            bucket, vcap, bool(inv_sig),
            with_merge=with_merge, compact=compact, squeeze_full=sq_full,
        )
        K = model.spec.num_lanes
        out = step(
            jnp.zeros((bucket, K), jnp.uint32),
            jnp.zeros((bucket,), bool),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)
        return ("step", bucket, vcap, inv_sig, with_merge, compact,
                sq_full, step_builder.use_pallas)
    if tag == "dvl":
        (_t, bucket, _vcap, ncp, widths, ln, inv_sig, dl, _pallas) = key
        if inv_sig and inv_sig != tuple(
            i.name for i in model.invariants
        ):
            return None  # belongs to a sibling invariant overlay
        pipe = DevicePipeline(
            step_builder, model, None, None, None,
            check_invariants=bool(inv_sig),
            visited_backend="device",
            on_degrade_chunk=None, compact_shift=2, compact_gate=4096,
            check_deadlock=dl,
        )
        fn = pipe._level_program(bucket, ncp, vcap, widths, ln)
        K = model.spec.num_lanes
        out = fn(
            jnp.zeros((ncp * bucket, K), jnp.uint32),
            jnp.int32(0),
            jnp.int32(0),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)
        return ("dvl", bucket, vcap, ncp, widths, ln, inv_sig, dl,
                step_builder.use_pallas)
    if tag == "fsc":
        (_t, bucket, _vcap, widths, with_merge, device_out, _pallas) = key
        pipe = FusedPipeline(
            step_builder, model, None, None, None,
            check_invariants=True,
            visited_backend=(
                "device" if with_merge
                else ("device-hash" if device_out else "host")
            ),
            on_degrade_chunk=None, compact_shift=2, compact_gate=4096,
        )
        fn = pipe.succ_step(bucket, widths, vcap)
        W = int(sum(widths))
        K = model.spec.num_lanes
        out = fn(
            jnp.zeros((bucket, K), jnp.uint32),
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), bool),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)
        return ("fsc", bucket, vcap, widths, with_merge, device_out,
                step_builder.use_pallas)
    return None
