from .bfs import CheckResult, PreparedKernels, Violation, check, prepare

__all__ = ["CheckResult", "PreparedKernels", "Violation", "check", "prepare"]
