from .bfs import CheckResult, Violation, check

__all__ = ["CheckResult", "Violation", "check"]
