"""Single-device level-synchronous BFS model checker.

This is the TPU-native replacement for TLC's worker loop (StateQueue + FPSet
+ per-state invariant evaluation) — the external Java engine the reference
corpus depends on (it vendors no checker; `*.toolbox` is gitignored,
/root/reference/.gitignore:1).

Per BFS level, one jitted step does:
  frontier[B, K] --unpack--> vmap over (state x choice) of every action kernel
  --> candidate successors [B, C, K] + enabled mask
  --> fingerprint pairs, lexsort, adjacent-dedup           (in-batch dedup)
  --> binary-search probe of the sorted visited set        (global dedup)
  --> compact new states to the front, merge fps into visited
  --> invariant predicate kernels on the new states

Shapes are static under jit: the frontier is padded to power-of-two buckets
and the visited set to a power-of-two capacity; the host loop re-pads and
lets a new (bucket, capacity) pair trigger a (cached) recompile — O(log n)
distinct shapes over a whole run, each compiled once.

Deadlock checking is off by default: the bounded models deadlock by design
once id sequences are exhausted and logs converge (every `Spec` in the corpus
is run with TLC's deadlock check disabled for the same reason).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from ..obs import metrics as _met
from ..obs.observer import RunObserver
from ..ops import dedup, hashset
from ..ops.fingerprint import fingerprint_lanes
from ..resilience import integrity as _integ
from ..resilience.checkpoints import CheckpointStore
from ..resilience.faults import FaultPlan
from ..resilience.integrity import IntegrityError
from ..resilience.resources import (
    ResourceExhausted,
    ResourceGovernor,
    is_disk_full,
)
from ..resilience.retry import ChunkRetryHandler
from .pipeline import (
    grow_visited as _grow_visited,
    make_pipeline,
    resolve_pipeline,
)

# insert-or-find on the device hash table; table + claim lattice donated so
# XLA updates them in place instead of copying O(capacity) per chunk
def _hash_insert_impl(t_hi, t_lo, claim, q_hi, q_lo, valid):
    return hashset.probe_insert(t_hi, t_lo, q_hi, q_lo, valid, claim=claim)


_hash_insert = jax.jit(_hash_insert_impl, donate_argnums=(0, 1, 2))

# device-hash table floor (module-level so tests can shrink it to exercise
# the growth / overflow-re-run machinery at small state counts)
_HASH_MIN_CAP = 1 << 16


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


class _CompileOnFirstCall:
    """Cache entry for a freshly built jitted step: the FIRST call is where
    jax traces + XLA compiles (jax.jit is lazy), so exactly that call is
    wrapped in a ``compile`` span — then the wrapper replaces itself with
    the bare jitted function.  This is what lets a warm serving path PROVE
    its cache hits: a job that re-uses every step shows zero compile spans
    in its trace (service/kernel_cache, docs/service.md).  With no active
    tracer the wrapper costs one dict store and disappears."""

    def __init__(self, fn, cache: dict, key, **attrs):
        self.fn = fn
        self._cache = cache
        self._key = key
        self._attrs = attrs

    def __call__(self, *args):
        from ..obs import tracer as _tr

        t0 = time.time()
        out = self.fn(*args)
        cur = _tr.current_tracer()
        if cur is not None:
            cur.emit_span("compile", t0, time.time(), **self._attrs)
        # swap in the bare jitted fn iff this entry is still current (a
        # capacity-growth eviction may already have dropped the key)
        if self._cache.get(self._key) is self:
            self._cache[self._key] = self.fn
        return out


def _round256(w: int) -> int:
    """Round up to the fingerprint-block alignment (single source of
    truth for widths_for and norm_widths — round-5 advisor item)."""
    return -(-w // 256) * 256


class AdaptiveCompact:
    """Per-action compact-buffer sizing policy, shared by the single-device
    engine and the sharded engine (round-5 review item: one policy, two
    hand-synced copies otherwise).

    Escalation: stay on the uniform legacy shift until a uniform attempt
    actually overflows (the uniform path is cheaper when it fits —
    docs/PROFILE_5R.md), then size each action's buffer at ~1.35x the
    run's measured high-water per-state enablement, pow2-rounded with
    overflow-learned floors.  Callers supply the per-state guard density
    (single-device: act_guard / chunk rows; sharded: max over shards of
    act_guard / shard rows) so the policy itself is engine-agnostic, and
    all inputs are host-replicated values so multi-process runs stay in
    lockstep.  KSPEC_ADAPTIVE_COMPACT=0 pins the legacy uniform-only
    behavior.
    """

    def __init__(self, actions, compact_shift: int, bucket_gate: int):
        self.actions = actions
        self.shift = compact_shift
        self.gate = bucket_gate
        self.hw = np.zeros(len(actions), np.float64)
        self.floor = np.zeros(len(actions), np.int64)
        self.on = os.environ.get("KSPEC_ADAPTIVE_COMPACT", "1") != "0"
        # Wide-model guard (TODO round-5 finding): a fully escalated
        # program on the 27-action mixed product reproducibly OOMs
        # XLA:CPU's LLVM at compile, while the uniform-shift program with
        # the SAME pipeline count compiles fine — the blowup tracks how
        # far the escalated shapes stray from the uniform ones, not the
        # pipeline count itself.  Above this many actions, escalation
        # widens ONLY the actions whose measured need exceeds their
        # uniform buffer and pins every other action at (approximately —
        # tuple widths are 256-rounded, and the tuple form skips the
        # uniform path's pre-sort squeeze) its uniform width.  This
        # brings the escalated program's buffer shapes much closer to
        # the compiling uniform ones; it is a heuristic, not a shape
        # guarantee — compile_fallback remains the backstop.  Narrow
        # models (the 9-action flagship, where full adaptation is
        # profiled and wins) are unaffected.
        self.max_pipe = int(os.environ.get("KSPEC_ADAPTIVE_MAX_PIPE", "16"))
        self.active = False

    def widths_for(self, bucket: int):
        """compact arg for this bucket: None (full path), the uniform
        legacy shift, or a per-action width tuple once escalated."""
        if self.shift <= 0 or bucket < self.gate:
            return None
        if not (self.on and self.active and self.hw.any()):
            return self.shift
        hybrid = len(self.actions) > self.max_pipe
        uni_rows = max(1, bucket >> self.shift)
        out = []
        for a, hw, floor in zip(self.actions, self.hw, self.floor):
            need = _next_pow2(max(256, int(1.35 * hw * bucket) + 1))
            if hybrid:
                # hybrid floors are doubled 256-multiples of (possibly
                # non-pow2) pinned uniform widths — re-rounding them
                # through _next_pow2 could run up to ~2x wider than the
                # intended doubling, drifting further from the
                # uniform-adjacent shapes this mode exists to preserve
                # (round-5 advisor item): size from the floor with
                # _round256 instead
                w = max(need, _round256(int(floor)))
            else:
                w = max(need, _next_pow2(int(floor)))
            w = min(w, bucket * a.n_choices)
            if hybrid:
                # pre-apply norm_widths' 256-rounding so the width stated
                # here is the width the program actually runs at
                w_uni = _round256(
                    min(uni_rows * a.n_choices, bucket * a.n_choices)
                )
                if w <= w_uni:
                    w = w_uni
            out.append(w)
        return tuple(out)

    def observe(self, density: np.ndarray):
        """Fold one attempt's per-state guard densities into the
        high-water marks."""
        np.maximum(self.hw, density, out=self.hw)

    def escalate(self, attempt, ovf_a, bucket: int, density: np.ndarray):
        """Next attempt after an expansion overflow of `attempt`.

        attempt: the overflowed compact arg (int = uniform shift, tuple =
        per-action widths).  ovf_a: per-action overflow flags (tuple
        case).  density: the overflowing attempt's complete per-state
        guard densities (phase A sweeps the full lattice regardless of
        buffer overflow, so these are exact).
        """
        if isinstance(attempt, int):
            if self.on:
                self.observe(density)
                self.active = True
                attempt = self.widths_for(bucket)
            if isinstance(attempt, int):  # adaptation off (or degenerate)
                return attempt - 1 if attempt > 1 else None
            return attempt
        nxt = tuple(
            min(2 * w, bucket * a.n_choices) if o else w
            for w, o, a in zip(attempt, ovf_a, self.actions)
        )
        for ai, o in enumerate(ovf_a):
            if o:
                self.floor[ai] = max(self.floor[ai], nxt[ai])
        return nxt

    def compile_fallback(self, bucket: int):
        """Shared response to an escalated per-action program failing to
        COMPILE (XLA:CPU's LLVM has been seen OOMing on the 27-action
        mixed product's escalated step): escalation is purely a
        performance knob, so pin adaptation off for the rest of the run
        and return the uniform attempt to retry the chunk with — the
        uniform overflow ladder (shift-1 ... full lattice) keeps results
        exact at every density.  One copy for both engines (the same
        rationale as this class itself)."""
        self.on = False
        self.active = False
        return (
            self.shift
            if self.shift > 0 and bucket >= self.gate
            else None
        )


@dataclass
class Violation:
    invariant: str
    depth: int
    state: object  # decoded canonical state (or raw dict if no decoder)
    trace: list  # [(action_name | "<init>", decoded state), ...] root -> violation


@dataclass
class CheckResult:
    model: str
    levels: list[int]  # distinct new states per BFS level (level 0 = inits)
    total: int
    diameter: int
    violation: Optional[Violation]
    seconds: float
    states_per_sec: float
    stats: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.violation is None


class _Step:
    """Builds and caches the jitted level step for one model."""

    def __init__(self, model: Model):
        self.model = model
        self.spec = model.spec
        self.K = self.spec.num_lanes
        self.C = model.total_fanout
        # opt-in Pallas fingerprint kernel (hashed mode only; bit-identical
        # to the jnp path — see ops/pallas_fingerprint.py)
        self.use_pallas = (
            os.environ.get("KSPEC_USE_PALLAS") == "1" and not self.spec.exact64
        )
        # global action id per flattened choice column
        act_ids = np.concatenate(
            [np.full(a.n_choices, i, np.int32) for i, a in enumerate(model.actions)]
        )
        self.act_ids = jnp.asarray(act_ids)
        # jitted-step cache shared across check() calls on the same Model:
        # re-tracing is the dominant cost for models with large emitted
        # expression trees (utils/tla_emit: seconds per shape), and the
        # traced steps are pure functions of (model, shape key)
        cache = getattr(model, "_step_cache", None)
        if cache is None:
            cache = {}
            try:
                model._step_cache = cache
            except AttributeError:
                pass  # exotic model object without attribute support
        self._cache = cache
        # every key ever BUILT for this model, growth evictions included —
        # what PreparedKernels.rewarm replays at the capacity fixed point
        log = getattr(model, "_step_compiled_log", None)
        if log is None:
            log = set()
            try:
                model._step_compiled_log = log
            except AttributeError:
                pass
        self._compiled_log = log

    def norm_widths(self, bucket: int, compact):
        """Normalize a compact spec to per-action buffer widths (rows).

        compact: None/0 -> full path (returns None); int -> the uniform
        legacy form, W_a = n_choices_a * (bucket >> compact); sequence ->
        explicit per-action widths, clamped to the action's full lattice
        width (at which overflow is impossible)."""
        acts = self.model.actions
        if not compact:
            return None
        if isinstance(compact, int):
            if (bucket >> compact) < 1:
                return None
            return tuple(max(1, bucket >> compact) * a.n_choices for a in acts)
        assert len(compact) == len(acts), (len(compact), len(acts))
        # Round caller-supplied widths up to a multiple of 256 (unless the
        # full lattice width — always a pow2 multiple of n_choices — is
        # smaller): fp_masked blocks the candidate buffer by
        # gcd(rows, 8192), so an odd width would give 1-row Pallas
        # fingerprint blocks (round-5 advisor item).  The alignment
        # invariant is enforced HERE, where the widths are created.
        return tuple(
            min(_round256(max(1, int(w))), bucket * a.n_choices)
            for w, a in zip(compact, acts)
        )

    def expand_width(self, bucket: int, compact) -> int:
        """Candidate rows produced by make_expand(bucket, compact)."""
        widths = self.norm_widths(bucket, compact)
        return bucket * self.C if widths is None else sum(widths)

    def make_expand(self, bucket: int, shift):
        """Expansion kernel: (states[B], fvalid[B]) ->
        (en_pre[B, C], cand[T, K], valid[T], parent[T], actid[T],
         act_en[n_actions], act_guard[n_actions], overflow[n_actions])
        with T = expand_width(bucket, shift).  act_en counts enabled
        successors post-CONSTRAINT (the action-coverage histogram);
        act_guard counts guard-enabled pairs pre-CONSTRAINT — the load the
        compact buffers actually hold, hence what adaptive sizing must
        track (on constraint-pruning models like AsyncIsr the two can
        differ widely).

        shift falsy (or an int shifting the bucket away): one phase over
        the full padded lattice (T = B*C; overflow is constant False).
        otherwise: two phases — a full-lattice guard sweep whose state
        *updates* are dead code (XLA eliminates them; guards alone are a few
        % of the kernel cost), then per-action compaction of the enabled
        (state, choice) pairs into a W_a-row buffer where the kernel,
        functional update, constraint and lane packing actually run.
        `shift` may be a single int (the uniform legacy form,
        W_a = n_choices_a * (B >> shift)) or a per-action width sequence —
        enablement density varies an order of magnitude across actions
        (26-29%% for LeaderWrite/BecomeLeader/Truncate vs <0.1%% for the
        fenced ISR mutations on the deep 5-broker workload), so per-action
        widths sized from measured enablement avoid both the dense
        actions' overflow-retry and the sparse actions' padding waste.
        overflow[a]=True iff action `a` enabled more pairs than its W_a
        buffer holds — the caller must re-run with a wider buffer for that
        action; outputs are incomplete in that case but never wrong-state
        (valid rows are always real successors)."""
        model, spec = self.model, self.spec
        C = self.C
        act_ids = self.act_ids
        widths = self.norm_widths(bucket, shift)
        n_actions = len(model.actions)
        # action boundaries for the enablement histogram (TLC's action
        # coverage analogue, SURVEY.md §5 "Metrics")
        bounds = np.cumsum([0] + [a.n_choices for a in model.actions])
        B = bucket
        M = B * C

        def _expand_full(states, fvalid):
            en_pre, en, packed = jax.vmap(self._expand_one)(states)  # [B,C]x2, [B,C,K]
            en = en & fvalid[:, None]
            guard_en = en_pre & fvalid[:, None]
            act_en = jnp.stack(
                [
                    jnp.sum(en[:, bounds[i] : bounds[i + 1]], dtype=jnp.int32)
                    for i in range(len(model.actions))
                ]
            )
            act_guard = jnp.stack(
                [
                    jnp.sum(
                        guard_en[:, bounds[i] : bounds[i + 1]],
                        dtype=jnp.int32,
                    )
                    for i in range(len(model.actions))
                ]
            )
            cand = packed.reshape(M, spec.num_lanes)
            valid = en.reshape(M)
            flat = jnp.arange(M, dtype=jnp.int32)
            return (
                en_pre,
                cand,
                valid,
                flat // C,
                act_ids[flat % C],
                act_en,
                act_guard,
                jnp.zeros((n_actions,), bool),
            )

        def _expand_compact(states, fvalid):
            def _guards_one(state):
                parts = []
                for a in model.actions:
                    choices = jnp.arange(a.n_choices, dtype=jnp.int32)
                    ok = jax.vmap(lambda c, s=state, a=a: a.kernel(s, c)[0])(choices)
                    parts.append(ok)
                return jnp.concatenate(parts)

            en_pre = jax.vmap(_guards_one)(states)  # [B, C] pre-constraint
            cand_parts, valid_parts, parent_parts, act_parts = [], [], [], []
            act_en_parts, act_guard_parts, ovf_parts = [], [], []
            for ai, a in enumerate(model.actions):
                na = a.n_choices
                W = widths[ai]
                ga = (en_pre[:, bounds[ai] : bounds[ai + 1]] & fvalid[:, None]).reshape(
                    B * na
                )
                n_en = jnp.sum(ga, dtype=jnp.int32)
                act_guard_parts.append(n_en)
                ovf_parts.append(n_en > W)
                cpos = jnp.where(ga, jnp.cumsum(ga) - 1, W)
                cidx = jnp.zeros((W,), jnp.int32).at[cpos].set(
                    jnp.arange(B * na, dtype=jnp.int32)
                )
                rowvalid = jnp.arange(W) < n_en
                sidx = cidx // na
                ch = cidx % na
                gstate = jax.tree.map(lambda x: x[sidx], states)
                ok, nxt = jax.vmap(a.kernel)(gstate, ch)
                ok = ok & rowvalid
                if model.constraint is not None:
                    ok = ok & jax.vmap(model.constraint)(nxt)
                cand_parts.append(jax.vmap(spec.pack)(nxt))
                valid_parts.append(ok)
                parent_parts.append(sidx)
                act_parts.append(jnp.full((W,), ai, jnp.int32))
                act_en_parts.append(jnp.sum(ok, dtype=jnp.int32))
            return (
                en_pre,
                jnp.concatenate(cand_parts, axis=0),
                jnp.concatenate(valid_parts),
                jnp.concatenate(parent_parts),
                jnp.concatenate(act_parts),
                jnp.stack(act_en_parts),
                jnp.stack(act_guard_parts),
                jnp.stack(ovf_parts),
            )

        return _expand_compact if widths is not None else _expand_full

    def _expand_one(self, state: dict):
        """All successors of one state: (enabled_pre_constraint[C],
        enabled[C], packed[C, K]).  The pre-constraint mask feeds deadlock
        detection (a state is deadlocked when no action is enabled,
        regardless of CONSTRAINT pruning)."""
        model, spec = self.model, self.spec
        pre_parts, ok_parts, packed_parts = [], [], []
        for a in model.actions:
            choices = jnp.arange(a.n_choices, dtype=jnp.int32)
            ok, nxt = jax.vmap(lambda c, s=state, a=a: a.kernel(s, c))(choices)
            pre_parts.append(ok)
            if model.constraint is not None:
                ok = ok & jax.vmap(model.constraint)(nxt)
            ok_parts.append(ok)
            packed_parts.append(jax.vmap(spec.pack)(nxt))
        return (
            jnp.concatenate(pre_parts),
            jnp.concatenate(ok_parts),
            jnp.concatenate(packed_parts, axis=0),
        )

    def inv_sig(self, with_invariants: bool) -> tuple:
        """The invariant-selection component of step-cache keys: the
        ORDERED invariant names when the program embeds the predicates,
        () otherwise.  Keying on the names (not a bool) lets invariant
        overlays of one base model (service/kernel_cache) share one step
        cache — invariant-free programs are shared across overlays, while
        each ordering's invariant-bearing programs key separately (the
        stack order fixes the first-violation rule)."""
        return (
            tuple(i.name for i in self.model.invariants)
            if with_invariants and self.model.invariants
            else ()
        )

    def cached(self, key, build, **attrs):
        """Compile-cache insert-or-get: `build()` must return the jitted
        callable; the first call of a fresh entry is wrapped in a
        ``compile`` span (_CompileOnFirstCall) and the key is appended to
        the compiled log PreparedKernels.rewarm replays."""
        if key not in self._cache:
            self._compiled_log.add(key)
            self._cache[key] = _CompileOnFirstCall(
                build(), self._cache, key, **attrs
            )
        return self._cache[key]

    def get(
        self,
        bucket: int,
        vcap: int,
        with_invariants: bool = True,
        with_merge: bool = True,
        compact=None,
        squeeze_full: bool = False,
    ):
        # use_pallas is in the key because the cache outlives this _Step
        # (it is shared per Model) and KSPEC_USE_PALLAS can toggle between
        # check() calls (scripts/tpu_window.py does exactly that).
        # squeeze_full only changes the program on the uniform-shift
        # compact path (per-action and full paths already run T = T_exp) —
        # normalize it so the sticky flag can't force recompiles of
        # byte-identical steps under fresh keys
        squeeze_full = (
            squeeze_full
            and isinstance(compact, int)
            and self.norm_widths(bucket, compact) is not None
        )
        compact_key = (
            tuple(compact) if isinstance(compact, (list, tuple)) else compact
        )
        key = (
            "step",
            bucket,
            vcap,
            self.inv_sig(with_invariants),
            with_merge,
            compact_key,
            squeeze_full,
            self.use_pallas,
        )
        return self.cached(
            key,
            lambda: jax.jit(
                self.build_raw(
                    bucket, vcap, with_invariants, with_merge, compact,
                    squeeze_full,
                )
            ),
            bucket=bucket,
            vcap=vcap,
            compact=repr(compact_key),
        )

    def build_raw(
        self,
        bucket: int,
        vcap: int,
        with_invariants: bool = True,
        with_merge: bool = True,
        compact=None,
        squeeze_full: bool = False,
    ):
        """The un-jitted level step (frontier, fvalid, vhi, vlo, vn) -> ...;
        exposed for the driver's compile checks and custom jit wrapping.
        with_merge=False skips the visited-set merge (host FpSet backend).

        compact: a right-shift amount — one int (uniform) or a per-action
        sequence — enabling the two-phase expansion.  Phase A sweeps all
        guards over the full padded choice lattice with the state *updates*
        dead-code-eliminated by XLA (guards alone are ~3% of the kernel
        cost — the expensive parts, the functional updates and the lane
        packing, never run for disabled candidates).  Phase B compacts each
        action's enabled (state, choice) pairs into a buffer of
        W_a = n_choices_a * (bucket >> shift_a) rows and re-runs that
        action's kernel, update and pack at the compacted width only.  The
        sort / visited-probe / merge then also run at the compacted total
        width (only a few percent of the lattice is ever enabled —
        RESULTS.md measures ~6% on Kip320).  The step returns a per-action
        overflow vector (plus one trailing squeeze-overflow flag): where
        set, that action enabled more pairs than its buffer holds, the
        outputs are INCOMPLETE, and the caller must re-run the chunk with a
        smaller shift for that action (the host loop retries and adapts;
        results stay exact either way).  squeeze_full=True disables the
        pre-sort squeeze width reduction (the retry fallback when the
        squeeze itself overflows)."""
        return self._build(
            bucket, vcap, with_invariants, with_merge, compact, squeeze_full
        )

    def _build(
        self,
        bucket: int,
        vcap: int,
        with_invariants: bool,
        with_merge: bool = True,
        compact=None,
        squeeze_full: bool = False,
    ):
        spec, model = self.spec, self.model
        C, K = self.C, self.K
        widths = self.norm_widths(bucket, compact)
        per_action = isinstance(compact, (list, tuple))
        shift = widths is not None  # truthy iff the compact path is on
        expand = self.make_expand(bucket, compact)
        # Candidate width the sort/probe/outputs run at.  On the compact
        # path a second-stage squeeze gathers the enabled candidates into a
        # narrower buffer before fingerprint/sort/probe — the sort is the
        # single most expensive stage, and its cost is set by this width.
        # Uniform-shift buffers are ~4x oversized (~25% occupied), so the
        # squeeze halves (squeeze overflow re-runs with squeeze_full — the
        # retry keeps results exact at every density).  Per-action widths
        # are already sized tight from measured enablement, so T is the
        # full compact width and the squeeze cannot overflow (it only
        # compacts rows to the front for the fingerprint/output stages).
        T_exp = self.expand_width(bucket, compact)
        if not shift or squeeze_full or per_action:
            T = T_exp
        else:
            T = max(256, T_exp >> 1)

        # Host-FpSet backend: the device holds no visited set, and the
        # native C++ open-addressing FpSet already dedups both in-batch and
        # globally on insert — so the device-side sort / visited-probe /
        # rank-merge stages are pure waste there.  Profiled on the flagship
        # bench chunk (32k rows, CPU): sort 56ms + probe 24ms + compact+
        # merge 410ms out of a 663ms step — 74% of the level step spent
        # deduplicating what the C++ set re-dedups anyway.  This branch
        # squeezes the enabled candidates to the front, fingerprints them,
        # and hands (rows, fps) straight to the host.
        host_dedup = not with_merge
        sent = jnp.uint32(dedup.SENT)

        def squeeze(cand, parent, actid, valid, width):
            """Compact enabled candidate rows to the front of a `width`
            buffer; overflow=True iff more than `width` rows are enabled."""
            n_en = jnp.sum(valid, dtype=jnp.int32)
            spos = jnp.where(valid, jnp.cumsum(valid) - 1, width)
            out = jnp.zeros((width, K), jnp.uint32).at[spos].set(cand)
            out_parent = jnp.full((width,), -1, jnp.int32).at[spos].set(parent)
            out_act = jnp.full((width,), -1, jnp.int32).at[spos].set(actid)
            rowvalid = jnp.arange(width) < n_en
            return out, out_parent, out_act, rowvalid, n_en, n_en > width

        def fp_masked(cand, valid):
            """Masked (hi, lo) fingerprints (Pallas opt-in or jnp path)."""
            if self.use_pallas:
                import math

                from ..ops.pallas_fingerprint import fingerprint_pallas

                interp = jax.default_backend() == "cpu"
                # block_rows must divide the buffer width (the largest
                # power-of-two divisor, capped at 8k rows/block): every
                # buffer here is 1024-aligned or a power-of-two multiple
                # of C, so blocks stay >= 256 rows
                rows = cand.shape[0]
                block = math.gcd(rows, 1 << 13)
                return fingerprint_pallas(
                    cand, valid, block_rows=block, interpret=interp
                )
            hi, lo = fingerprint_lanes(cand, spec.exact64)
            return jnp.where(valid, hi, sent), jnp.where(valid, lo, sent)

        def frontier_invariants(states, fvalid):
            """Per-invariant (any-violated, first-index) on the frontier
            being expanded (each state is checked exactly once, at
            expansion; BFS order: states before successors)."""
            if not (with_invariants and model.invariants):
                return jnp.stack([jnp.bool_(False)]), jnp.stack([jnp.int32(0)])
            if model.invariants_fused is not None:
                # one trace for all predicates: shared subtrees (e.g. the
                # WeakIsr/StrongIsr quantifier core in emitted models)
                # evaluate once
                ok = jax.vmap(model.invariants_fused)(states)  # [B, n_inv]
                bad = fvalid[:, None] & ~ok
                return jnp.any(bad, axis=0), jnp.argmax(bad, axis=0)
            viol_any, viol_idx = [], []
            for inv in model.invariants:
                ok = jax.vmap(inv.pred)(states)
                bad = fvalid & ~ok
                viol_any.append(jnp.any(bad))
                viol_idx.append(jnp.argmax(bad))
            return jnp.stack(viol_any), jnp.stack(viol_idx)

        def step(frontier, fvalid, vhi, vlo, vn):
            states = jax.vmap(spec.unpack)(frontier)
            (
                en_pre,
                cand,
                valid,
                parent,
                actid,
                act_en,
                act_guard,
                exp_ovf,
            ) = expand(states, fvalid)
            deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
            dl_any = jnp.any(deadlocked)
            dl_idx = jnp.argmax(deadlocked)

            # overflow contract: bool[n_actions + 1] — per-action compact-
            # buffer overflow plus one trailing squeeze-overflow flag
            def ovf_vec(sq_ovf=None):
                tail = (
                    jnp.zeros((1,), bool)
                    if sq_ovf is None
                    else jnp.atleast_1d(sq_ovf)
                )
                return jnp.concatenate([exp_ovf, tail])

            if host_dedup:
                out, out_parent, out_act, rowvalid, n_en, sq_ovf = squeeze(
                    cand, parent, actid, valid, T
                )
                overflow = ovf_vec(sq_ovf)
                out_hi, out_lo = fp_masked(out, rowvalid)
                viol_any, viol_idx = frontier_invariants(states, fvalid)
                return (
                    out,
                    out_parent,
                    out_act,
                    n_en,
                    vhi,
                    vlo,
                    vn,
                    viol_any,
                    viol_idx,
                    dl_any,
                    dl_idx,
                    act_en,
                    out_hi,
                    out_lo,
                    overflow,
                    act_guard,
                )

            if shift:
                cand, parent, actid, valid, _, sq_ovf = squeeze(
                    cand, parent, actid, valid, T
                )
                overflow = ovf_vec(sq_ovf)
            else:
                overflow = ovf_vec()

            hi, lo = fp_masked(cand, valid)
            # minimal-payload sort: only the original index rides through the
            # sort network; state rows/parents are gathered once afterwards
            order = jnp.lexsort((lo, hi))
            hi_s, lo_s = hi[order], lo[order]
            invalid_s = (hi_s == sent) & (lo_s == sent)
            first = dedup.first_occurrence_mask(hi_s, lo_s, invalid_s)
            seen, rank = dedup.rank_sorted(vhi, vlo, vn, hi_s, lo_s)
            is_new = first & ~seen

            # compact new states to the front (OOB scatter indices are dropped)
            pos = jnp.where(is_new, jnp.cumsum(is_new) - 1, T)
            out = jnp.zeros((T, K), jnp.uint32).at[pos].set(cand[order])
            out_parent = jnp.full((T,), -1, jnp.int32).at[pos].set(parent[order])
            out_act = jnp.full((T,), -1, jnp.int32).at[pos].set(actid[order])
            out_hi = jnp.full((T,), sent).at[pos].set(hi_s)
            out_lo = jnp.full((T,), sent).at[pos].set(lo_s)
            out_rank = jnp.zeros((T,), jnp.int32).at[pos].set(rank)
            new_n = jnp.sum(is_new, dtype=jnp.int32)

            if with_merge:
                vhi2, vlo2, vn2 = dedup.merge_ranked(
                    vhi, vlo, vn, out_hi, out_lo, out_rank, new_n, vcap
                )
            else:
                vhi2, vlo2, vn2 = vhi, vlo, vn

            viol_any, viol_idx = frontier_invariants(states, fvalid)
            return (
                out,
                out_parent,
                out_act,
                new_n,
                vhi2,
                vlo2,
                vn2,
                viol_any,
                viol_idx,
                dl_any,
                dl_idx,
                act_en,
                out_hi,
                out_lo,
                overflow,
                act_guard,
            )

        return step


class PreparedKernels:
    """Reusable, warm engine kernels for one model — the serving daemon's
    unit of caching (service/kernel_cache.py), split out of :func:`check`.

    ``check`` builds a ``_Step`` per call; because the jitted-step cache
    lives on the Model object it already re-warms across calls, but the
    serving path needs the preparation to be an explicit, inspectable
    artifact: ``prepare(model)`` once, then ``check(model,
    prepared=pk)`` any number of times — the second and every later check
    of the same schema shape re-uses every compiled step (zero ``compile``
    spans in its trace, the daemon's warm-path proof).  ``warmup``
    optionally pre-compiles the step for a given frontier bucket so even
    the FIRST job of a shape pays no compile inside its latency budget.
    """

    def __init__(self, model: Model):
        self.model = model
        self.step = _Step(model)
        # The last run's FINAL visited capacity, fed back as check()'s
        # visited_capacity_hint so WARM runs preallocate the device
        # visited set at exactly the size the shape needs.  Without it
        # every run replays the capacity-doubling ladder, and each
        # doubling EVICTS the steps compiled for the outgrown capacity —
        # i.e. a "warm" run would recompile the whole ladder again
        # (measured 5s/run on the tiny truncate model).  Feeding back the
        # final CAPACITY (a power of two the engine itself derived) makes
        # the hint a fixed point: the next run of the same knobs starts
        # at the same vcap, so every step-cache key matches and the warm
        # trace shows zero compile spans.  The hash backend sizes its
        # table from a state count instead, so non-device runs feed back
        # res.total.
        self.capacity_hint = None
        self._hint_is_capacity = False  # True iff hint is a device vcap

    def note_result(self, res: "CheckResult") -> None:
        """Feed a finished run's visited sizing back into the hint."""
        stats = res.stats or {}
        if stats.get("visited_backend") == "device":
            cap = stats.get("visited_capacity") or res.total
            self._hint_is_capacity = True
        else:
            cap = res.total
            self._hint_is_capacity = False
        self.capacity_hint = max(self.capacity_hint or 0, cap)

    def rewarm(self) -> int:
        """Close the warm-capacity gap left by a run that GREW the device
        visited set: growth evicts the steps compiled at every outgrown
        capacity, but the buckets those steps served (the small early
        levels) recur on the next run of this shape — which starts at the
        new capacity fixed point and would pay one compile per missing
        (bucket, final-capacity) variant.  Re-compile them now, off any
        job's latency path, so the second job of a shape shows zero
        compile spans even when the first had to grow (the serving
        warm-path contract; the daemon calls this right after a run,
        still inside its busy-heartbeat window).  Returns the number of
        variants compiled."""
        cap = self.capacity_hint
        if not cap or not getattr(self, "_hint_is_capacity", False):
            return 0  # non-device backends never evict on growth
        from .pipeline import key_vcap, warm_key

        done = 0
        for key in list(self.step._compiled_log):
            vcap = key_vcap(key)
            if vcap is None or vcap == cap:
                continue  # no capacity component, or already at the
                # fixed point (guard kernels never evict on growth)
            target = tuple(
                cap if i == 2 else f for i, f in enumerate(key)
            )
            if target in self.step._cache:
                continue
            if warm_key(self.step, self.model, key, cap) is not None:
                done += 1
        return done

    @property
    def compiled_steps(self) -> int:
        """Distinct (shape, variant) step programs built so far."""
        return len(self.step._cache)

    def warmup(
        self,
        bucket: int = 256,
        vcap: int = 1 << 12,
        check_invariants: bool = True,
        with_merge: bool = True,
        compact=None,
        squeeze_full: bool = False,
    ) -> None:
        """Force trace + XLA compile of one step shape by running it on an
        all-invalid frontier (fvalid all False: no successor is enabled, no
        verdict can fire — pure compilation, results discarded)."""
        bucket = _next_pow2(max(32, bucket))
        vcap = _next_pow2(max(64, vcap))
        step = self.step.get(
            bucket, vcap, check_invariants, with_merge=with_merge,
            compact=compact, squeeze_full=squeeze_full,
        )
        K = self.model.spec.num_lanes
        out = step(
            jnp.zeros((bucket, K), jnp.uint32),
            jnp.zeros((bucket,), bool),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.full(vcap, 0xFFFFFFFF, jnp.uint32),
            jnp.int32(0),
        )
        jax.block_until_ready(out)


def prepare(model: Model) -> PreparedKernels:
    """Prepare (and cache on the model) the reusable jitted engine kernels
    for `model` — the explicit warm entry point ``check(...,
    prepared=...)`` consumes."""
    return PreparedKernels(model)


def _pad_rows(arr: np.ndarray, n: int, fill=0):
    if arr.shape[0] == n:
        return arr
    pad_shape = (n - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, arr.dtype)])


# --- frontier adapters: the level loop runs identically over an in-RAM
# array or a disk-spilled FrontierReader (storage/frontier) — same global
# offsets, same chunk boundaries, hence bit-identical counts and traces
def _f_rows(f) -> int:
    return f.shape[0] if isinstance(f, np.ndarray) else f.rows


def _f_chunks(f, chunk: int):
    if isinstance(f, np.ndarray):
        for s in range(0, f.shape[0], chunk):
            yield s, f[s : s + chunk]
    else:
        yield from f.iter_chunks(chunk)


def _f_row(f, i: int) -> np.ndarray:
    return f[i] if isinstance(f, np.ndarray) else f.row(i)


def _f_all(f) -> np.ndarray:
    return f if isinstance(f, np.ndarray) else f.read_all()


def walk_trace(trace_store, actions, decode_row, inv_name, depth, idx) -> Violation:
    """Parent-pointer counterexample reconstruction, shared by both engines.

    trace_store[level] = (rows, parent, act): the level's states in discovery
    order, each new state's parent index into the previous level, and the
    action id that produced it.  Walks level `depth` index `idx` back to an
    init state and returns the Violation with the root->violation trace.
    """
    chain = []
    i = idx
    for d in range(depth, 0, -1):
        rows, parent, act = trace_store[d]
        chain.append((actions[int(act[i])].name, decode_row(rows[i])))
        i = int(parent[i])
    rows0, _, _ = trace_store[0]
    chain.append(("<init>", decode_row(rows0[i])))
    chain.reverse()
    return Violation(invariant=inv_name, depth=depth, state=chain[-1][1], trace=chain)


def check(
    model: Model,
    max_depth: Optional[int] = None,
    max_states: Optional[int] = None,
    store_trace: bool = True,
    min_bucket: int = 256,
    check_invariants: bool = True,
    progress=None,
    collect_levels: Optional[list] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    checkpoint_keep: int = 3,
    check_deadlock: bool = False,
    stats_path: Optional[str] = None,
    visited_backend: str = "device",
    chunk_size: int = 32768,
    visited_capacity_hint: Optional[int] = None,
    visited_capacity_exact: Optional[int] = None,
    compact_shift: int = 2,
    compact_gate: int = 4096,
    pipeline: Optional[str] = None,
    mem_budget=None,
    spill_dir: Optional[str] = None,
    store: str = "auto",
    disk_budget=None,
    run=None,
    prepared: Optional[PreparedKernels] = None,
    collect_trace: Optional[list] = None,
    governor: Optional[ResourceGovernor] = None,
    integrity_shadow: Optional[float] = None,
    overlap: Optional[bool] = None,
    seed: Optional[dict] = None,
) -> CheckResult:
    """Breadth-first exhaustive check of `model`. Stops at first violation.

    check_deadlock: when True (TLC's CHECK_DEADLOCK TRUE), a reachable state
    with no enabled action is reported as a violation of the pseudo-invariant
    "Deadlock" (CONSTRAINT pruning does not mask enabledness).  Default off:
    the bounded corpus models deadlock by design (SURVEY.md §2.4).

    stats_path: append one JSON line per BFS level (depth, frontier size,
    enabled candidates, new/dup counts, per-action enablement histogram,
    wall ms) — the PROGRESS.jsonl observability stream (SURVEY.md §5); the
    same records land in CheckResult.stats["levels"].

    visited_backend:
    - "device": sorted fingerprint pair set in HBM — dedup by lexsort +
      binary-search probe + rank-scatter merge.  The merge rebuilds
      O(capacity) per chunk, which dominates at small frontiers.
    - "device-hash": open-addressing hash table in HBM (ops/hashset) —
      insert-or-find in O(batch · expected-probes) per chunk, independent
      of table size; no sort, no merge.  The recommended device-resident
      backend.
    - "host": the native C++ open-addressing FpSet (native/fpset.cpp) does
      ALL dedup on the host — the TLC-FPSet spill mode for state spaces
      whose fingerprints outgrow device memory (device HBM then holds only
      O(chunk x fanout) transient data), and the fastest mode on a CPU
      "device".
    With hashed (non-exact64) fingerprints all backends accept TLC's usual
    64-bit collision risk; all three produce identical counts and traces.

    chunk_size: frontiers larger than this stream through the compiled step
    in pieces (cross-chunk dedup via the shared visited set), bounding the
    number of jit-compiled shapes and peak device memory regardless of
    state-space size.

    visited_capacity_hint: preallocate the device visited set for ~this many
    states (plus one chunk of insert headroom) so capacity doubling (one
    recompile per doubling) never triggers on runs whose state-space size
    is roughly known.

    visited_capacity_exact: preallocate the device visited set at exactly
    this capacity (no headroom added) — for callers replaying a PRIOR
    run's final capacity (PreparedKernels.capacity_hint), where an exact
    fixed point is what keeps every warm step-cache key identical.

    compact_shift: two-phase expansion — sweep guards over the full padded
    lattice (state updates dead-code-eliminated), then run each action's
    update+pack and the sort/probe/merge at 1/2^compact_shift of the lattice
    width (only a few percent is ever enabled).  Purely a performance knob:
    a chunk whose enabled count overflows a compact buffer is re-run at
    double the width (the step reports overflow; results stay exact).  0
    disables compaction.

    pipeline: level-pipeline implementation (engine/pipeline.py; the
    jax-free registry in pipeline_registry.py is the validated name
    set — unknown names raise, `cli pipelines --list` describes them):
    "fused" (default; $KSPEC_PIPELINE overrides) = successor mega-kernels
    — per chunk, ONE batched guard-predicate-matrix launch over the
    (frontier x choice) lattice, C-speed host compaction into one shared
    data-driven-width buffer, and ONE update-skeleton launch
    (gather -> action update -> CONSTRAINT -> pack -> fingerprint), i.e.
    2 successor launches per chunk instead of one per action;
    "device" = the device-resident level pipeline — a bounded
    lax.while_loop processes EVERY gated chunk of a level inside one
    dispatched program (expansion, in-jit segmented compaction,
    fingerprints, intra-level dedup, verdicts all on-device), i.e. <=2
    successor launches per level.  On the sorted-set "device" backend
    the visited probe + digest folds run in-jit and the O(capacity)
    visited merge runs once per LEVEL instead of once per chunk; on
    the "host" backend (incl. the disk tier) the visited probe is
    DEFERRED to ONE batched host FpSet/tiered-run call per level
    (host syncs O(1)/level instead of O(chunks), serial winner rule
    preserved).  Requires analyzer-proven per-field value hulls
    (analysis.field_hulls — a hard precondition, not env-disablable
    like the build gate); the "device-hash" backend and any other
    unmet precondition degrade to the fused per-chunk ladder
    (stats["device"]["fallback"] records why, naming the backend);
    "legacy" = the historical per-action monolithic step.  All are
    bit-identical — same level counts, duplicate accounting,
    first-violation rule, trace values and digest chains
    (tests/test_pipeline.py, tests/test_integrity.py); a fused program
    that fails to compile degrades the run to legacy (recorded in
    stats["degradations"] and stats["pipeline_fallback"]).
    compact_gate: frontier-bucket floor below which every pipeline runs
    the uncompacted full-lattice path (small levels; default 4096).

    checkpoint_dir: when set, the (visited set, frontier, level counters) are
    persisted every `checkpoint_every` BFS levels (default 1 = per level; a
    crash loses at most checkpoint_every-1 levels of work) and a run restarts
    from the last saved level if a checkpoint exists — the natural fit for a
    level-synchronous engine (SURVEY.md §5 "Checkpoint / resume"; TLC keeps
    this externally).  Checkpoints are hardened (resilience.checkpoints):
    every array is checksummed into an in-file manifest, the newest
    `checkpoint_keep` generations rotate under atomic promotes, and a
    corrupt/truncated newest generation falls back automatically to the
    newest verifying one instead of aborting the run.  Checkpointed runs
    don't retain parent-pointer traces across restarts, so store_trace is
    forced off — a violation found after a resume reports the violating
    state with an EMPTY trace (known trace-loss limitation: re-deriving the
    path would need a re-walk from the init states; docs/resilience.md).

    Fault injection (resilience.faults): a `KSPEC_FAULT` plan exercises the
    recovery paths deterministically — level-boundary / checkpoint-write
    crashes, mid-merge disk-tier crashes (`crash@merge:N`), checkpoint
    corruption, transient backend errors (retried with bounded exponential
    backoff; count in result.stats["transient_retries"]) and the
    escalated-compile OOM (degrades to the uniform compact path; recorded
    in result.stats["degradations"]).

    Out-of-core storage (storage/): `store` = "auto" | "ram" | "disk".
    "disk" (or "auto" with a `mem_budget`) activates the disk tier for
    state spaces that outgrow RAM: the host FpSet is bounded at
    `mem_budget` bytes and spills sorted, bloom-gated fingerprint runs to
    `spill_dir` (periodic k-way merge; lookups touch disk only on probable
    hits), the frontier spills to chunked segments consumed in discovery
    order, and parent pointers go to an append-only on-disk log so
    counterexample traces are reconstructed from the log — including after
    a checkpoint resume (this retires the empty-trace-after-resume
    limitation for this engine).  The disk tier implies
    visited_backend="host" (the disk tier spills the host level of the
    hierarchy; device backends stay the in-HBM hot path) and is
    bit-identical to the in-RAM path: same counts, depths, and trace
    values (tests/test_storage.py forces tiny budgets to prove it).
    Checkpoints record the storage manifest (run names + frontier segment
    offsets) instead of re-serializing state — the disk tier itself is the
    durable state.

    run: an obs.RunContext — correlates this run's stats/spans/metrics
    under one run_id in the run directory (docs/observability.md).  With
    run=None and a bare stats_path the per-level stream is emitted exactly
    as before the obs subsystem existed (the shim contract,
    tests/test_obs.py).

    prepared: a :class:`PreparedKernels` for this model (``prepare``):
    the serving daemon's warm path — every compiled step is re-used, so a
    warm check pays zero trace/compile (its span trace shows zero
    ``compile`` spans).  Must wrap the SAME model object.

    collect_trace: external list receiving the per-level trace store
    ``(rows, parent, act)`` tuples (filled only while store_trace is on) —
    the batched multi-config runner (service/batch.py) derives per-job
    counterexample traces from a shared exploration through this.

    governor: a pre-built :class:`ResourceGovernor` to use instead of the
    env-derived one — the serving daemon's per-TENANT budget instances
    (service/scheduler.py); a breach inside this check raises the same
    typed ResourceExhausted without touching any other job's budgets.

    integrity_shadow: sampled shadow re-execution rate in [0, 1]
    ($KSPEC_INTEGRITY_SHADOW is the env twin; default 0 = off).  A
    deterministically sampled chunk is re-executed through an independent
    path BEFORE its outputs are committed — the legacy pipeline for
    fused-gated chunks (counts, new-fingerprint multiset and verdict
    flags must match the fused result bit-for-bit), and the host
    fingerprint oracle (numpy recomputation of every emitted row's
    fingerprint) for every sampled chunk — so silent device/compaction
    corruption is caught in-flight, typed, and never enters a
    checkpoint.  Always-on independent of the rate: the per-level digest
    chain over the new-state fingerprint multiset (stamped into
    checkpoints + verified at every level boundary, on resume, and by
    the offline `cli verify-checkpoint`), the save-time visited-set
    self-check, and read-side storage checksums.  Any failure raises the
    typed :class:`IntegrityError` (CLI exit 76) with the run manifest
    stamped ``integrity-violation`` (resilience.integrity,
    docs/resilience.md).  KSPEC_INTEGRITY=0 disables the whole layer.

    seed: resume-shaped warm start from a VERIFIED prior exploration of
    the same model (the service's persistent state-space cache,
    service/state_cache.py): a dict of ``visited_fps`` (uint64 multiset
    of every visited fingerprint), ``frontier`` (the boundary level's
    packed uint32 rows), ``levels``, ``total``, ``depth`` and
    ``digest_chain`` (the [L, 4] chain array).  The run then starts by
    expanding the boundary at ``depth`` instead of Init — exactly the
    checkpoint-resume semantics, including the limitation: parent
    pointers below the seed do not exist, so ``store_trace`` is forced
    off and a violation found past the seed reports its state with an
    empty trace.  The level-boundary chain verify re-proves the seeded
    frontier against the seeded chain before anything is expanded.
    Counts, levels, verdicts are bit-identical to a cold run of the
    larger bound (tests/test_fleet.py).  Mutually exclusive with
    ``checkpoint_dir`` and the disk tier.

    overlap: async level-pipelined execution ($KSPEC_OVERLAP is the env
    twin; default ON, ``off``/False = the historical serial behavior and
    the bit-identity oracle).  Three overlaps (docs/engine.md § Async
    execution): (1) a two-slot staged chunk pipeline — chunk k+1's
    device programs are dispatched before chunk k's host commit
    (fingerprint-set insert, arena assembly, digest folds) runs, so host
    work drains behind the in-flight update-skeleton launch (JAX async
    dispatch; per-chunk ``step`` spans carry dispatch/device-wait
    attribution); (2) disk-tier spill-run merges run on a background
    worker (storage/tiered.py — lookups keep serving from the immutable
    inputs, adoption and error propagation happen on this thread);
    (3) checkpoint writes move to a writer thread (the engine snapshots
    metadata + digest chain + dumps synchronously; verification, the
    checksummed write and the atomic promote run in the background,
    with ENOSPC/fault errors re-raised here at the next level
    boundary).  Results are bit-identical either way — counts,
    duplicate accounting, first-violation rule, trace values, digest
    chains (tests/test_overlap.py pins the matrix).

    disk_budget: byte budget for the spill + checkpoint directories
    (resilience.resources.ResourceGovernor; KSPEC_DISK_BUDGET is the env
    twin, KSPEC_RSS_BUDGET / KSPEC_LEVEL_DEADLINE arm the RSS and
    per-level-deadline watchdogs).  Crossing the soft fraction triggers
    reclamation (tmp janitor, eager merges, checkpoint-generation prune,
    deletion-barrier flush); a hard breach — or a real/injected ENOSPC
    from any storage writer — performs checkpoint-then-clean-exit: the
    newest consistent state is saved, the run directory is stamped
    `resource-exhausted`, and a typed ResourceExhausted propagates (the
    CLI maps it to exit code 75).  The on-disk state still passes `cli
    verify-checkpoint`, and resuming after the operator frees space is
    bit-identical to an uninterrupted run (tests/test_resources.py).
    """
    spec = model.spec
    # encoding-soundness gate (analysis; KSPEC_ANALYZE=0 disables): an
    # action that can write outside its declared field ranges would be
    # silently truncated by the bit packer — refuse to explore instead
    # of returning a wrong verdict (memoized per model name)
    from ..analysis import require_encoding_sound

    require_encoding_sound(model)
    if prepared is not None and prepared.model is not model:
        raise ValueError("prepared kernels wrap a different model object")
    step_builder = prepared.step if prepared is not None else _Step(model)
    K, C = spec.num_lanes, step_builder.C

    # unified telemetry: run_id-stamped stats/spans/metrics when a run
    # context is given; the exact historical stats_path stream otherwise
    obs_ = RunObserver(run, stats_path, engine="bfs")

    from ..storage import resolve_store

    use_disk = resolve_store(store, mem_budget)
    want_trace = store_trace
    if use_disk:
        # the disk tier spills the HOST level of the hierarchy; traces
        # ride the on-disk parent log instead of the in-RAM trace store
        visited_backend = "host"
        store_trace = False

    fault = FaultPlan.from_env()
    chunk_retry = ChunkRetryHandler.from_env("[engine]")
    # async overlap layer (overlap.py; $KSPEC_OVERLAP, default on):
    # io_worker carries background spill-run merges, ckpt_worker the
    # async checkpoint writes; the two-slot chunk pipeline below needs
    # no thread (JAX async dispatch is the worker)
    from ..overlap import (
        AsyncWorker,
        close_workers,
        overlap_enabled,
        worker_counters,
    )

    overlap_on = overlap_enabled(overlap)
    io_worker = AsyncWorker("kspec-io") if overlap_on else None
    ckpt_worker = (
        AsyncWorker("kspec-ckpt")
        if overlap_on and checkpoint_dir is not None
        else None
    )

    def _shutdown_async(drain: bool) -> None:
        close_workers((io_worker, ckpt_worker), drain)
    # state-integrity defense (resilience.integrity): always-on level
    # digest chain + sampled shadow re-execution; KSPEC_INTEGRITY=0 is
    # the kill switch (bench baselines, emergency escape hatch)
    chain = _integ.LevelDigestChain() if _integ.enabled() else None
    shadow_rate = (
        _integ.shadow_rate(integrity_shadow) if chain is not None else 0.0
    )
    ckpt_store = None  # built once ckpt_ident is known
    # newest durably checkpointed level (None = not checkpointing):
    # level-crash faults defer until the target level is checkpointed so
    # a supervised restart converges (FaultPlan.crash)
    last_ckpt_depth = None
    if checkpoint_dir is not None:
        store_trace = False
        last_ckpt_depth = 0
        checkpoint_every = max(1, int(checkpoint_every))
    if seed is not None:
        if checkpoint_dir is not None:
            raise ValueError(
                "seed= and checkpoint_dir are mutually exclusive (a seed "
                "IS a resume; layering the two would race their chains)"
            )
        if use_disk:
            raise ValueError("seed= requires the in-RAM store")
        # same limitation as checkpoint resume: parent pointers below the
        # seed do not exist, so traces cannot be reconstructed
        store_trace = False

    inits = [
        {k: np.asarray(v, np.int32) for k, v in s.items()} for s in model.init_states()
    ]
    init_packed = np.stack([np.asarray(spec.pack(s)) for s in inits])
    # dedup inits (all corpus models have a single deterministic init)
    init_packed = np.unique(init_packed, axis=0)
    n0 = init_packed.shape[0]

    if visited_backend not in ("device", "host", "device-hash"):
        raise ValueError(
            "visited_backend must be 'device', 'device-hash' or 'host', "
            f"got {visited_backend!r}"
        )
    host_set = None
    ht_hi = ht_lo = ht_claim = None  # device-hash table (ops/hashset)
    hash_n = 0
    # ht_claim is allocated LAZILY at the insert site (the jnp probe path
    # needs it; the Pallas path does not), so table (re)builds just reset
    # it to None.  pallas_vmem_noted: warn once per run on VMEM fallback.
    pallas_vmem_noted = False

    def _u64(hi, lo):
        return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
            lo
        ).astype(np.uint64)

    t0 = time.perf_counter()
    hi0, lo0 = fingerprint_lanes(jnp.asarray(init_packed), spec.exact64)
    disk = None
    ephemeral_spill = None
    if visited_backend == "host":
        if use_disk:
            from ..storage import (
                DEFAULT_MEM_BUDGET,
                DiskTierStore,
                parse_mem_budget,
            )

            budget = (
                parse_mem_budget(mem_budget)
                if mem_budget is not None
                else DEFAULT_MEM_BUDGET
            )
            sd = spill_dir or (
                os.path.join(checkpoint_dir, "spill") if checkpoint_dir else None
            )
            if sd is None:
                import tempfile

                # anonymous spill space: removed after a completed run (a
                # crashed one cannot be resumed without a checkpoint, so
                # its temp data is dead weight either way)
                sd = tempfile.mkdtemp(prefix="kspec-spill-")
                ephemeral_spill = sd
            disk = DiskTierStore(
                sd,
                budget,
                lanes=K,
                gc_barrier=checkpoint_keep if checkpoint_dir else 0,
                seg_rows=int(
                    os.environ.get("KSPEC_SPILL_SEG_ROWS", str(1 << 18))
                ),
                runs_per_merge=int(
                    os.environ.get("KSPEC_SPILL_RUNS_PER_MERGE", "8")
                ),
                fault_plan=fault,
                trace=want_trace or checkpoint_dir is not None,
                merge_worker=io_worker,
            )
            host_set = disk.fpset  # init fps inserted at start_fresh/resume
        else:
            from ..native import FpSet

            host_set = FpSet()
            host_set.insert(_u64(hi0, lo0))
        vcap = 64  # placeholder shapes; the device never holds the visited set
        vhi = jnp.full(vcap, 0xFFFFFFFF, jnp.uint32)
        vlo = jnp.full(vcap, 0xFFFFFFFF, jnp.uint32)
        vn = jnp.int32(0)
    elif visited_backend == "device-hash":
        ht_hi, ht_lo = hashset.table_from_pairs(
            np.asarray(hi0),
            np.asarray(lo0),
            min_cap=_next_pow2(
                max(
                    _HASH_MIN_CAP,
                    4 * (visited_capacity_hint
                         or visited_capacity_exact or 0),
                )
            ),
        )
        ht_claim = None
        hash_n = n0
        vcap = 64  # placeholder shapes for the step signature
        vhi = jnp.full(vcap, 0xFFFFFFFF, jnp.uint32)
        vlo = jnp.full(vcap, 0xFFFFFFFF, jnp.uint32)
        vn = jnp.int32(0)
    else:
        order = np.lexsort((np.asarray(lo0), np.asarray(hi0)))
        chunk_clamped = _next_pow2(max(min_bucket, chunk_size))
        # hint: ~state count, padded with one chunk's worth of insert
        # headroom so the growth check never fires on a roughly-known run.
        # exact: a capacity floor (a prior run's FINAL vcap) used
        # verbatim, so warm serving runs land on the exact same capacity —
        # same step-cache keys, zero recompiles (PreparedKernels)
        vcap = _next_pow2(
            max(
                n0,
                min_bucket * C,
                2,
                visited_capacity_exact or 0,
                (visited_capacity_hint + chunk_clamped * C)
                if visited_capacity_hint
                else 0,
            )
        )
        vhi = np.full(vcap, 0xFFFFFFFF, np.uint32)
        vlo = np.full(vcap, 0xFFFFFFFF, np.uint32)
        vhi[:n0] = np.asarray(hi0)[order]
        vlo[:n0] = np.asarray(lo0)[order]
        vhi, vlo = jnp.asarray(vhi), jnp.asarray(vlo)
        vn = jnp.int32(n0)

    levels = [n0]
    total = n0
    # per level: (packed[np], parent[np], act[np]); aliased to the
    # caller's list when collect_trace is given (service/batch.py)
    trace_store = collect_trace if collect_trace is not None else []
    trace_store.clear()
    if store_trace:
        trace_store.append((init_packed, np.full(n0, -1), np.full(n0, -1)))
    if collect_levels is not None:
        collect_levels.append(init_packed)

    def decode_state(packed_row: np.ndarray):
        s = {k: np.asarray(v) for k, v in spec.unpack(jnp.asarray(packed_row)).items()}
        return model.decode(s) if model.decode else s

    def _drop_ephemeral_spill():
        if ephemeral_spill is not None:
            import shutil

            shutil.rmtree(ephemeral_spill, ignore_errors=True)

    def build_violation(inv_name, depth, idx):
        if disk is not None and disk.has_trace(depth):
            # reconstruct from the on-disk parent log: O(depth) single-
            # record reads through the mmap'd level segments — this is
            # what makes traces survive checkpoint/resume
            return walk_trace(
                disk.plog.view(), model.actions, decode_state, inv_name, depth, idx
            )
        return walk_trace(trace_store, model.actions, decode_state, inv_name, depth, idx)

    def have_trace(depth) -> bool:
        return store_trace or (disk is not None and disk.has_trace(depth))

    # invariants on init states
    if check_invariants and model.invariants:
        st0 = jax.vmap(spec.unpack)(jnp.asarray(init_packed))
        for inv in model.invariants:
            ok = np.asarray(jax.vmap(inv.pred)(st0))
            if not ok.all():
                idx = int(np.argmax(~ok))
                dt = time.perf_counter() - t0
                viol = Violation(
                    invariant=inv.name,
                    depth=0,
                    state=decode_state(init_packed[idx]),
                    trace=[("<init>", decode_state(init_packed[idx]))],
                )
                _drop_ephemeral_spill()
                _shutdown_async(drain=True)
                res = CheckResult(
                    model.name, levels, total, 0, viol, dt, total / max(dt, 1e-9)
                )
                obs_.finish(res)
                obs_.close()
                return res

    frontier_np = init_packed
    depth = 0
    violation = None
    result_stats: dict = {}
    collect_stats = obs_.collect
    obs_.config(
        model=model.name,
        visited_backend=visited_backend,
        store="disk" if use_disk else "ram",
        mem_budget=mem_budget,
        chunk_size=chunk_size,
        checkpoint_dir=checkpoint_dir,
        platform=jax.default_backend(),
    )

    # identity stamp: a checkpoint may only resume the same model, constants,
    # invariant selection, and deadlock setting (a resume never re-checks
    # already-explored levels, so a stricter check must start fresh)
    inv_names = ",".join(sorted(i.name for i in model.invariants)) if check_invariants else "-"
    ckpt_ident = (
        f"{model.name}|lanes={spec.num_lanes}|backend={visited_backend}|"
        f"inv={inv_names}|dl={check_deadlock}|"
        + ",".join(f"{f.name}:{f.shape}:{f.lo}:{f.hi}" for f in spec.fields)
        + ("|store=disk" if use_disk else "")
    )
    def _spill_ref_errors(arrays: dict) -> list:
        """Disk-tier load validator: CRC-verify every spill run and
        frontier segment a generation REFERENCES before accepting it —
        a generation whose referenced run rotted on disk (flip@spill)
        then falls back to an older one that predates the corrupt file
        (whose deterministic re-exploration rewrites it), instead of
        crashing mid-restore."""
        if disk is None or "spill_manifest" not in arrays:
            return []
        from ..storage.frontier import FrontierReader as _FR
        from ..storage.frontier import SegmentCorrupt as _SC

        man = json.loads(str(arrays["spill_manifest"]))
        errs = _integ.spill_run_errors(
            disk.fpset.dir, (man.get("fpset") or {}).get("runs", ())
        )
        try:
            _FR(disk.frontier_dir, man["frontier"], verify=True)
        except _SC as e:
            errs.append(f"referenced frontier segment corrupt: {e}")
        return errs

    resumed = False
    resumed_chain_arr = None
    if checkpoint_dir is not None:
        ckpt_store = CheckpointStore(
            checkpoint_dir,
            "bfs_checkpoint.npz",
            ident=ckpt_ident,
            keep=checkpoint_keep,
            fault_plan=fault,
            # chain-mismatch generations (CRC-consistent content
            # corruption) fall back exactly like checksum failures: the
            # run resumes from the newest CHAIN-VERIFIED generation
            validators=(
                (_integ.checkpoint_chain_errors, _spill_ref_errors)
                if chain is not None
                else (_spill_ref_errors,)
            ),
        )
        if ckpt_worker is not None:
            ckpt_store.attach_writer(ckpt_worker)
        loaded = ckpt_store.load()
        if loaded is not None:
            resumed = True
            snap, _, _gen = loaded
            if "digest_chain" in snap:
                resumed_chain_arr = snap["digest_chain"]
            if disk is not None:
                # the checkpoint references the disk tier, it does not
                # contain it: reopen the manifest's runs + frontier
                # segments IN PLACE (host_set aliases disk.fpset),
                # re-seed the budget-bounded hot set
                disk.resume(
                    json.loads(str(snap["spill_manifest"])), snap["host_fps"]
                )
                frontier_np = disk.pending()
            elif host_set is not None:
                frontier_np = snap["frontier"]
                from ..native import FpSet

                host_set = FpSet(initial_capacity=max(64, 2 * len(snap["host_fps"])))
                host_set.insert(snap["host_fps"])
            elif ht_hi is not None:
                frontier_np = snap["frontier"]
                live_hi = snap["hash_hi"]
                live_lo = snap["hash_lo"]
                hash_n = live_hi.shape[0]
                ht_hi, ht_lo = hashset.table_from_pairs(
                    live_hi, live_lo, min_cap=_HASH_MIN_CAP
                )
                ht_claim = None
            else:
                frontier_np = snap["frontier"]
                vcap = int(snap["vcap"])
                n = int(snap["vn"])
                pad = np.full(vcap - n, 0xFFFFFFFF, np.uint32)
                vhi = jnp.asarray(np.concatenate([snap["vhi"], pad]))
                vlo = jnp.asarray(np.concatenate([snap["vlo"], pad]))
                vn = jnp.int32(n)
            levels = snap["levels"].tolist()
            total = int(snap["total"])
            depth = int(snap["depth"])
            last_ckpt_depth = depth
            # crash faults at or below the resume level count as fired
            # (a supervised restart must converge, not crash-loop)
            fault.set_start_depth(depth)

    seeded = False
    if seed is not None:
        # warm start from a verified cached exploration (state_cache):
        # structurally identical to the checkpoint-resume path above,
        # sourced from the portable artifact instead of a generation.
        # The visited set is reconstructed from the u64 fingerprint
        # multiset — every backend's visited state is a pure function of
        # it — and the boundary frontier is expanded next, so the level
        # loop continues exactly where the cached run's bound cut it.
        seeded = True
        seed_fps = np.sort(
            np.ascontiguousarray(np.asarray(seed["visited_fps"], np.uint64))
        )
        s_hi = (seed_fps >> np.uint64(32)).astype(np.uint32)
        s_lo = (seed_fps & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        frontier_np = np.ascontiguousarray(
            np.asarray(seed["frontier"], np.uint32)
        ).reshape(-1, K)
        n_seed = int(seed_fps.shape[0])
        if visited_backend == "host":
            from ..native import FpSet

            host_set = FpSet(initial_capacity=max(64, 2 * n_seed))
            host_set.insert(seed_fps)
        elif visited_backend == "device-hash":
            ht_hi, ht_lo = hashset.table_from_pairs(
                s_hi, s_lo, min_cap=_HASH_MIN_CAP
            )
            ht_claim = None
            hash_n = n_seed
        else:
            seed_chunk = _next_pow2(max(min_bucket, chunk_size))
            vcap = _next_pow2(
                max(
                    n_seed + seed_chunk * C,
                    min_bucket * C,
                    2,
                    visited_capacity_exact or 0,
                )
            )
            pad = np.full(vcap - n_seed, 0xFFFFFFFF, np.uint32)
            # u64 sort order == (hi, lo) lexsort order: the split lanes
            # land exactly as the sorted-set backend stores them
            vhi = jnp.asarray(np.concatenate([s_hi, pad]))
            vlo = jnp.asarray(np.concatenate([s_lo, pad]))
            vn = jnp.int32(n_seed)
        levels = [int(v) for v in seed["levels"]]
        total = int(seed["total"])
        depth = int(seed["depth"])
        # crash faults at or below the seed level count as fired, the
        # same convergence rule as a checkpoint resume
        fault.set_start_depth(depth)

    if disk is not None and not resumed:
        # fresh out-of-core run: the spill directory namespace belongs to
        # this run (stale runs must not pre-seed the visited set)
        disk.start_fresh(init_packed, np.asarray(_u64(hi0, lo0)))
        frontier_np = disk.pending()

    if chain is not None:
        if seeded:
            # the cached chain IS the continuation proof, exactly like a
            # resumed checkpoint's: the level-boundary verify below must
            # prove the seeded frontier against its sealed entry before
            # anything is expanded
            chain = (
                _integ.LevelDigestChain.from_array(seed["digest_chain"])
                if seed.get("digest_chain") is not None
                else _integ.LevelDigestChain.from_levels(levels)
            )
        elif resumed:
            # the chain IS the continuation proof: a resumed run extends
            # the stamped chain, and the frontier verify below checks the
            # loaded frontier against its sealed entry.  Pre-integrity
            # checkpoints rebuild an unanchored chain (counts only)
            chain = (
                _integ.LevelDigestChain.from_array(resumed_chain_arr)
                if resumed_chain_arr is not None
                else _integ.LevelDigestChain.from_levels(levels)
            )
        else:
            chain.fold(_integ.pair_u64(hi0, lo0))
            chain.seal(0, n0)

    def _chain_stamp() -> dict:
        # an UNANCHORED chain (rebuilt from a pre-integrity checkpoint's
        # counts — its digests are unknown, stored as zeros) must never
        # be stamped: a stamped zero-digest chain would fail the
        # cumulative visited check on the NEXT load and permanently
        # reject every post-upgrade generation.  Such runs keep saving
        # chain-less checkpoints; anchoring restarts with the next fresh
        # run
        return (
            {"digest_chain": chain.to_array()}
            if chain is not None and chain.anchored
            else {}
        )

    def _readback_chain(path: str, at_depth: int) -> None:
        if chain is not None and chain.anchored:
            _integ.readback_chain(path, depth=at_depth)

    # async-checkpoint bookkeeping (KSPEC_OVERLAP): `last_ckpt_depth`
    # stays the SUBMITTED depth (save-cadence decisions), while
    # `ckpt_durable_depth` advances only when a write has atomically
    # promoted — crash-fault deferral and flip gating key on durability,
    # so a deferred crash can never fire ahead of the checkpoint that
    # makes its restart converge.  `ckpt_barrier_tokens` carries each
    # in-flight save's deletion-barrier watermark (DeferredDeleter.mark):
    # the barrier advances for exactly the files scheduled BEFORE that
    # save's snapshot, preserving the sync ordering contract.
    ckpt_durable_depth = last_ckpt_depth
    ckpt_barrier_tokens: list = []
    sync_io_s = 0.0  # wall spent on SYNChronous checkpoint writes

    def _ckpt_reap(completed) -> None:
        nonlocal ckpt_durable_depth
        for d, _path in completed:
            ckpt_durable_depth = (
                d if ckpt_durable_depth is None
                else max(ckpt_durable_depth, d)
            )
            if disk is not None:
                tok = ckpt_barrier_tokens.pop(0) if ckpt_barrier_tokens \
                    else None
                disk.fpset.deleter.on_save(upto=tok)

    def _ckpt_poll(block: bool = False) -> None:
        # join point for async saves: surfaces writer errors (typed
        # ENOSPC, injected crashes) on the engine thread and advances
        # the durable-depth + deletion-barrier bookkeeping
        if ckpt_worker is None or ckpt_store is None:
            return
        _ckpt_reap(
            ckpt_store.drain_async() if block else ckpt_store.poll_async()
        )

    def _save_checkpoint(sync: bool = False):
        # The async-checkpoint split (docs/resilience.md): everything
        # mutable is SNAPSHOTTED here, synchronously — level metadata,
        # the digest chain, the visited dump (a fresh array from every
        # backend), a copy of the frontier — and the checksummed write,
        # rotation and atomic promote run on the writer thread.  The
        # save-time chain verification moves to the writer too, still
        # BEFORE the promote (detected corruption never enters a
        # checkpoint); ENOSPC and injected faults re-raise at the next
        # _ckpt_poll, preserving the typed exits.
        nonlocal ckpt_durable_depth, sync_io_s
        run_async = ckpt_worker is not None and not sync
        t_sync0 = time.perf_counter()
        # only the live prefix of the visited set is saved (the sentinel
        # padding is rebuilt on resume from vcap/vn); uncompressed — live
        # fingerprints are high-entropy and zlib only burns time
        n = int(vn)
        d_save = depth
        levels_arr = np.asarray(levels)
        # flip injections are gated on an ANCHORED chain: they rehearse
        # detection, and an unanchored chain (pre-integrity resume)
        # cannot detect — injecting there would just silently corrupt
        if chain is not None and chain.anchored and fault.flip(
            "ckpt", d_save, ckpt_depth=ckpt_durable_depth
        ):
            # CRC-consistent metadata corruption: the manifest is built
            # AFTER this flip, so every per-array checksum passes over
            # the corrupt content — only the digest chain flags it
            levels_arr = levels_arr.copy()
            _integ.flip_bit(levels_arr)

        def _dispatch(arrays: dict, pre_write=None, barrier: bool = False):
            nonlocal ckpt_durable_depth, sync_io_s
            if run_async:
                if barrier:
                    ckpt_barrier_tokens.append(disk.fpset.deleter.mark())
                ckpt_store.save_async(
                    d_save, arrays, pre_write=pre_write,
                    after_promote=lambda p: _readback_chain(p, d_save),
                )
                return
            if pre_write is not None:
                pre_write()
            path = ckpt_store.save(d_save, arrays)
            if barrier:
                # a new durable generation exists: advance the deferred-
                # deletion barrier (merged-away runs / consumed frontier
                # segments older than every retained generation unlink)
                disk.on_checkpoint_saved()
            _readback_chain(path, d_save)
            ckpt_durable_depth = (
                d_save if ckpt_durable_depth is None
                else max(ckpt_durable_depth, d_save)
            )
            sync_io_s += time.perf_counter() - t_sync0

        if disk is not None:
            # the disk tier IS the durable state: record the run manifest
            # + frontier-segment offsets + the (budget-bounded) hot dump,
            # never the runs/segments themselves.  (The hot dump is a
            # SUBSET of the visited set, so the cumulative-digest
            # self-check does not apply here — the spilled runs carry
            # their own read-side-verified CRCs instead.)
            _dispatch(
                dict(
                    spill_manifest=json.dumps(disk.manifest()),
                    host_fps=disk.fpset.hot_dump(),
                    vcap=vcap,
                    levels=levels_arr,
                    total=total,
                    **_chain_stamp(),
                ),
                barrier=True,
            )
            return
        if host_set is not None:
            extra = {"host_fps": host_set.dump()}
            pk = "host_fps"
        elif ht_hi is not None:
            th = np.asarray(ht_hi)
            tl = np.asarray(ht_lo)
            live = ~((th == hashset.SENT) & (tl == hashset.SENT))
            extra = {"hash_hi": th[live], "hash_lo": tl[live]}
            pk = "hash_hi"
        else:
            extra = {
                "vhi": np.asarray(vhi[:n]),
                "vlo": np.asarray(vlo[:n]),
                "vn": n,
            }
            pk = "vhi"
        pre_write = None
        if chain is not None and chain.anchored:
            if fault.flip("fpset", d_save, ckpt_depth=ckpt_durable_depth):
                corrupted = np.array(extra[pk], copy=True)
                _integ.flip_bit(corrupted)
                extra[pk] = corrupted
            if host_set is not None:
                dump_fps = np.asarray(extra["host_fps"], np.uint64)
            elif ht_hi is not None:
                dump_fps = _integ.pair_u64(extra["hash_hi"], extra["hash_lo"])
            else:
                dump_fps = _integ.pair_u64(extra["vhi"], extra["vlo"])
            # save-time self-check: the dump must digest to the chain's
            # running total BEFORE the write — corruption detected here
            # never enters a checkpoint.  Async: the chain is snapshotted
            # now (it keeps evolving on this thread) and the check runs
            # on the writer, still pre-promote.
            chain_snap = (
                _integ.LevelDigestChain.from_array(chain.to_array())
                if run_async
                else chain
            )

            def pre_write(chain_snap=chain_snap, dump_fps=dump_fps):
                _integ.count_check()
                chain_snap.verify_visited(dump_fps, depth=d_save)

        frontier_arr = frontier_np
        if run_async and isinstance(frontier_arr, np.ndarray):
            # the live frontier buffer stays mutable on this thread
            # (arena growth, flip injection) — the writer gets a copy
            frontier_arr = np.array(frontier_arr, copy=True)
        _dispatch(
            dict(
                frontier=frontier_arr,
                vcap=vcap,
                levels=levels_arr,
                total=total,
                **extra,
                **_chain_stamp(),
            ),
            pre_write=pre_write,
        )

    chunk = _next_pow2(max(min_bucket, chunk_size))
    chunk_floor = _next_pow2(max(32, min_bucket))

    # Resource governance (resilience.resources): disk/RSS budgets + the
    # per-level deadline watchdog, with soft-breach reclamation and a
    # typed checkpoint-then-clean-exit on hard breach.  A caller-supplied
    # governor (the serving daemon's per-tenant instances) takes
    # precedence over the env-derived one
    if governor is None:
        governor = ResourceGovernor.from_env(
            disk_budget=disk_budget,
            watch_dirs=[disk.dir if disk is not None else None, checkpoint_dir],
            fault_plan=fault,
        )

    def _final_save():
        # checkpoint-then-clean-exit: persist the just-completed level
        # even off the checkpoint_every cadence, so the operator resumes
        # from the breach point, not checkpoint_every-1 levels earlier.
        # Synchronous + drained: the typed exit's contract is a DURABLE
        # state, so the async tail is joined first
        nonlocal last_ckpt_depth
        if ckpt_store is None:
            return
        _ckpt_poll(block=True)
        if last_ckpt_depth != depth or ckpt_durable_depth != depth:
            _save_checkpoint(sync=True)
            last_ckpt_depth = depth

    def _reclaim():
        # soft-breach reclamation, in dependency order (docs/resilience.md):
        # quiesce background work -> tmp janitor -> eager run merge ->
        # fresh checkpoint (references the merged state) -> prune older
        # generations -> flush the deletion barrier (everything still
        # pending was referenced only by the generations just pruned).
        # The quiesce (inside sweep_tmp/reclaim_merge/flush_deleted and
        # the blocking ckpt poll here) is what keeps a reclaim from
        # racing a background merge promote or an in-flight checkpoint
        # write (PR 10 small fix; regression-tested)
        nonlocal last_ckpt_depth
        merged = False
        if disk is not None:
            disk.sweep_tmp()
            merged = disk.reclaim_merge()
        if ckpt_store is not None:
            _ckpt_poll(block=True)
            # skip the save when the periodic one just ran at this depth
            # and no merge changed the on-disk state (the newest gen
            # already references everything the flush keeps) — the
            # pressure path is exactly where write bandwidth is scarcest
            if merged or last_ckpt_depth != depth or \
                    ckpt_durable_depth != depth:
                _save_checkpoint(sync=True)
                last_ckpt_depth = depth
            ckpt_store.prune(keep_gens=1)
            if disk is not None:
                disk.flush_deleted()

    # Adaptive per-action compact sizing (two-phase expansion, SURVEY §2.3):
    # enablement density varies two orders of magnitude across actions
    # (deep 5-broker chunks: LeaderWrite/Truncate at 26-29% of their
    # lattice vs fenced ISR mutations at <0.1%).  The policy — uniform
    # shift until a uniform attempt overflows, then measured high-water
    # widths with learned floors — lives in AdaptiveCompact, shared with
    # the sharded engine (docs/PROFILE_5R.md has the measurements).
    adapt = AdaptiveCompact(model.actions, compact_shift,
                            bucket_gate=compact_gate)

    def _degrade_chunk():
        # device RESOURCE_EXHAUSTED: halve the streaming chunk size for
        # the rest of the run (ChunkRetryHandler's degradation contract)
        nonlocal chunk
        chunk = max(chunk_floor, chunk >> 1)

    # The level-pipeline: per-chunk expand/squeeze/fingerprint (+ the
    # device backend's in-jit dedup) behind one interface — the
    # device-resident whole-level program, the fused 2-launch
    # mega-kernel path or the legacy per-action path
    # (engine/pipeline.py; all bit-identical)
    pipe = make_pipeline(
        resolve_pipeline(pipeline),
        step_builder=step_builder,
        model=model,
        adapt=adapt,
        chunk_retry=chunk_retry,
        fault=fault,
        check_invariants=check_invariants,
        visited_backend=visited_backend,
        on_degrade_chunk=_degrade_chunk,
        compact_shift=compact_shift,
        compact_gate=compact_gate,
        check_deadlock=check_deadlock,
    )
    if getattr(pipe, "name", "") == "device" and shadow_rate > 0 and \
            pipe.device_fallback is None:
        # shadow re-execution replays single chunks from their pre-chunk
        # visited state — a state the whole-level program never
        # materializes.  The documented ladder: shadowed runs take the
        # fused per-chunk path (docs/engine.md § Device-resident level
        # pipeline)
        pipe.device_fallback = (
            "integrity shadow re-execution needs per-chunk replay"
        )

    def _shadow_exec(piece, fp_n, bucket, start, pre_v, cvcap,
                     out, out_hi, out_lo, nn, viol_any, dl_any):
        """Sampled shadow re-execution of one committed-candidate chunk
        (see check()'s integrity_shadow docstring).  Two independent
        oracles, both BEFORE the outputs feed the visited set:

        - host fingerprint oracle (every sampled chunk): the numpy twin
          recomputes each emitted row's fingerprint — rows and fps
          diverging means corruption between the kernel and the host;
        - legacy cross-execution (fused-gated chunks): the whole chunk
          re-runs through the legacy per-action pipeline from the same
          pre-chunk visited state — counts, the new-fingerprint multiset
          and the verdict flags must match the fused result exactly (the
          PR 7 bit-identity contract, used as a runtime oracle)."""
        from ..obs import metrics as _met

        t0 = time.perf_counter()
        main_fps = _integ.pair_u64(
            np.asarray(out_hi[:nn]), np.asarray(out_lo[:nn])
        )
        rows = np.asarray(out[:nn])
        oracle = _integ.fingerprint_rows(rows, spec.exact64)
        mode = "host-oracle"
        if not np.array_equal(oracle, main_fps):
            bad = int(np.argmax(oracle != main_fps))
            raise IntegrityError(
                "shadow",
                f"host fingerprint oracle mismatch at depth {depth} chunk "
                f"start {start} row {bad}: recomputed {int(oracle[bad]):#x}"
                f" != emitted {int(main_fps[bad]):#x}",
                depth=depth,
            )
        # the device pipeline delegates shadowed runs to its fused
        # per-chunk ladder, so the cross-exec gate reads the FUSED
        # implementation either way
        fp = getattr(pipe, "fused", pipe)
        if (
            getattr(fp, "name", "") == "fused"
            and not getattr(fp, "fallback", False)
            and fp._gate(bucket)
        ):
            mode = "legacy-cross"
            (l_out, _lp, _la, l_new, _h1, _h2, _h3, l_viol, _vi,
             l_dl, _di, _ae, l_hi, l_lo, _ag, _launch) = (
                fp.legacy.run_chunk(
                    piece, fp_n, bucket, depth, *pre_v, cvcap
                )
            )
            ln = int(l_new)
            l_fps = _integ.pair_u64(
                np.asarray(l_hi[:ln]), np.asarray(l_lo[:ln])
            )
            if ln != nn or _integ.digest_fps(l_fps) != _integ.digest_fps(
                main_fps
            ):
                raise IntegrityError(
                    "shadow",
                    f"legacy cross-execution diverged at depth {depth} "
                    f"chunk start {start}: fused emitted {nn} "
                    f"fingerprints, legacy {ln} (or multiset digests "
                    f"differ) — one of the two pipelines produced "
                    f"corrupt successors",
                    depth=depth,
                )
            if not np.array_equal(
                np.asarray(viol_any), np.asarray(l_viol)
            ) or bool(dl_any) != bool(l_dl):
                raise IntegrityError(
                    "shadow",
                    f"verdict flags diverged between fused and legacy at "
                    f"depth {depth} chunk start {start}",
                    depth=depth,
                )
        _met.inc("kspec_integrity_shadow_total")
        _integ.count_check()
        obs_.chunk_span(
            "shadow", time.perf_counter() - t0,
            depth=depth, start=start, rows=int(fp_n), mode=mode,
        )


    def _grow_arena(nn: int) -> None:
        """Ensure the level arena holds >= nn more rows past a_w (the
        all-novel worst case insert_compact writes unchecked) — ONE
        growth policy for the per-chunk and device-level commits.
        Growth copies only the filled prefix (amortized O(level))."""
        nonlocal a_rows, a_parent, a_act, a_cap
        if a_w + nn <= a_cap:
            return
        a_cap = max(2 * a_cap, a_w + nn)
        na = np.empty((a_cap, K), np.uint32)
        na[:a_w] = a_rows[:a_w]
        a_rows = na
        npar = np.empty(a_cap, np.int64)
        npar[:a_w] = a_parent[:a_w]
        a_parent = npar
        nact = np.empty(a_cap, np.int32)
        nact[:a_w] = a_act[:a_w]
        a_act = nact

    def _commit_chunk(st) -> bool:
        """Commit one staged chunk: block on its device outputs
        (finalize), run the verdict checks and shadow oracle, then the
        backend-specific host assembly — the visited-set insert, arena/
        trace accumulation and digest folds.  Commits run strictly in
        dispatch order on this thread; returns True when a verdict
        fired (the level stops and any younger staged chunk is
        discarded uncommitted)."""
        nonlocal vhi, vlo, vn, verdict, lvl_new, prof_step, prof_host_s
        nonlocal lvl_launches, lvl_launches_max, run_launches_max
        nonlocal lvl_act_en, a_w  # arena buffers grow via _grow_arena
        nonlocal ht_hi, ht_lo, ht_claim, hash_n, pallas_vmem_noted
        (start, fp_n, bucket, finalize, pre_v, shadow, dispatch_s,
         t_staged, piece, pre_vcap) = st
        queued_s = time.perf_counter() - t_staged
        t_wait = time.perf_counter()
        (
            out,
            out_parent,
            out_act,
            new_n,
            _vh,
            _vl,
            _vn,
            viol_any,
            viol_idx,
            dl_any,
            dl_idx,
            act_en,
            out_hi,
            out_lo,
            act_guard,
            launches,
        ) = finalize()
        act_en_np = np.asarray(act_en, np.int64)
        # frontier-level verdicts (states being expanded = level `depth`)
        if check_invariants:
            viol_any_np = np.asarray(viol_any)
            if viol_any_np.any():
                inv_i = int(np.argmax(viol_any_np))
                idx = start + int(np.asarray(viol_idx)[inv_i])
                verdict = ("invariant", idx, model.invariants[inv_i].name)
                return True
        if check_deadlock and bool(dl_any):
            verdict = ("deadlock", start + int(dl_idx), "Deadlock")
            return True
        nn = int(new_n)
        if shadow:
            # pre_vcap: the visited capacity AT DISPATCH — the next
            # chunk's dispatch may have grown `vcap` before this commit,
            # and the shadow cross-exec replays against the pre-chunk
            # visited refs, which are sized at the old capacity
            _shadow_exec(
                piece, fp_n, bucket, start, pre_v, pre_vcap,
                out, out_hi, out_lo, nn, viol_any, dl_any,
            )
        wait_s = time.perf_counter() - t_wait
        step_s = dispatch_s + wait_s
        prof_step += step_s
        lvl_launches += launches
        lvl_launches_max = max(lvl_launches_max, launches)
        run_launches_max = max(run_launches_max, launches)
        # dispatch vs device-wait attribution (overlap accounting): with
        # overlap on, queued_ms is how long the chunk sat staged while
        # the previous chunk committed — device time hidden behind host
        # work; wait_ms is the residual block on the outputs at commit
        obs_.chunk_span(
            "step", step_s, depth=depth, start=start, rows=fp_n,
            bucket=bucket, launches=launches,
            dispatch_ms=round(dispatch_s * 1e3, 2),
            wait_ms=round(wait_s * 1e3, 2),
            queued_ms=round(queued_s * 1e3, 2),
        )
        t_host = time.perf_counter()
        if host_set is not None and nn:
            if use_arena:
                _grow_arena(nn)
                w = host_set.insert_compact(
                    np.ascontiguousarray(out_hi[:nn], np.uint32),
                    np.ascontiguousarray(out_lo[:nn], np.uint32),
                    np.ascontiguousarray(out[:nn], np.uint32),
                    np.ascontiguousarray(out_parent[:nn], np.int32),
                    start,
                    np.ascontiguousarray(out_act[:nn], np.int32),
                    a_rows[a_w:],
                    a_parent[a_w:],
                    a_act[a_w:],
                )
                a_w += w
                lvl_new += w
                if chain is not None and w:
                    # arena rows are the committed novel states;
                    # the numpy twin recomputes their fps (the C
                    # pass hands back rows, not fingerprints)
                    chain.fold(
                        _integ.fingerprint_rows(
                            a_rows[a_w - w : a_w], spec.exact64
                        )
                    )
            else:  # tiered disk store, or no native toolchain
                rows = np.asarray(out[:nn])
                fps_u64 = _u64(
                    np.asarray(out_hi[:nn]), np.asarray(out_lo[:nn])
                )
                mask = host_set.insert(fps_u64)
                if disk is not None:
                    # novel rows stream straight to the spilled
                    # frontier + parent log in discovery order (int64
                    # parents: level-global indices can pass 2^31 at
                    # the scales this tier exists for)
                    disk.append(
                        rows[mask],
                        np.asarray(out_parent[:nn], np.int64)[mask] + start,
                        np.asarray(out_act[:nn])[mask],
                    )
                else:
                    lvl_rows.append(rows[mask])
                    lvl_parent.append(
                        np.asarray(out_parent[:nn])[mask] + start
                    )
                    lvl_act.append(np.asarray(out_act[:nn])[mask])
                lvl_new += int(mask.sum())
                if chain is not None:
                    chain.fold(fps_u64[mask.astype(bool)])
        elif ht_hi is not None and nn:
            # device-hash backend: insert-or-find on the HBM table; a
            # probe-budget overflow grows the table and re-runs the
            # SAME batch, OR-accumulating novelty (rows inserted by the
            # failed attempt report "seen" on the re-run, so nothing is
            # double-counted or lost)
            valid = jnp.arange(out_hi.shape[0]) < new_n
            isnew = np.zeros(out_hi.shape[0], bool)
            while True:
                # Pallas probe kernel (ops/pallas_hashset) — the actual
                # TPU dedup kernel a live hardware window profiles;
                # interpret mode on CPU, bit-identical winners
                # (tests/test_pallas.py).  It stages the whole table in
                # VMEM, so beyond MAX_VMEM_CAP slots it cannot compile
                # — fall back to the jnp HBM probe, loudly, and keep
                # checking per iteration (a mid-run rehash can cross
                # the threshold).
                use_p = use_p_hbm = False
                if step_builder.use_pallas:
                    # lazy import: the default (non-pallas) path must
                    # not depend on jax.experimental.pallas at all
                    from ..ops import pallas_hashset as pallas_hs

                    use_p = pallas_hs.fits_vmem(ht_hi.shape[0])
                    # beyond the VMEM gate: the HBM-resident DMA
                    # kernel (opt-in until a hardware window profiles
                    # its per-slot descriptor overhead)
                    use_p_hbm = not use_p and (
                        os.environ.get("KSPEC_PALLAS_HBM") == "1"
                    )
                if (
                    step_builder.use_pallas
                    and not use_p
                    and not use_p_hbm
                    and not pallas_vmem_noted
                ):
                    pallas_vmem_noted = True
                    print(
                        "[kspec] KSPEC_USE_PALLAS: table capacity "
                        f"{ht_hi.shape[0]} exceeds the VMEM-staged "
                        f"kernel's limit ({pallas_hs.MAX_VMEM_CAP}); "
                        "falling back to the jnp HBM probe path "
                        "(KSPEC_PALLAS_HBM=1 selects the HBM-resident "
                        "DMA kernel instead)",
                        file=sys.stderr,
                        flush=True,
                    )
                if use_p_hbm:
                    ht_hi, ht_lo, m, _ni, ovf = (
                        pallas_hs.probe_insert_pallas_hbm(
                            ht_hi,
                            ht_lo,
                            out_hi,
                            out_lo,
                            valid,
                            interpret=jax.default_backend() == "cpu",
                        )
                    )
                    ht_claim = None
                elif use_p:
                    # KSPEC_PALLAS_GROUP: interleaved probe chains per
                    # round (memory-level parallelism; winners
                    # bit-identical — ops/pallas_hashset)
                    ht_hi, ht_lo, m, _ni, ovf = (
                        pallas_hs.probe_insert_pallas(
                            ht_hi,
                            ht_lo,
                            out_hi,
                            out_lo,
                            valid,
                            interpret=jax.default_backend() == "cpu",
                            group=int(
                                os.environ.get("KSPEC_PALLAS_GROUP", "8")
                            ),
                        )
                    )
                    ht_claim = None
                else:
                    if ht_claim is None:
                        ht_claim = hashset.new_claim(ht_hi.shape[0])
                    ht_hi, ht_lo, ht_claim, m, _ni, ovf = _hash_insert(
                        ht_hi, ht_lo, ht_claim, out_hi, out_lo, valid
                    )
                isnew |= np.asarray(m)
                if not bool(ovf):
                    break
                ht_hi, ht_lo = hashset.rehash_into(
                    ht_hi, ht_lo, 2 * ht_hi.shape[0]
                )
                ht_claim = None
            mask = isnew[:nn]
            hash_n += int(mask.sum())
            lvl_rows.append(np.asarray(out[:nn])[mask])
            lvl_parent.append(np.asarray(out_parent[:nn])[mask] + start)
            lvl_act.append(np.asarray(out_act[:nn])[mask])
            lvl_new += int(mask.sum())
            if chain is not None:
                chain.fold(
                    _integ.pair_u64(
                        np.asarray(out_hi[:nn])[mask],
                        np.asarray(out_lo[:nn])[mask],
                    )
                )
        elif nn:
            lvl_rows.append(np.asarray(out[:nn]))
            lvl_parent.append(np.asarray(out_parent[:nn]) + start)
            lvl_act.append(np.asarray(out_act[:nn]))
            lvl_new += nn
            if chain is not None:
                # device backend: the in-jit dedup already
                # compacted exactly the new states to the front
                chain.fold(
                    _integ.pair_u64(
                        np.asarray(out_hi[:nn]),
                        np.asarray(out_lo[:nn]),
                    )
                )
        host_s = time.perf_counter() - t_host
        prof_host_s += host_s
        obs_.chunk_span(
            "host-assembly", host_s, depth=depth, start=start, new=nn,
            backend=visited_backend,
        )
        if collect_stats:
            lvl_act_en += act_en_np

        return False

    def _commit_device_level(fin, dispatch_s: float, plan) -> bool:
        """Commit a whole device-resident level (DevicePipeline.run_level):
        block on the level program's outputs, apply the serial commit
        loop's verdict rule, then the host bookkeeping.

        Device backend: trace accumulation and the digest-chain fold
        from the DEVICE-computed (count, xor, sum) accumulator
        (bit-exact with the per-chunk host folds; ops/devlevel.py).

        Host backend (deferred-probe mode): the level's novel
        candidates — unique within the level, chunk-major candidate
        order — are probed/inserted against the host FpSet / disk tier
        in ONE batched call (the tentpole: host syncs O(1) per level).
        The serial winner rule is preserved because intra-level
        duplicates were already resolved on device with the earlier
        chunk winning, and the batch replays in exactly the order the
        serial per-chunk commits would have inserted; the digest chain
        folds the probe SURVIVORS, the same multiset the serial commits
        fold.  Verdicts derive from the frontier states being expanded
        (already probed/committed by the previous level), so the
        deferred probe cannot change them — nothing needs re-deriving.

        Returns True when a verdict fired (the level's tail chunks are
        never dispatched — the serial break)."""
        nonlocal verdict, lvl_new, prof_step, prof_host_s
        nonlocal lvl_launches, lvl_launches_max, run_launches_max
        nonlocal lvl_act_en, lvl_probe_ms, a_w
        t_wait = time.perf_counter()
        out = fin()
        wait_s = time.perf_counter() - t_wait
        step_s = dispatch_s + wait_s
        prof_step += step_s
        launches = out["launches"]
        lvl_launches += launches
        lvl_launches_max = max(lvl_launches_max, launches)
        run_launches_max = max(run_launches_max, launches)
        # attribution: run_level BLOCKS on the level program (its
        # overflow-flag read is the one device sync per level), so the
        # whole blocked wall is device-wait — there is no in-flight
        # dispatch window like the per-chunk staged contract has
        obs_.chunk_span(
            "step", step_s, depth=depth, start=0, rows=plan[2],
            bucket=plan[0], launches=launches, chunks=plan[1],
            pipeline="device",
            dispatch_ms=0.0,
            wait_ms=round(step_s * 1e3, 2), queued_ms=0.0,
        )
        if out["verdict"] is not None:
            kind, idx, inv_i = out["verdict"]
            verdict = (
                kind,
                idx,
                model.invariants[inv_i].name
                if kind == "invariant"
                else "Deadlock",
            )
            return True
        t_host = time.perf_counter()
        nn = out["new_n"]
        if host_set is not None:
            # the deferred batched probe — ONE host call for the level
            t_probe = time.perf_counter()
            committed = 0
            if nn:
                if use_arena:
                    _grow_arena(nn)
                    # parents are already level-global (the device
                    # program added each chunk's offset), so base 0
                    committed = host_set.insert_compact(
                        out["hi"],
                        out["lo"],
                        np.ascontiguousarray(out["rows"], np.uint32),
                        np.ascontiguousarray(out["parent"], np.int32),
                        0,
                        np.ascontiguousarray(out["act"], np.int32),
                        a_rows[a_w:],
                        a_parent[a_w:],
                        a_act[a_w:],
                    )
                    if chain is not None and committed:
                        chain.fold(
                            _integ.fingerprint_rows(
                                a_rows[a_w: a_w + committed],
                                spec.exact64,
                            )
                        )
                    a_w += committed
                else:  # tiered disk store, or no native toolchain
                    fps_u64 = _u64(out["hi"], out["lo"])
                    # the disk tier's level-batched form probes every
                    # spilled run ONCE for the whole (sorted) level
                    # batch; plain FpSets take the ordinary batch insert
                    mask = (
                        host_set.insert_level(fps_u64)
                        if hasattr(host_set, "insert_level")
                        else host_set.insert(fps_u64)
                    ).astype(bool)
                    rows = out["rows"][mask]
                    par = out["parent"].astype(np.int64)[mask]
                    acts = out["act"][mask]
                    if disk is not None:
                        disk.append(rows, par, acts)
                    else:
                        lvl_rows.append(rows)
                        lvl_parent.append(par)
                        lvl_act.append(acts)
                    committed = int(mask.sum())
                    if chain is not None:
                        chain.fold(fps_u64[mask])
                lvl_new += committed
            probe_s = time.perf_counter() - t_probe
            lvl_probe_ms += probe_s * 1e3
            obs_.chunk_span(
                "host-probe", probe_s, depth=depth, rows=nn,
                new=committed, backend=visited_backend,
                batched="level",
            )
        elif nn:
            lvl_rows.append(out["rows"])
            lvl_parent.append(out["parent"])
            lvl_act.append(out["act"])
            lvl_new += nn
            if chain is not None:
                chain.fold_digest(*out["digest"])
        host_s = time.perf_counter() - t_host
        prof_host_s += host_s
        obs_.chunk_span(
            "host-assembly", host_s, depth=depth, start=0, new=nn,
            backend=visited_backend,
        )
        if collect_stats:
            lvl_act_en += out["act_en"]
        return False

    # storage read-side corruption (read-verified CRCs on spill runs /
    # frontier segments / parent-log levels) surfaces as these typed
    # exceptions mid-run — all integrity violations, exit 76
    from ..storage.frontier import SegmentCorrupt
    from ..storage.parent_log import ParentLogCorrupt
    from ..storage.runs import RunCorrupt

    exhausted: Optional[ResourceExhausted] = None
    integrity_fail: Optional[IntegrityError] = None
    run_launches_max = 0  # per-chunk max actually DISPATCHED this run
    overlap_staged_peak = 0  # most chunks ever staged at once (<= 2)

    def _io_counters():
        return worker_counters((io_worker, ckpt_worker))
    try:
        while _f_rows(frontier_np) > 0:
            # async join point: adopt finished background merges and
            # promoted checkpoints, surfacing any worker error (typed
            # faults, ENOSPC) on this thread before more work builds on
            # un-validated state.  With an armed fault plan the join is
            # BLOCKING: deterministic injection (crash deferral, flip
            # gating, enospc surfacing) must not depend on writer-thread
            # timing — fault rehearsals trade the overlap win for
            # reproducibility at level boundaries
            _ckpt_poll(block=bool(fault.specs))
            if disk is not None:
                if fault.specs:
                    disk.quiesce()
                disk.poll_async()
            lvl_io0 = _io_counters()
            lvl_sync_io0 = sync_io_s
            # level-boundary fault injection point (resilience.faults);
            # crash deferral keys on the DURABLE checkpoint depth, so an
            # in-flight async save can never arm a crash whose restart
            # would not converge
            fault.crash("level", depth, ckpt_depth=ckpt_durable_depth)
            if chain is not None:
                sp = fault.flip(
                    "frontier", depth, ckpt_depth=ckpt_durable_depth
                )
                if isinstance(frontier_np, np.ndarray):
                    if sp:
                        _integ.flip_bit(frontier_np)
                    # the frontier about to be expanded must digest to
                    # the entry sealed when its level was discovered — a
                    # bit flipped in the buffer between levels (or a
                    # frontier loaded from a CRC-consistent corrupted
                    # checkpoint) is caught HERE, before it poisons
                    # successors
                    _integ.count_check()
                    chain.verify_level(
                        depth,
                        _integ.fingerprint_rows(frontier_np, spec.exact64),
                    )
                elif sp and frontier_np.paths():
                    # disk-spilled frontier: the flip lands in a segment
                    # FILE (there is no long-lived host buffer to flip);
                    # the read-side segment CRC catches it at the first
                    # chunk read of this level
                    from ..resilience.faults import corrupt_file

                    frontier_np._read_verified.clear()
                    corrupt_file(frontier_np.paths()[0])
            if max_depth is not None and depth >= max_depth:
                break
            if max_states is not None and total >= max_states:
                break
            f_total = _f_rows(frontier_np)
            t_level = time.perf_counter()
            # begin marker (ph=B): a crash mid-level leaves it unmatched, which
            # is exactly what `cli report` uses to pin where the run died
            obs_.level_begin(depth + 1, f_total)
            governor.level_begin(depth + 1)  # arm the per-level deadline
            # A frontier larger than `chunk` is streamed through the same
            # compiled step in chunk_size pieces: cross-chunk duplicates are
            # caught because each chunk probes the visited set updated by the
            # previous one.  This bounds both the number of compiled shapes
            # (O(log chunk) buckets, ever) and peak device memory (O(chunk*C)).
            lvl_rows, lvl_parent, lvl_act = [], [], []
            lvl_new = 0
            lvl_act_en = np.zeros(len(model.actions), np.int64)
            lvl_launches = 0  # successor-kernel launches this level
            lvl_launches_max = 0  # ... and the per-chunk maximum
            lvl_probe_ms = 0.0  # deferred batched host-probe wall
            verdict = None  # (kind, global_frontier_idx, inv_name)
            # Host-native backend: assemble the next level in a preallocated
            # arena via the fused C pass (native.FpSet.insert_compact) — one
            # cache-friendly sweep per chunk instead of u64 packing + novelty
            # mask + masked gathers + per-level concatenate.  Growth copies
            # only the filled prefix (amortized O(level)).
            if disk is not None:
                disk.begin_level(depth + 1)
            use_arena = host_set is not None and host_set.native
            if use_arena:
                a_cap = max(1 << 14, int(1.5 * f_total))
                a_rows = np.empty((a_cap, K), np.uint32)
                a_parent = np.empty(a_cap, np.int64)
                a_act = np.empty(a_cap, np.int32)
                a_w = 0
            prof_step = prof_host_s = 0.0
            # Two-slot staged chunk pipeline (KSPEC_OVERLAP, docs/
            # engine.md § Async execution): each chunk's device programs
            # are DISPATCHED first (pipe.run_chunk_staged — JAX async
            # dispatch leaves the update-skeleton launch draining), and
            # the PREVIOUS chunk's host commit (fingerprint-set insert,
            # arena assembly, digest folds) runs while it drains.  At
            # most two chunks are ever staged (the one committing + the
            # one dispatched); commits happen strictly in chunk order,
            # so counts, novelty decisions, first-violation and traces
            # are bit-identical to the serial path — which is literally
            # this same code with overlap_on False (dispatch followed by
            # an immediate commit).
            staged = None
            # Device-resident level path (DevicePipeline, engine/
            # pipeline.py): ONE dispatched while_loop program runs every
            # gated chunk of this level — expansion, in-jit compaction,
            # fingerprints, dedup, verdicts and digest folds all
            # on-device, the visited merge once per level — <=2
            # successor launches per LEVEL.  A sub-gate tail chunk (only
            # ever the last, partial one) falls through to the per-chunk
            # loop below at its serial offset, preserving the legacy
            # full-lattice candidate order below the gate
            # (bit-identity).  A verdict inside the device span, like
            # the serial break, leaves the tail undispatched.
            dev_handled = 0
            dev_plan = (
                pipe.plan_level(f_total, chunk, min_bucket)
                if getattr(pipe, "name", "") == "device"
                else None
            )
            if dev_plan is not None:
                governor.poll(depth)
                # disk tier: the spilled frontier's handled prefix is
                # materialized for the device span — it must be staged
                # into the device buffer anyway, so this is one host
                # copy of what the per-chunk loop would read piecewise.
                # A level too large to materialize degrades to the
                # per-chunk ladder, which streams chunks from disk —
                # the same sticky-fallback contract as a compile
                # failure, never a crashed run.  Two layers: a PRE-SIZE
                # gate (Linux overcommit means a doomed allocation can
                # OOM-kill the process during the copy rather than
                # raise, so waiting for MemoryError is not enough) and
                # the MemoryError catch for allocators that do raise.
                mat_bytes = f_total * K * 4
                mat_budget = int(os.environ.get(
                    "KSPEC_DEVLEVEL_MAT_BUDGET", str(1 << 31)
                ))
                if (not isinstance(frontier_np, np.ndarray)
                        and mat_bytes > mat_budget):
                    pipe._mark_fallback(
                        f"spilled frontier too large to materialize "
                        f"for the device span ({mat_bytes} B > "
                        f"KSPEC_DEVLEVEL_MAT_BUDGET {mat_budget} B)",
                        depth,
                    )
                    dev_plan = None
                else:
                    try:
                        dev_rows = (
                            frontier_np
                            if isinstance(frontier_np, np.ndarray)
                            else _f_all(frontier_np)
                        )
                    except MemoryError as e:
                        pipe._mark_fallback(
                            f"frontier materialization failed "
                            f"({f_total} rows): {e}"[:200],
                            depth,
                        )
                        dev_plan = None
            if dev_plan is not None:
                t_attempt = time.perf_counter()
                dres = pipe.run_level(
                    dev_rows, f_total, depth, vhi, vlo, vn, vcap,
                    dev_plan,
                )
                if dres is not None:
                    vhi, vlo, vn, vcap, dev_fin = dres
                    dispatch_s = time.perf_counter() - t_attempt
                    dev_handled = dev_plan[2]
                    if _commit_device_level(dev_fin, dispatch_s,
                                            dev_plan):
                        dev_handled = f_total  # verdict: skip the tail
            # Tail iteration after a device-resident span: a fully-
            # handled level skips it entirely, and a disk-tier tail
            # slices the ALREADY-materialized rows at the same serial
            # chunk boundaries (dev_handled is a chunk multiple by
            # plan) — the spilled frontier's iter_chunks performs real
            # segment reads even for skipped chunks, so neither case
            # may re-read the device-handled prefix from disk.
            if dev_handled >= f_total:
                tail_chunks = ()
            elif dev_handled and not isinstance(frontier_np, np.ndarray):
                tail_chunks = (
                    (s, dev_rows[s: s + chunk])
                    for s in range(dev_handled, f_total, chunk)
                )
            else:
                tail_chunks = _f_chunks(frontier_np, chunk)
            for start, piece in tail_chunks:
                if start < dev_handled:
                    continue  # committed by the device-resident span
                governor.poll(depth)  # deadline watchdog (cheap)
                fp_n = piece.shape[0]
                bucket = _next_pow2(max(fp_n, min_bucket))
                M = bucket * C
                if visited_backend == "device":
                    need = int(vn) + M
                    if need > vcap:
                        # one shared growth policy with the device level
                        # path (pipeline.grow_visited); growth is
                        # monotonic, so the outgrown capacity's compiled
                        # steps are evicted immediately here
                        vhi, vlo, vcap = _grow_visited(
                            vhi, vlo, vcap, need,
                            cache=step_builder._cache,
                        )
                elif ht_hi is not None and 2 * hash_n > ht_hi.shape[0]:
                    # keep load factor under ~1/2 so linear probing stays short
                    ht_hi, ht_lo = hashset.rehash_into(
                        ht_hi, ht_lo, 2 * ht_hi.shape[0]
                    )
                    ht_claim = None
                # One chunk through the level-pipeline: expand -> squeeze ->
                # fingerprint (+ the device backend's in-jit dedup), with
                # overflow retries / escalation / failure degradation owned
                # by the pipeline implementation (engine/pipeline.py).  The
                # outputs are COMMITTED — exact regardless of which
                # implementation or retry path produced them.
                shadow = shadow_rate > 0 and _integ.sample_chunk(
                    depth, start, shadow_rate
                )
                # pre-chunk visited refs: the shadow legacy cross-exec
                # replays the chunk from the same starting state (jax
                # arrays are immutable, so holding them is free)
                pre_v = (vhi, vlo, vn) if shadow else None
                t_attempt = time.perf_counter()
                vhi, vlo, vn, finalize = pipe.run_chunk_staged(
                    piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
                )
                cur = (
                    start, fp_n, bucket, finalize, pre_v, shadow,
                    time.perf_counter() - t_attempt, time.perf_counter(),
                    piece, vcap,
                )
                if overlap_on:
                    overlap_staged_peak = max(
                        overlap_staged_peak, 2 if staged is not None else 1
                    )
                    if staged is not None and _commit_chunk(staged):
                        # a verdict in chunk k: the just-dispatched chunk
                        # k+1 is DISCARDED uncommitted — exactly what the
                        # serial path's break does (its device work is
                        # pure and side-effect-free until commit)
                        staged = None
                        break
                    staged = cur
                else:
                    if _commit_chunk(cur):
                        break
            if staged is not None and verdict is None:
                _commit_chunk(staged)
            staged = None

            if verdict is not None:
                kind, idx, inv_name = verdict
                if disk is not None:
                    disk.abort_level()  # partial next-level writer: discard
                if have_trace(depth):
                    violation = build_violation(inv_name, depth, idx)
                else:
                    violation = Violation(
                        invariant=inv_name,
                        depth=depth,
                        state=decode_state(_f_row(frontier_np, idx)),
                        trace=[],
                    )
                break

            new_n = lvl_new
            if use_arena:
                next_frontier = a_rows[:a_w]
                level_parent = a_parent[:a_w]
                level_act = a_act[:a_w]
                if (store_trace or collect_levels is not None) and a_w < int(
                    0.95 * a_cap
                ):
                    # retained levels: shrink-copy so the trace store doesn't
                    # hold the arena's growth headroom for the whole run
                    next_frontier = next_frontier.copy()
                    level_parent = level_parent.copy()
                    level_act = level_act.copy()
            elif disk is not None:
                # publish the level: segments + parent-log frame become the
                # pending frontier; the consumed level's segments go behind
                # the checkpoint-generation deletion barrier
                next_frontier = disk.end_level()
                level_parent = level_act = None  # trace lives in the log
            else:
                next_frontier = (
                    np.concatenate(lvl_rows)
                    if lvl_rows
                    else np.empty((0, K), np.uint32)
                )
                level_parent = (
                    np.concatenate(lvl_parent)
                    if lvl_parent
                    else np.empty(0, np.int64)
                )
                level_act = (
                    np.concatenate(lvl_act) if lvl_act else np.empty(0, np.int64)
                )
            depth += 1
            if new_n:
                levels.append(new_n)
                total += new_n
            if chain is not None:
                if new_n:
                    # seal the level: the folded multiset digest becomes
                    # the chain entry (count disagreement raises typed)
                    chain.seal(depth, new_n)
                else:
                    chain.reset_fold()
            if collect_stats:
                enabled_total = int(lvl_act_en.sum())
                # heartbeat-enveloped (kind/ts/unix): the per-level stats
                # stream doubles as the supervisor's liveness signal.  The obs
                # shim emits the historical record shape (and, with a run
                # context, additionally stamps run_id, closes the level span,
                # and folds the metrics registry + Prometheus export)
                rec = obs_.level(
                    depth=depth,
                    frontier=f_total,
                    enabled_candidates=enabled_total,
                    new=new_n,
                    duplicates=enabled_total - new_n,
                    total=total,
                    level_ms=round((time.perf_counter() - t_level) * 1e3, 1),
                    step_ms=round(prof_step * 1e3, 1),
                    host_ms=round(prof_host_s * 1e3, 1),
                    action_enablement={
                        a.name: int(c) for a, c in zip(model.actions, lvl_act_en.tolist())
                    },
                )
                # launch accounting rides only the in-memory result (and
                # the per-chunk step spans): the emitted stats stream is
                # a pinned record-for-record historical contract
                # (tests/test_obs.py shim equivalence)
                result_stats.setdefault("levels", []).append(
                    {
                        **rec,
                        "successor_launches": lvl_launches,
                        "launches_per_chunk_max": lvl_launches_max,
                        # deferred batched host-probe attribution (the
                        # host-backend device path): in-memory records
                        # + the gauge/span side channels only — the
                        # emitted stats stream stays record-for-record
                        # historical (PR 7/10/13 precedent)
                        **(
                            {"host_probe_ms": round(lvl_probe_ms, 2)}
                            if lvl_probe_ms
                            else {}
                        ),
                    }
                )
                # launches/level gauge (obs): the device pipeline's
                # acceptance signal — <=2 steady-state on the
                # device-resident path, O(chunks)x2 on fused
                _met.set_gauge(
                    "kspec_successor_launches_level", lvl_launches
                )
                if lvl_probe_ms:
                    # probe-ms/level gauge: the deferred-probe beat
                    # `cli report` renders next to launches/level
                    _met.set_gauge(
                        "kspec_host_probe_ms", round(lvl_probe_ms, 2)
                    )
            if collect_levels is not None and new_n:
                collect_levels.append(_f_all(next_frontier))
            if store_trace:
                trace_store.append((next_frontier, level_parent, level_act))
            if progress:
                progress(depth, new_n, total)

            frontier_np = next_frontier
            if ckpt_store is not None and depth % checkpoint_every == 0:
                _save_checkpoint()
                last_ckpt_depth = depth
            # level-boundary resource governance: pressure gauges, injected
            # stall, soft-breach reclamation, hard-breach typed clean exit
            governor.level_end(depth, reclaim=_reclaim, save_hook=_final_save)
            # per-level overlap accounting (obs: `kspec_overlap_efficiency`
            # is how machine-readable "storage I/O fully hidden" is —
            # ROADMAP item 2's acceptance): hidden = worker-busy wall not
            # re-exposed as caller blocking; exposed = blocking waits on
            # workers + synchronous checkpoint writes.  Attached to the
            # IN-MEMORY level records only (the emitted stats stream is a
            # pinned historical contract, like the launch counters)
            if collect_stats and result_stats.get("levels"):
                busy1, blk1 = _io_counters()
                hid = max(
                    0.0, (busy1 - lvl_io0[0]) - (blk1 - lvl_io0[1])
                )
                exp = (blk1 - lvl_io0[1]) + (sync_io_s - lvl_sync_io0)
                eff = hid / (hid + exp) if (hid + exp) > 1e-9 else 1.0
                rec_mem = result_stats["levels"][-1]
                rec_mem["io_hidden_ms"] = round(hid * 1e3, 2)
                rec_mem["io_exposed_ms"] = round(exp * 1e3, 2)
                rec_mem["overlap_efficiency"] = round(eff, 4)
                _met.set_gauge("kspec_overlap_efficiency", round(eff, 4))
                _met.inc("kspec_io_hidden_ms_total", round(hid * 1e3, 2))
                _met.inc("kspec_io_exposed_ms_total", round(exp * 1e3, 2))
        # drain the async tail INSIDE the typed-error scope: a pending
        # checkpoint's ENOSPC or a background merge's injected fault must
        # map to the same typed exits as their synchronous twins
        _ckpt_poll(block=True)
        if disk is not None:
            disk.quiesce()
    except ResourceExhausted as e:
        exhausted = e
    except IntegrityError as e:
        integrity_fail = e
    except (RunCorrupt, SegmentCorrupt, ParentLogCorrupt) as e:
        # read-side storage checksum failure: silent on-disk corruption
        # caught at consumption time — typed exactly like every other
        # integrity violation
        integrity_fail = IntegrityError("storage", str(e), depth=depth)
    except OSError as e:
        if not is_disk_full(e):
            raise
        # a real ENOSPC from a storage/checkpoint writer outside the
        # injected paths: same typed clean exit (every writer cleans
        # up its tmp on failure, so the promoted state is intact)
        exhausted = ResourceExhausted("enospc", str(e), depth=depth)
    if integrity_fail is not None:
        # typed terminal (resilience.integrity): stamp the manifest so
        # `cli report` renders the integrity beat, then propagate for the
        # CLI's exit-76 mapping.  The supervisor restarts; the resume
        # path's chain validator skips corrupted generations, so the
        # restart resumes from the newest CHAIN-VERIFIED one.  Corrupt
        # in-memory state is deliberately NOT checkpointed here (unlike
        # the resource exit's final save): the newest durable generation
        # predates the detected corruption by construction.
        try:
            _integ.record_violation(integrity_fail)
            if disk is not None:
                disk.abort_level()  # partial next-level writer: discard
            obs_.abort(
                "integrity-violation",
                site=integrity_fail.site,
                depth=integrity_fail.depth,
                detail=integrity_fail.detail[:300],
                distinct_states=total,
            )
            obs_.close()
        except OSError:
            pass
        _drop_ephemeral_spill()
        _shutdown_async(drain=False)
        raise integrity_fail
    if exhausted is not None:
        # the terminal path itself writes (manifest rewrite, metrics
        # snapshot) to the same full filesystem — best-effort only, so a
        # second ENOSPC can't demote the typed exit-75 into a torn crash
        try:
            if disk is not None:
                disk.abort_level()  # partial next-level writer: discard
            # typed terminal: the run manifest records WHY (`cli report`
            # renders the RESOURCE_EXHAUSTED verdict beat from it), and the
            # exception propagates for the CLI's exit-code-75 mapping
            obs_.abort(
                "resource-exhausted",
                reason=exhausted.reason,
                depth=exhausted.depth,
                detail=exhausted.detail,
                distinct_states=total,
                **governor.stats(),
            )
            obs_.close()
        except OSError:
            pass
        _shutdown_async(drain=False)
        raise exhausted

    if violation is None and check_invariants and model.invariants and _f_rows(frontier_np):
        # the loop was cut (max_depth/max_states) before the remaining
        # frontier was expanded — its states still need their invariant pass
        st = jax.vmap(spec.unpack)(jnp.asarray(_f_all(frontier_np)))
        for inv in model.invariants:
            ok = np.asarray(jax.vmap(inv.pred)(st))
            if not ok.all():
                idx = int(np.argmax(~ok))
                violation = (
                    build_violation(inv.name, depth, idx)
                    if have_trace(depth)
                    else Violation(
                        invariant=inv.name,
                        depth=depth,
                        state=decode_state(_f_row(frontier_np, idx)),
                        trace=[],
                    )
                )
                break

    dt = time.perf_counter() - t0
    result_stats.update(
        {
            "visited_capacity": int(vcap),
            "fanout": C,
            "lanes": K,
            "visited_backend": visited_backend,
            "pipeline": pipe.name,
            "pipeline_fallback": bool(getattr(pipe, "fallback", False)),
            # measured, not the pipeline's nominal figure: sub-gate
            # chunks delegate to the per-action path and a fused
            # compile-fallback runs legacy for the rest of the run, so
            # only the observed per-chunk maximum is honest here
            "launches_per_chunk_max": run_launches_max,
            "adaptive_active": adapt.active,
            # state-space-cache seeding (service/state_cache.py): the
            # depth this run's frontier was seeded at instead of Init
            **({"seeded_from_depth": int(seed["depth"])} if seeded else {}),
            # device-resident level pipeline accounting (DevicePipeline):
            # how many levels ran as single dispatched programs, and why
            # (if ever) the run left the device path for the fused ladder
            **(
                {
                    "device": {
                        "levels": pipe.device_levels,
                        "fallback": pipe.device_fallback,
                    }
                }
                if getattr(pipe, "name", "") == "device"
                else {}
            ),
            "adaptive_compile_fallback": bool(
                getattr(pipe, "legacy", pipe).compile_fallback
            ),
            "transient_retries": chunk_retry.retries_total,
            "degradations": chunk_retry.degradations,
            # async-overlap accounting (overlap.py): the staged-chunk
            # bound is structural (two slots) — tests pin peak <= 2
            "overlap": {
                "enabled": overlap_on,
                "staged_chunks_peak": overlap_staged_peak,
                "sync_ckpt_io_s": round(sync_io_s, 4),
                **(
                    {"io_worker": io_worker.stats()}
                    if io_worker is not None
                    else {}
                ),
                **(
                    {"ckpt_worker": ckpt_worker.stats()}
                    if ckpt_worker is not None
                    else {}
                ),
            },
        }
    )
    if host_set is not None:
        result_stats["host_fpset_size"] = len(host_set)
    if disk is not None:
        result_stats["spill"] = disk.stats()
        result_stats["spill_dir"] = disk.dir
        result_stats["mem_budget"] = disk.fpset.mem_budget
    if ht_hi is not None:
        result_stats["hash_table_capacity"] = int(ht_hi.shape[0])
        result_stats["hash_table_size"] = hash_n
    _drop_ephemeral_spill()
    _shutdown_async(drain=True)
    res = CheckResult(
        model=model.name,
        levels=levels,
        total=total,
        diameter=len(levels) - 1,
        violation=violation,
        seconds=dt,
        states_per_sec=total / max(dt, 1e-9),
        stats=result_stats,
    )
    obs_.finish(res)
    obs_.close()
    return res
