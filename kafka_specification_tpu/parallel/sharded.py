"""Mesh-sharded BFS: the distributed engine (SURVEY.md §2.6).

TLC parallelizes with Java worker threads over a shared FPSet; the TPU-native
equivalent shards the frontier AND the fingerprint set across a 1-D device
mesh and exchanges ownership over ICI collectives:

- the frontier lives sharded across devices (axis 'd'); each device expands
  its shard with the same vmapped action kernels as the single-device engine
  (including the two-phase guard-sweep/compact expansion),
- every candidate successor is owned by the device selected by its
  fingerprint (owner = fp_lo mod D — fingerprint-range sharding),
- candidates are routed to their owner with bucket-by-owner `lax.all_to_all`
  (per-shard ICI traffic ≈ the candidate width, independent of mesh size —
  SURVEY §2.6), with `lax.all_gather` + ownership filtering kept as the
  simple fallback (exchange="all_gather"); the owner dedups them against its
  local sorted fingerprint shard and keeps its new states as its shard of
  the next frontier — hash ownership keeps shards balanced with no
  host-side reshuffle.

Everything runs under `jax.jit` + `shard_map` over a `jax.sharding.Mesh`, so
the same code drives 8 virtual CPU devices in CI, one real TPU chip, or a
v5e-8 pod slice — XLA inserts the ICI collectives.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax >= 0.5 promotes shard_map to the top level; the replicated-value
# checking flag was separately renamed check_rep -> check_vma.  Feature-
# detect BOTH independently (there are versions with a top-level shard_map
# that still takes check_rep), so the engine runs across the whole window.
try:
    _shard_map = jax.shard_map
except AttributeError:  # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_sm_params = _inspect.signature(_shard_map).parameters
_SHARD_MAP_KW = (
    {"check_vma": False}
    if "check_vma" in _sm_params
    else {"check_rep": False}
    if "check_rep" in _sm_params
    else {}
)
del _inspect, _sm_params

from ..engine.bfs import (
    AdaptiveCompact,
    CheckResult,
    Violation,
    _next_pow2,
    _Step,
    walk_trace,
)
from ..ops import devlevel
from ..pipeline_registry import resolve_pipeline
from ..models.base import Model
from ..obs import metrics as _met
from ..obs.observer import RunObserver
from ..ops import dedup, hashset
from ..resilience import integrity as _integ
from ..resilience.checkpoints import CheckpointStore
from ..resilience.faults import FaultPlan
from ..resilience.heartbeat import append_jsonl, heartbeat_record
from ..resilience.integrity import IntegrityError
from ..resilience.resources import (
    ResourceExhausted,
    ResourceGovernor,
    is_disk_full,
)
from ..resilience.retry import ChunkRetryHandler
from ..storage.parent_log import ShardedParentLog
from .multihost import (
    fetch_global,
    is_coordinator,
    is_multiprocess,
    or_across_processes,
    put_global,
)
from ..ops.fingerprint import fingerprint_lanes


# per-shard hash-table floor (module-level so tests can shrink it to
# exercise growth at small state counts)
_HASH_MIN_CAP = 1 << 14


def _shard_tables_from_pairs(per_shard, min_cap: int):
    """Uniform-capacity per-shard tables from per-shard (hi, lo) pairs.

    All shards must share one capacity (the shard_map operand is one
    [D, cap] array); if any shard's build grows past the target (probe
    overflow — improbable at 1/4 load but handled, never asserted), every
    shard is rebuilt at the larger capacity.  Returns (vhi, vlo, cap)."""
    cap = _next_pow2(max(min_cap, 4 * max((len(h) for h, _ in per_shard), default=1)))
    while True:
        ths, tls = [], []
        redo = False
        for h, lo in per_shard:
            th, tl = hashset.table_from_pairs(h, lo, min_cap=cap)
            if th.shape[0] != cap:
                cap = int(th.shape[0])
                redo = True
                break
            ths.append(np.asarray(th))
            tls.append(np.asarray(tl))
        if not redo:
            return np.stack(ths), np.stack(tls), cap


def _grow_hash_tables(dev_vhi, dev_vlo, new_cap: int, shard1):
    """Rehash every shard's HBM hash table into (>=) `new_cap` slots.

    Host-driven (runs between chunk attempts, amortized O(n) per
    doubling); fetch_global/put_global keep it multi-process-correct —
    every process computes the identical grown tables.  Returns
    (dev_vhi, dev_vlo, cap)."""
    old_hi = fetch_global(dev_vhi)  # [D, cap]
    old_lo = fetch_global(dev_vlo)
    live = ~((old_hi == hashset.SENT) & (old_lo == hashset.SENT))
    per_shard = [
        (old_hi[d][live[d]], old_lo[d][live[d]]) for d in range(old_hi.shape[0])
    ]
    nh, nl, cap = _shard_tables_from_pairs(per_shard, new_cap)
    return put_global(nh, shard1), put_global(nl, shard1), cap


def _norm_shift(bucket: int, shift: int) -> int:
    """Shift actually applied by the step for this bucket (single source of
    truth shared with check_sharded's buffer sizing)."""
    return 0 if (shift and (bucket >> shift) < 1) else shift


def _default_dest_w(T: int, D: int) -> int:
    return max(64, T // D)


def mesh_layouts(mesh: Mesh) -> dict:
    """EXPLICIT mesh-axis layouts for every mesh-resident tensor class
    (the sharding-rule pattern of SNIPPETS.md [1][3]): one named
    NamedSharding/PartitionSpec per logical tensor instead of the old
    implicit ``P('d')``-for-everything.  These are asserted in tests
    (tests/test_sharded_device.py), so a future real-ICI window inherits
    correct, named layouts for free:

    - ``frontier``  [D*B, K]  packed state rows: row dim sharded over the
      mesh axis, the K packed lanes replicated within a shard;
    - ``fvalid``    [D*B]     per-row validity mask, sharded like rows;
    - ``fpset``     [D, vcap] per-shard sorted fingerprint lanes (or the
      device-hash table slots): shard-major dim sharded, each shard's
      capacity dim local to its device;
    - ``pershard``  [D]       per-shard scalars (visited counts, pending
      lengths, chunk counts);
    - ``exchange``  [D*R(,K)] exchange receive buffers — what the
      all_to_all/all_gather fills, row dim sharded by OWNER shard.
    """
    return {
        "frontier": NamedSharding(mesh, P("d", None)),
        "fvalid": NamedSharding(mesh, P("d")),
        "fpset": NamedSharding(mesh, P("d", None)),
        "pershard": NamedSharding(mesh, P("d")),
        "exchange": NamedSharding(mesh, P("d", None)),
    }


def _fp_digest(dhi, dlo, mask):  # kspec: traced
    """Exchange framing record: order-invariant (count, xor_hi, xor_lo,
    sum_hi, sum_lo) over a masked fingerprint multiset — the payload's
    integrity stamp.  Computed per shard BEFORE and AFTER the
    collective; the host compares the global combines, so any bit the
    fabric (or a buffer in between) flips in a routed fingerprint
    desyncs the two (resilience.integrity).  uint32 lanes: TPUs have no
    64-bit ALU, and wrapping 32-bit sums/xors combine across shards
    just as commutatively."""
    z = jnp.uint32(0)
    mh = jnp.where(mask, dhi, z)
    ml = jnp.where(mask, dlo, z)
    return jnp.stack([
        jnp.sum(mask, dtype=jnp.uint32),
        jax.lax.reduce(mh, z, jax.lax.bitwise_xor, [0]),
        jax.lax.reduce(ml, z, jax.lax.bitwise_xor, [0]),
        jnp.sum(mh, dtype=jnp.uint32),
        jnp.sum(ml, dtype=jnp.uint32),
    ])


def _acc_digest(acc, dig, enabled):  # kspec: traced
    """Fold one chunk's [5] framing digest into a running per-level
    accumulator with the SAME combine rule the host applies across
    shards: counts and wrapping sums add, xors xor.  `enabled` masks
    out chunks the serial path would have discarded (overflowed
    attempts)."""
    z = jnp.zeros((5,), jnp.uint32)
    d = jnp.where(enabled, dig, z)
    return jnp.stack([
        acc[0] + d[0],
        acc[1] ^ d[1],
        acc[2] ^ d[2],
        acc[3] + d[3],
        acc[4] + d[4],
    ])


def _combine_digs(dig: np.ndarray) -> tuple:
    """Host-side global combine of per-shard [D, 5] framing digests
    (counts sum exactly, xors xor, wrapping-u32 sums wrap) — one shared
    implementation for the per-chunk and the device-level compares."""
    s64 = dig.astype(np.uint64)
    return (
        int(dig[:, 0].astype(np.int64).sum()),
        int(np.bitwise_xor.reduce(dig[:, 1])),
        int(np.bitwise_xor.reduce(dig[:, 2])),
        int(s64[:, 3].sum() & np.uint64(0xFFFFFFFF)),
        int(s64[:, 4].sum() & np.uint64(0xFFFFFFFF)),
    )


def _make_exchange(D: int, W: int, R: int, K: int, exchange: str,
                   compress: bool):
    """Build the traced per-chunk candidate exchange — ONE source for
    the per-chunk sharded step and the device-resident level program
    (the two must not drift on routing, codec or framing semantics).

    Returns fn(hi, lo, cand, parent_g, actid, valid, me) ->
    (r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest) with the received
    buffers R rows wide; see _make_sharded_step's docstring for the
    routing/codec/bit-identity contract."""
    sent = jnp.uint32(dedup.SENT)
    a2a = lambda x: jax.lax.all_to_all(  # noqa: E731
        x, "d", split_axis=0, concat_axis=0, tiled=True
    )
    if exchange == "all_to_all" and compress:
        from ..ops import fpcompress as _fpc

        Wr = max(32, W // 2)  # compact row budget (valid-first rows)
        NWc = _fpc.default_stream_words(W)

        def route(hi, lo, cand, parent_g, actid, valid, me):  # kspec: traced
            owner = jnp.where(
                valid, (lo % jnp.uint32(D)).astype(jnp.int32), D
            )
            s_hi, s_lo, s_cand, s_par, s_act, cnts = [], [], [], [], [], []
            for d in range(D):
                mask = owner == d
                cnts.append(jnp.sum(mask, dtype=jnp.int32))
                cpos = jnp.where(mask, jnp.cumsum(mask) - 1, W)
                s_hi.append(jnp.full((W,), sent).at[cpos].set(hi))
                s_lo.append(jnp.full((W,), sent).at[cpos].set(lo))
                s_cand.append(jnp.zeros((W, K), jnp.uint32).at[cpos].set(cand))
                s_par.append(jnp.full((W,), -1, jnp.int32).at[cpos].set(parent_g))
                s_act.append(jnp.full((W,), -1, jnp.int32).at[cpos].set(actid))
            b_hi = jnp.stack(s_hi)  # [D, W]
            b_lo = jnp.stack(s_lo)
            cnts_a = jnp.stack(cnts)  # [D]
            # STABLE per-bucket fingerprint sort (vmapped: ONE batched
            # sort program, not D copies — compile-time matters on this
            # engine's many step shapes): sentinels (max u64) sink last,
            # ties keep candidate order — the property the bit-identity
            # argument in _make_sharded_step's docstring rests on
            perm = jax.vmap(lambda h, l: jnp.lexsort((l, h)))(b_hi, b_lo)
            b_hi = jnp.take_along_axis(b_hi, perm, axis=1)
            b_lo = jnp.take_along_axis(b_lo, perm, axis=1)
            b_cand = jnp.take_along_axis(
                jnp.stack(s_cand), perm[:, :, None], axis=1
            )
            b_par = jnp.take_along_axis(jnp.stack(s_par), perm, axis=1)
            b_act = jnp.take_along_axis(jnp.stack(s_act), perm, axis=1)
            s_words, s_hdr, ovf_pack = jax.vmap(
                lambda h, l, c: _fpc.pack_sorted(h, l, c, NWc)
            )(b_hi, b_lo, cnts_a)
            ovf_dest = jnp.any(cnts_a > W) | jnp.any(
                ovf_pack | (cnts_a > Wr)
            )
            r_words = a2a(s_words)  # [D, NWc]
            r_hdr = a2a(s_hdr)  # [D, HDR + NB]
            r_cand_c = a2a(b_cand[:, :Wr])  # [D, Wr, K]
            r_par_c = a2a(b_par[:, :Wr])
            r_act_c = a2a(b_act[:, :Wr].astype(jnp.uint8))
            # in-jit decode per source segment; the framing digest the
            # caller computes runs over THESE decoded lanes, so fabric
            # integrity covers the packed stream, the header and the
            # codec
            dec_hi, dec_lo = jax.vmap(
                lambda wds, hd: _fpc.unpack_sorted(wds, hd, W)
            )(r_words, r_hdr)
            r_hi = dec_hi.reshape(R)
            r_lo = dec_lo.reshape(R)
            # compact rows pad back to W slots per source segment; the
            # live rows are the first cnt of each (valid-first after the
            # bucket sort), exactly aligned with the decoded lanes
            r_cand = (
                jnp.zeros((D, W, K), jnp.uint32)
                .at[:, :Wr].set(r_cand_c)
                .reshape(R, K)
            )
            r_parent = (
                jnp.full((D, W), -1, jnp.int32)
                .at[:, :Wr].set(r_par_c)
                .reshape(R)
            )
            r_act = (
                jnp.full((D, W), -1, jnp.int32)
                .at[:, :Wr].set(r_act_c.astype(jnp.int32))
                .reshape(R)
            )
            return r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest

    elif exchange == "all_to_all":

        def route(hi, lo, cand, parent_g, actid, valid, me):  # kspec: traced
            owner = jnp.where(
                valid, (lo % jnp.uint32(D)).astype(jnp.int32), D
            )
            s_hi, s_lo, s_cand, s_par, s_act, cnts = [], [], [], [], [], []
            for d in range(D):
                mask = owner == d
                cnt = jnp.sum(mask, dtype=jnp.int32)
                cnts.append(cnt)
                cpos = jnp.where(mask, jnp.cumsum(mask) - 1, W)
                s_hi.append(jnp.full((W,), sent).at[cpos].set(hi))
                s_lo.append(jnp.full((W,), sent).at[cpos].set(lo))
                s_cand.append(jnp.zeros((W, K), jnp.uint32).at[cpos].set(cand))
                s_par.append(jnp.full((W,), -1, jnp.int32).at[cpos].set(parent_g))
                s_act.append(jnp.full((W,), -1, jnp.int32).at[cpos].set(actid))
            ovf_dest = jnp.any(jnp.stack(cnts) > W)
            r_hi = a2a(jnp.stack(s_hi)).reshape(R)
            r_lo = a2a(jnp.stack(s_lo)).reshape(R)
            r_cand = a2a(jnp.stack(s_cand)).reshape(R, K)
            r_parent = a2a(jnp.stack(s_par)).reshape(R)
            r_act = a2a(jnp.stack(s_act)).reshape(R)
            return r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest

    else:

        def route(hi, lo, cand, parent_g, actid, valid, me):  # kspec: traced
            ovf_dest = jnp.bool_(False)
            r_hi = jax.lax.all_gather(hi, "d", tiled=True)  # [D*T]
            r_lo = jax.lax.all_gather(lo, "d", tiled=True)
            r_cand = jax.lax.all_gather(cand, "d", tiled=True)  # [D*T, K]
            r_valid = jax.lax.all_gather(valid, "d", tiled=True)
            r_parent = jax.lax.all_gather(parent_g, "d", tiled=True)
            r_act = jax.lax.all_gather(actid, "d", tiled=True)
            mine = r_valid & ((r_lo % jnp.uint32(D)).astype(jnp.int32) == me)
            r_hi = jnp.where(mine, r_hi, sent)
            r_lo = jnp.where(mine, r_lo, sent)
            return r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest

    return route


def _make_sharded_step(
    model: Model,
    mesh: Mesh,
    bucket: int,
    vcap: int,
    compact: Optional[int] = None,
    exchange: str = "all_to_all",
    dest_w: Optional[int] = None,
    with_merge: bool = True,
    hash_table: bool = False,
    compress: bool = False,
):
    """Jitted sharded level step.

    Global shapes (D = mesh size):
      frontier [D*bucket, K], fvalid [D*bucket]
      vhi/vlo  [D, vcap]  (per-device sorted fingerprint shard), vn [D]
    Returns per-shard compacted new states [D*R, K] (R = per-shard receive
    width), per-shard new counts [D], updated visited, violation flags, and
    two overflow flags (expansion compaction / destination buckets) — when
    either is set the outputs are incomplete and the caller must re-run the
    chunk at a larger width.

    compact: two-phase expansion shift (engine.bfs._Step.make_expand) — the
    guard sweep runs on the full lattice, update+pack+sort only on the
    enabled ~6%.

    exchange: how candidate fingerprints reach their owner shard
    (owner = fp_lo mod D — fingerprint-range sharding):
      - "all_to_all": bucket-by-owner + lax.all_to_all.  Each shard routes
        its candidates into D per-destination buckets of dest_w rows and
        sends each bucket only to its owner: per-shard ICI traffic is
        D*dest_w ≈ the candidate width, independent of mesh size (the
        SURVEY §2.6 design; docs/DISTRIBUTED.md has the padding-factor
        accounting).
      - "all_gather": every shard receives ALL candidates and filters to
        the ones it owns — D× the bytes, kept as the simple/robust
        fallback.

    compress (all_to_all only; KSPEC_OVERLAP's exchange leg, ROADMAP
    item 5): each destination bucket is stably SORTED by fingerprint
    (sentinels last), its fingerprint lanes ride the wire bit-packed/
    delta-encoded (ops/fpcompress — the padding tail packs to ~zero
    bits), its candidate rows/parents ride at a compacted half-width,
    and action ids travel as u8 — >=2x fewer exchange bytes per chunk.
    Decoding happens in-jit on the receiver, and the post-exchange
    framing digest is computed over the DECODED payload, so the PR 9
    fabric-integrity contract covers the codec itself.  Bit-identity
    holds because the per-bucket sort is STABLE: duplicate fingerprints
    keep their candidate order inside a bucket and buckets keep their
    source-shard order, so the receiver's stable lexsort elects exactly
    the winners the uncompressed path elects (same counts, same trace
    values).  A bucket too dense for its packed stream or compact row
    budget raises the destination-overflow flag and the chunk re-runs
    on the existing width ladder.
    """
    spec = model.spec
    expander = _Step(model)
    K, C = spec.num_lanes, expander.C
    D = mesh.devices.size
    # compact: None (full path), int (uniform legacy shift) or a per-action
    # width tuple (adaptive sizing — engine.bfs.make_expand handles both;
    # round-5 port of the single-device adaptive compact widths)
    if isinstance(compact, tuple):
        shift = compact
    else:
        shift = _norm_shift(bucket, int(compact) if compact else 0)
    expand = expander.make_expand(bucket, shift)
    T = expander.expand_width(bucket, shift)
    if exchange not in ("all_to_all", "all_gather"):
        raise ValueError(f"unknown exchange {exchange!r}")
    # per-destination row budget (all_to_all): default 4x headroom over a
    # uniform spread of the typical ~6%-enabled candidate load
    W = dest_w if dest_w is not None else _default_dest_w(T, D)
    R = D * W if exchange == "all_to_all" else D * T  # receive width
    route = _make_exchange(D, W, R, K, exchange, compress)

    def shard_body(frontier, fvalid, vhi, vlo, vn):  # kspec: traced
        # per-shard views: frontier [bucket, K], vhi [1, vcap], vn [1]
        vhi, vlo, vn = vhi[0], vlo[0], vn[0]
        me = jax.lax.axis_index("d")

        states = jax.vmap(spec.unpack)(frontier)
        en_pre, cand, valid, parent, actid, act_en, act_guard, ovf_expand = expand(
            states, fvalid
        )
        deadlocked = fvalid & ~jnp.any(en_pre, axis=1)

        hi, lo = fingerprint_lanes(cand, spec.exact64)
        sent = jnp.uint32(dedup.SENT)
        hi = jnp.where(valid, hi, sent)
        lo = jnp.where(valid, lo, sent)
        # parent as a mesh-global frontier row id (survives the exchange)
        parent_g = me.astype(jnp.int32) * bucket + parent

        sent_dig = _fp_digest(hi, lo, valid)

        r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest = route(
            hi, lo, cand, parent_g, actid, valid, me
        )

        # post-exchange framing digest over the received (non-sentinel)
        # candidates: across all shards the received multiset must be
        # exactly the sent multiset (all_to_all routes each valid
        # candidate to exactly one owner; all_gather + ownership filter
        # partitions the same set) — compared host-side per committed
        # chunk (overflowed attempts are discarded before the compare)
        recv_dig = _fp_digest(
            r_hi, r_lo, ~((r_hi == sent) & (r_lo == sent))
        )

        # minimal-payload sort over the received (owned) candidates: the
        # sort both dedups the batch (first-occurrence) and fixes the
        # shard's discovery order deterministically
        order = jnp.lexsort((r_lo, r_hi))
        hi_s, lo_s = r_hi[order], r_lo[order]
        invalid_s = (hi_s == sent) & (lo_s == sent)
        first = dedup.first_occurrence_mask(hi_s, lo_s, invalid_s)
        ovf_probe = jnp.bool_(False)
        if hash_table:
            # per-shard HBM open-addressing table (ops/hashset): vhi/vlo
            # carry the table slots; insert-or-find replaces both the
            # sorted-visited probe AND the O(vcap) rank-merge.  The call
            # is functional (no donation here): a retried chunk simply
            # discards the attempt's returned tables, so the existing
            # overflow discipline stays exact.
            q_hi = jnp.where(first, hi_s, sent)
            q_lo = jnp.where(first, lo_s, sent)
            # claim=None: a fresh per-shard claim lattice per chunk (an
            # HBM memset of cap/D int32 — microseconds at pod scale);
            # carrying it across chunks would need a third shard_map
            # operand for little gain at per-shard table sizes
            vhi2, vlo2, _claim, is_new, _nn, ovf_probe = hashset.probe_insert(
                vhi, vlo, q_hi, q_lo, first
            )
            vn2 = vn
            rank = jnp.zeros((R,), jnp.int32)
        else:
            seen, rank = dedup.rank_sorted(vhi, vlo, vn, hi_s, lo_s)
            is_new = first & ~seen

        pos = jnp.where(is_new, jnp.cumsum(is_new) - 1, R)
        out = jnp.zeros((R, K), jnp.uint32).at[pos].set(r_cand[order])
        out_parent = jnp.full((R,), -1, jnp.int32).at[pos].set(r_parent[order])
        out_act = jnp.full((R,), -1, jnp.int32).at[pos].set(r_act[order])
        out_hi = jnp.full((R,), sent).at[pos].set(hi_s)
        out_lo = jnp.full((R,), sent).at[pos].set(lo_s)
        out_rank = jnp.zeros((R,), jnp.int32).at[pos].set(rank)
        new_n = jnp.sum(is_new, dtype=jnp.int32)

        if hash_table:
            pass  # vhi2/vlo2 already hold the updated table
        elif with_merge:
            vhi2, vlo2, vn2 = dedup.merge_ranked(
                vhi, vlo, vn, out_hi, out_lo, out_rank, new_n, vcap
            )
        else:
            # host-FpSet backend: the device holds no visited set (the
            # placeholder probe above sees vn=0); the host inserts each
            # shard's batch-deduped fingerprints into its own FpSet
            vhi2, vlo2, vn2 = vhi, vlo, vn

        # invariants on the frontier shard being expanded (checked once per
        # state, at expansion; `states` is already unpacked)
        viol_any, viol_idx = [], []
        if model.invariants:
            for inv in model.invariants:
                ok = jax.vmap(inv.pred)(states)
                bad = fvalid & ~ok
                viol_any.append(jnp.any(bad))
                viol_idx.append(jnp.argmax(bad))
        else:
            viol_any, viol_idx = [jnp.bool_(False)], [jnp.int32(0)]

        return (
            out,  # [R, K] per-shard compacted (out_spec concatenates to [D*R])
            out_parent,
            out_act,
            new_n[None],
            vhi2[None],
            vlo2[None],
            vn2[None],
            jnp.stack(viol_any)[None],  # [1, n_inv] per shard -> [D, n_inv]
            jnp.stack(viol_idx)[None],
            jnp.any(deadlocked)[None],
            jnp.argmax(deadlocked)[None],
            act_en[None],  # [1, n_actions] -> [D, n_actions]
            # per-action expansion overflow + pre-constraint guard counts:
            # the host sizes adaptive per-action compact buffers from the
            # guard histogram exactly as the single-device engine does
            # (replicated-deterministic — every process sees the same
            # fetched globals)
            ovf_expand[None],  # [1, n_actions] -> [D, n_actions]
            act_guard[None],  # [1, n_actions] -> [D, n_actions]
            ovf_dest[None],
            ovf_probe[None],  # device-hash probe-budget overflow
            out_hi,  # [R] per shard (host-FpSet backend reads these)
            out_lo,
            sent_dig[None],  # [1, 5] -> [D, 5] exchange framing digests
            recv_dig[None],
        )

    # EXPLICIT per-tensor layouts (mesh_layouts): operands and results
    # name which dim rides the mesh axis instead of the old implicit
    # P("d")-for-everything (same placement, now spelled out and
    # asserted in tests so a real-ICI mesh inherits it unchanged)
    sharded = _shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P("d", None),  # frontier rows
            P("d"),        # fvalid
            P("d", None),  # visited hi lanes / hash slots
            P("d", None),  # visited lo lanes / hash slots
            P("d"),        # per-shard visited counts
        ),
        out_specs=(
            P("d", None),  # compacted new rows [D*R, K]
            P("d"),        # parents
            P("d"),        # action ids
            P("d"),        # per-shard new counts
            P("d", None),  # updated visited hi
            P("d", None),  # updated visited lo
            P("d"),        # updated visited counts
            P("d", None),  # viol_any [D, n_inv]
            P("d", None),  # viol_idx [D, n_inv]
            P("d"),        # deadlock any
            P("d"),        # deadlock idx
            P("d", None),  # act_en [D, n_actions]
            P("d", None),  # ovf_expand [D, n_actions]
            P("d", None),  # act_guard [D, n_actions]
            P("d"),        # ovf_dest
            P("d"),        # ovf_probe
            P("d"),        # out_hi
            P("d"),        # out_lo
            P("d", None),  # sent framing digests [D, 5]
            P("d", None),  # recv framing digests [D, 5]
        ),
        **_SHARD_MAP_KW,
    )
    return jax.jit(sharded)


def _grow_sorted_shards(dev_vhi, dev_vlo, vcap: int, new_cap: int,
                        layout):
    """Grow every shard's sorted visited pair set to `new_cap` slots
    (sentinel-padded) — the one growth path for the per-chunk loop and
    the device-resident level driver.  Multi-process takes the host
    round trip (every process must contribute its shards); single-
    process grows on device with no host copy."""
    D = dev_vhi.shape[0]
    if is_multiprocess():
        grown_hi = fetch_global(dev_vhi)
        grown_lo = fetch_global(dev_vlo)
        pad = np.full(
            (D, new_cap - grown_hi.shape[1]), 0xFFFFFFFF, np.uint32
        )
        dev_vhi = put_global(
            np.concatenate([grown_hi, pad], axis=1), layout
        )
        dev_vlo = put_global(
            np.concatenate([grown_lo, pad], axis=1), layout
        )
    else:
        pad = jnp.full(
            (D, new_cap - dev_vhi.shape[1]), 0xFFFFFFFF, jnp.uint32
        )
        dev_vhi = jax.device_put(
            jnp.concatenate([dev_vhi, pad], axis=1), layout
        )
        dev_vlo = jax.device_put(
            jnp.concatenate([dev_vlo, pad], axis=1), layout
        )
    return dev_vhi, dev_vlo, new_cap


def _make_sharded_level(
    model: Model,
    mesh: Mesh,
    expander: _Step,
    B: int,
    NCp: int,
    vcap: int,
    widths: tuple,
    LN: int,
    exchange: str,
    dest_w: int,
    compress: bool,
    check_deadlock: bool,
):
    """The sharded device-resident LEVEL program: every gated chunk of a
    BFS level runs inside ONE dispatched ``lax.while_loop`` per shard —
    the PR 12 single-device level body composed with the per-chunk
    collective exchange — so a level costs O(1) collective-bearing
    launches per shard instead of O(chunks).

    Per while_loop iteration (= one serial chunk), each shard:
    dynamic-slices its chunk from the device-resident frontier buffer
    [NCp*B, K] -> compacted expansion (make_expand's per-action in-jit
    cumsum/scatter — the exact action-major candidate order of the
    per-chunk path) -> fingerprints -> per-destination bucketing + the
    ``all_to_all`` (or all_gather) exchange, with the PR 10 compression
    codec in-loop when enabled (_make_exchange: ONE routing source with
    the per-chunk step) -> DUAL-PROBE dedup of the received candidates
    (stable lexsort winners vs the READ-ONLY visited shard AND a
    device-resident per-shard level-new sorted set) -> in-jit
    (count, xor, sum) digest folds (ops/devlevel) + framing-digest
    accumulation -> dynamic-offset next-frontier append.  The
    O(capacity) visited merge runs ONCE per shard after the loop.

    Bit-identity with the per-chunk path holds chunk for chunk: the
    routing, per-bucket stable sort and receiver lexsort are the same
    traced code (_make_exchange), novelty against (visited ∪ level-new)
    equals the per-chunk path's chunk-by-chunk merged visited set
    (routing sends a fingerprint to the same owner shard every time),
    and winners of equal fingerprints are decided by the same stable
    sort over the same candidate order.  Verdict priority mirrors the
    serial commit loop exactly — invariants beat deadlock within a
    chunk, the first invariant (in declaration order) violated by ANY
    shard wins, then the lowest shard — elected REPLICATED via
    all_gather so the while_loop condition stays uniform across the
    mesh (a collective inside a loop requires every participant to
    agree on the trip count).  Overflow flags (expansion segment,
    destination bucket / codec budget, level-new capacity) combine
    replicated via pmax: an overflowing level stops committing and the
    host re-dispatches ONCE from the pre-level visited state at exact
    measured widths — <=2 launches per level per shard even then.

    Returns the jitted program over global operands
    (fbuf [D*NCp*B, K], flen [D], ncs [D], vhi/vlo [D, vcap], vn [D])
    laid out per :func:`mesh_layouts`.
    """
    spec = model.spec
    K = spec.num_lanes
    D = mesh.devices.size
    expand = expander.make_expand(B, widths)
    T = expander.expand_width(B, widths)
    W = dest_w
    R = D * W if exchange == "all_to_all" else D * T
    OC = LN + R  # output buffer: one chunk of append headroom past LN
    F = NCp * B  # per-shard frontier buffer rows
    n_actions = len(model.actions)
    route = _make_exchange(D, W, R, K, exchange, compress)
    from ..engine.pipeline import sorted_dedup_stage

    def level_body(fbuf, flen, ncs, vhi, vlo, vn):  # kspec: traced
        flen = flen[0]
        ncs = ncs[0]
        vhi, vlo, vn = vhi[0], vlo[0], vn[0]
        me = jax.lax.axis_index("d")
        sent = jnp.uint32(dedup.SENT)

        def body(carry):  # kspec: traced
            (i, orows, opar, oact, on, lhi, llo, ln,
             vkind, vshard, vinv, vidx,
             act_en, agmax, dig, s_acc, r_acc, ovf, nclean) = carry
            start = i * B
            rows = jax.lax.dynamic_slice(fbuf, (start, 0), (B, K))
            fvalid = (
                start + jnp.arange(B, dtype=jnp.int32)
            ) < flen
            states = jax.vmap(spec.unpack)(rows)
            (en_pre, cand, valid, parent, actid, a_en, a_guard,
             exp_ovf) = expand(states, fvalid)
            deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
            hi, lo = fingerprint_lanes(cand, spec.exact64)
            hi = jnp.where(valid, hi, sent)
            lo = jnp.where(valid, lo, sent)
            # parent as a mesh-global LEVEL row id: src shard * F +
            # (chunk offset + row) — the host decodes src_d = pg // F,
            # level row = pg % F (chunk offsets are i*B by plan)
            parent_g = me.astype(jnp.int32) * F + (start + parent)
            sent_dig = _fp_digest(hi, lo, valid)
            (r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest) = route(
                hi, lo, cand, parent_g, actid, valid, me
            )
            recv_dig = _fp_digest(
                r_hi, r_lo, ~((r_hi == sent) & (r_lo == sent))
            )
            # the SHARED winner-selection sequence (one source of truth
            # with the per-chunk paths): primary set = this shard's
            # level-new sorted set (its ranks drive the gated merge
            # below), also_seen_in = the read-only visited shard
            (n_out, n_par, n_act, new_n, n_hi, n_lo, _l1, _l2, _l3,
             n_rank) = sorted_dedup_stage(
                r_cand, r_parent, r_act,
                ~((r_hi == sent) & (r_lo == sent)),
                r_hi, r_lo, lhi, llo, ln, LN, R, K, False,
                also_seen_in=(vhi, vlo, vn),
            )
            # frontier verdicts, serial priority (the per-inv loop is
            # the per-chunk step's exact semantics)
            if model.invariants:
                v_any, v_idx = [], []
                for inv in model.invariants:
                    ok = jax.vmap(inv.pred)(states)
                    bad = fvalid & ~ok
                    v_any.append(jnp.any(bad))
                    v_idx.append(jnp.argmax(bad).astype(jnp.int32))
                viol_any = jnp.stack(v_any)
                viol_idx = jnp.stack(v_idx)
            else:
                viol_any = jnp.zeros((1,), bool)
                viol_idx = jnp.zeros((1,), jnp.int32)
            # REPLICATED verdict election: every shard derives the same
            # winner from the gathered flags, so the loop condition
            # stays uniform across the mesh
            g_viol = jax.lax.all_gather(
                viol_any[None], "d", tiled=True
            )  # [D, n_inv]
            g_vix = jax.lax.all_gather(viol_idx[None], "d", tiled=True)
            dl_pair = jnp.stack([
                jnp.any(deadlocked).astype(jnp.int32),
                jnp.argmax(deadlocked).astype(jnp.int32),
            ])
            g_dl = jax.lax.all_gather(dl_pair[None], "d", tiled=True)
            inv_any = jnp.any(g_viol)
            inv_i = jnp.argmax(jnp.any(g_viol, axis=0)).astype(jnp.int32)
            d_inv = jnp.argmax(g_viol[:, inv_i]).astype(jnp.int32)
            dl_any = jnp.bool_(check_deadlock) & jnp.any(g_dl[:, 0] > 0)
            d_dl = jnp.argmax(g_dl[:, 0]).astype(jnp.int32)
            kind = jnp.where(
                inv_any, jnp.int32(1),
                jnp.where(dl_any, jnp.int32(2), jnp.int32(0)),
            )
            vd = jnp.where(inv_any, d_inv, d_dl)
            vix_l = jnp.where(
                inv_any, g_vix[d_inv, inv_i], g_dl[d_dl, 1]
            ) + start
            take = (vkind == 0) & (kind != 0)
            commit = kind == 0  # a verdict chunk commits nothing
            # REPLICATED overflow flags (pmax): every shard must agree
            # on commit gating and the host's re-dispatch decision
            ln_ovf = jax.lax.pmax(
                (commit & ((ln + new_n) > LN)).astype(jnp.int32), "d"
            ) > 0
            this_ovf = jax.lax.pmax(
                (jnp.any(exp_ovf) | ovf_dest).astype(jnp.int32), "d"
            ) > 0
            commit_ok = commit & ~ovf & ~ln_ovf
            # framing accumulates for every chunk the serial path would
            # have COMPARED: clean chunks, including a verdict chunk
            # (the serial commit checks framing before the verdict)
            clean = ~ovf & ~this_ovf & ~ln_ovf
            app_n = jnp.where(commit_ok, new_n, 0)
            orows = devlevel.append_rows(orows, n_out, on)
            opar = devlevel.append_vec(opar, n_par, on)
            oact = devlevel.append_vec(oact, n_act, on)
            lhi, llo, ln = dedup.merge_ranked(
                lhi, llo, ln, n_hi, n_lo, n_rank, app_n, LN
            )
            dig = devlevel.combine_digest(
                dig,
                devlevel.masked_digest(
                    n_hi, n_lo, jnp.arange(R) < app_n
                ),
            )
            s_acc = _acc_digest(s_acc, sent_dig, clean)
            r_acc = _acc_digest(r_acc, recv_dig, clean)
            act_en = act_en + jnp.where(commit_ok, a_en, 0)
            agmax = jnp.maximum(agmax, a_guard)
            nclean = nclean + jnp.where(clean, 1, 0)
            ovf = ovf | this_ovf | ln_ovf
            return (i + 1, orows, opar, oact, on + app_n,
                    lhi, llo, ln,
                    jnp.where(take, kind, vkind),
                    jnp.where(take, vd, vshard),
                    jnp.where(take, inv_i, vinv),
                    jnp.where(take, vix_l, vidx),
                    act_en, agmax, dig, s_acc, r_acc, ovf, nclean)

        def cond(carry):  # kspec: traced
            return (carry[0] < ncs) & (carry[8] == 0)

        init = (
            jnp.int32(0),
            jnp.zeros((OC, K), jnp.uint32),
            jnp.full((OC,), -1, jnp.int32),
            jnp.full((OC,), -1, jnp.int32),
            jnp.int32(0),
            jnp.full((LN,), sent),
            jnp.full((LN,), sent),
            jnp.int32(0),
            jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
            jnp.zeros((n_actions,), jnp.int32),
            jnp.zeros((n_actions,), jnp.int32),
            devlevel.zero_digest(),
            jnp.zeros((5,), jnp.uint32),
            jnp.zeros((5,), jnp.uint32),
            jnp.bool_(False),
            jnp.int32(0),
        )
        (_i, orows, opar, oact, on, lhi, llo, _ln, vkind, vshard,
         vinv, vidx, act_en, agmax, dig, s_acc, r_acc, ovf,
         nclean) = jax.lax.while_loop(cond, body, init)
        # ONE O(capacity) merge per shard per level (the per-chunk path
        # pays one per chunk): every level-new entry is disjoint from
        # the visited shard by construction, so the rank-scatter merge
        # of the sorted level-new prefix lands the identical sorted
        # visited array
        _s, rank_v = dedup.rank_sorted(vhi, vlo, vn, lhi, llo)
        vhi, vlo, vn = dedup.merge_ranked(
            vhi, vlo, vn, lhi, llo, rank_v, on, vcap
        )
        dc, dxh, dxl, dlimbs = dig
        return (
            orows,  # [OC, K] -> [D*OC, K]
            opar,
            oact,
            on[None],
            vhi[None],
            vlo[None],
            vn[None],
            vkind[None], vshard[None], vinv[None], vidx[None],
            act_en[None],  # [1, n_actions]
            agmax[None],
            dc[None], dxh[None], dxl[None],  # digest accumulator...
            dlimbs[None],  # ... (count, xors, 16-bit sum limbs)
            s_acc[None], r_acc[None],  # [1, 5] framing accumulators
            ovf[None],
            nclean[None],
        )

    sharded = _shard_map(
        level_body,
        mesh=mesh,
        in_specs=(
            P("d", None),  # frontier buffer rows [D*F, K]
            P("d"),        # per-shard pending lengths
            P("d"),        # per-shard (replicated-value) chunk counts
            P("d", None),  # visited hi lanes
            P("d", None),  # visited lo lanes
            P("d"),        # per-shard visited counts
        ),
        out_specs=(
            P("d", None),  # next-frontier rows [D*OC, K]
            P("d"),        # parents (mesh-global level row ids)
            P("d"),        # action ids
            P("d"),        # per-shard new counts
            P("d", None),  # merged visited hi
            P("d", None),  # merged visited lo
            P("d"),        # merged visited counts
            P("d"), P("d"), P("d"), P("d"),  # verdict kind/shard/inv/idx
            P("d", None),  # act_en [D, n_actions]
            P("d", None),  # agmax [D, n_actions]
            P("d"), P("d"), P("d"),  # digest count/xor_hi/xor_lo
            P("d", None),  # digest sum limbs [D, 4]
            P("d", None),  # sent framing accumulator [D, 5]
            P("d", None),  # recv framing accumulator [D, 5]
            P("d"),        # replicated overflow flag
            P("d"),        # clean (counted) chunks
        ),
        **_SHARD_MAP_KW,
    )
    return jax.jit(sharded)


def _make_sharded_level_host(
    model: Model,
    mesh: Mesh,
    expander: _Step,
    B: int,
    NCp: int,
    widths: tuple,
    LN: int,
    exchange: str,
    dest_w: int,
    compress: bool,
    check_deadlock: bool,
):
    """The sharded device-resident level program for the HOST (and
    disk-tier) visited backends — :func:`_make_sharded_level`'s
    deferred-probe twin.  Three deltas from the device-backend program:

    - no visited shards ride the program at all: novelty inside the
      level is decided against each shard's device-resident level-new
      sorted set alone (the same stable-lexsort winners — and the same
      SORTED emission order — as the per-chunk sharded host step), and
      each owner shard's host FpSet probes the level's novel candidates
      in ONE batched insert after the program completes
      (check_sharded._run_device_level's host branch): O(1) host syncs
      AND O(1) collective-bearing launches per shard per level;
    - the emitted prefix carries its fingerprint lanes out (ohi/olo
      accumulators) so the host probe never recomputes them;
    - no in-jit digest folds — the chain's multiset is only known after
      the probe, so the host folds the survivors exactly as the
      per-chunk host commit does (fingerprint_rows over the kept rows).

    The exchange (+ codec) still runs inside the loop, and the framing
    digests still accumulate — fabric integrity is independent of where
    the visited set lives.  Bit-identity with the per-chunk sharded
    host path holds chunk for chunk: routing sends a fingerprint to the
    same owner shard every time, so (level-new ∪ host set) partitions
    novelty exactly as the per-chunk path's serial inserts do, with the
    earlier chunk winning cross-chunk intra-level duplicates — the same
    winner the serial per-chunk FpSet insert picks."""
    spec = model.spec
    K = spec.num_lanes
    D = mesh.devices.size
    expand = expander.make_expand(B, widths)
    T = expander.expand_width(B, widths)
    W = dest_w
    R = D * W if exchange == "all_to_all" else D * T
    OC = LN + R  # output buffer: one chunk of append headroom past LN
    F = NCp * B  # per-shard frontier buffer rows
    n_actions = len(model.actions)
    route = _make_exchange(D, W, R, K, exchange, compress)
    from ..engine.pipeline import sorted_dedup_stage

    def level_body(fbuf, flen, ncs):  # kspec: traced
        flen = flen[0]
        ncs = ncs[0]
        me = jax.lax.axis_index("d")
        sent = jnp.uint32(dedup.SENT)

        def body(carry):  # kspec: traced
            (i, orows, opar, oact, ohi, olo, on, lhi, llo, ln,
             vkind, vshard, vinv, vidx,
             act_en, agmax, s_acc, r_acc, ovf, nclean) = carry
            start = i * B
            rows = jax.lax.dynamic_slice(fbuf, (start, 0), (B, K))
            fvalid = (
                start + jnp.arange(B, dtype=jnp.int32)
            ) < flen
            states = jax.vmap(spec.unpack)(rows)
            (en_pre, cand, valid, parent, actid, a_en, a_guard,
             exp_ovf) = expand(states, fvalid)
            deadlocked = fvalid & ~jnp.any(en_pre, axis=1)
            hi, lo = fingerprint_lanes(cand, spec.exact64)
            hi = jnp.where(valid, hi, sent)
            lo = jnp.where(valid, lo, sent)
            parent_g = me.astype(jnp.int32) * F + (start + parent)
            sent_dig = _fp_digest(hi, lo, valid)
            (r_hi, r_lo, r_cand, r_parent, r_act, ovf_dest) = route(
                hi, lo, cand, parent_g, actid, valid, me
            )
            recv_dig = _fp_digest(
                r_hi, r_lo, ~((r_hi == sent) & (r_lo == sent))
            )
            # the SHARED winner-selection sequence, primary set = this
            # shard's level-new sorted set, NO visited probe (that is
            # the host's one batched call after the program)
            (n_out, n_par, n_act, new_n, n_hi, n_lo, _l1, _l2, _l3,
             n_rank) = sorted_dedup_stage(
                r_cand, r_parent, r_act,
                ~((r_hi == sent) & (r_lo == sent)),
                r_hi, r_lo, lhi, llo, ln, LN, R, K, False,
            )
            # frontier verdicts, replicated election (identical to the
            # device-backend program — verdicts derive from frontier
            # states only, so the deferred probe cannot change them)
            if model.invariants:
                v_any, v_idx = [], []
                for inv in model.invariants:
                    ok = jax.vmap(inv.pred)(states)
                    bad = fvalid & ~ok
                    v_any.append(jnp.any(bad))
                    v_idx.append(jnp.argmax(bad).astype(jnp.int32))
                viol_any = jnp.stack(v_any)
                viol_idx = jnp.stack(v_idx)
            else:
                viol_any = jnp.zeros((1,), bool)
                viol_idx = jnp.zeros((1,), jnp.int32)
            g_viol = jax.lax.all_gather(
                viol_any[None], "d", tiled=True
            )
            g_vix = jax.lax.all_gather(viol_idx[None], "d", tiled=True)
            dl_pair = jnp.stack([
                jnp.any(deadlocked).astype(jnp.int32),
                jnp.argmax(deadlocked).astype(jnp.int32),
            ])
            g_dl = jax.lax.all_gather(dl_pair[None], "d", tiled=True)
            inv_any = jnp.any(g_viol)
            inv_i = jnp.argmax(jnp.any(g_viol, axis=0)).astype(jnp.int32)
            d_inv = jnp.argmax(g_viol[:, inv_i]).astype(jnp.int32)
            dl_any = jnp.bool_(check_deadlock) & jnp.any(g_dl[:, 0] > 0)
            d_dl = jnp.argmax(g_dl[:, 0]).astype(jnp.int32)
            kind = jnp.where(
                inv_any, jnp.int32(1),
                jnp.where(dl_any, jnp.int32(2), jnp.int32(0)),
            )
            vd = jnp.where(inv_any, d_inv, d_dl)
            vix_l = jnp.where(
                inv_any, g_vix[d_inv, inv_i], g_dl[d_dl, 1]
            ) + start
            take = (vkind == 0) & (kind != 0)
            commit = kind == 0  # a verdict chunk commits nothing
            ln_ovf = jax.lax.pmax(
                (commit & ((ln + new_n) > LN)).astype(jnp.int32), "d"
            ) > 0
            this_ovf = jax.lax.pmax(
                (jnp.any(exp_ovf) | ovf_dest).astype(jnp.int32), "d"
            ) > 0
            commit_ok = commit & ~ovf & ~ln_ovf
            clean = ~ovf & ~this_ovf & ~ln_ovf
            app_n = jnp.where(commit_ok, new_n, 0)
            orows = devlevel.append_rows(orows, n_out, on)
            opar = devlevel.append_vec(opar, n_par, on)
            oact = devlevel.append_vec(oact, n_act, on)
            ohi = devlevel.append_vec(ohi, n_hi, on)
            olo = devlevel.append_vec(olo, n_lo, on)
            lhi, llo, ln = dedup.merge_ranked(
                lhi, llo, ln, n_hi, n_lo, n_rank, app_n, LN
            )
            s_acc = _acc_digest(s_acc, sent_dig, clean)
            r_acc = _acc_digest(r_acc, recv_dig, clean)
            act_en = act_en + jnp.where(commit_ok, a_en, 0)
            agmax = jnp.maximum(agmax, a_guard)
            nclean = nclean + jnp.where(clean, 1, 0)
            ovf = ovf | this_ovf | ln_ovf
            return (i + 1, orows, opar, oact, ohi, olo, on + app_n,
                    lhi, llo, ln,
                    jnp.where(take, kind, vkind),
                    jnp.where(take, vd, vshard),
                    jnp.where(take, inv_i, vinv),
                    jnp.where(take, vix_l, vidx),
                    act_en, agmax, s_acc, r_acc, ovf, nclean)

        def cond(carry):  # kspec: traced
            return (carry[0] < ncs) & (carry[10] == 0)

        init = (
            jnp.int32(0),
            jnp.zeros((OC, K), jnp.uint32),
            jnp.full((OC,), -1, jnp.int32),
            jnp.full((OC,), -1, jnp.int32),
            jnp.full((OC,), sent),
            jnp.full((OC,), sent),
            jnp.int32(0),
            jnp.full((LN,), sent),
            jnp.full((LN,), sent),
            jnp.int32(0),
            jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0),
            jnp.zeros((n_actions,), jnp.int32),
            jnp.zeros((n_actions,), jnp.int32),
            jnp.zeros((5,), jnp.uint32),
            jnp.zeros((5,), jnp.uint32),
            jnp.bool_(False),
            jnp.int32(0),
        )
        (_i, orows, opar, oact, ohi, olo, on, _lh, _ll, _ln, vkind,
         vshard, vinv, vidx, act_en, agmax, s_acc, r_acc, ovf,
         nclean) = jax.lax.while_loop(cond, body, init)
        return (
            orows,  # [OC, K] -> [D*OC, K]
            opar,
            oact,
            ohi,  # [OC] novel-candidate fingerprint lanes (host probe)
            olo,
            on[None],
            vkind[None], vshard[None], vinv[None], vidx[None],
            act_en[None],
            agmax[None],
            s_acc[None], r_acc[None],  # [1, 5] framing accumulators
            ovf[None],
            nclean[None],
        )

    sharded = _shard_map(
        level_body,
        mesh=mesh,
        in_specs=(
            P("d", None),  # frontier buffer rows [D*F, K]
            P("d"),        # per-shard pending lengths
            P("d"),        # per-shard (replicated-value) chunk counts
        ),
        out_specs=(
            P("d", None),  # next-frontier candidate rows [D*OC, K]
            P("d"),        # parents (mesh-global level row ids)
            P("d"),        # action ids
            P("d"),        # candidate fingerprint hi lanes
            P("d"),        # candidate fingerprint lo lanes
            P("d"),        # per-shard pre-probe candidate counts
            P("d"), P("d"), P("d"), P("d"),  # verdict kind/shard/inv/idx
            P("d", None),  # act_en [D, n_actions]
            P("d", None),  # agmax [D, n_actions]
            P("d", None),  # sent framing accumulator [D, 5]
            P("d", None),  # recv framing accumulator [D, 5]
            P("d"),        # replicated overflow flag
            P("d"),        # clean (counted) chunks
        ),
        **_SHARD_MAP_KW,
    )
    return jax.jit(sharded)


class ShardedDeviceLevel:
    """Policy/state holder for the sharded device-resident level path
    (`--pipeline device`): the preconditions, the serial-chunking plan,
    and the width/level-new sizing ladders.  The dispatch/commit driver
    lives in check_sharded (it needs the engine loop's locals); this
    object is what survives across levels.

    Preconditions mirror the single-device DevicePipeline: a sorted-
    dedup visited backend — "device" (in-jit dual-probe + one merge per
    shard per level) or "host"/disk tier (deferred-probe mode: ONE
    batched per-shard host FpSet insert per level) — AND analyzer-
    proven per-field value hulls (engine.pipeline.device_hull_fallback
    — a HARD precondition, the in-jit pack stage has no host visibility
    between chunks).  The registry's per-backend matrix
    (pipeline_registry.backend_fallback_reason) is the one source of
    which backends serve natively; any unmet precondition or
    compile/dispatch failure sets `fallback` (sticky) and the run
    degrades to the per-chunk sharded ladder — results identical,
    launches O(chunks)."""

    def __init__(self, model: Model, mesh: Mesh, expander: _Step,
                 adapt: AdaptiveCompact, visited_backend: str,
                 check_deadlock: bool):
        from ..engine.pipeline import PooledWidths, device_hull_fallback
        from ..pipeline_registry import backend_fallback_reason

        self.model = model
        self.mesh = mesh
        self.expander = expander
        self.adapt = adapt
        self.check_deadlock = check_deadlock
        self.pool = PooledWidths(model.actions)
        self._ln_hw = 0  # per-level new-state high water (LN ladder)
        self.levels = 0  # levels actually run device-resident
        self.launches_last = 0
        #: deferred-probe mode: the per-shard level programs carry no
        #: visited shards; the host probes each shard's level batch once
        self.host_mode = visited_backend == "host"
        self.fallback: Optional[str] = backend_fallback_reason(
            "device", visited_backend
        )
        if self.fallback is None:
            self.fallback = device_hull_fallback(model)

    def _gated(self, B: int) -> bool:
        """The serial path must run the compacted (action-major)
        expansion at this bucket — below the gate it runs the full
        lattice in state-major order, which only the per-chunk path
        produces (the same bit-identity guard as the single-device
        plan_level)."""
        w = self.adapt.widths_for(B)
        if w is None:
            return False
        if isinstance(w, int):
            return _norm_shift(B, w) != 0
        return True

    def plan_level(self, lens, chunk: int, min_bucket: int):
        """-> (B, n_chunks) when the level program can serve (a prefix
        of) this level's serial chunks, else None.  The plan mirrors
        check_sharded's serial chunking EXACTLY: the serial bucket is
        min(next_pow2(max(rem, min_bucket//D, 32)), chunk) with rem the
        max remaining rows over shards — the device program covers the
        prefix of chunks whose serial bucket equals the uniform program
        bucket; a smaller-bucket tail runs through the per-chunk loop
        at its serial offsets afterwards (bit-identity)."""
        if self.fallback is not None:
            return None
        D = self.mesh.devices.size
        rem = max(lens) if lens else 0
        if rem <= 0:
            return None
        mb = max(min_bucket // D, 32)
        if rem <= chunk:
            B = min(_next_pow2(max(rem, mb)), chunk)
            return (B, 1) if self._gated(B) else None
        if not self._gated(chunk):
            return None
        nfull, r = 0, rem
        while r > 0 and min(_next_pow2(max(r, mb)), chunk) == chunk:
            nfull += 1
            r -= chunk
        return (chunk, nfull) if nfull else None

    def widths(self, B: int):
        n = len(self.model.actions)
        return self.expander.norm_widths(
            B, self.pool.widths_for(B, np.zeros(n), B)
        )

    def exact_widths(self, B: int, agmax: np.ndarray):
        return self.expander.norm_widths(
            B, self.pool.widths_for(B, agmax.astype(np.float64), B)
        )

    def observe(self, agmax: np.ndarray, B: int, new_total_max: int
                ) -> None:
        """Fold one committed level's measured maxima into the sizing
        ladders (pool widths + the shared LN high-water)."""
        np.maximum(
            self.pool.hw, agmax.astype(np.float64) / max(B, 1),
            out=self.pool.hw,
        )
        self._ln_hw = max(self._ln_hw, int(new_total_max))
        self.levels += 1

    def mark_fallback(self, reason: str, depth: int) -> None:
        self.fallback = reason
        from ..obs import tracer as _obs_t

        _obs_t.event(
            "pipeline-fallback", depth=depth, pipeline="sharded-device",
            to="per-chunk", error=reason[:200],
        )


def _elastic_reshard(
    snap,
    part_arrays,
    old_D: int,
    old_P: int,
    old_pending,
    *,
    D: int,
    spec,
    visited_backend: str,
    use_disk: bool,
    host_sets,
    shard_proc,
    my_proc: int,
    spill_base,
    vcap: int,
    shard_visited,
):
    """Re-bucket a D-shard checkpoint onto the current D-shard layout.

    Ownership is pure fingerprint arithmetic (owner = fp_lo mod D), so an
    elastic resume is a deterministic re-bucketing of every piece of
    persisted state — the pending frontiers and the visited fingerprints
    of whichever backend the run uses — with no re-exploration:

    - pending rows are re-fingerprinted and dealt to their new owners
      (within a shard the old concatenated order is preserved, so the
      re-bucketing is deterministic and the parent-log boundary rewrite
      can mirror it);
    - device / device-hash shards are rebuilt from the snapshot's live
      fingerprint pairs;
    - host FpSets are rebuilt from the (possibly per-host-part) dumps;
    - tiered disk sets re-insert every old shard's hot dump + run files
      into the new shards' sets.  Old run files are NOT deleted: they go
      behind the new sets' checkpoint-generation deletion barrier (new
      run numbering continues past them), so every retained pre-reshard
      generation still resolves until it rotates away.

    Returns (pending, host_sets, vhi, vlo, vn, vcap, shard_visited) with
    only the backend-relevant entries changed.
    """
    K = spec.num_lanes
    vhi = vlo = vn = None

    rows_all = (
        np.concatenate(old_pending)
        if any(p.shape[0] for p in old_pending)
        else np.empty((0, K), np.uint32)
    )
    if rows_all.shape[0]:
        rhi, rlo = fingerprint_lanes(jnp.asarray(rows_all), spec.exact64)
        rowner = np.asarray(rlo).astype(np.int64) % D
    else:
        rowner = np.empty(0, np.int64)
    pending = [rows_all[rowner == d] for d in range(D)]

    if visited_backend == "host" and use_disk:
        from ..storage.runs import SortedRun

        srcs = (
            [part_arrays[f"host{p}"] for p in range(old_P)]
            if old_P > 1
            else [snap]
        )
        old_mans = [None] * old_D
        old_hots = [np.empty(0, np.uint64)] * old_D
        for src in srcs:
            mans = json.loads(str(src["spill_manifest"]))
            hot_flat, lens = src["host_hot"], src["host_hot_lens"]
            at = 0
            for d, ln in enumerate(lens):
                ln = int(ln)
                if mans[d] is not None:
                    old_mans[d] = mans[d]
                    old_hots[d] = np.asarray(
                        hot_flat[at : at + ln], np.uint64
                    )
                at += ln
        # continue run numbering past every old layout's files so a
        # re-used shard directory never collides with barrier-protected
        # old runs
        next_seq = max(
            (int(m["seq"]) for m in old_mans if m is not None), default=0
        )
        for d in range(D):
            if host_sets[d] is not None:
                host_sets[d].seq = next_seq

        def deal(fps: np.ndarray) -> None:
            # re-bucket one source array; the new sets spill past their
            # budgets as usual, so peak residency stays O(one old run),
            # never O(visited) — the whole point of the disk tier
            fo = (fps & np.uint64(0xFFFFFFFF)).astype(np.int64) % D
            for d in range(D):
                if host_sets[d] is None:
                    continue
                sel = fps[fo == d]
                if len(sel):
                    host_sets[d].insert(sel)

        for k in range(old_D):
            old_files = []
            deal(old_hots[k])
            if old_mans[k] is not None:
                shard_dir = os.path.join(spill_base, f"shard{k}")
                for m in old_mans[k]["runs"]:
                    r = SortedRun(shard_dir, m, verify=True)
                    deal(np.asarray(r.arr))
                    old_files.append(r.path)
                # in-flight deferred deletions from the old layout keep
                # aging out under the new sets' barriers
                old_files.extend(
                    os.path.normpath(os.path.join(shard_dir, p))
                    for _, p in old_mans[k].get("pending_delete", ())
                )
            # retire the old layout's files behind the deletion barrier
            # of a deterministic owner (old shard k -> new set k mod D),
            # so every retained pre-reshard generation still resolves
            tgt = host_sets[k % D]
            if tgt is not None and old_files:
                tgt.deleter.schedule(old_files)
    elif visited_backend == "host":
        from ..native import FpSet

        if old_P > 1:
            all_fps = np.concatenate(
                [np.asarray(part_arrays[f"host{p}"]["host_fps"], np.uint64)
                 for p in range(old_P)]
            )
        else:
            all_fps = np.asarray(snap["host_fps"], np.uint64)
        fowner = (all_fps & np.uint64(0xFFFFFFFF)).astype(np.int64) % D
        host_sets = []
        for d in range(D):
            if shard_proc[d] != my_proc:
                host_sets.append(None)
                continue
            sel = all_fps[fowner == d]
            s = FpSet(initial_capacity=max(64, 2 * len(sel)))
            if len(sel):
                s.insert(sel)
            host_sets.append(s)
    elif visited_backend == "device-hash":
        flat_hi = np.asarray(snap["hash_hi"], np.uint32)
        flat_lo = np.asarray(snap["hash_lo"], np.uint32)
        howner = flat_lo.astype(np.int64) % D
        per_shard = [
            (flat_hi[howner == d], flat_lo[howner == d]) for d in range(D)
        ]
        shard_visited = np.asarray(
            [len(h) for h, _ in per_shard], np.int64
        )
        vhi, vlo, vcap = _shard_tables_from_pairs(per_shard, _HASH_MIN_CAP)
        vn = np.zeros((D,), np.int32)
    else:  # device: sorted per-shard pair sets
        vn_old = snap["vn"]
        his, los = [], []
        for d in range(old_D):
            n = int(vn_old[d])
            his.append(np.asarray(snap["vhi"])[d, :n])
            los.append(np.asarray(snap["vlo"])[d, :n])
        all_hi = np.concatenate(his) if his else np.empty(0, np.uint32)
        all_lo = np.concatenate(los) if los else np.empty(0, np.uint32)
        downer = all_lo.astype(np.int64) % D
        counts = np.bincount(downer, minlength=D)
        vcap = _next_pow2(max(1024, 2 * int(counts.max() if len(counts) else 1)))
        vhi = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vlo = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vn = np.zeros((D,), np.int32)
        for d in range(D):
            sel = np.nonzero(downer == d)[0]
            order = np.lexsort((all_lo[sel], all_hi[sel]))
            vhi[d, : len(sel)] = all_hi[sel][order]
            vlo[d, : len(sel)] = all_lo[sel][order]
            vn[d] = len(sel)

    return pending, host_sets, vhi, vlo, vn, vcap, shard_visited


def check_sharded(
    model: Model,
    mesh: Optional[Mesh] = None,
    max_depth: Optional[int] = None,
    max_states: Optional[int] = None,
    min_bucket: int = 256,
    progress=None,
    check_deadlock: bool = False,
    chunk_size: int = 16384,
    store_trace: bool = True,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    checkpoint_keep: int = 3,
    stats_path: Optional[str] = None,
    compact_shift: int = 2,
    compact_gate: int = 1024,
    exchange: str = "all_to_all",
    visited_backend: str = "device",
    mem_budget=None,
    spill_dir: Optional[str] = None,
    store: str = "auto",
    disk_budget=None,
    run=None,
    shard_heartbeat_dir: Optional[str] = None,
    overlap: Optional[bool] = None,
    pipeline: Optional[str] = None,
) -> CheckResult:
    """Exhaustive sharded BFS over `mesh` (default: 1-D mesh of all devices).

    Semantics match engine.check (same models, same counts).  With
    store_trace (default), per-level (states, parent, action) records are
    kept on the host in shard-major discovery order, and a violation is
    reported with the full parent-pointer counterexample path; disable for
    pure-throughput runs at pod scale.

    checkpoint_dir: level-synchronous checkpoint/resume — persists the
    per-shard pending frontiers and fingerprint shards every
    `checkpoint_every` levels (default 1 = per level; a crash loses at most
    checkpoint_every-1 levels); a run restarts from the last saved level.
    A checkpoint binds to (model, constants, invariant selection, deadlock
    flag) — NOT to the mesh layout: the writing layout is stamped
    (mesh_D/mesh_P) and resuming on a different shard or process count
    takes the ELASTIC path, re-bucketing fingerprint-range ownership onto
    the new mesh (docs/resilience.md § Distributed resilience).  With
    store_trace requested, each level's (rows, parent, action) slices are
    also published to per-shard on-disk parent logs under
    `<checkpoint_dir>/plog/`, so a violation found AFTER a resume still
    reports the full counterexample trace (the in-RAM trace store remains
    off for checkpointed runs).  Checkpoints are hardened as in
    engine.check (resilience.checkpoints): per-array checksums,
    keep-last-`checkpoint_keep` rotation with atomic promote, automatic
    fallback to the newest verifying generation, and — for the per-host
    FpSet part files — a cross-shard consistency check: a generation
    whose parts disagree with the main file's level (or mesh layout) is
    treated as torn and skipped.  Fault injection (`KSPEC_FAULT`,
    including shard-targeted `crash@shard<d>:level:N` scoping) and
    transient-error retry mirror engine.check, with the injection point
    at the exchange step.

    shard_heartbeat_dir (or $KSPEC_SHARD_HEARTBEAT_DIR, or `<run
    dir>/shards` when a run context is given): every process appends one
    heartbeat line per BFS level to `proc<i>.jsonl` there — the fleet
    supervisor's per-shard liveness signal and `cli report`'s
    died-mid-level shard attribution.

    compact_shift: two-phase expansion (see engine.check) — guards sweep the
    full lattice, update/pack/sort/exchange run at 1/2^shift of it.  0
    disables.  compact_gate: the bucket size below which chunks run the
    full (uncompacted) lattice — this engine's historical 1024; exposed
    (like engine.check's compact_gate) so tests can force small gated
    chunks through the compacted and device-resident paths.  exchange: "all_to_all" (bucket-by-owner routing, per-shard
    ICI traffic independent of mesh size) or "all_gather" (every shard sees
    every candidate — D× the bytes, simple fallback).  Both are exact; any
    buffer overflow is detected on device and the chunk re-runs wider.

    visited_backend: "device" keeps each shard's sorted fingerprint set in
    its own HBM (lexsort + probe + O(vcap) rank-merge per chunk);
    "device-hash" keeps each shard's set as an HBM open-addressing hash
    table instead (ops/hashset — O(batch) insert-or-find, no merge; the
    recommended device-resident backend); "host" gives each shard its own
    native C++ open-addressing FpSet on the host (keyed by owner —
    ownership routing guarantees a fingerprint always lands in the same
    shard's set), so the distributed engine can check state spaces whose
    fingerprints outgrow HBM — the TLC-FPSet spill mode of engine.check,
    now at pod scale.  Device memory then holds only O(chunk × fanout)
    transient data per shard.

    Out-of-core storage (storage/): `store` = "auto" | "ram" | "disk" and
    `mem_budget` activate the disk tier for the host backend — each
    shard's FpSet becomes a budget-bounded TieredFpSet spilling sorted,
    bloom-gated fingerprint runs under `spill_dir`/shard<d> (fingerprint-
    range ownership is unchanged: a fingerprint's owner shard, hence its
    run directory, never moves).  Bit-identical counts vs the in-RAM host
    path; checkpoints record each shard's run manifest + (budget-bounded)
    hot dump instead of the full fingerprint sets.  The frontier and
    traces stay in RAM in this engine (the single-device engine carries
    the disk frontier + parent log).

    run: an obs.RunContext (docs/observability.md) — per-level stats gain
    per-shard frontier/new/duplicate breakdowns and an exchange-imbalance
    gauge; spans/metrics/manifest land in the run directory.  In a
    multi-process job only the coordinator observes (the replicated host
    loops would otherwise write D copies of every artifact).

    overlap: async level-pipelined execution ($KSPEC_OVERLAP, default
    on; ``off`` = the historical serial behavior, the bit-identity
    oracle).  In this engine it enables (1) the COMPRESSED all_to_all —
    per-destination buckets stably sorted by fingerprint, fingerprint
    lanes bit-packed/delta-encoded (ops/fpcompress), rows/parents at a
    compacted half-width, action ids as u8, with the post-exchange
    framing digest computed over the DECODED payload (>=2x fewer
    exchange bytes, fabric integrity unweakened; defaults on only where
    a real fabric carries the collective — on the virtual CPU mesh the
    codec is pure compute overhead — and KSPEC_EXCHANGE_COMPRESS=1/0
    forces either way); (2) staged chunk commit on the
    host backend — chunk k+1's program (expand + exchange) is dispatched
    before chunk k's host commit runs, so per-shard FpSet inserts hide
    behind the in-flight exchange and vice versa; (3) background
    spill-run merges per shard and (4) async checkpoint writes, exactly
    as in engine.check.  Bit-identical results across the knob: counts,
    traces, digest chains (tests/test_overlap.py).

    disk_budget: spill + checkpoint directory byte budget
    (resilience.resources) — soft breach reclaims (tmp janitor, eager
    per-shard merges, checkpoint-generation prune, deletion-barrier
    flush), hard breach (or a real/injected ENOSPC from any storage
    writer, incl. the `enospc@...` / `stall@level:N` faults with
    `shard<d>:` scopes) performs checkpoint-then-clean-exit with a typed
    ResourceExhausted (CLI exit code 75).  In a multi-process fleet the
    breaching process exits typed, its peers wedge in the next
    collective, and the fleet supervisor classifies the rc-75 exit as a
    resource verdict instead of restarting into the same full disk.

    pipeline: level-pipeline selection (--pipeline / $KSPEC_PIPELINE;
    `cli pipelines --list` shows the per-ENGINE support matrix).  In
    this engine "device" selects the SHARDED DEVICE-RESIDENT LEVEL
    path: with visited_backend="device" and analyzer-proven per-field
    value hulls (engine.pipeline.device_hull_fallback — the same HARD
    precondition as the single-device device pipeline), each shard runs
    an entire level's worth of gated chunks inside ONE dispatched
    ``lax.while_loop`` program (expansion, the per-chunk collective
    exchange + compression codec, dual-probe dedup against the
    read-only visited shard + a per-shard level-new sorted set, in-jit
    digest folds), so a level costs O(1) collective-bearing launches
    per shard instead of O(chunks), with the O(capacity) visited merge
    paid once per level per shard — bit-identical to the per-chunk
    path (counts, duplicate accounting, first-violation rule, trace
    values, digest chains).  Unmet preconditions, sub-gate tail chunks
    and compile/dispatch failures degrade to the per-chunk sharded
    ladder (sticky, `pipeline-fallback` event, stats["device"]);
    "legacy" (and "fused", which has no sharded variant) run the
    per-chunk path — the bit-identity oracle.  Unknown names are
    rejected loudly (pipeline_registry.resolve_pipeline).
    """
    # encoding-soundness gate (analysis; KSPEC_ANALYZE=0 disables) —
    # same refusal contract as engine.check, memoized per model name
    from ..analysis import require_encoding_sound

    require_encoding_sound(model)
    if mesh is None:
        mesh = Mesh(np.array(jax.devices()), ("d",))
    D = mesh.devices.size
    # per-process shard heartbeat stream (the fleet supervisor's per-shard
    # liveness signal and `cli report`'s died-mid-level attribution): every
    # process — not just the obs coordinator — appends one line per level
    # to <dir>/proc<i>.jsonl
    hb_dir = shard_heartbeat_dir or os.environ.get("KSPEC_SHARD_HEARTBEAT_DIR")
    if hb_dir is None and run is not None:
        hb_dir = os.path.join(run.dir, "shards")
    if run is not None and not is_coordinator():
        run = None
    obs_ = RunObserver(run, stats_path, engine="sharded")
    spec = model.spec
    expander = _Step(model)  # width bookkeeping only; steps build their own
    C = expander.C
    K = spec.num_lanes

    inits = [
        {k: np.asarray(v, np.int32) for k, v in s.items()} for s in model.init_states()
    ]
    init_packed = np.unique(
        np.stack([np.asarray(spec.pack(s)) for s in inits]), axis=0
    )
    n0 = init_packed.shape[0]

    t0 = time.perf_counter()
    # invariants on the init states (semantics must match engine.check)
    if model.invariants:
        st0 = jax.vmap(spec.unpack)(jnp.asarray(init_packed))
        for inv in model.invariants:
            ok = np.asarray(jax.vmap(inv.pred)(st0))
            if not ok.all():
                idx = int(np.argmax(~ok))
                st = {
                    k: np.asarray(v)
                    for k, v in spec.unpack(jnp.asarray(init_packed[idx])).items()
                }
                dec = model.decode(st) if model.decode else st
                res = CheckResult(
                    model.name,
                    [n0],
                    n0,
                    0,
                    Violation(
                        invariant=inv.name,
                        depth=0,
                        state=dec,
                        trace=[("<init>", dec)],
                    ),
                    time.perf_counter() - t0,
                    0.0,
                    stats={"devices": D},
                )
                obs_.finish(res)
                obs_.close()
                return res
    from ..storage import resolve_store

    use_disk = resolve_store(store, mem_budget)
    if use_disk:
        # the disk tier spills the HOST level of the hierarchy
        visited_backend = "host"
    if visited_backend not in ("device", "device-hash", "host"):
        raise ValueError(
            f"visited_backend must be 'device', 'device-hash' or 'host', "
            f"got {visited_backend!r}"
        )
    obs_.config(
        model=model.name,
        devices=D,
        exchange=exchange,
        visited_backend=visited_backend,
        store="disk" if use_disk else "ram",
        mem_budget=mem_budget,
        checkpoint_dir=checkpoint_dir,
        platform=jax.default_backend(),
    )
    host_sets = None
    spill_base = None
    ephemeral_spill = None

    def _u64(hi, lo):
        return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)

    # distribute inits to owner shards; per-shard sorted visited arrays
    hi0, lo0 = fingerprint_lanes(jnp.asarray(init_packed), spec.exact64)
    hi0, lo0 = np.asarray(hi0), np.asarray(lo0)
    owner0 = lo0 % D
    # which process hosts each shard's device (per-host FpSet ownership)
    shard_proc = [int(dev.process_index) for dev in mesh.devices.flat]
    my_proc = jax.process_index()
    my_shards = [d for d in range(D) if shard_proc[d] == my_proc]
    if visited_backend == "host":
        from ..native import FpSet

        # one FpSet per shard, living ONLY on the process that hosts the
        # shard's device: ownership routing sends a fingerprint to the same
        # shard every time, so per-shard sets never need cross-talk, and
        # per-host ownership divides set memory and insert work by the
        # process count (novelty masks are OR-merged across processes to
        # keep the replicated host loop in lockstep)
        if use_disk:
            from ..storage import (
                DEFAULT_MEM_BUDGET,
                TieredFpSet,
                parse_mem_budget,
            )

            budget = (
                parse_mem_budget(mem_budget)
                if mem_budget is not None
                else DEFAULT_MEM_BUDGET
            )
            spill_base = spill_dir or (
                os.path.join(checkpoint_dir, "spill") if checkpoint_dir else None
            )
            if spill_base is None:
                import tempfile

                # anonymous spill space: removed after a completed run
                spill_base = tempfile.mkdtemp(prefix="kspec-spill-")
                ephemeral_spill = spill_base
            # per-shard run directories; the byte budget divides across
            # the shards THIS PROCESS hosts (mem_budget is per-process
            # residency, matching engine.check — a multi-host job gets
            # budget bytes per host, not budget/P).  Init fingerprints
            # are inserted at the fresh/resume decision below (a resume
            # must not pre-wipe the runs its manifest references).
            n_local = max(1, sum(1 for p in shard_proc if p == my_proc))
            host_sets = [
                TieredFpSet(
                    os.path.join(spill_base, f"shard{d}"),
                    max(1, budget // n_local),
                    runs_per_merge=int(
                        os.environ.get("KSPEC_SPILL_RUNS_PER_MERGE", "8")
                    ),
                    gc_barrier=checkpoint_keep if checkpoint_dir else 0,
                )
                if shard_proc[d] == my_proc
                else None
                for d in range(D)
            ]
        else:
            host_sets = [
                FpSet() if shard_proc[d] == my_proc else None for d in range(D)
            ]
            for d in range(D):
                sel = np.nonzero(owner0 == d)[0]
                if len(sel) and host_sets[d] is not None:
                    host_sets[d].insert(_u64(hi0[sel], lo0[sel]))
        vcap = 64  # device placeholders; the device never holds the set
        vhi = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vlo = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vn = np.zeros((D,), np.int32)
    elif visited_backend == "device-hash":
        # per-shard HBM open-addressing tables (ops/hashset), carried in
        # the vhi/vlo slots; vn is unused (the tables track membership)
        per_shard = [
            (hi0[owner0 == d], lo0[owner0 == d]) for d in range(D)
        ]
        vhi, vlo, vcap = _shard_tables_from_pairs(per_shard, _HASH_MIN_CAP)
        vn = np.zeros((D,), np.int32)
    else:
        vcap = _next_pow2(max(1024, 4 * n0))
        vhi = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vlo = np.full((D, vcap), 0xFFFFFFFF, np.uint32)
        vn = np.zeros((D,), np.int32)
        for d in range(D):
            sel = np.nonzero(owner0 == d)[0]
            order = np.lexsort((lo0[sel], hi0[sel]))
            vhi[d, : len(sel)] = hi0[sel][order]
            vlo[d, : len(sel)] = lo0[sel][order]
            vn[d] = len(sel)

    # per-shard pending frontiers live on the host; each level streams them
    # through the compiled step in fixed-size chunks (same scheme as
    # engine.check: cross-chunk dedup rides the per-shard visited sets, so
    # the compiled-shape count and device memory stay bounded at pod scale)
    pending = [init_packed[owner0 == d] for d in range(D)]
    chunk = _next_pow2(max(32, chunk_size))
    # per-shard distinct-state counts (device-hash growth policy + stats)
    shard_visited = np.bincount(owner0, minlength=D).astype(np.int64)

    if exchange not in ("all_to_all", "all_gather"):
        raise ValueError(f"unknown exchange {exchange!r}")
    levels = [n0]
    total = n0
    depth = 0
    violation = None
    result_levels: list = []  # per-level stats records (mirrors engine.check)
    steps = {}
    w_extra = 0  # extra doublings of the all_to_all per-destination width
    exch_bytes_total = 0  # exchange wire bytes actually moved (all_to_all)
    exch_raw_bytes_total = 0  # ... and the raw-layout bytes at same widths
    overlap_staged_peak = 0  # most chunks ever staged at once (<= 2)

    def _io_counters():
        return worker_counters((io_worker, ckpt_worker))

    # Adaptive per-action compact sizing (round-5 port of the single-device
    # engine's policy — one shared implementation, engine.bfs.AdaptiveCompact).
    # All inputs derive from fetch_global'd arrays and host-known shard
    # sizes, so every process computes identical widths (replicated-
    # deterministic — the shard_map operands stay in lockstep).  The
    # sharded bucket gate stays at this engine's historical 1024.
    adapt = AdaptiveCompact(model.actions, compact_shift,
                            bucket_gate=compact_gate)
    adaptive_fallback = False

    # level-pipeline selection (pipeline_registry: loud rejection of
    # typos — the sharded engine no longer silently ignores --pipeline).
    # "device" arms the sharded device-resident level path below; every
    # other registered name runs the per-chunk step (the registry's
    # per-engine matrix documents which combinations degrade and why)
    pipe_name = resolve_pipeline(pipeline)
    sdev = (
        ShardedDeviceLevel(
            model, mesh, expander, adapt, visited_backend, check_deadlock
        )
        if pipe_name == "device"
        else None
    )

    def _shard_density(act_guard_np, took):
        """Per-state guard density for the policy: max over shards of
        guard_counts / shard_rows."""
        dens = act_guard_np.astype(np.float64) / np.maximum(
            took.astype(np.float64), 1.0
        )[:, None]
        return dens.max(axis=0)

    fault = FaultPlan.from_env()
    # shard-targeted faults (crash@shard<d>:..., docs/resilience.md) fire
    # only on the process hosting the named shard's device — in a fleet,
    # exactly one process dies and its peers wedge in the next collective,
    # which is the failure the fleet supervisor exists to catch
    fault.set_local_shards(my_shards)
    fault.validate_shards(D)
    # async overlap layer (overlap.py; $KSPEC_OVERLAP, default on) — the
    # same knob as engine.check: background per-shard merges + async
    # checkpoint writes ride worker threads, the staged chunk commit and
    # the compressed exchange ride the step itself.  The resolution is
    # env-replicated, so every process takes the same path (lockstep).
    from ..overlap import (
        AsyncWorker,
        close_workers,
        overlap_enabled,
        worker_counters,
    )

    overlap_on = overlap_enabled(overlap)
    # Compressed exchange default: ON where a real fabric carries the
    # all_to_all (the bytes are the scarce resource compression buys
    # back), OFF on the virtual CPU mesh (no wire — the codec's encode/
    # decode compute is pure overhead there; BENCH_r10 measures the
    # trade both ways).  KSPEC_EXCHANGE_COMPRESS=1/0 forces either.
    _comp_env = os.environ.get("KSPEC_EXCHANGE_COMPRESS", "")
    compress_on = (
        overlap_on
        and exchange == "all_to_all"
        and len(model.actions) < 255  # act ids ride the wire as u8
        and (
            _comp_env == "1"
            or (_comp_env != "0" and jax.default_backend() != "cpu")
        )
    )
    io_worker = AsyncWorker("kspec-io") if overlap_on else None
    ckpt_worker = (
        AsyncWorker("kspec-ckpt")
        if overlap_on and checkpoint_dir is not None
        else None
    )

    def _shutdown_async(drain: bool) -> None:
        close_workers((io_worker, ckpt_worker), drain)
    # state-integrity defense (resilience.integrity): the same always-on
    # level digest chain as the single-device engine — the digest is over
    # the new-state fingerprint MULTISET, which is shard-layout-invariant,
    # so chains are comparable across engines and survive elastic resumes
    # unchanged — plus the exchange framing check below
    chain = _integ.LevelDigestChain() if _integ.enabled() else None
    hb_path = None
    if hb_dir:
        os.makedirs(hb_dir, exist_ok=True)
        hb_path = os.path.join(hb_dir, f"proc{my_proc}.jsonl")

    def _shard_beat(done_depth: int, **extra) -> None:
        if hb_path is None:
            return
        append_jsonl(
            hb_path,
            heartbeat_record(
                "shard-heartbeat",
                proc=int(my_proc),
                pid=os.getpid(),
                shards=my_shards,
                depth=int(done_depth),
                **extra,
            ),
        )

    if use_disk:
        # the plan is parsed after the per-shard sets are built — hand it
        # to them now (mid-merge crash injection, crash@merge:N), along
        # with the background-merge worker (KSPEC_OVERLAP)
        for s in host_sets:
            if s is not None:
                s.fault_plan = fault
                s.merge_worker = io_worker
    chunk_retry = ChunkRetryHandler.from_env("[sharded]")
    ckpt_store = None
    # newest durably checkpointed level (None = not checkpointing):
    # level-crash faults defer until the target level is checkpointed so
    # a supervised restart converges (FaultPlan.crash)
    last_ckpt_depth = None
    resumed = False
    elastic_resumed = False
    plog = None  # per-shard on-disk parent log (checkpointed runs only)
    inv_names = ",".join(sorted(i.name for i in model.invariants))
    # NB: the mesh layout (D, P) is deliberately NOT part of the identity:
    # a checkpoint binds to the *search* (model, constants, invariants,
    # backend), and resuming it on a different shard/process count is the
    # elastic-resume path below, not a config mismatch.  The layout that
    # wrote a generation is stamped as mesh_D/mesh_P arrays instead.
    _fields_ident = ",".join(
        f"{f.name}:{f.shape}:{f.lo}:{f.hi}" for f in spec.fields
    ) + ("|store=disk" if use_disk else "")
    ckpt_ident = (
        f"{model.name}|lanes={spec.num_lanes}|"
        f"backend={visited_backend}|"
        f"inv={inv_names}|dl={check_deadlock}|" + _fields_ident
    )
    # the pre-elastic ident baked the layout in; accepting it (for THIS
    # mesh exactly) keeps checkpoints written by older code resumable
    # after an upgrade — a legacy checkpoint from a different layout
    # still refuses (it carries no mesh stamps to re-bucket from)
    ckpt_ident_legacy = (
        f"{model.name}|lanes={spec.num_lanes}|D={D}|"
        f"P={jax.process_count()}|backend={visited_backend}|"
        f"inv={inv_names}|dl={check_deadlock}|" + _fields_ident
    )
    if checkpoint_dir is not None:
        want_trace = store_trace
        store_trace = False
        last_ckpt_depth = 0
        checkpoint_every = max(1, int(checkpoint_every))
        def _spill_ref_errors(arrays: dict) -> list:
            """Disk-tier load validator: CRC-verify every per-shard spill
            run a generation references (flip@spill recovery: fall back
            to a generation predating the corrupt file — its
            deterministic re-exploration rewrites it)."""
            if not use_disk or "spill_manifest" not in arrays:
                return []
            errs = []
            for d, man in enumerate(json.loads(str(arrays["spill_manifest"]))):
                errs += _integ.spill_run_errors(
                    os.path.join(spill_base, f"shard{d}"),
                    (man or {}).get("runs", ()),
                )
            return errs

        ckpt_store = CheckpointStore(
            checkpoint_dir,
            "sharded_checkpoint.npz",
            ident=ckpt_ident,
            keep=checkpoint_keep,
            fault_plan=fault,
            ident_aliases=(ckpt_ident_legacy,),
            # CRC-consistent content corruption falls back exactly like a
            # checksum failure: resume from the newest CHAIN-VERIFIED
            # generation (resilience.integrity)
            validators=(
                (_integ.checkpoint_chain_errors, _spill_ref_errors)
                if chain is not None
                else (_spill_ref_errors,)
            ),
        )
        if ckpt_worker is not None:
            ckpt_store.attach_writer(ckpt_worker)
        if want_trace:
            # per-shard on-disk parent logs: counterexample traces that
            # survive checkpoint resume (the sharded twin of the single-
            # device engine's disk-tier parent log — docs/resilience.md)
            plog = ShardedParentLog(
                os.path.join(checkpoint_dir, "plog"),
                K,
                D,
                local_shards=my_shards,
                epoch_writer=is_coordinator(),
                fault_plan=fault,
            )

        def _parts_for(main):
            # per-host FpSet part files, derived from the layout recorded
            # in the MAIN file: a same-layout resume needs only this
            # process's part (cross-shard consistency is still enforced),
            # an elastic resume needs every old host's part to re-bucket.
            # A stamp-less main is a pre-elastic legacy checkpoint, which
            # can only have passed the ident check via the same-layout
            # alias — so its layout IS the current one
            old_P_ = (
                int(main["mesh_P"])
                if "mesh_P" in main
                else jax.process_count()
            )
            old_D_ = int(main["mesh_D"]) if "mesh_D" in main else D
            if visited_backend != "host" or old_P_ <= 1:
                return ()
            if old_D_ == D and old_P_ == jax.process_count():
                return (f"host{my_proc}",)
            return tuple(f"host{p}" for p in range(old_P_))

        loaded = ckpt_store.load(parts=_parts_for)
        if loaded is not None:
            resumed = True
            snap, part_arrays, _gen = loaded
            if chain is not None:
                # restore the digest chain (layout-invariant: an elastic
                # resume re-buckets rows, never the level multisets);
                # pre-integrity checkpoints rebuild unanchored from counts
                chain = (
                    _integ.LevelDigestChain.from_array(snap["digest_chain"])
                    if "digest_chain" in snap
                    else _integ.LevelDigestChain.from_levels(
                        snap["levels"].tolist()
                    )
                )
            # stamp-less legacy snapshots passed the ident check via the
            # same-layout alias, so their layout is by construction the
            # current one (never spuriously elastic)
            old_D = int(snap["mesh_D"]) if "mesh_D" in snap else D
            old_P = (
                int(snap["mesh_P"])
                if "mesh_P" in snap
                else jax.process_count()
            )
            elastic_resumed = old_D != D or old_P != jax.process_count()
            plens = snap["pending_lens"]
            flat = snap["pending"]
            pending, at = [], 0
            for ln in plens:
                pending.append(flat[at : at + int(ln)])
                at += int(ln)
            levels = snap["levels"].tolist()
            total = int(snap["total"])
            depth = int(snap["depth"])
            last_ckpt_depth = depth
            # crash faults at or below the resume level count as fired
            fault.set_start_depth(depth)
            if elastic_resumed:
                (
                    pending,
                    host_sets,
                    new_vhi,
                    new_vlo,
                    new_vn,
                    vcap,
                    shard_visited,
                ) = _elastic_reshard(
                    snap,
                    part_arrays,
                    old_D,
                    old_P,
                    pending,
                    D=D,
                    spec=spec,
                    visited_backend=visited_backend,
                    use_disk=use_disk,
                    host_sets=host_sets,
                    shard_proc=shard_proc,
                    my_proc=my_proc,
                    spill_base=spill_base,
                    vcap=vcap,
                    shard_visited=shard_visited,
                )
                if new_vhi is not None:
                    # device-resident backends got rebuilt shard arrays;
                    # host backends keep their placeholder device views
                    vhi, vlo, vn = new_vhi, new_vlo, new_vn
                if plog is not None and is_multiprocess():
                    # the boundary-level rewrite atomically replaces
                    # segments other processes may concurrently be
                    # reading to build their own permutation (shard dirs
                    # overlap between layouts) — without a barrier the
                    # rewrite is racy, so a MULTI-process elastic resume
                    # stays trace-less; single-process elastic (and all
                    # same-layout resumes) keep full traces
                    plog = None
                if plog is not None and not plog.reshard(depth, pending):
                    plog = None  # old segments unreadable: trace-less
                from ..obs import tracer as _obs_t

                _obs_t.event(
                    "elastic-reshard",
                    depth=depth,
                    from_shards=old_D,
                    to_shards=D,
                    from_procs=old_P,
                    to_procs=jax.process_count(),
                )
                _met.inc("kspec_elastic_reshards_total")
            elif host_sets is not None and use_disk:
                # per-shard tiered sets: restore IN PLACE from the
                # checkpointed run manifests + hot dumps (the runs stay on
                # disk; the checkpoint only references them)
                src = (
                    part_arrays[f"host{my_proc}"]
                    if is_multiprocess()
                    else snap
                )
                mans = json.loads(str(src["spill_manifest"]))
                hot_flat, lens = src["host_hot"], src["host_hot_lens"]
                at = 0
                for d, ln in enumerate(lens):
                    ln = int(ln)
                    if host_sets[d] is not None:
                        host_sets[d].restore(mans[d], hot_flat[at : at + ln])
                    at += ln
            elif host_sets is not None:
                from ..native import FpSet

                if is_multiprocess():
                    part = part_arrays[f"host{my_proc}"]
                    fps_flat, lens = part["host_fps"], part["host_lens"]
                else:
                    fps_flat, lens = snap["host_fps"], snap["host_lens"]
                at = 0
                host_sets = []
                for d, ln in enumerate(lens):
                    if shard_proc[d] != my_proc:
                        host_sets.append(None)
                        at += int(ln)
                        continue
                    s = FpSet(initial_capacity=max(64, 2 * int(ln)))
                    s.insert(fps_flat[at : at + int(ln)])
                    at += int(ln)
                    host_sets.append(s)
            elif visited_backend == "device-hash":
                lens = snap["hash_lens"]
                flat_hi, flat_lo = snap["hash_hi"], snap["hash_lo"]
                shard_visited = lens.astype(np.int64)
                per_shard, at = [], 0
                for ln in lens:
                    ln = int(ln)
                    per_shard.append(
                        (flat_hi[at : at + ln], flat_lo[at : at + ln])
                    )
                    at += ln
                vhi, vlo, vcap = _shard_tables_from_pairs(
                    per_shard, _HASH_MIN_CAP
                )
            else:
                vcap = int(snap["vcap"])
                vn = snap["vn"]
                w = snap["vhi"].shape[1]
                pad = np.full((D, vcap - w), 0xFFFFFFFF, np.uint32)
                vhi = np.concatenate([snap["vhi"], pad], axis=1)
                vlo = np.concatenate([snap["vlo"], pad], axis=1)
            if plog is not None and not elastic_resumed and not plog.resume(
                depth
            ):
                plog = None  # no resolvable epochs: trace-less as before
        if is_multiprocess():
            # split-brain guard: each process verifies its own part files,
            # so per-host corruption could make hosts fall back to
            # DIFFERENT generations — resuming the replicated lockstep
            # loop at mismatched depths would desync the collectives.
            # All processes vote their resume level (0 = fresh start) and
            # must agree exactly.  (64Ki levels is far beyond any real
            # diameter; the vote is one cheap allgather.)
            vote = np.zeros(1 << 16, bool)
            vote[min(depth, vote.size - 1)] = True
            if or_across_processes(vote).sum() != 1:
                raise ValueError(
                    "checkpoint resume disagreement: processes verified "
                    "different checkpoint generations (per-host part "
                    "corruption?) — restore or delete "
                    f"{checkpoint_dir} and restart"
                )

    if use_disk and not resumed:
        # fresh out-of-core run: each owned shard claims its run
        # directory and seeds its init fingerprints
        for d in range(D):
            if host_sets[d] is not None:
                host_sets[d].start_fresh()
                sel = np.nonzero(owner0 == d)[0]
                if len(sel):
                    host_sets[d].insert(_u64(hi0[sel], lo0[sel]))

    if chain is not None and not resumed:
        chain.fold(_integ.pair_u64(hi0, lo0))
        chain.seal(0, n0)

    # explicit per-tensor mesh layouts (mesh_layouts; asserted in
    # tests/test_sharded_device.py): shard1 keeps its historical name as
    # the [D, cap] per-shard-table layout for the growth helpers
    layouts = mesh_layouts(mesh)
    shard1 = layouts["fpset"]
    dev_vhi = put_global(vhi, layouts["fpset"])
    dev_vlo = put_global(vlo, layouts["fpset"])
    dev_vn = put_global(vn, layouts["pershard"])

    # async-checkpoint bookkeeping (KSPEC_OVERLAP; mirrors engine.bfs):
    # `last_ckpt_depth` = submitted, `ckpt_durable_depth` = promoted.
    # Crash deferral / flip gating key on durability; completion
    # callbacks (deletion-barrier advance, chain read-back) run on THIS
    # thread in submission order as saves promote.
    ckpt_durable_depth = last_ckpt_depth
    ckpt_cbs: list = []

    def _ckpt_poll(block: bool = False) -> None:
        nonlocal ckpt_durable_depth
        if ckpt_worker is None or ckpt_store is None:
            return
        done = (
            ckpt_store.drain_async() if block else ckpt_store.poll_async()
        )
        for d, path in done:
            cb = ckpt_cbs.pop(0) if ckpt_cbs else None
            if cb is not None:
                cb(path)
            ckpt_durable_depth = (
                d if ckpt_durable_depth is None
                else max(ckpt_durable_depth, d)
            )

    def _store_save(arrays, part=None, on_done=None,
                    sync: bool = False) -> None:
        """One checkpoint-store write, sync or on the writer thread.
        `on_done(path)` runs after the atomic promote — on this thread
        at the next _ckpt_poll when async (barrier advances and chain
        read-backs stay on the engine thread / writer respectively)."""
        nonlocal ckpt_durable_depth
        if ckpt_worker is not None and not sync:
            ckpt_cbs.append(on_done)
            ckpt_store.save_async(depth, arrays, part=part)
            return
        path = ckpt_store.save(depth, arrays, part=part)
        if on_done is not None:
            on_done(path)
        ckpt_durable_depth = (
            depth if ckpt_durable_depth is None
            else max(ckpt_durable_depth, depth)
        )

    def _advance_spill_gc(marks=None):
        # a new durable generation exists: advance each owned tiered
        # set's deferred-deletion barrier (merged-away runs older than
        # every retained generation get unlinked).  `marks` (async
        # saves) restrict the advance to the files scheduled before the
        # save's snapshot — see storage.tiered.DeferredDeleter.mark
        if use_disk:
            for s in host_sets:
                if s is not None:
                    s.deleter.on_save(
                        upto=None if marks is None else marks.get(id(s))
                    )

    def _gc_marks():
        return (
            {
                id(s): s.deleter.mark()
                for s in host_sets
                if s is not None
            }
            if use_disk
            else None
        )

    def _levels_for_save():
        """The coordinator main's levels array, with the flip@ckpt
        CRC-consistent corruption injected BEFORE the manifest is built
        (resilience.integrity; the post-save read-back + the load-time
        chain validator are what must catch it)."""
        levels_arr = np.asarray(levels)
        # anchored-only, like every flip injection: an unanchored chain
        # cannot detect what it corrupts (engine.bfs._save_checkpoint)
        if chain is not None and chain.anchored and fault.flip(
            "ckpt", depth, ckpt_depth=ckpt_durable_depth
        ):
            levels_arr = levels_arr.copy()
            _integ.flip_bit(levels_arr)
        return levels_arr

    def _chain_stamp() -> dict:
        # never stamp an UNANCHORED chain (rebuilt from a pre-integrity
        # checkpoint: digests unknown) — see engine.bfs._chain_stamp
        return (
            {"digest_chain": chain.to_array()}
            if chain is not None and chain.anchored
            else {}
        )

    def _readback_chain(path: str, at_depth: int) -> None:
        if chain is not None and chain.anchored:
            _integ.readback_chain(path, depth=at_depth)

    def _save_checkpoint(sync: bool = False):
        if host_sets is not None and use_disk:
            # record run manifests + hot dumps — the runs ARE the durable
            # state; the checkpoint references them
            hots = [
                s.hot_dump() if s is not None else np.empty(0, np.uint64)
                for s in host_sets
            ]
            payload = {
                "host_hot": np.concatenate(hots),
                "host_hot_lens": np.asarray([len(x) for x in hots]),
                "spill_manifest": json.dumps(
                    [s.manifest() if s is not None else None for s in host_sets]
                ),
                # layout stamp: parts pair with mains by (depth, layout) —
                # after an elastic re-save a stale old-layout part can
                # share the depth (resilience.checkpoints._find_part)
                "mesh_D": D,
                "mesh_P": jax.process_count(),
            }
            marks = _gc_marks()
            if is_multiprocess():
                # non-coordinators: the part save is their only write —
                # the deletion barrier advances when IT promotes
                _store_save(
                    payload,
                    part=f"host{my_proc}",
                    on_done=(
                        None
                        if is_coordinator()
                        else lambda _p, m=marks: _advance_spill_gc(m)
                    ),
                    sync=sync,
                )
                extra = {}
            else:
                extra = payload
            if not is_coordinator():
                return
            main = dict(
                pending=np.concatenate(pending)
                if any(p.shape[0] for p in pending)
                else np.empty((0, K), np.uint32),
                pending_lens=np.asarray([p.shape[0] for p in pending]),
                vcap=vcap,
                levels=_levels_for_save(),
                total=total,
                **extra,
                **_chain_stamp(),
            )
            # single-process runs carry the payload (incl. its layout
            # stamp) inline; multi-process mains stamp their own
            main["mesh_D"] = D
            main["mesh_P"] = jax.process_count()

            def _main_done(path, m=marks, d=depth):
                _advance_spill_gc(m)
                _readback_chain(path, d)

            _store_save(main, on_done=_main_done, sync=sync)
            return
        if host_sets is not None:
            dumps = [
                s.dump() if s is not None else np.empty(0, np.uint64)
                for s in host_sets
            ]
            if is_multiprocess():
                # per-host ownership: each process persists its own shards
                # in a sidecar part file; a same-layout resume is symmetric
                # (the mesh_D/mesh_P stamps pair parts with mains), and an
                # elastic resume reads every old host's part to re-bucket.
                # The part carries the level it snapshots: a crash between
                # the part writes and the coordinator's main write would leave
                # parts one level ahead of (or behind) the main file, and
                # resuming such a torn pair would silently skip the
                # re-expanded frontier's subtrees — the depth cross-check
                # on load skips that generation (falling back to an older
                # consistent one) instead.
                _store_save(
                    dict(
                        host_fps=np.concatenate(dumps),
                        host_lens=np.asarray([len(x) for x in dumps]),
                        mesh_D=D,
                        mesh_P=jax.process_count(),
                    ),
                    part=f"host{my_proc}",
                    sync=sync,
                )
                extra = {}
            else:
                extra = {
                    "host_fps": np.concatenate(dumps)
                    if dumps
                    else np.empty(0, np.uint64),
                    "host_lens": np.asarray([len(x) for x in dumps]),
                }
        elif visited_backend == "device-hash":
            # dump each shard's live pairs (slot order is rebuilt on
            # resume by reinsertion)
            th = fetch_global(dev_vhi)
            tl = fetch_global(dev_vlo)
            live = ~((th == hashset.SENT) & (tl == hashset.SENT))
            extra = {
                "hash_hi": th[live],
                "hash_lo": tl[live],
                "hash_lens": live.sum(axis=1),
            }
        else:
            # trim the common sentinel tail (rebuilt on resume from vcap)
            vn_np = fetch_global(dev_vn)
            extra = {
                "vhi": fetch_global(dev_vhi)[:, : int(vn_np.max())],
                "vlo": fetch_global(dev_vlo)[:, : int(vn_np.max())],
                "vn": vn_np,
            }
        if chain is not None and chain.anchored:
            # flip@fpset injection + the save-time cumulative-digest
            # self-check (pre-write: detected corruption never enters a
            # checkpoint).  The full visited multiset is process-local
            # only outside the per-host-parts layout, so multiprocess
            # host runs skip (their per-host dumps are partial by design)
            pk = None
            if host_sets is not None and not is_multiprocess():
                pk = "host_fps"
            elif visited_backend == "device-hash":
                pk = "hash_hi"
            elif host_sets is None:
                pk = "vhi"
            if pk is not None and pk in extra:
                if fault.flip("fpset", depth, ckpt_depth=last_ckpt_depth):
                    corrupted = np.array(extra[pk], copy=True)
                    _integ.flip_bit(corrupted)
                    extra[pk] = corrupted
                if pk == "host_fps":
                    dump_fps = np.asarray(extra["host_fps"], np.uint64)
                elif pk == "hash_hi":
                    dump_fps = _integ.pair_u64(
                        extra["hash_hi"], extra["hash_lo"]
                    )
                else:
                    vhi_np = np.asarray(extra["vhi"])
                    vlo_np = np.asarray(extra["vlo"])
                    vns = np.asarray(extra["vn"]).ravel()
                    dump_fps = np.concatenate(
                        [
                            _integ.pair_u64(
                                vhi_np[d, : int(n)], vlo_np[d, : int(n)]
                            )
                            for d, n in enumerate(vns.tolist())
                        ]
                    ) if vns.size else np.empty(0, np.uint64)
                _integ.count_check()
                chain.verify_visited(dump_fps, depth=depth)
        if not is_coordinator():
            return  # one writer per job; all processes hold identical state
        _store_save(
            dict(
                pending=np.concatenate(pending)
                if any(p.shape[0] for p in pending)
                else np.empty((0, K), np.uint32),
                pending_lens=np.asarray([p.shape[0] for p in pending]),
                vcap=vcap,
                levels=_levels_for_save(),
                total=total,
                mesh_D=D,
                mesh_P=jax.process_count(),
                **extra,
                **_chain_stamp(),
            ),
            on_done=lambda p, d=depth: _readback_chain(p, d),
            sync=sync,
        )

    # Resource governance (resilience.resources): disk budget over the
    # spill + checkpoint dirs, RSS/deadline watchdogs, injected stall —
    # per process (each host watches its own disk/RSS; in a fleet the
    # breaching process exits typed and the supervisor classifies it)
    governor = ResourceGovernor.from_env(
        disk_budget=disk_budget,
        watch_dirs=[spill_base, checkpoint_dir],
        fault_plan=fault,
    )

    def _final_save():
        # checkpoint-then-clean-exit: persist the just-completed level
        # even off the checkpoint_every cadence.  Synchronous + drained:
        # the typed exit's contract is a DURABLE on-disk state
        nonlocal last_ckpt_depth
        if ckpt_store is None:
            return
        _ckpt_poll(block=True)
        if last_ckpt_depth != depth or ckpt_durable_depth != depth:
            _save_checkpoint(sync=True)
            last_ckpt_depth = depth

    def _reclaim():
        # soft-breach reclamation (docs/resilience.md): tmp janitor ->
        # eager per-shard merges -> fresh checkpoint -> prune generations
        # (coordinator; parts of pruned gens go with them) -> flush each
        # owned shard's deletion barrier
        nonlocal last_ckpt_depth
        merged = False
        if use_disk:
            from ..storage.atomic import sweep_tmp

            for s in host_sets:
                if s is not None:
                    # quiesce the merge worker BEFORE the tmp sweep: a
                    # background merge's half-written tmp is live work,
                    # not a stray (PR 10 small fix; regression-tested)
                    s.quiesce()
                    sweep_tmp(s.dir)
                    if len(s.runs) > 1:
                        s.merge()
                        merged = True
        if ckpt_store is not None:
            _ckpt_poll(block=True)
            # save only when something changed since the periodic save at
            # this depth (same guard as engine.bfs._reclaim)
            if merged or last_ckpt_depth != depth or \
                    ckpt_durable_depth != depth:
                _save_checkpoint(sync=True)
                last_ckpt_depth = depth
            if is_coordinator():
                ckpt_store.prune(keep_gens=1)
            if use_disk:
                for s in host_sets:
                    if s is not None:
                        s.deleter.flush()

    if elastic_resumed:
        # persist one generation in the NEW layout immediately: a crash
        # before the next periodic save then resumes into this layout
        # without re-paying the re-bucketing read, and for the disk tier
        # the re-bucketed runs become durably referenced before any old
        # run can start aging out of the deletion barrier
        _save_checkpoint()

    def decode_row(row):
        st = {k: np.asarray(v) for k, v in spec.unpack(jnp.asarray(row)).items()}
        return model.decode(st) if model.decode else st

    # per level, shard-major discovery order: (rows, parent_global, act)
    trace_store = []
    if store_trace:
        init_rows = np.concatenate(pending) if n0 else np.empty((0, K), np.uint32)
        trace_store.append(
            (init_rows, np.full(n0, -1, np.int64), np.full(n0, -1, np.int64))
        )
    if plog is not None and not resumed:
        # level 0 = the init states, parentless, in shard-major order
        plog.start_fresh()
        plog.write_level(
            0,
            pending,
            [np.full(p.shape[0], -1, np.int64) for p in pending],
            [np.full(p.shape[0], -1, np.int64) for p in pending],
        )
    # parent/act bookkeeping is needed by EITHER trace consumer (the
    # in-RAM store or the on-disk per-shard parent logs)
    collect_trace = store_trace or plog is not None

    def build_violation(inv_name, d_level, idx):
        """Full trace when any source can resolve it, else None (the
        caller reports the violating state trace-less)."""
        if store_trace:
            return walk_trace(
                trace_store, model.actions, decode_row, inv_name, d_level, idx
            )
        if plog is not None and plog.has_levels(d_level):
            # per-shard on-disk parent logs: O(depth) single-row reads —
            # this is what makes sharded traces survive checkpoint resume
            return walk_trace(
                plog.view(), model.actions, decode_row, inv_name, d_level, idx
            )
        return None

    _shard_beat(depth, event="start", resumed=bool(resumed))
    cut = False
    exhausted: Optional[ResourceExhausted] = None
    integrity_fail: Optional[IntegrityError] = None
    from ..storage.parent_log import ParentLogCorrupt
    from ..storage.runs import RunCorrupt

    try:
        while any(p.shape[0] for p in pending):
            # async join point (every process joins identically — the
            # workers' job streams are replicated-deterministic): adopt
            # finished merges/checkpoint promotes, surface worker errors.
            # BLOCKING under an armed fault plan so deterministic
            # injection never depends on writer-thread timing
            _ckpt_poll(block=bool(fault.specs))
            if use_disk:
                for s in host_sets:
                    if s is not None:
                        if fault.specs:
                            s.quiesce()
                        s.poll_merge()
            lvl_io0 = _io_counters()
            # level-boundary fault injection point (resilience.faults); the
            # plan derives from the replicated env, so every process raises
            # (or not) in lockstep; crash deferral keys on the DURABLE
            # checkpoint depth (an in-flight async save must not arm a
            # crash whose restart would not converge)
            fault.crash("level", depth, ckpt_depth=ckpt_durable_depth)
            if chain is not None:
                sp = fault.flip(
                    "frontier", depth, ckpt_depth=ckpt_durable_depth
                )
                if sp:
                    # a shard scope targets THAT shard's pending buffer
                    # (falling back to the first non-empty one when the
                    # targeted shard happens to own no rows this level —
                    # an empty buffer has no bit to flip)
                    d0 = sp.shard if sp.shard is not None else 0
                    if pending[d0].size == 0:
                        d0 = next(
                            (d for d in range(D) if pending[d].size), d0
                        )
                    _integ.flip_bit(pending[d0])
                # frontier verify: the pending shards' combined multiset
                # must digest to the entry sealed at discovery (the
                # per-shard split is layout; the multiset is the search)
                parts = [
                    _integ.fingerprint_rows(p, spec.exact64)
                    for p in pending
                    if p.shape[0]
                ]
                _integ.count_check()
                chain.verify_level(
                    depth,
                    np.concatenate(parts)
                    if parts
                    else np.empty(0, np.uint64),
                )
            if max_depth is not None and depth >= max_depth:
                cut = True
                break
            if max_states is not None and total >= max_states:
                cut = True
                break
            t_level = time.perf_counter()
            obs_.level_begin(depth + 1, int(sum(p.shape[0] for p in pending)))
            governor.level_begin(depth + 1)  # arm the per-level deadline
            next_pending = [[] for _ in range(D)]
            next_parent = [[] for _ in range(D)]
            next_act = [[] for _ in range(D)]
            lvl_act_en = np.zeros(len(model.actions), np.int64)
            lvl_new_per_shard = np.zeros(D, np.int64)
            # per-shard breakdowns for the stats stream (exchange imbalance is
            # invisible in coordinator-aggregated totals): enabled candidates
            # per SOURCE shard, and — host backend, where the coordinator sees
            # the novelty masks — received candidates per OWNER shard
            lvl_en_per_shard = np.zeros(D, np.int64)
            lvl_recv_per_shard = np.zeros(D, np.int64)
            lvl_exch_bytes = lvl_exch_raw_bytes = 0
            # dispatched collective-bearing programs this level — one
            # launch PER SHARD each (the kspec_shard_launches_level
            # gauge and the device path's O(1)/level contract)
            lvl_dispatches = 0
            lvl_probe_ms = 0.0  # deferred batched host-probe wall
            offs = [0] * D
            # base offset of each shard's rows in this level's shard-major order
            prev_base = np.concatenate([[0], np.cumsum([p.shape[0] for p in pending])])
            verdict = None  # (inv_name, frontier_row_np, global_idx)

            def _build_chunk():
                """Assemble the next chunk's per-shard frontier slice, or
                None when the level is exhausted."""
                rem = max(p.shape[0] - o for p, o in zip(pending, offs))
                if rem <= 0:
                    return None
                governor.poll(depth)  # deadline watchdog (cheap)
                bucket = min(_next_pow2(max(rem, min_bucket // D, 32)), chunk)
                frontier = np.zeros((D, bucket, K), np.uint32)
                took = np.zeros(D, np.int32)
                chunk_off = np.asarray(offs, np.int64)
                for d in range(D):
                    rows = pending[d][offs[d] : offs[d] + bucket]
                    frontier[d, : rows.shape[0]] = rows
                    took[d] = rows.shape[0]
                    offs[d] += rows.shape[0]
                fvalid = np.arange(bucket)[None, :] < took[:, None]
                return [bucket, frontier, took, chunk_off, fvalid,
                        time.perf_counter()]

            def _attempt_once(ctx, attempt, w_try, compress=None):
                """Dispatch ONE attempt of a chunk (no flag fetches) with
                the shared failure policy applied around the dispatch.
                -> (outs, (attempt, w_try, ca, T, W, R)).  The overflow-
                retry ladder lives in _flags_retry/_resolve_chunk: a
                uniform-shift expansion overflow escalates to per-action
                adaptive widths seeded from the overflowing attempt's
                guard counts (or, with adaptation off, steps the shift
                toward the full path); a per-action overflow doubles the
                offending buffers (floored for the rest of the run);
                destination-bucket (or compressed-payload) overflow
                doubles the per-dest width.  A failed attempt's visited
                arrays are simply discarded (the step is functional), so
                results stay exact at every width.  Width retries are
                CHUNK-LOCAL (learned floors persist)."""
                nonlocal vcap, dev_vhi, dev_vlo, chunk, adaptive_fallback
                nonlocal lvl_dispatches
                if compress is None:
                    compress = compress_on
                bucket = ctx[0]
                while True:
                    if isinstance(attempt, int):
                        ca = _norm_shift(bucket, attempt) or None
                    else:
                        ca = attempt  # per-action width tuple, or None (full)
                    T = expander.expand_width(bucket, ca)
                    W = min(T, _default_dest_w(T, D) << w_try)
                    R = D * W if exchange == "all_to_all" else D * T
                    if visited_backend == "device-hash":
                        # keep every shard's table under ~1/2 load so linear
                        # probing stays short (shard_visited is host-tracked)
                        if 2 * int(shard_visited.max()) > vcap:
                            dev_vhi, dev_vlo, vcap = _grow_hash_tables(
                                dev_vhi, dev_vlo, 2 * vcap, shard1
                            )
                    if visited_backend == "device":
                        # grow per-shard visited capacity for the worst-case merge
                        # (one shared growth path with the device level driver)
                        need = int(fetch_global(dev_vn).max()) + R
                        if need > vcap:
                            dev_vhi, dev_vlo, vcap = _grow_sorted_shards(
                                dev_vhi, dev_vlo, vcap, _next_pow2(need),
                                layouts["fpset"],
                            )

                    key = (bucket, vcap, ca, exchange, W, compress)
                    try:
                        # exchange-step fault injection point (the jitted step
                        # below carries the all_to_all/all_gather exchange)
                        injected = fault.chunk_error(
                            escalated=isinstance(ca, (list, tuple))
                        )
                        if injected is not None:
                            raise injected
                        if key not in steps:
                            steps[key] = _make_sharded_step(
                                model,
                                mesh,
                                bucket,
                                vcap,
                                compact=ca,
                                exchange=exchange,
                                dest_w=W,
                                with_merge=visited_backend == "device",
                                hash_table=visited_backend == "device-hash",
                                compress=compress,
                            )
                        outs = steps[key](
                            put_global(
                                ctx[1].reshape(D * bucket, K),
                                layouts["frontier"],
                            ),
                            put_global(
                                ctx[4].reshape(D * bucket),
                                layouts["fvalid"],
                            ),
                            dev_vhi,
                            dev_vlo,
                            dev_vn,
                        )
                        lvl_dispatches += 1
                    except Exception as e:  # noqa: BLE001 — XLA compile/run
                        # one failure policy for both engines (resilience
                        # .retry.ChunkRetryHandler): transient -> bounded-
                        # backoff re-run of the same attempt (the functional
                        # step committed nothing); failed ESCALATED compile ->
                        # uniform fallback; else re-raise.  Transient retry is
                        # single-process only: a REAL transient error is
                        # per-host, and one host re-issuing the collective
                        # while its peers don't would desync the replicated
                        # lockstep loop — multi-process jobs surface it to the
                        # supervisor's restart-from-checkpoint layer instead.
                        action = chunk_retry.handle(
                            e,
                            escalated=isinstance(ca, (list, tuple)),
                            depth=depth,
                            retry_transient=not is_multiprocess(),
                        )
                        if action == "retry":
                            continue
                        if action == "degrade_chunk":
                            # device RESOURCE_EXHAUSTED: identical shapes would
                            # die identically — halve the streaming chunk for
                            # the rest of the run (single-process only: the
                            # handler re-raises under multiprocess, where a
                            # lone process shrinking would desync the fleet)
                            chunk = max(_next_pow2(max(32, min_bucket // D)),
                                        chunk >> 1)
                        steps.pop(key, None)
                        attempt = adapt.compile_fallback(bucket)
                        adaptive_fallback = True
                        continue
                    return outs, (attempt, w_try, ca, T, W, R, compress)

            def _flags_retry(ctx, outs, meta):
                """Fetch the attempt's overflow flags; -> None when it
                committed clean, else the (attempt, w_try) to re-run
                with (applying the escalation/widening/table-growth
                policy — see _attempt_once's docstring)."""
                nonlocal vcap, dev_vhi, dev_vlo
                attempt, w_try, ca, T, W, R, compress = meta
                ovf_expand, act_guard = outs[12], outs[13]
                ovf_dest, ovf_probe = outs[14], outs[15]
                if ca is not None:
                    ovf_np = fetch_global(ovf_expand)  # [D, n_actions]
                    if ovf_np.any():
                        return (
                            adapt.escalate(
                                attempt,
                                ovf_np.any(axis=0),
                                ctx[0],
                                _shard_density(
                                    fetch_global(act_guard), ctx[2]
                                ),
                            ),
                            w_try,
                            compress,
                        )
                if exchange == "all_to_all" and fetch_global(
                    ovf_dest
                ).any():
                    if W < T:
                        return (attempt, w_try + 1, compress)
                    if compress:
                        # the raw path CANNOT overflow at full width (every
                        # candidate fits W == T slots) — only the codec's
                        # packed-stream / compact-row budgets can.  The
                        # ladder is topped out, so this chunk falls back
                        # to the RAW exchange (results identical; only
                        # the wire layout changes)
                        return (attempt, w_try, False)
                if visited_backend == "device-hash" and bool(
                    fetch_global(ovf_probe).any()
                ):
                    # a shard exhausted its probe budget: grow every
                    # shard's table and re-run the chunk (the attempt's
                    # returned tables are discarded — the step is
                    # functional, so nothing was committed)
                    dev_vhi, dev_vlo, vcap = _grow_hash_tables(
                        dev_vhi, dev_vlo, 2 * vcap, shard1
                    )
                    return (attempt, w_try, compress)
                return None

            def _resolve_chunk(st):
                """Flag-check a dispatched chunk, re-running the ladder
                synchronously on any overflow, then install the committed
                attempt's visited arrays."""
                nonlocal dev_vhi, dev_vlo, dev_vn
                ctx, outs, meta = st
                while True:
                    nxt = _flags_retry(ctx, outs, meta)
                    if nxt is None:
                        break
                    outs, meta = _attempt_once(
                        ctx, nxt[0], nxt[1], compress=nxt[2]
                    )
                st[1], st[2] = outs, meta
                dev_vhi, dev_vlo, dev_vn = outs[4], outs[5], outs[6]

            def _commit_sharded(st):
                """Commit one resolved chunk: exchange framing check,
                verdict checks, output fetches and per-shard host-set
                inserts/trace/digest accumulation.  Commits run strictly
                in dispatch order; returns True when a verdict fired."""
                nonlocal verdict, lvl_act_en, lvl_new_per_shard
                nonlocal lvl_en_per_shard, lvl_recv_per_shard
                nonlocal shard_visited, lvl_exch_bytes, lvl_exch_raw_bytes
                ctx, outs, meta = st
                bucket, frontier, took, chunk_off, _fv, t_chunk = ctx
                _attempt, _wt, _ca, T, W, R, compress = meta
                (
                    out, out_parent, out_act, new_n, _vh, _vl, _vn,
                    viol_any, viol_idx, dl_any, dl_idx, act_en,
                    _ovfe, act_guard, _ovfd, _ovfp,
                    out_hi, out_lo, sent_dig, recv_dig,
                ) = outs
                # exchange framing check (resilience.integrity): across
                # the whole mesh, the received candidate multiset must
                # combine to exactly the sent one — XOR/sum digests are
                # commutative, so per-shard records compare globally.
                # flip@exchange drives the detector's observation (like
                # stall@level does the watchdog's): a real ICI bit flip
                # desyncs the same two in-jit digests.  With the
                # compressed exchange the received digest is computed
                # over the DECODED payload, so the codec + headers are
                # inside the protection boundary.
                if chain is not None:
                    sd = np.asarray(fetch_global(sent_dig), np.uint32)
                    rd = np.array(fetch_global(recv_dig), np.uint32)
                    sp = fault.flip(
                        "exchange", depth + 1, ckpt_depth=ckpt_durable_depth
                    )
                    if sp:
                        rd[sp.shard if sp.shard is not None else 0, 1] ^= 0x10
                    _integ.count_check()
                    if _combine_digs(sd) != _combine_digs(rd):
                        raise IntegrityError(
                            "exchange",
                            f"exchange payload framing mismatch at level "
                            f"{depth + 1}: sent digest {_combine_digs(sd)} "
                            f"!= received {_combine_digs(rd)} ({exchange}; "
                            f"a routed fingerprint was corrupted in "
                            f"flight)",
                            depth=depth,
                        )
                # adapt buffer sizing from the committed attempt's guard counts
                # (mirrors engine.check; no-op until escalation activates)
                adapt.observe(_shard_density(fetch_global(act_guard), took))
                # exchange wire accounting (ROADMAP item 5's measure):
                # bytes this chunk's all_to_all actually moved vs the raw
                # (uncompressed) layout's bytes at the same widths
                if exchange == "all_to_all":
                    raw_b = D * D * W * (8 + 4 * K + 4 + 4)
                    if compress:
                        from ..ops import fpcompress as _fpc

                        Wr = max(32, W // 2)
                        sent_b = D * D * (
                            4 * _fpc.default_stream_words(W)
                            + 4 * _fpc.header_words(W)
                            + Wr * (4 * K + 4 + 1)
                        )
                    else:
                        sent_b = raw_b
                    lvl_exch_bytes += sent_b
                    lvl_exch_raw_bytes += raw_b
                obs_.chunk_span(
                    "exchange",
                    time.perf_counter() - t_chunk,
                    depth=depth,
                    bucket=bucket,
                    exchange=exchange,
                    compressed=compress,
                )
                # frontier-level verdicts (states being expanded = level `depth`)
                viol_any_np = fetch_global(viol_any)  # [D, n_inv]
                if viol_any_np.any():
                    inv_i = int(np.argmax(viol_any_np.any(axis=0)))
                    d = int(np.argmax(viol_any_np[:, inv_i]))
                    idx = int(fetch_global(viol_idx)[d, inv_i])
                    gidx = int(prev_base[d] + chunk_off[d] + idx)
                    verdict = (model.invariants[inv_i].name, frontier[d, idx], gidx)
                    return True
                if check_deadlock and fetch_global(dl_any).any():
                    d = int(np.argmax(fetch_global(dl_any)))
                    idx = int(fetch_global(dl_idx)[d])
                    gidx = int(prev_base[d] + chunk_off[d] + idx)
                    verdict = ("Deadlock", frontier[d, idx], gidx)
                    return True
                counts = fetch_global(new_n)
                # received candidates per OWNER shard (post-exchange, pre-host-
                # dedup on the host backend; == novel on device backends)
                lvl_recv_per_shard += counts.astype(np.int64)
                M_per = out.shape[0] // D
                # device-side slice to the widest shard before the host copy —
                # the padded buffer is mostly empty
                cmax = int(counts.max())
                out3 = fetch_global(out.reshape(D, M_per, K)[:, :cmax])
                if collect_trace:
                    parent_np = fetch_global(out_parent.reshape(D, M_per)[:, :cmax])
                    act_np = fetch_global(out_act.reshape(D, M_per)[:, :cmax])
                if host_sets is not None and cmax:
                    hi3 = fetch_global(out_hi.reshape(D, M_per)[:, :cmax])
                    lo3 = fetch_global(out_lo.reshape(D, M_per)[:, :cmax])
                    # global dedup: each shard's OWNER process inserts into its
                    # FpSet (batch dedup already happened on device; insert()
                    # returns the first-time mask); the masks are OR-merged so
                    # every process sees the identical novelty decision
                    masks = np.zeros((D, cmax), bool)
                    for d in range(D):
                        c = int(counts[d])
                        if c and host_sets[d] is not None:
                            masks[d, :c] = host_sets[d].insert(
                                _u64(hi3[d, :c], lo3[d, :c])
                            ).astype(bool)
                    masks = or_across_processes(masks)
                newc = np.zeros(D, np.int64)
                for d in range(D):
                    c = int(counts[d])
                    if not c:
                        continue
                    rows = out3[d, :c]
                    p = parent_np[d, :c].astype(np.int64) if collect_trace else None
                    a = act_np[d, :c].astype(np.int64) if collect_trace else None
                    if host_sets is not None:
                        mask = masks[d, :c]
                        rows = rows[mask]
                        if collect_trace:
                            p, a = p[mask], a[mask]
                        c = rows.shape[0]
                        if not c:
                            continue
                    next_pending[d].append(rows)
                    if chain is not None:
                        # fold this shard's new states into the level
                        # digest via the numpy fingerprint twin (rows are
                        # what the host actually keeps — digesting them,
                        # then checking the chain against the device
                        # fingerprints at save time, cross-checks the
                        # two representations for free)
                        chain.fold(
                            _integ.fingerprint_rows(rows, spec.exact64)
                        )
                    if collect_trace:
                        # step parents are d_src*bucket + i within this padded
                        # chunk -> level-global index in shard-major order
                        src_d = p // bucket
                        src_i = p % bucket
                        next_parent[d].append(
                            prev_base[src_d] + chunk_off[src_d] + src_i
                        )
                        next_act[d].append(a)
                    newc[d] = c
                lvl_new_per_shard += newc
                shard_visited += newc
                if obs_.collect:
                    act_en_np = fetch_global(act_en).astype(np.int64)
                    lvl_act_en += act_en_np.sum(axis=0)
                    lvl_en_per_shard += act_en_np.sum(axis=1)
                return False

            def _run_device_level():
                """The sharded device-resident level path (--pipeline
                device): dispatch ONE _make_sharded_level program
                covering this level's full-size serial chunks, with the
                <=1 exact-bound re-dispatch on overflow, then commit its
                outputs exactly as the per-chunk commits would have —
                O(1) collective-bearing launches per shard per level.
                On success `offs` advances past the handled prefix so
                the per-chunk loop below runs only the (sub-bucket)
                tail at its serial offsets; on failure it marks the
                sticky fallback and leaves offs untouched (the
                per-chunk ladder runs the whole level)."""
                nonlocal vcap, dev_vhi, dev_vlo, dev_vn, verdict
                nonlocal lvl_act_en, lvl_new_per_shard, lvl_en_per_shard
                nonlocal lvl_recv_per_shard, shard_visited
                nonlocal lvl_exch_bytes, lvl_exch_raw_bytes
                nonlocal lvl_dispatches, lvl_probe_ms
                lens = [p.shape[0] for p in pending]
                plan = sdev.plan_level(lens, chunk, min_bucket)
                if plan is None:
                    return
                B, nc = plan
                NCp = _next_pow2(nc)
                F = NCp * B
                chunk_retry.reset_chunk()
                widths = sdev.widths(B)
                T = expander.expand_width(B, widths)
                W = _default_dest_w(T, D)
                R = D * W if exchange == "all_to_all" else D * T
                # level-new ladder: ONE sizing policy with the single-
                # device device pipeline (ops/devlevel)
                LN = devlevel.level_new_capacity(T, sdev._ln_hw, nc * R)
                compress = compress_on
                exact = False
                dispatched = 0
                host_mode = sdev.host_mode
                # output-tuple indices differ between the two program
                # variants (the host program carries no visited shards
                # or digest folds, but adds the ohi/olo accumulators)
                (i_cnt, i_vk, i_vd, i_vinv, i_vix, i_aen, i_agm,
                 i_sd, i_rd, i_ovf, i_ncl) = (
                    (5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15)
                    if host_mode
                    else (3, 7, 8, 9, 10, 11, 12, 17, 18, 19, 20)
                )
                t0l = time.perf_counter()
                # only the handled prefix rides the device buffer; a
                # smaller-bucket serial tail runs per-chunk afterwards
                fbuf = np.zeros((D, F, K), np.uint32)
                flen = np.zeros(D, np.int32)
                for d in range(D):
                    n = min(nc * B, lens[d])
                    fbuf[d, :n] = pending[d][:n]
                    flen[d] = n
                pre_v = (dev_vhi, dev_vlo, dev_vn)
                while True:
                    try:
                        injected = fault.chunk_error(escalated=True)
                        if injected is not None:
                            raise injected
                        if host_mode:
                            key = ("lvlh", B, NCp, widths, LN, W,
                                   exchange, compress)
                            if key not in steps:
                                steps[key] = _make_sharded_level_host(
                                    model, mesh, expander, B, NCp,
                                    widths, LN, exchange, W, compress,
                                    check_deadlock,
                                )
                            outs = steps[key](
                                put_global(
                                    fbuf.reshape(D * F, K),
                                    layouts["frontier"],
                                ),
                                put_global(flen, layouts["pershard"]),
                                put_global(
                                    np.full(D, nc, np.int32),
                                    layouts["pershard"],
                                ),
                            )
                        else:
                            need = int(
                                fetch_global(pre_v[2]).max()
                            ) + min(nc * R, LN + R)
                            if need > vcap:
                                g_hi, g_lo, vcap = _grow_sorted_shards(
                                    pre_v[0], pre_v[1], vcap,
                                    _next_pow2(need), layouts["fpset"],
                                )
                                pre_v = (g_hi, g_lo, pre_v[2])
                            key = ("lvl", B, NCp, vcap, widths, LN, W,
                                   exchange, compress)
                            if key not in steps:
                                steps[key] = _make_sharded_level(
                                    model, mesh, expander, B, NCp,
                                    vcap, widths, LN, exchange, W,
                                    compress, check_deadlock,
                                )
                            outs = steps[key](
                                put_global(
                                    fbuf.reshape(D * F, K),
                                    layouts["frontier"],
                                ),
                                put_global(flen, layouts["pershard"]),
                                put_global(
                                    np.full(D, nc, np.int32),
                                    layouts["pershard"],
                                ),
                                pre_v[0], pre_v[1], pre_v[2],
                            )
                        dispatched += 1
                        lvl_dispatches += 1
                        # the one device sync per level: the overflow-
                        # flag read forces the whole level program
                        overflow = bool(fetch_global(outs[i_ovf]).any())
                    except Exception as e:  # noqa: BLE001 — XLA
                        action = chunk_retry.handle(
                            e, escalated=True, depth=depth,
                            retry_transient=not is_multiprocess(),
                        )
                        if action == "retry":
                            continue
                        sdev.mark_fallback(
                            f"{type(e).__name__}: {e}"[:200], depth
                        )
                        return
                    agmax_np = fetch_global(outs[i_agm]).max(
                        axis=0
                    ).astype(np.int64)
                    vk = int(fetch_global(outs[i_vk])[0])
                    if overflow and vk == 0 and not exact:
                        # a segment / destination bucket / codec budget
                        # / the level-new set overflowed: outputs are
                        # incomplete — discard and re-dispatch ONCE from
                        # the pre-level visited state at exact measured
                        # widths, full per-destination width (the raw
                        # wire cannot overflow at W == T) and the safe
                        # level-new bound: <=2 launches per shard per
                        # level even on overflow levels.  A verdict
                        # overrides: it derives from frontier states
                        # only, so it is exact regardless.
                        widths = sdev.exact_widths(B, agmax_np)
                        T = expander.expand_width(B, widths)
                        W = T
                        R = D * W if exchange == "all_to_all" else D * T
                        LN = devlevel.level_new_bound(nc * R)
                        compress = False  # only codec budgets overflow at W==T
                        exact = True
                        continue
                    break
                # committed: install the merged visited arrays (the
                # host-mode program carries no visited shards — the
                # host sets below ARE the visited state)
                if not host_mode:
                    dev_vhi, dev_vlo, dev_vn = outs[4], outs[5], outs[6]
                counts = fetch_global(outs[i_cnt]).astype(np.int64)  # [D]
                sdev.observe(agmax_np, B, int(counts.max()))
                sdev.launches_last = dispatched
                adapt.observe(agmax_np.astype(np.float64) / max(B, 1))
                # exchange framing check over the LEVEL-accumulated
                # digests (count/xor/sum accumulate commutatively, so
                # one compare per level detects exactly what the
                # per-chunk compares detect).  A committed overflow
                # only reaches here under a verdict override; the
                # accumulators then cover the clean pre-overflow chunk
                # prefix (the `clean` mask is replicated, so every
                # shard accumulated the same subset) — compared anyway:
                # a corruption in those chunks must still alarm, it
                # must never be laundered by a later verdict
                if chain is not None:
                    sd = np.asarray(fetch_global(outs[i_sd]), np.uint32)
                    rd = np.array(fetch_global(outs[i_rd]), np.uint32)
                    sp = fault.flip(
                        "exchange", depth + 1,
                        ckpt_depth=ckpt_durable_depth,
                    )
                    if sp:
                        rd[sp.shard if sp.shard is not None else 0,
                           1] ^= 0x10
                    _integ.count_check()
                    if _combine_digs(sd) != _combine_digs(rd):
                        raise IntegrityError(
                            "exchange",
                            f"exchange payload framing mismatch across "
                            f"level {depth + 1}: sent digest "
                            f"{_combine_digs(sd)} != received "
                            f"{_combine_digs(rd)} ({exchange}, device "
                            f"level program; a routed fingerprint was "
                            f"corrupted in flight)",
                            depth=depth,
                        )
                obs_.chunk_span(
                    "exchange-level",
                    time.perf_counter() - t0l,
                    depth=depth,
                    bucket=B,
                    chunks=nc,
                    launches=dispatched,
                    exchange=exchange,
                    compressed=compress,
                )
                # wire accounting: nclean counted chunks at the
                # committed dispatch's widths (same per-chunk formulas
                # as the per-chunk path)
                if exchange == "all_to_all":
                    ncl = int(fetch_global(outs[i_ncl])[0])
                    raw_b = D * D * W * (8 + 4 * K + 4 + 4)
                    if compress:
                        from ..ops import fpcompress as _fpc

                        Wr = max(32, W // 2)
                        sent_b = D * D * (
                            4 * _fpc.default_stream_words(W)
                            + 4 * _fpc.header_words(W)
                            + Wr * (4 * K + 4 + 1)
                        )
                    else:
                        sent_b = raw_b
                    lvl_exch_bytes += ncl * sent_b
                    lvl_exch_raw_bytes += ncl * raw_b
                if vk:
                    d = int(fetch_global(outs[i_vd])[0])
                    inv_i = int(fetch_global(outs[i_vinv])[0])
                    lidx = int(fetch_global(outs[i_vix])[0])
                    gidx = int(prev_base[d] + lidx)
                    name = (
                        model.invariants[inv_i].name
                        if vk == 1
                        else "Deadlock"
                    )
                    verdict = (name, pending[d][lidx], gidx)
                    for d2 in range(D):
                        # the serial break: the tail is never dispatched
                        offs[d2] = lens[d2]
                    return
                OC = LN + R
                cmax = int(counts.max())
                if cmax:
                    out3 = fetch_global(
                        outs[0].reshape(D, OC, K)[:, :cmax]
                    )
                    if collect_trace:
                        par3 = fetch_global(
                            outs[1].reshape(D, OC)[:, :cmax]
                        )
                        act3 = fetch_global(
                            outs[2].reshape(D, OC)[:, :cmax]
                        )
                if host_mode:
                    # Deferred once-per-level batched host probe: each
                    # owner shard's FpSet / disk tier takes the level's
                    # novel candidates (unique within the level, the
                    # per-chunk sorted emission order the serial host
                    # commits replay) in ONE insert; masks are OR-merged
                    # across processes so every process sees the same
                    # novelty decision — host syncs O(1) per shard per
                    # level instead of O(chunks)
                    t_probe = time.perf_counter()
                    masks = np.zeros((D, max(cmax, 1)), bool)
                    if cmax:
                        hi3 = fetch_global(
                            outs[3].reshape(D, OC)[:, :cmax]
                        )
                        lo3 = fetch_global(
                            outs[4].reshape(D, OC)[:, :cmax]
                        )
                        for d in range(D):
                            c = int(counts[d])
                            if c and host_sets[d] is not None:
                                s = host_sets[d]
                                fps = _u64(hi3[d, :c], lo3[d, :c])
                                masks[d, :c] = (
                                    s.insert_level(fps)
                                    if hasattr(s, "insert_level")
                                    else s.insert(fps)
                                ).astype(bool)
                        masks = or_across_processes(masks)
                    newc = np.zeros(D, np.int64)
                    for d in range(D):
                        c = int(counts[d])
                        if not c:
                            continue
                        mask = masks[d, :c]
                        rows = out3[d, :c][mask]
                        c2 = rows.shape[0]
                        if not c2:
                            continue
                        next_pending[d].append(rows)
                        if chain is not None:
                            # fold the probe SURVIVORS via the numpy
                            # fingerprint twin, deliberately NOT the
                            # device lanes in hi3/lo3: digesting the
                            # rows the host actually keeps, then
                            # checking the chain against the device
                            # fingerprints at save time, cross-checks
                            # the two representations for free (the
                            # per-chunk host commit's exact rationale)
                            chain.fold(
                                _integ.fingerprint_rows(
                                    rows, spec.exact64
                                )
                            )
                        if collect_trace:
                            pg = par3[d, :c][mask].astype(np.int64)
                            next_parent[d].append(
                                prev_base[pg // F] + (pg % F)
                            )
                            next_act[d].append(
                                act3[d, :c][mask].astype(np.int64)
                            )
                        newc[d] = c2
                    lvl_probe_ms += (
                        time.perf_counter() - t_probe
                    ) * 1e3
                    obs_.chunk_span(
                        "host-probe",
                        time.perf_counter() - t_probe,
                        depth=depth, rows=int(counts.sum()),
                        new=int(newc.sum()), batched="level",
                    )
                    lvl_new_per_shard += newc
                    lvl_recv_per_shard += counts
                    shard_visited += newc
                else:
                    for d in range(D):
                        c = int(counts[d])
                        if not c:
                            continue
                        next_pending[d].append(out3[d, :c])
                        if collect_trace:
                            pg = par3[d, :c].astype(np.int64)
                            # mesh-global level row ids -> level-global
                            # indices in shard-major order (the plan's
                            # chunk offsets are i*B, already inside pg)
                            next_parent[d].append(
                                prev_base[pg // F] + (pg % F)
                            )
                            next_act[d].append(
                                act3[d, :c].astype(np.int64)
                            )
                    if chain is not None:
                        # per-shard in-jit chain folds: the device-
                        # computed (count, xor, sum) accumulators fold
                        # bit-exactly like the per-chunk host folds
                        # over the same rows
                        _integ.fold_shard_device_digests(
                            chain,
                            fetch_global(outs[13]),
                            fetch_global(outs[14]),
                            fetch_global(outs[15]),
                            fetch_global(outs[16]),
                        )
                    lvl_new_per_shard += counts
                    lvl_recv_per_shard += counts
                    shard_visited += counts
                if obs_.collect:
                    act_en_np = fetch_global(outs[i_aen]).astype(
                        np.int64
                    )
                    lvl_act_en += act_en_np.sum(axis=0)
                    lvl_en_per_shard += act_en_np.sum(axis=1)
                for d in range(D):
                    offs[d] = min(nc * B, lens[d])

            if sdev is not None and sdev.fallback is None:
                # Device-resident level path: one dispatched while_loop
                # program per shard covers every full-size gated chunk
                # of this level; the per-chunk loop below then runs only
                # the remaining serial tail (or, on fallback, the whole
                # level) — bit-identical either way.
                governor.poll(depth)
                _run_device_level()

            # Staged commit (KSPEC_OVERLAP, host backend only — the at-
            # scale configuration; device backends chain each chunk's
            # visited arrays through the step, so their chunks serialize
            # by data flow): chunk k+1's program is dispatched — flags
            # UNREAD, so nothing blocks on it — before chunk k's flag
            # fetches and host commit run.  While the host inserts chunk
            # k's fingerprints, chunk k+1's expand + all_to_all drain;
            # on a per-shard imbalance the exchange wall hides behind
            # the host wall and vice versa.  An overflow discovered at
            # resolve time re-runs only that chunk (host-backend chunks
            # are independent until commit — the FpSets are only touched
            # here, in dispatch order), so results stay exact and
            # bit-identical to the serial path.
            stage_chunks = overlap_on and visited_backend == "host"
            staged_sh = None
            while verdict is None:
                ctx = _build_chunk()
                if ctx is None:
                    break
                outs, meta = _attempt_once(
                    ctx, adapt.widths_for(ctx[0]), w_extra
                )
                cur = [ctx, outs, meta]
                if stage_chunks:
                    overlap_staged_peak = max(
                        overlap_staged_peak,
                        2 if staged_sh is not None else 1,
                    )
                    if staged_sh is not None:
                        _resolve_chunk(staged_sh)
                        if _commit_sharded(staged_sh):
                            staged_sh = None
                            break
                    staged_sh = cur
                else:
                    _resolve_chunk(cur)
                    if _commit_sharded(cur):
                        break
            if staged_sh is not None and verdict is None:
                _resolve_chunk(staged_sh)
                _commit_sharded(staged_sh)
            staged_sh = None

            if verdict is not None:
                inv_name, row, gidx = verdict
                violation = build_violation(inv_name, depth, gidx) or Violation(
                    invariant=inv_name,
                    depth=depth,
                    state=decode_row(row),
                    trace=[],
                )
                break

            n_new = int(lvl_new_per_shard.sum())
            exch_bytes_total += lvl_exch_bytes
            exch_raw_bytes_total += lvl_exch_raw_bytes
            depth += 1
            if n_new:
                levels.append(n_new)
                total += n_new
            if chain is not None:
                if n_new:
                    chain.seal(depth, n_new)
                else:
                    chain.reset_fold()
            if obs_.collect and is_coordinator():
                enabled_total = int(lvl_act_en.sum())
                # heartbeat-enveloped (kind/ts/unix): the per-level stats
                # stream doubles as the supervisor's liveness signal.  Beyond
                # the coordinator-aggregated totals, the record carries the
                # per-shard breakdowns (frontier rows expanded per shard,
                # enabled per source shard, new per owner shard, and — host
                # backend, where the coordinator computes the novelty masks —
                # duplicates per owner shard) so exchange imbalance is
                # visible without re-running the level
                shard_extra = {}
                if host_sets is not None:
                    shard_extra["shard_duplicates"] = (
                        lvl_recv_per_shard - lvl_new_per_shard
                    ).tolist()
                rec = obs_.level(
                    depth=depth,
                    frontier=int(prev_base[-1]),
                    enabled_candidates=enabled_total,
                    new=n_new,
                    duplicates=enabled_total - n_new,
                    total=total,
                    level_ms=round((time.perf_counter() - t_level) * 1e3, 1),
                    shard_new=lvl_new_per_shard.tolist(),
                    shard_frontier=np.diff(prev_base).astype(np.int64).tolist(),
                    shard_enabled=lvl_en_per_shard.tolist(),
                    **shard_extra,
                    action_enablement={
                        a.name: int(c) for a, c in zip(model.actions, lvl_act_en.tolist())
                    },
                )
                # exchange wire accounting + overlap attribution ride the
                # IN-MEMORY records only (the emitted stats stream is a
                # pinned historical contract, like the launch counters)
                busy1, blk1 = _io_counters()
                result_levels.append({
                    **rec,
                    "exch_bytes": int(lvl_exch_bytes),
                    "exch_raw_bytes": int(lvl_exch_raw_bytes),
                    # dispatched collective-bearing programs this level
                    # (= launches PER SHARD; in-memory only, like the
                    # launch counters of the single-device engine)
                    "shard_launches": int(lvl_dispatches),
                    # deferred batched host-probe attribution (host-
                    # backend device path; in-memory records + gauge/
                    # span side channels only)
                    **(
                        {"host_probe_ms": round(lvl_probe_ms, 2)}
                        if lvl_probe_ms
                        else {}
                    ),
                    "io_hidden_ms": round(
                        max(0.0, (busy1 - lvl_io0[0])
                            - (blk1 - lvl_io0[1])) * 1e3, 2),
                    "io_exposed_ms": round((blk1 - lvl_io0[1]) * 1e3, 2),
                })
                _met.set_gauge(
                    "kspec_shard_launches_level", int(lvl_dispatches)
                )
                if lvl_probe_ms:
                    _met.set_gauge(
                        "kspec_host_probe_ms", round(lvl_probe_ms, 2)
                    )
                if lvl_exch_raw_bytes:
                    _met.set_gauge(
                        "kspec_exchange_bytes_level", int(lvl_exch_bytes)
                    )
                    _met.set_gauge(
                        "kspec_exchange_compression_ratio",
                        round(lvl_exch_raw_bytes / max(lvl_exch_bytes, 1), 3),
                    )
            if progress:
                progress(depth, n_new, total)
            _shard_beat(depth, new=n_new, total=total)
            pending = [
                np.concatenate(next_pending[d])
                if next_pending[d]
                else np.empty((0, K), np.uint32)
                for d in range(D)
            ]
            if plog is not None:
                # publish the level's per-shard parent-log segments BEFORE the
                # checkpoint save: a checkpoint at depth R then implies the
                # log resolves every level <= R (segments past a crash are
                # rewritten byte-identically by the deterministic re-run)
                plog.write_level(
                    depth,
                    pending,
                    [
                        np.concatenate(next_parent[d])
                        if next_parent[d]
                        else np.empty(0, np.int64)
                        for d in range(D)
                    ],
                    [
                        np.concatenate(next_act[d])
                        if next_act[d]
                        else np.empty(0, np.int64)
                        for d in range(D)
                    ],
                )
            if ckpt_store is not None and depth % checkpoint_every == 0:
                _save_checkpoint()
                last_ckpt_depth = depth
            if store_trace:
                trace_store.append(
                    (
                        np.concatenate(pending)
                        if n_new
                        else np.empty((0, K), np.uint32),
                        np.concatenate(
                            [x for lst in next_parent for x in lst]
                            or [np.empty(0, np.int64)]
                        ),
                        np.concatenate(
                            [x for lst in next_act for x in lst]
                            or [np.empty(0, np.int64)]
                        ),
                    )
                )
            # level-boundary resource governance: pressure gauges, injected
            # stall, soft-breach reclamation, hard-breach typed clean exit.
            # Multi-process: NO reclaim/save hooks — both reach
            # _save_checkpoint, whose device-backend dumps are collectives,
            # and a breach can be process-LOCAL (RSS, a host's own disk),
            # so a lone breacher issuing a collective would wedge forever
            # instead of exiting typed; it exits rc-75 from the last
            # lockstep checkpoint instead, which the fleet supervisor
            # classifies as the resource verdict
            multi = is_multiprocess()
            governor.level_end(
                depth,
                reclaim=None if multi else _reclaim,
                save_hook=None if multi else _final_save,
            )
        # drain the async tail INSIDE the typed-error scope: a pending
        # checkpoint's ENOSPC or a background merge's injected fault must
        # map to the same typed exits as their synchronous twins
        _ckpt_poll(block=True)
        if use_disk:
            for s in host_sets:
                if s is not None:
                    s.quiesce()
    except ResourceExhausted as e:
        exhausted = e
    except IntegrityError as e:
        integrity_fail = e
    except (RunCorrupt, ParentLogCorrupt) as e:
        # read-side storage checksum failure (spill runs / parent-log
        # segments): silent on-disk corruption caught at consumption
        integrity_fail = IntegrityError("storage", str(e), depth=depth)
    except OSError as e:
        if not is_disk_full(e):
            raise
        # a real ENOSPC from a storage/checkpoint writer outside the
        # injected paths: same typed clean exit (every writer cleans
        # up its tmp on failure, so the promoted state is intact)
        exhausted = ResourceExhausted("enospc", str(e), depth=depth)
    if integrity_fail is not None:
        # typed terminal (resilience.integrity): stamp the run manifest +
        # shard heartbeat, then propagate for the CLI's exit-76 mapping;
        # the restart resumes from the newest chain-verified generation
        # (the load validators skip corrupted ones).  In a fleet the
        # raising process exits 76 and its peers wedge in the next
        # collective — the fleet supervisor tears down and restarts, the
        # same contract as every shard-scoped fault
        try:
            _integ.record_violation(integrity_fail)
            _shard_beat(
                depth,
                event="integrity-violation",
                site=integrity_fail.site,
                detail=integrity_fail.detail[:200],
            )
            obs_.abort(
                "integrity-violation",
                site=integrity_fail.site,
                depth=integrity_fail.depth,
                detail=integrity_fail.detail[:300],
                distinct_states=total,
            )
            obs_.close()
        except OSError:
            pass
        _shutdown_async(drain=False)
        raise integrity_fail
    if exhausted is not None:
        # typed terminal: stamp the run manifest, mark the shard
        # heartbeat (fleet supervisors and `cli report` attribute the
        # exit to this process), and propagate for the exit-75 mapping.
        # All best-effort: these writes hit the same full filesystem, and
        # a second ENOSPC must not demote the typed exit into a crash
        try:
            _shard_beat(
                depth,
                event="resource-exhausted",
                reason=exhausted.reason,
                detail=exhausted.detail[:200],
            )
            obs_.abort(
                "resource-exhausted",
                reason=exhausted.reason,
                depth=exhausted.depth,
                detail=exhausted.detail,
                distinct_states=total,
                **governor.stats(),
            )
            obs_.close()
        except OSError:
            pass
        _shutdown_async(drain=False)
        raise exhausted

    if violation is None and cut and model.invariants:
        # cutoff left the last frontier unexpanded — run its invariant pass
        # (shard-major order matches trace_store's level layout)
        rows = np.concatenate(pending) if pending else np.empty((0, K), np.uint32)
        if rows.shape[0]:
            st = jax.vmap(spec.unpack)(jnp.asarray(rows))
            for inv in model.invariants:
                ok = np.asarray(jax.vmap(inv.pred)(st))
                if not ok.all():
                    idx = int(np.argmax(~ok))
                    violation = build_violation(
                        inv.name, depth, idx
                    ) or Violation(
                        invariant=inv.name,
                        depth=depth,
                        state=decode_row(rows[idx]),
                        trace=[],
                    )
                    break

    dt = time.perf_counter() - t0
    _shutdown_async(drain=True)
    _shard_beat(depth, event="finish", ok=violation is None)
    spill_stats = (
        {
            "spill": [s.stats() if s is not None else None for s in host_sets],
            "spill_dir": spill_base,
        }
        if use_disk
        else {}
    )
    if ephemeral_spill is not None:
        import shutil

        shutil.rmtree(ephemeral_spill, ignore_errors=True)
    res = CheckResult(
        model=model.name,
        levels=levels,
        total=total,
        diameter=len(levels) - 1,
        violation=violation,
        seconds=dt,
        states_per_sec=total / max(dt, 1e-9),
        stats={
            "devices": D,
            **({"levels": result_levels} if result_levels else {}),
            "visited_capacity_per_shard": int(vcap),
            "fanout": C,
            "visited_backend": visited_backend,
            "exchange": exchange,
            "pipeline": pipe_name,
            # explicit mesh-axis layouts (mesh_layouts): recorded so a
            # run artifact names the placement every tensor class used
            "mesh_layouts": {
                k: str(v.spec) for k, v in layouts.items()
            },
            **(
                {
                    "device": {
                        "levels": sdev.levels,
                        "fallback": sdev.fallback,
                    }
                }
                if sdev is not None
                else {}
            ),
            "adaptive_active": adapt.active,
            "adaptive_compile_fallback": adaptive_fallback,
            "transient_retries": chunk_retry.retries_total,
            "degradations": chunk_retry.degradations,
            "overlap": {
                "enabled": overlap_on,
                "staged_chunks_peak": overlap_staged_peak,
                **(
                    {"io_worker": io_worker.stats()}
                    if io_worker is not None
                    else {}
                ),
                **(
                    {"ckpt_worker": ckpt_worker.stats()}
                    if ckpt_worker is not None
                    else {}
                ),
            },
            "exchange_compressed": compress_on,
            "exchange_bytes_total": int(exch_bytes_total),
            "exchange_raw_bytes_total": int(exch_raw_bytes_total),
            **(
                {
                    "host_fpset_sizes": [
                        len(s) if s is not None else None for s in host_sets
                    ]
                }
                if host_sets is not None
                else {}
            ),
            **(
                {"shard_visited": shard_visited.tolist()}
                if visited_backend == "device-hash"
                else {}
            ),
            **spill_stats,
        },
    )
    obs_.finish(res)
    obs_.close()
    return res
