"""Multi-host (DCN) support for the sharded engine (SURVEY.md §2.6).

TLC's distributed mode spreads workers over TLCServer/TLCWorker JVMs; the
TPU-native equivalent runs the SAME `check_sharded` host loop on every
process of a multi-host program (`jax.distributed.initialize`), with the
1-D frontier mesh spanning all hosts' devices.  XLA then lays the
`all_to_all` fingerprint exchange over ICI within a slice and DCN across
slices — no hand-written networking, exactly like the NCCL-less design the
north star prescribes.

Controller model: REPLICATED HOST LOOP.  Every process executes the same
deterministic Python loop over the same global (host-side) frontier data,
so control decisions (chunk splits, bucket sizes, retries, termination)
agree everywhere without a coordinator:

- `put_global`  — device placement: each process contributes only its
  addressable shards (`jax.make_array_from_process_local_data`); on a
  single process it degrades to `jax.device_put`.
- `fetch_global` — result readback: all-gathers non-addressable shards
  (`multihost_utils.process_allgather`) so every process sees the same
  global ndarray; single-process it is `np.asarray`.

Both helpers are in the check_sharded hot path already, so the engine is
multi-host-shaped by construction; this module is the only place that
distinguishes the two regimes.  The host-FpSet spill backend is per-host
owned: each process keeps FpSets only for the shards whose devices it
hosts, computes their novelty masks locally, and the masks are OR-merged
across processes (`or_across_processes`) so the replicated loop stays in
lockstep — host memory and insert work both scale down 1/P.

This environment has a single host (one tunnel-attached chip), so the
multi-process regime is exercised only via the single-process degenerate
path plus `dryrun_multichip`'s virtual mesh; the code paths are kept
explicit and small so a real pod can validate them directly.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Initialize JAX's multi-host runtime if configured; no-op otherwise.

    Explicit args win; else the standard env vars drive it
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID); any
    field left unset is passed as None so jax.distributed's own cluster
    auto-detection (SLURM / TPU pod metadata) fills it in.  Also runs
    initialize() with all-None args when KSPEC_MULTIHOST=1, for clusters
    that are fully auto-detectable.  Returns {"process_id",
    "process_count", "local_devices", "global_devices"}.
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    want = addr is not None or os.environ.get("KSPEC_MULTIHOST") == "1"
    if want:
        if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
            num_processes = int(os.environ["JAX_NUM_PROCESSES"])
        if process_id is None and "JAX_PROCESS_ID" in os.environ:
            process_id = int(os.environ["JAX_PROCESS_ID"])
        # NB: must run before anything initializes the XLA backend (even
        # jax.process_count() would), so no jax queries happen first
        try:
            try:
                jax.distributed.initialize(
                    coordinator_address=addr,
                    num_processes=num_processes,
                    process_id=process_id,
                )
            except ValueError:
                if addr is None or num_processes is not None or process_id is not None:
                    raise
                # explicit coordinator, no topology given anywhere, and
                # jax's cluster auto-detection found nothing -> the
                # 1-process degenerate launch (the testable path here)
                jax.distributed.initialize(
                    coordinator_address=addr, num_processes=1, process_id=0
                )
        except RuntimeError as e:
            # idempotent re-entry (e.g. resume path): already initialized
            if "already" not in str(e).lower():
                raise
    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def put_global(arr: np.ndarray, sharding):
    """Place a (host-replicated) global ndarray onto the mesh.

    Single process: plain device_put.  Multi-process: every process holds
    the same global array (replicated host loop), so each contributes its
    addressable shards via make_array_from_process_local_data.
    """
    if not is_multiprocess():
        return jax.device_put(arr, sharding)
    # local data = the rows this process's devices own; for a 1-D sharding
    # over contiguous equal shards this is a contiguous slice
    return jax.make_array_from_process_local_data(
        sharding, _local_slice(arr, sharding), arr.shape
    )


def _local_slice(arr: np.ndarray, sharding) -> np.ndarray:
    idx = sharding.addressable_devices_indices_map(arr.shape)
    slices = list(idx.values())
    # contiguity holds for the engine's 1-D meshes (devices in mesh order)
    starts = sorted(s[0].start or 0 for s in slices)
    stops = sorted(s[0].stop if s[0].stop is not None else arr.shape[0] for s in slices)
    return arr[starts[0] : stops[-1]]


def fetch_global(garr) -> np.ndarray:
    """Read a possibly multi-host-sharded jax.Array back as the full global
    ndarray, identical on every process."""
    if not is_multiprocess():
        return np.asarray(garr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(garr, tiled=True))


def is_coordinator() -> bool:
    """True on the process that performs singleton side effects
    (checkpoint writes, stats files)."""
    return jax.process_index() == 0


def or_across_processes(arr: np.ndarray) -> np.ndarray:
    """Element-wise OR of a boolean ndarray across all processes.

    The host-FpSet novelty masks are computed only by each shard's owner
    process (per-host set ownership); OR-merging them gives every process
    the identical global mask the replicated host loop requires.
    Single-process: identity.
    """
    if not is_multiprocess():
        return arr
    from jax.experimental import multihost_utils

    g = multihost_utils.process_allgather(arr.astype(np.uint8))  # [P, ...]
    return np.asarray(g).any(axis=0)
