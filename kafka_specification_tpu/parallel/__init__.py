from .sharded import check_sharded

__all__ = ["check_sharded"]
