"""kafka_specification_tpu — a TPU-native explicit-state model checker.

This package reproduces the capabilities of the reference corpus
`hachikuji/kafka-specification` (TLA+ models of Kafka's single-partition
replication protocol: KIP-101 -> KIP-279 -> KIP-320 truncation/fencing lineage
plus the AsyncIsr/AlterIsr model) and supplies the checking engine those specs
outsource to the external TLC tool — rebuilt TPU-first on JAX/XLA:

- protocol state encoded as fixed-width int tensors (`ops.packing.StateSpec`),
- `Next` actions and safety invariants compiled to `jax.vmap`'d successor and
  predicate kernels (`models/`),
- TLC's StateQueue + FPSet replaced by a device-resident BFS frontier with
  64-bit fingerprint dedup (`engine/`), sharded over a device mesh with
  `shard_map` + `all_to_all` fingerprint routing (`parallel/`),
- a pure-Python oracle interpreter of the same TLA+ semantics (`oracle/`)
  serving as the golden cross-check in place of stock TLC,
- a TLA+ expression front-end (`utils/tla_expr` -> `utils/tla_emit`) that
  emits the same kernels mechanically from the reference text — every
  corpus module builds both ways, and the two paths agree on exact
  per-level state sets (`models/emitted.py`).

Layout:
    ops/       packing, fingerprinting, sorting/dedup primitives
    models/    tensor encodings + action/invariant kernels per TLA+ module
    engine/    BFS checker, trace reconstruction, checkpointing, stats
    parallel/  mesh-sharded frontier (ICI collectives; multi-host via DCN)
    oracle/    slow set-semantics reference interpreter (golden source)
    storage/   out-of-core tier: bloom-gated fingerprint runs on disk,
               spilled frontier segments, on-disk parent log (--mem-budget)
    resilience/ fault injection, hardened checkpoints, retry, supervisor
    obs/       unified telemetry: run directories + manifests, span
               tracer, metrics registry, `cli report` renderer
    utils/     TLC-compatible .cfg parsing, TLA+ front-end, CLI
"""

__version__ = "0.1.0"


def check(*args, **kwargs):
    """Single-device exhaustive check (see engine.bfs.check)."""
    from .engine.bfs import check as _check

    return _check(*args, **kwargs)


def check_sharded(*args, **kwargs):
    """Mesh-sharded exhaustive check (see parallel.sharded.check_sharded)."""
    from .parallel.sharded import check_sharded as _check_sharded

    return _check_sharded(*args, **kwargs)


def oracle_bfs(*args, **kwargs):
    """Pure-Python reference interpreter (see oracle.interp.oracle_bfs)."""
    from .oracle.interp import oracle_bfs as _oracle_bfs

    return _oracle_bfs(*args, **kwargs)


def load_config(path):
    """Parse a TLC .cfg file (see utils.cfg.parse_cfg)."""
    from .utils.cfg import parse_cfg

    return parse_cfg(path)


def build_model(module, cfg, oracle=False, emitted=False, reference=None):
    """Instantiate a model from a TLA+ module name + parsed TLC config.

    emitted=True builds the mechanically emitted kernels (the CLI's
    default path when the reference corpus is on disk); reference
    overrides the checkout location (else KSPEC_REFERENCE)."""
    from .utils.cfg import build_model as _build_model

    return _build_model(
        module, cfg, oracle=oracle, emitted=emitted, reference=reference
    )
