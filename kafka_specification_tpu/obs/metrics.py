"""Metrics registry: counters / gauges / histograms, JSONL + Prometheus.

One registry per run.  The engines update it through ``RunObserver``
(obs/observer.py); deep call sites (checkpoint writes, spill merges,
transient retries) bump counters through the module-level :func:`inc` /
:func:`set_gauge` helpers, which no-op unless a run is active — mirroring
the tracer's global-current pattern so storage/resilience need no
plumbing.

Exports, refreshed on every snapshot call (the engines snapshot per BFS
level, so a multi-day run's scrape is at most one level stale):

- ``metrics.jsonl`` — append-only heartbeat-enveloped snapshots (history;
  the report renderer reads the last one even from a crashed run).
- ``metrics.prom``  — the Prometheus *textfile-collector* format, written
  atomically (tmp + rename) so node_exporter's textfile collector (or any
  scraper that re-reads the file) never sees a torn export.  Every sample
  carries a ``run_id`` label; extra labels (e.g. ``shard``) ride alongside.

Metric names use the ``kspec_`` prefix and Prometheus conventions
(``*_total`` for counters).  docs/observability.md lists them all.

Must stay jax-free.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from .. import durable_io as _dio
from ..resilience.heartbeat import heartbeat_record
from .atomicio import atomic_write_text

# histogram default buckets: per-level wall times span 4ms toy levels to
# multi-minute deep-product levels (RUNPROD464_r5.log)
DEFAULT_MS_BUCKETS = (10, 50, 100, 500, 1000, 5000, 30_000, 120_000, 600_000)


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    def __init__(self, run_id: str = "",
                 const_labels: Optional[dict] = None):
        """``const_labels`` ride on every exported sample alongside
        ``run_id`` — the serving daemon stamps ``instance``/``host`` so N
        fleet daemons' scraped series never collide on one name."""
        self.run_id = run_id
        self.const_labels = dict(const_labels or {})
        self.counters: dict = {}
        self.gauges: dict = {}
        self.hists: dict = {}  # name -> {buckets, counts[], sum, count}
        # one registry may be updated from concurrent in-process jobs (the
        # serving daemon): read-modify-write counters and histogram cells
        # would otherwise drop increments under the interleaving
        self._lock = threading.Lock()

    # --- instruments ------------------------------------------------------
    def inc(self, name: str, value=1, **labels) -> None:
        k = _key(name, labels)
        with self._lock:
            self.counters[k] = self.counters.get(k, 0) + value

    def set_gauge(self, name: str, value, **labels) -> None:
        with self._lock:
            self.gauges[_key(name, labels)] = value

    def observe(self, name: str, value, buckets=DEFAULT_MS_BUCKETS) -> None:
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = {
                    "buckets": list(buckets),
                    "counts": [0] * (len(buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            i = 0
            for i, b in enumerate(h["buckets"]):
                if value <= b:
                    break
            else:
                i = len(h["buckets"])
            h["counts"][i] += 1
            h["sum"] += value
            h["count"] += 1

    # --- export -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {
                    n: {
                        "sum": round(h["sum"], 3),
                        "count": h["count"],
                        "buckets": dict(
                            zip([str(b) for b in h["buckets"]] + ["+Inf"],
                                _cum(h["counts"]))
                        ),
                    }
                    for n, h in self.hists.items()
                },
            }

    def write_jsonl(self, path: str) -> None:
        rec = heartbeat_record("metrics", run_id=self.run_id,
                               **({"labels": self.const_labels}
                                  if self.const_labels else {}),
                               **self.snapshot())
        _dio.append_text(path, json.dumps(rec) + "\n")

    def write_prom(self, path: str) -> None:
        """Atomic Prometheus textfile export (tmp + rename: a scraper
        re-reading the path mid-write never sees a torn file)."""
        rid = ",".join(
            [f'run_id="{self.run_id}"']
            + [f'{k}="{self.const_labels[k]}"'
               for k in sorted(self.const_labels)]
        )
        with self._lock:  # consistent copies: no size-change mid-iteration
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = {
                n: {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"],
                    "count": h["count"],
                }
                for n, h in self.hists.items()
            }

        def sample(key, value):
            # merge the run_id label into an existing {labels} suffix
            if key.endswith("}"):
                return f"{key[:-1]},{rid}}} {value}"
            return f"{key}{{{rid}}} {value}"

        lines = []
        seen_types = set()

        def type_line(key, mtype):
            base = key.split("{", 1)[0]
            if base not in seen_types:
                seen_types.add(base)
                lines.append(f"# TYPE {base} {mtype}")

        for k in sorted(counters):
            type_line(k, "counter")
            lines.append(sample(k, counters[k]))
        for k in sorted(gauges):
            type_line(k, "gauge")
            lines.append(sample(k, gauges[k]))
        for n in sorted(hists):
            h = hists[n]
            type_line(n, "histogram")
            for le, c in zip([str(b) for b in h["buckets"]] + ["+Inf"],
                             _cum(h["counts"])):
                lines.append(sample(f'{n}_bucket{{le="{le}"}}', c))
            lines.append(sample(f"{n}_sum", round(h["sum"], 3)))
            lines.append(sample(f"{n}_count", h["count"]))
        # no fsync — a scrape artifact needs no power-loss durability,
        # and the serving daemon exports per verdict (bench.py --serve)
        atomic_write_text(path, "\n".join(lines) + "\n", fsync=False)


def _cum(counts):
    out, acc = [], 0
    for c in counts:
        acc += c
        out.append(acc)
    return out


# --- module-level current registry (deep call sites, zero plumbing) -------
#
# Thread-LOCAL like the tracer's current (obs/tracer.py): concurrent
# in-process jobs each activate their own registry without cross-stamping.
_active = threading.local()


def set_registry(reg: Optional[MetricsRegistry]) -> None:
    _active.registry = reg


def current_registry() -> Optional[MetricsRegistry]:
    return getattr(_active, "registry", None)


def inc(name: str, value=1, **labels) -> None:
    reg = current_registry()
    if reg is not None:
        reg.inc(name, value, **labels)


def set_gauge(name: str, value, **labels) -> None:
    reg = current_registry()
    if reg is not None:
        reg.set_gauge(name, value, **labels)
