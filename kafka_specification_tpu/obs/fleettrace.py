"""Fleet trace plane: one trace per job, from submit to verdict.

The per-run observability (obs/runctx, obs/tracer) stops at the engine
boundary: a run_id covers one engine invocation on one host.  A *job*
lives longer — queue wait, router placement, re-route after a host
death, claim, scheduler grouping, batch/solo execution, state-cache
consult, verify, publish — and PRs 14–17 spread that life across hosts
with no single artifact to read it back from.  This module is that
artifact.

Trace context
-------------
:func:`mint_trace` runs at ``JobQueue.submit`` and plants the context
*inside the job spec file*::

    spec["trace"] = {"trace_id": "tr-<job_id>",
                     "span_id": "<root span id>",
                     "anchor_unix": <submitted_unix>}

Because the spec file IS the job's identity across re-route, crash
takeover, and sweep batching, the context survives every hand-off with
zero side channels.  Specs without a ``trace`` key (older submitters)
no-op every stamp site — emission helpers return ``None`` on a missing
context, never raise.

Record shape and durability
---------------------------
Every fleet span/event is one JSON line in the obs/tracer.py record
shape, wrapped in the shared heartbeat envelope (``ts``/``unix``), and
written with the tracer's untearable idiom: one ``os.write`` on an
``O_APPEND`` fd per record, so concurrent writers interleave whole
lines and a kill can tear only the line being written.  Reassembly goes
through :func:`obs.tracer.read_jsonl_tolerant`, so a torn final line —
or a tear anywhere, after adoption appends past it — never breaks
``cli trace``.

Layout: ``<root>/traces/<job_id>.jsonl`` where ``<root>`` is a host's
service dir (queue/daemon stamps) or the router dir (placement and
re-route stamps).  One job's trace is the tolerant union of that file
across every root; a missing host contributes nothing and fails
nothing.

Skew normalization
------------------
Hosts' clocks disagree (``KSPEC_CLOCK_SKEW`` allowance; ``skew@host``
injects real offsets, possibly negative).  Every record carries the
submit-time ``anchor_unix`` and its emitting clock domain (``host``,
``pid``).  :func:`assemble` pulls each domain forward so none of its
records precede the anchor — the submit instant is, by construction,
the earliest moment of the job — and clamps every derived stage
duration at zero.  ``cli trace`` therefore never renders a negative
stage, no matter what ``skew@host`` injected.

Vocabulary
----------
:data:`SPAN_KINDS` / :data:`EVENT_KINDS` register the fleet vocabulary;
:data:`ENGINE_SPAN_KINDS` / :data:`ENGINE_EVENT_KINDS` register the
per-run tracer's.  Emitting an unregistered fleet kind raises; the
:func:`lint_trace_vocabulary` pass (wired into ``cli analyze`` and a
tier-1 test) statically scans the package for literal kind call sites
and fails on anything unregistered or undocumented, so the tables in
docs/observability.md cannot silently drift from what the code emits.

Must stay jax-free (imported by the queue/router/daemon chain).
"""

from __future__ import annotations

import json
import os
import re
from contextlib import contextmanager
from typing import Optional

from .. import durable_io as _dio
from ..utils import clock as _clk
from ..resilience.heartbeat import heartbeat_record
from .tracer import read_jsonl_tolerant

TRACES_DIR = "traces"

#: fleet span kinds: one entry per stamp site class.  Keys are the
#: ``span`` field of emitted records; values document the emitter and
#: ride into docs/observability.md (the lint keeps them in sync).
SPAN_KINDS = {
    "job-submit": "queue: spec published into pending/ (the trace root)",
    "route-place": "router: admission + health-aware host choice",
    "queue-claim": "queue: pending->claimed rename + lease write",
    "sched-group": "daemon: scheduler batched this job into a group",
    "svc-run": "daemon: batch/solo engine run (run_id links the child)",
    "cache-lookup": "daemon: state-cache consult (hit/seed/miss/fallback)",
    "cache-publish": "daemon: federated state-space cache publish",
    "verdict-publish": "daemon: atomic verdict write + claim retire",
}

#: fleet event kinds: annotations, not durations — a re-route is a typed
#: fact about the job's life, not a gap in its waterfall.
EVENT_KINDS = {
    "route-reroute": "router: pending job moved off a dead host",
    "queue-requeue": "queue: orphaned claim taken over (crash adoption)",
    "sweep-member": "sweep: job submitted as a portfolio point",
}

#: per-run engine tracer vocabulary (obs/tracer.py emitters) — the other
#: half of the registry the lint holds against docs/observability.md.
ENGINE_SPAN_KINDS = {
    "level", "compile", "step", "shadow", "host-assembly", "host-probe",
    "exchange", "exchange-level", "spill-run-write", "spill-merge",
    "checkpoint-write", "checkpoint-verify",
}
ENGINE_EVENT_KINDS = {
    "pipeline-fallback", "xprof-start", "xprof-stop",
    "retry", "chunk-degrade", "compile-fallback", "checkpoint-fallback",
    "integrity-violation", "elastic-reshard",
}

#: typed latency decomposition, in waterfall order.  docs/observability.md
#: documents how each is derived from the span tree.
STAGES = ("queue-wait", "placement", "claim", "group-wait",
          "compile", "explore", "verify", "publish")


# --- context ---------------------------------------------------------------

def new_span_id() -> str:
    """Cross-host-unique without coordination (48 random bits)."""
    return os.urandom(6).hex()


def mint_trace(job_id: str, anchor_unix: float) -> dict:
    """The trace context planted in the spec at submit.  The trace id is
    derived from the job id so any component holding a spec (or even
    just a job id) can address the trace; the anchor is the submit-time
    clock every stage duration is measured against."""
    return {
        "trace_id": f"tr-{job_id}",
        "span_id": new_span_id(),
        "anchor_unix": round(float(anchor_unix), 3),
    }


def trace_path(root: str, job_id: str) -> str:
    return os.path.join(root, TRACES_DIR, f"{job_id}.jsonl")


def now() -> float:
    """The fleet-trace clock: wall time plus any injected ``skew@host``
    offset, so the chaos drill shifts trace stamps exactly like it
    shifts heartbeat/lease stamps (and normalization must undo it)."""
    try:
        from ..resilience.faults import injected_skew_s
        return _clk.now() + injected_skew_s()
    except Exception:
        return _clk.now()


# --- emission --------------------------------------------------------------

def _identity(attrs: dict) -> dict:
    """Clock-domain identity stamped on every record.  ``host`` follows
    the same env the skew fault keys on (KSPEC_HOST_INSTANCE), so the
    domain a record claims is the domain whose clock stamped it."""
    ident = {"pid": os.getpid()}
    host = os.environ.get("KSPEC_HOST_INSTANCE")
    if host is not None:
        ident["host"] = host
    inst = os.environ.get("KSPEC_DAEMON_INSTANCE")
    if inst is not None:
        ident["instance"] = inst
    for k in ("host", "instance"):
        if k in attrs:
            v = attrs.pop(k)
            if v is not None:
                ident[k] = str(v)
    return ident


def _append(path: str, rec: dict) -> bool:
    """The tracer's untearable idiom — whole record, one O_APPEND write
    — with the newline LEADING instead of trailing: a trace file is
    shared across incarnations and hosts, so a record appended after a
    predecessor's torn tail must terminate that tail and start on a
    fresh line, or the glue would eat the first record the survivor
    writes (the per-run tracer owns its fd for life and never faces
    this).  Telemetry must never take a component down — OSError reads
    as ``False``, never raises."""
    payload = ("\n" + json.dumps(rec)).encode()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
        _dio.note_append(path, payload)
        return True
    except OSError:
        return False


def emit_span(root: str, trace: Optional[dict], kind: str,
              t0: float, t1: float, *, job_id: str,
              parent_id: Optional[str] = None,
              span_id: Optional[str] = None, **attrs) -> Optional[str]:
    """Append one completed fleet span under ``root``.  No-op (returns
    None) without a trace context — specs predating the trace plane
    flow through every stamp site unchanged."""
    if not isinstance(trace, dict) or "trace_id" not in trace:
        return None
    if kind not in SPAN_KINDS:
        raise ValueError(f"unregistered fleet span kind {kind!r} "
                         "(register it in obs.fleettrace.SPAN_KINDS)")
    sid = span_id or new_span_id()
    ident = _identity(attrs)
    rec = heartbeat_record(
        "span", t=now(), ph="E", span=kind, span_id=sid,
        parent_id=parent_id, t0=round(t0, 3),
        ms=round((t1 - t0) * 1e3, 1),
        trace_id=trace["trace_id"], job_id=job_id,
        anchor_unix=trace.get("anchor_unix"), **ident, **attrs,
    )
    return sid if _append(trace_path(root, job_id), rec) else None


def emit_event(root: str, trace: Optional[dict], kind: str, *,
               job_id: str, **attrs) -> bool:
    """Append one point annotation (re-route, requeue, sweep membership)
    under ``root``.  Same no-op contract as :func:`emit_span`."""
    if not isinstance(trace, dict) or "trace_id" not in trace:
        return False
    if kind not in EVENT_KINDS:
        raise ValueError(f"unregistered fleet event kind {kind!r} "
                         "(register it in obs.fleettrace.EVENT_KINDS)")
    ident = _identity(attrs)
    rec = heartbeat_record(
        "event", t=now(), event=kind,
        trace_id=trace["trace_id"], job_id=job_id,
        anchor_unix=trace.get("anchor_unix"), **ident, **attrs,
    )
    return _append(trace_path(root, job_id), rec)


@contextmanager
def fleet_span(root: str, trace: Optional[dict], kind: str, *,
               job_id: str, **attrs):
    """Context-manager form of :func:`emit_span` for sites that bracket
    real work.  Yields a dict the body may fill with extra attrs; the
    span is emitted on NORMAL exit only — an exception propagates with
    nothing written, exactly like a killed process (partial traces show
    what the dead incarnation finished, never what it was mid-way
    through)."""
    t0 = now()
    extra: dict = {}
    yield extra
    emit_span(root, trace, kind, t0, now(), job_id=job_id,
              **{**attrs, **extra})


# --- reassembly ------------------------------------------------------------

def load_trace(roots, job_id: str) -> list:
    """Tolerant union of one job's trace file across every root (host
    service dirs + the router dir).  Missing files — a host that never
    touched the job, or one whose disk died — contribute nothing."""
    recs = []
    for root in roots:
        recs.extend(read_jsonl_tolerant(trace_path(root, job_id)))
    return recs


def _domain(rec: dict):
    return (rec.get("host"), rec.get("pid"))


def assemble(records: list, job_id: Optional[str] = None) -> dict:
    """Normalize one job's records into a skew-corrected span tree plus
    the typed stage decomposition.

    Normalization: per clock domain (host, pid), shift every timestamp
    forward by ``max(0, anchor - earliest_t0)`` — a domain whose clock
    ran behind the submitter's would otherwise place work before the
    submit instant, which is physically impossible.  Domains running
    ahead are left alone (their stamps stay ordered and non-negative);
    every derived stage duration is additionally clamped at zero.
    Output timestamps are ``t0n``/``t1n``/``tn``: seconds relative to
    the anchor."""
    spans = [dict(r) for r in records
             if r.get("kind") == "span" and r.get("trace_id")]
    events = [dict(r) for r in records
              if r.get("kind") == "event" and r.get("trace_id")]
    anchors = [r["anchor_unix"] for r in spans + events
               if isinstance(r.get("anchor_unix"), (int, float))]
    anchor = min(anchors) if anchors else None
    trace_id = next(
        (r["trace_id"] for r in spans + events), None
    )
    if job_id is None:
        job_id = next((r.get("job_id") for r in spans + events), None)

    shifts: dict = {}
    if anchor is not None:
        firsts: dict = {}
        for r in spans:
            t0 = r.get("t0")
            if isinstance(t0, (int, float)):
                d = _domain(r)
                firsts[d] = min(firsts.get(d, t0), t0)
        for r in events:
            t = r.get("unix")
            if isinstance(t, (int, float)):
                d = _domain(r)
                firsts[d] = min(firsts.get(d, t), t)
        shifts = {d: max(0.0, anchor - first)
                  for d, first in firsts.items()}

    for r in spans:
        shift = shifts.get(_domain(r), 0.0)
        t0 = r.get("t0")
        if isinstance(t0, (int, float)) and anchor is not None:
            r["t0n"] = round(t0 + shift - anchor, 3)
            r["t1n"] = round(r["t0n"] + max(0.0, r.get("ms", 0.0)) / 1e3, 3)
    for r in events:
        shift = shifts.get(_domain(r), 0.0)
        t = r.get("unix")
        if isinstance(t, (int, float)) and anchor is not None:
            r["tn"] = round(max(0.0, t + shift - anchor), 3)

    spans.sort(key=lambda r: (r.get("t0n", 0.0), r.get("span", "")))
    events.sort(key=lambda r: (r.get("tn", 0.0), r.get("event", "")))

    ends = [r["t1n"] for r in spans if "t1n" in r]
    ends += [r["tn"] for r in events if "tn" in r]
    hosts = sorted({str(r["host"]) for r in spans + events
                    if r.get("host") is not None})
    return {
        "trace_id": trace_id,
        "job_id": job_id,
        "anchor_unix": anchor,
        "spans": spans,
        "events": events,
        "hosts": hosts,
        "shifts": {"{}:{}".format(*d): round(s, 3)
                   for d, s in shifts.items() if s},
        "duration_ms": round(max(ends) * 1e3, 1) if ends else None,
        "stages": stage_decomposition(spans),
        "complete": any(r.get("span") == "verdict-publish" for r in spans),
    }


def stage_decomposition(spans: list) -> dict:
    """The typed latency decomposition (ms per stage, None = stage never
    happened).  Durations come from normalized timestamps and are
    clamped at zero — see :func:`assemble`."""
    by_kind: dict = {}
    for r in spans:
        if "t0n" in r:
            by_kind.setdefault(r.get("span"), []).append(r)

    def total_ms(kind):
        rs = by_kind.get(kind)
        if not rs:
            return None
        return round(sum(max(0.0, r.get("ms", 0.0)) for r in rs), 1)

    stages = dict.fromkeys(STAGES)
    claims = by_kind.get("queue-claim", [])
    runs = by_kind.get("svc-run", [])
    lookups = by_kind.get("cache-lookup", [])
    if claims:
        stages["queue-wait"] = round(
            max(0.0, min(r["t0n"] for r in claims)) * 1e3, 1
        )
    stages["placement"] = total_ms("route-place")
    stages["claim"] = total_ms("queue-claim")
    if runs and claims:
        last_claim_end = max(r["t1n"] for r in claims)
        stages["group-wait"] = round(
            max(0.0, min(r["t0n"] for r in runs) - last_claim_end) * 1e3, 1
        )
    if runs:
        compile_ms = sum(
            float(r.get("compile_ms") or 0.0) for r in runs
        )
        stages["compile"] = round(compile_ms, 1)
        stages["explore"] = round(
            max(0.0, sum(max(0.0, r.get("ms", 0.0)) for r in runs)
                - compile_ms), 1
        )
    if lookups:
        stages["verify"] = total_ms("cache-lookup")
    pub = [total_ms("verdict-publish"), total_ms("cache-publish")]
    if any(v is not None for v in pub):
        stages["publish"] = round(sum(v or 0.0 for v in pub), 1)
    return stages


# --- rendering -------------------------------------------------------------

_BAR_WIDTH = 28


def render_trace(data: dict) -> str:
    """The cross-host waterfall: one line per span (bar scaled over the
    trace duration), annotations interleaved at their instant, stage
    decomposition at the foot."""
    if not data.get("spans") and not data.get("events"):
        return f"trace {data.get('trace_id') or '?'}: no records found"
    total = max(data.get("duration_ms") or 0.0, 1e-6)
    head = (
        f"Trace {data['trace_id']} (job {data['job_id']}): "
        f"{len(data['spans'])} spans, {len(data['events'])} annotations, "
        f"{total:.0f}ms"
    )
    if data["hosts"]:
        head += ", hosts " + ",".join(data["hosts"])
    if not data.get("complete"):
        head += "  [incomplete: no verdict-publish span]"
    out = [head]
    if data.get("shifts"):
        out.append(
            "  skew-normalized: "
            + ", ".join(f"domain {d} pulled +{s:.3f}s"
                        for d, s in sorted(data["shifts"].items()))
        )
    rows = [("span", r.get("t0n", 0.0), r) for r in data["spans"]]
    rows += [("event", r.get("tn", 0.0), r) for r in data["events"]]
    rows.sort(key=lambda x: x[1])
    for what, t, r in rows:
        off = f"+{t * 1e3:8.1f}ms"
        if what == "event":
            detail = " ".join(
                f"{k}={r[k]}" for k in ("from_host", "to_host", "from_pid",
                                        "sweep_id", "reason", "why")
                if r.get(k) is not None
            )
            out.append(f"  {off} ~ {r['event']:<16} [annotation] {detail}")
            continue
        ms = max(0.0, r.get("ms", 0.0))
        lead = int(_BAR_WIDTH * (t * 1e3) / total)
        width = max(1, int(round(_BAR_WIDTH * ms / total)))
        bar = " " * min(lead, _BAR_WIDTH - 1) + "#" * min(
            width, _BAR_WIDTH - min(lead, _BAR_WIDTH - 1)
        )
        who = "host" + str(r["host"]) if r.get("host") is not None else "-"
        detail = " ".join(
            f"{k}={r[k]}" for k in ("run_id", "outcome", "group_size",
                                    "states", "verdict")
            if r.get(k) is not None
        )
        out.append(
            f"  {off} {r['span']:<16} |{bar:<{_BAR_WIDTH}}| "
            f"{ms:8.1f}ms {who:<7} {detail}".rstrip()
        )
    stages = data.get("stages") or {}
    shown = [(s, stages[s]) for s in STAGES if stages.get(s) is not None]
    if shown:
        out.append(
            "  stages: " + " | ".join(f"{s} {v:.1f}ms" for s, v in shown)
        )
    return "\n".join(out)


# --- fleet report ----------------------------------------------------------

def _pctl(values, q: float):
    if not values:
        return None
    s = sorted(values)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def list_trace_jobs(roots) -> list:
    """Every job id with a trace file under any root, sorted."""
    jobs = set()
    for root in roots:
        try:
            names = os.listdir(os.path.join(root, TRACES_DIR))
        except OSError:
            continue
        jobs.update(
            n[: -len(".jsonl")] for n in names if n.endswith(".jsonl")
        )
    return sorted(jobs)


def fleet_report_data(roots, exemplars: int = 5) -> dict:
    """Aggregate every trace under ``roots`` into the SLO evidence
    artifact: per-stage p50/p95 over completed traces, cache hit ratio,
    chaos annotation tally, and the slowest-trace exemplars with their
    full decomposition."""
    roots = list(dict.fromkeys(roots))
    traces = []
    for job_id in list_trace_jobs(roots):
        recs = load_trace(roots, job_id)
        if recs:
            traces.append(assemble(recs, job_id=job_id))
    complete = [t for t in traces if t["complete"]]
    stage_values: dict = {s: [] for s in STAGES}
    for t in complete:
        for s, v in (t["stages"] or {}).items():
            if v is not None:
                stage_values[s].append(v)
    lookups = {"hit": 0, "seed": 0, "miss": 0, "fallback": 0}
    annotations: dict = {}
    for t in traces:
        for r in t["spans"]:
            if r.get("span") == "cache-lookup":
                outcome = str(r.get("outcome"))
                if outcome in lookups:
                    lookups[outcome] += 1
        for r in t["events"]:
            k = r["event"]
            annotations[k] = annotations.get(k, 0) + 1
    n_lookups = sum(lookups.values())
    durations = [t["duration_ms"] for t in complete
                 if t["duration_ms"] is not None]
    slowest = sorted(
        (t for t in complete if t["duration_ms"] is not None),
        key=lambda t: -t["duration_ms"],
    )[:exemplars]
    return {
        "roots": roots,
        "traces": len(traces),
        "completed": len(complete),
        "stages": {
            s: {
                "n": len(vs),
                "p50_ms": _pctl(vs, 0.50),
                "p95_ms": _pctl(vs, 0.95),
            }
            for s, vs in stage_values.items() if vs
        },
        "duration": {
            "n": len(durations),
            "p50_ms": _pctl(durations, 0.50),
            "p95_ms": _pctl(durations, 0.95),
        },
        "cache": {
            "lookups": n_lookups,
            **lookups,
            "hit_ratio": (
                round(lookups["hit"] / n_lookups, 3) if n_lookups else None
            ),
        },
        "annotations": annotations,
        "slowest": [
            {
                "job_id": t["job_id"],
                "duration_ms": t["duration_ms"],
                "hosts": t["hosts"],
                "stages": t["stages"],
                "annotations": [r["event"] for r in t["events"]],
            }
            for t in slowest
        ],
    }


def render_fleet_report(data: dict) -> str:
    out = [
        f"Fleet report over {len(data['roots'])} root(s): "
        f"{data['traces']} traces, {data['completed']} completed"
    ]
    if data["stages"]:
        out.append("  stage            n      p50        p95")
        for s in STAGES:
            row = data["stages"].get(s)
            if row:
                out.append(
                    f"  {s:<14} {row['n']:>4} {row['p50_ms']:>8.1f}ms "
                    f"{row['p95_ms']:>8.1f}ms"
                )
        d = data["duration"]
        if d["n"]:
            out.append(
                f"  {'end-to-end':<14} {d['n']:>4} {d['p50_ms']:>8.1f}ms "
                f"{d['p95_ms']:>8.1f}ms"
            )
    c = data["cache"]
    out.append(
        f"  cache: {c['lookups']} lookups — {c['hit']} hit / "
        f"{c['seed']} seed / {c['miss']} miss / {c['fallback']} fallback"
        + (f" (hit ratio {c['hit_ratio']:.1%})"
           if c["hit_ratio"] is not None else "")
    )
    if data["annotations"]:
        out.append(
            "  chaos annotations: " + ", ".join(
                f"{k}={v}" for k, v in sorted(data["annotations"].items())
            )
        )
    for t in data["slowest"]:
        stages = t["stages"] or {}
        top = sorted(
            ((s, v) for s, v in stages.items() if v),
            key=lambda x: -x[1],
        )[:3]
        out.append(
            f"  slowest {t['job_id']}: {t['duration_ms']:.0f}ms "
            + " ".join(f"{s}={v:.0f}ms" for s, v in top)
            + (" [" + ",".join(t["annotations"]) + "]"
               if t["annotations"] else "")
        )
    return "\n".join(out)


# --- live fleet view (`cli top`) ------------------------------------------

def _parse_prom_hists(path: str) -> dict:
    """Histogram series from one metrics*.prom export:
    ``{name: {"buckets": {le: cum}, "sum": float, "count": int}}`` with
    labels stripped (the rollup aggregates across daemons)."""
    out: dict = {}
    try:
        with open(path) as fh:
            lines = fh.read().splitlines()
    except OSError:
        return out

    def slot(base):
        return out.setdefault(
            base, {"buckets": {}, "sum": 0.0, "count": 0}
        )

    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        try:
            key, val = ln.rsplit(" ", 1)
            value = float(val)
        except ValueError:
            continue
        base, _, labels = key.partition("{")
        if base.endswith("_bucket"):
            m = re.search(r'le="([^"]+)"', labels)
            if m:
                b = slot(base[: -len("_bucket")])["buckets"]
                b[m.group(1)] = b.get(m.group(1), 0.0) + value
        elif base.endswith("_sum"):
            slot(base[: -len("_sum")])["sum"] += value
        elif base.endswith("_count"):
            slot(base[: -len("_count")])["count"] += int(value)
    return out


def hist_pctl(hist: dict, q: float):
    """Percentile estimate from cumulative buckets: the smallest upper
    bound whose cumulative count covers the quantile (the standard
    textfile-collector approximation; +Inf reads as the largest finite
    bound so a pathological tail still renders a number)."""
    count = hist.get("count", 0)
    if not count:
        return None
    target = q * count

    def bkey(le):
        return float("inf") if le == "+Inf" else float(le)

    finite = [bkey(le) for le in hist["buckets"] if le != "+Inf"]
    for le in sorted(hist["buckets"], key=bkey):
        if hist["buckets"][le] >= target:
            if le == "+Inf":
                return max(finite) if finite else None
            return float(le)
    return max(finite) if finite else None


def _count_jobs(root: str, sub: str) -> int:
    """Queue depth from the on-disk layout (``<root>/queue/<state>``)."""
    try:
        return len([
            n for n in os.listdir(os.path.join(root, "queue", sub))
            if n.endswith(".json")
        ])
    except OSError:
        return 0


def _sweep_jobs(root: str) -> dict:
    """In-flight sweep membership by queue stage, via the deterministic
    ``sw-<sweep>-<point>`` job-id prefix (sweep/portfolio.job_id_for)."""
    out = {}
    for sub in ("pending", "claimed", "done"):
        try:
            names = os.listdir(os.path.join(root, "queue", sub))
        except OSError:
            names = []
        out[sub] = len([
            n for n in names
            if n.startswith("sw-") and n.endswith(".json")
        ])
    return out


def _daemon_rows(svc: str) -> list:
    """One row per heartbeat*.jsonl: last record's state + age."""
    rows = []
    try:
        names = sorted(
            n for n in os.listdir(svc)
            if n.startswith("heartbeat") and n.endswith(".jsonl")
        )
    except OSError:
        return rows
    wall = _clk.now()
    for name in names:
        recs = read_jsonl_tolerant(os.path.join(svc, name))
        last = recs[-1] if recs else {}
        unix = last.get("unix")
        rows.append({
            "file": name,
            "pid": last.get("pid"),
            "state": last.get("state") or last.get("event") or "?",
            "age_s": (
                round(max(0.0, wall - unix), 1)
                if isinstance(unix, (int, float)) else None
            ),
        })
    return rows


def top_data(service_dirs, router_dir: Optional[str] = None) -> dict:
    """One frame of the live fleet view, entirely from on-disk state:
    queue depths + daemon heartbeats per host, per-stage p50/p95 from
    the daemons' stage histograms, cache hit ratio from the counter
    rollup, and in-flight sweep progress from job-id prefixes."""
    from .report import host_metrics_rollup

    hosts = []
    if router_dir:
        from ..service.router import Router

        router = Router(router_dir)
        for i, h in enumerate(router.healths()):
            hosts.append({
                "host": i,
                "dir": router.queues[i].dir,
                "state": h["state"],
            })
    else:
        for i, root in enumerate(service_dirs):
            hosts.append({"host": i, "dir": root, "state": "-"})

    hist_total: dict = {}
    counters_total: dict = {}
    for h in hosts:
        root = h["dir"]
        svc = os.path.join(root, "service")
        h["pending"] = _count_jobs(root, "pending")
        h["claimed"] = _count_jobs(root, "claimed")
        h["done"] = _count_jobs(root, "done")
        h["daemons"] = _daemon_rows(svc)
        h["sweep"] = _sweep_jobs(root)
        for key, value in host_metrics_rollup(svc).items():
            base = key.partition("{")[0]
            counters_total[base] = counters_total.get(base, 0.0) + value
        try:
            proms = sorted(
                n for n in os.listdir(svc)
                if n.startswith("metrics") and n.endswith(".prom")
            )
        except OSError:
            proms = []
        for name in proms:
            for base, hist in _parse_prom_hists(
                os.path.join(svc, name)
            ).items():
                agg = hist_total.setdefault(
                    base, {"buckets": {}, "sum": 0.0, "count": 0}
                )
                for le, c in hist["buckets"].items():
                    agg["buckets"][le] = agg["buckets"].get(le, 0.0) + c
                agg["sum"] += hist["sum"]
                agg["count"] += hist["count"]

    prefix = "kspec_svc_stage_"
    stages = {}
    for base, hist in hist_total.items():
        if base.startswith(prefix) and base.endswith("_ms"):
            stage = base[len(prefix): -len("_ms")].replace("_", "-")
            stages[stage] = {
                "n": hist["count"],
                "p50_ms": hist_pctl(hist, 0.50),
                "p95_ms": hist_pctl(hist, 0.95),
            }
    hits = counters_total.get("kspec_svc_state_cache_hits_total", 0.0)
    misses = counters_total.get("kspec_svc_state_cache_misses_total", 0.0)
    seeds = counters_total.get("kspec_svc_state_cache_seeds_total", 0.0)
    looked = hits + misses + seeds
    sweep = {
        sub: sum(h["sweep"][sub] for h in hosts)
        for sub in ("pending", "claimed", "done")
    }
    return {
        "router": router_dir,
        "hosts": hosts,
        "stages": stages,
        "cache": {
            "hits": hits,
            "hit_ratio": round(hits / looked, 3) if looked else None,
        },
        "sweep": sweep,
    }


def render_top(data: dict) -> str:
    out = [
        "kspec top — " + (
            f"router {data['router']}" if data["router"]
            else f"{len(data['hosts'])} host(s)"
        )
    ]
    out.append("  host  state   pending  claimed  done   daemons")
    for h in data["hosts"]:
        ds = " ".join(
            "{}{}".format(
                d["state"],
                f"@{d['age_s']}s" if d["age_s"] is not None else "",
            )
            for d in h["daemons"]
        ) or "-"
        out.append(
            f"  {h['host']:<5} {h['state']:<7} {h['pending']:>7}  "
            f"{h['claimed']:>7}  {h['done']:>4}   {ds}"
        )
    if data["stages"]:
        parts = []
        for s in STAGES:
            row = data["stages"].get(s)
            if row and row["p50_ms"] is not None:
                parts.append(
                    f"{s} p50={row['p50_ms']:.0f}/p95={row['p95_ms']:.0f}ms"
                )
        if parts:
            out.append("  stages: " + " | ".join(parts))
    c = data["cache"]
    out.append(
        "  cache: "
        + (f"{c['hit_ratio']:.1%} hit ratio ({c['hits']:.0f} hits)"
           if c["hit_ratio"] is not None else "no lookups yet")
    )
    sw = data["sweep"]
    total = sum(sw.values())
    if total:
        out.append(
            f"  sweep: {sw['done']}/{total} done "
            f"({sw['pending']} pending, {sw['claimed']} in flight)"
        )
    return "\n".join(out)


# --- vocabulary lint -------------------------------------------------------

# literal kind call sites.  Engine tracer calls put the kind FIRST
# (span("level", ...), chunk_span("step", ...)); fleet emitters put it
# THIRD (emit_span(root, trace, "queue-claim", ...)).  Dynamic sites
# (emit_span(kind, ...) with a variable) are invisible by design — their
# literals live at the callers, which ARE scanned.
_LINT_PATTERNS = (
    (re.compile(
        r'\b(?:span|begin|chunk_span|emit_span)\(\s*"([a-z0-9-]+)"'
    ), "span", "engine"),
    (re.compile(r'\bevent\(\s*"([a-z0-9-]+)"'), "event", "engine"),
    (re.compile(
        r'\b(?:emit_span|fleet_span)\(\s*[^,"\n]+,\s*[^,"\n]+,'
        r'\s*"([a-z0-9-]+)"'
    ), "span", "fleet"),
    (re.compile(
        r'\bemit_event\(\s*[^,"\n]+,\s*[^,"\n]+,\s*"([a-z0-9-]+)"'
    ), "event", "fleet"),
)

_DOCSTRING_RE = re.compile(r'""".*?"""|\'\'\'.*?\'\'\'', re.S)

_REGISTRIES = {
    ("span", "engine"): ENGINE_SPAN_KINDS,
    ("event", "engine"): ENGINE_EVENT_KINDS,
    ("span", "fleet"): SPAN_KINDS,
    ("event", "fleet"): EVENT_KINDS,
}


def lint_trace_vocabulary(package_root: Optional[str] = None,
                          docs_path: Optional[str] = None) -> list:
    """Static registry lint: every literal span/event kind emitted by
    the package must be registered above, and every registered kind must
    appear in docs/observability.md.  Returns a list of
    ``{path, line, kind, problem}`` findings (empty = clean); wired into
    ``cli analyze`` and pinned by a tier-1 test so the documented trace
    vocabulary cannot drift from what the code emits."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
    if docs_path is None:
        docs_path = os.path.join(
            os.path.dirname(package_root), "docs", "observability.md"
        )
    findings = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            try:
                with open(path) as fh:
                    src = fh.read()
            except OSError:
                continue
            # docstrings carry example calls; only real code sites count
            scrubbed = _DOCSTRING_RE.sub(
                lambda m: "\n" * m.group(0).count("\n"), src
            )
            for pattern, what, plane in _LINT_PATTERNS:
                for m in pattern.finditer(scrubbed):
                    kind = m.group(1)
                    if kind not in _REGISTRIES[(what, plane)]:
                        findings.append({
                            "path": os.path.relpath(
                                path, os.path.dirname(package_root)
                            ),
                            "line": scrubbed[: m.start()].count("\n") + 1,
                            "kind": kind,
                            "problem": (
                                f"unregistered {plane} {what} kind "
                                f"(obs.fleettrace registries)"
                            ),
                        })
    try:
        with open(docs_path) as fh:
            docs = fh.read()
    except OSError:
        docs = None
    if docs is not None:
        documented = set(re.findall(r"`([a-z0-9-]+)`", docs))
        for registry in _REGISTRIES.values():
            for kind in sorted(registry):
                if kind not in documented:
                    findings.append({
                        "path": os.path.relpath(
                            docs_path, os.path.dirname(package_root)
                        ),
                        "line": 0,
                        "kind": kind,
                        "problem": "registered kind missing from docs",
                    })
    return findings
