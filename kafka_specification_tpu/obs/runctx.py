"""RunContext: one run_id, one directory, one manifest for a whole run.

Every ``cli check`` / ``resilient_run.py`` invocation gets a run directory
(``--run-dir``, default ``runs/<run_id>/`` under the current directory or
``$KSPEC_RUNS_ROOT``) that collects what previously landed wherever each
caller pointed it:

    runs/<run_id>/
      manifest.json    config, engine, git describe, knobs, lineage, status
      stats.jsonl      the engines' per-level heartbeat stream (--stats)
      spans.jsonl      nested spans + point events (obs/tracer)
      metrics.jsonl    per-level metric snapshots (obs/metrics)
      metrics.prom     Prometheus textfile export (atomic, scrapable)
      events.jsonl     supervisor events (resilient runs)
      logs/            per-attempt child logs (resilient runs)
      spill/           disk-tier default when --mem-budget is set
      xprof/           jax.profiler windows (KSPEC_OBS_XPROF)

The manifest is written atomically at open (status "running"), updated
with a resume-lineage entry every time an existing run directory is
reopened (supervised restarts resume *into the same run*: the run_id is
the correlation key across attempts), and finalized by ``finish`` with the
terminal status + result summary.  A manifest stuck at "running" whose
heartbeat has gone stale is exactly what ``cli report``'s stall verdict
keys on.

Must stay jax-free (resilient_run.py / tpu_sentry.py import this from a
parent that must survive a wedged accelerator tunnel).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

from ..resilience.heartbeat import heartbeat_record
from .atomicio import atomic_write_json
from .metrics import MetricsRegistry, set_registry
from .tracer import SpanTracer, set_tracer

MANIFEST = "manifest.json"


def new_run_id() -> str:
    """Sortable, collision-resistant without coordination:
    <utc-stamp>-<pid>-<4 hex>."""
    return "{}-{}-{}".format(
        time.strftime("%Y%m%dT%H%M%S", time.gmtime()),
        os.getpid(),
        os.urandom(2).hex(),
    )


def default_run_dir(run_id: str) -> str:
    root = os.environ.get("KSPEC_RUNS_ROOT", "runs")
    return os.path.join(root, run_id)


_GIT_DESCRIBE_CACHE: dict = {}


def git_describe(cwd: Optional[str] = None) -> Optional[str]:
    # memoized per (process, cwd): the checkout cannot change under a
    # live process, and the serving daemon opens a RunContext PER JOB —
    # 30ms of `git describe` per verdict was the warm path's single
    # largest cost before the memo (bench.py --serve)
    key = cwd or os.path.dirname(os.path.abspath(__file__))
    if key in _GIT_DESCRIBE_CACHE:
        return _GIT_DESCRIBE_CACHE[key]
    try:
        p = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            cwd=key,
            capture_output=True,
            text=True,
            timeout=10,
        )
        out = p.stdout.strip() or None if p.returncode == 0 else None
    except Exception:
        # transient subprocess failure (timeout under load, fork error):
        # do NOT memoize — one bad moment must not stamp git=None on
        # every job of a serve-forever daemon.  A clean nonzero exit
        # ("not a git repository") IS deterministic and cached below.
        return None
    _GIT_DESCRIBE_CACHE[key] = out
    return out


# back-compat alias: the manifest writer moved to the public
# obs.atomicio.atomic_write_json (fsync rationale lives there)
_atomic_write_json = atomic_write_json


class RunContext:
    def __init__(self, run_dir: Optional[str] = None,
                 run_id: Optional[str] = None, durable: bool = True):
        """Open (creating if needed) a run directory.

        A fresh directory gets a new run_id + manifest; an existing one is
        *resumed*: its manifest's run_id is adopted and a lineage entry is
        appended (checkpoint lineage across supervised restarts).

        durable=False skips the per-write manifest fsync — for run dirs
        that are pure observability because the durable record lives
        elsewhere (the serving daemon's per-job dirs, whose contract is
        the queue's verdict file).  Writes stay atomic either way."""
        self.durable = durable
        existing = None
        if run_dir is not None and os.path.isfile(
            os.path.join(run_dir, MANIFEST)
        ):
            try:
                with open(os.path.join(run_dir, MANIFEST)) as fh:
                    existing = json.load(fh)
            except ValueError:
                existing = None  # torn manifest: treat as fresh
        if existing is not None and existing.get("run_id"):
            run_id = existing["run_id"]
        self.run_id = run_id or new_run_id()
        self.dir = os.path.normpath(run_dir or default_run_dir(self.run_id))
        os.makedirs(self.dir, exist_ok=True)
        self.manifest_path = os.path.join(self.dir, MANIFEST)
        self.stats_path = os.path.join(self.dir, "stats.jsonl")
        self.spans_path = os.path.join(self.dir, "spans.jsonl")
        self.metrics_jsonl = os.path.join(self.dir, "metrics.jsonl")
        self.metrics_prom = os.path.join(self.dir, "metrics.prom")
        self.events_path = os.path.join(self.dir, "events.jsonl")
        self.log_dir = os.path.join(self.dir, "logs")
        self.spill_dir = os.path.join(self.dir, "spill")

        self.tracer = SpanTracer(self.spans_path, self.run_id)
        self.metrics = MetricsRegistry(self.run_id)

        if existing is not None:
            self.manifest = existing
            self.manifest.setdefault("lineage", []).append(
                {"event": "reopen", "pid": os.getpid(),
                 **_ts_fields()}
            )
            self.manifest["status"] = "running"
            self.manifest["pid"] = os.getpid()
        else:
            self.manifest = {
                "run_id": self.run_id,
                "status": "running",
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "cwd": os.getcwd(),
                "git": git_describe(),
                "lineage": [
                    {"event": "open", "pid": os.getpid(), **_ts_fields()}
                ],
                **_ts_fields("created", "created_unix"),
            }
        self.write_manifest()

    # --- manifest ---------------------------------------------------------
    def write_manifest(self) -> None:
        _atomic_write_json(self.manifest_path, self.manifest,
                           fsync=self.durable)

    def update_manifest(self, **fields) -> None:
        self.manifest.update(fields)
        self.write_manifest()

    def record_config(self, **fields) -> None:
        """Stamp run configuration (module, engine, knobs...) — keys land
        under manifest['config'], merged across calls (a resumed run may
        re-record identical config; new keys win)."""
        cfg = self.manifest.setdefault("config", {})
        cfg.update({k: v for k, v in fields.items() if v is not None})
        self.write_manifest()

    # --- activation (global tracer/registry for deep call sites) ----------
    def activate(self) -> None:
        set_tracer(self.tracer)
        set_registry(self.metrics)

    def deactivate(self) -> None:
        self.tracer.xprof_force_stop()  # windows must flush even when a
        set_tracer(None)                # verdict cut the level loop early
        set_registry(None)
        self.tracer.close()

    # --- exports ----------------------------------------------------------
    def snapshot_metrics(self) -> None:
        self.metrics.write_jsonl(self.metrics_jsonl)
        self.metrics.write_prom(self.metrics_prom)

    def finish(self, status: str, **summary) -> None:
        """Terminal manifest update + final metric snapshot."""
        self.manifest["status"] = status
        self.manifest.setdefault("lineage", []).append(
            {"event": "finish", "status": status, **_ts_fields()}
        )
        if summary:
            self.manifest["result"] = summary
        self.write_manifest()
        self.snapshot_metrics()


def _ts_fields(ts_key: str = "ts", unix_key: str = "unix") -> dict:
    rec = heartbeat_record("x")
    return {ts_key: rec["ts"], unix_key: rec["unix"]}
