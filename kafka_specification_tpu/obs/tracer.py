"""Span tracer: nested, run_id-stamped spans to an untearable JSONL.

Every span record is one JSON line written with a single ``os.write`` on an
``O_APPEND`` file descriptor — the same append-only idiom the mosaic
ladder's per-rung banking uses: a hard kill can tear at most the final
line (the reader tolerates exactly that), never an earlier one.

Record shapes (all carry the shared heartbeat envelope kind/ts/unix from
``resilience.heartbeat`` plus ``run_id``):

    {"kind": "span",  "ph": "E", "span": "<span kind>", "span_id": ...,
     "parent_id": ..., "t0": ..., "ms": ..., <attrs>}       completed span
    {"kind": "span",  "ph": "B", "span": "<span kind>", ...}  begin marker
    {"kind": "event", "event": "<event kind>", <attrs>}     point-in-time

Begin markers are emitted only for the long-lived kinds the engines mark
explicitly (``level``) so a crash mid-level is visible in the log; every
other span lands as one "E" record at exit (span bodies that crash emit
nothing — the surrounding begin marker and the heartbeat stream carry the
forensics).

Deep call sites (storage spills, checkpoint writes, retry backoff) use the
module-level :func:`span` / :func:`event` helpers, which no-op unless a
run context is active — so the storage and resilience layers need no
plumbing and stay usable without the obs subsystem.

Optional ``jax.profiler`` windows: ``KSPEC_OBS_XPROF=<span_kind>[:<lo>[-<hi>]]``
arms a profiler trace (TensorBoard format, written under the run
directory's ``xprof/``) around spans of that kind whose ``depth`` attr
falls in the range — e.g. ``KSPEC_OBS_XPROF=level:3-5`` profiles BFS
levels 3..5.  jax is imported lazily and only when a window arms; the
tracer itself must stay jax-free (it is imported by supervisor parents
that never touch a possibly-wedged accelerator tunnel).

Must stay jax-free at import time.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

from ..resilience.heartbeat import heartbeat_record

XPROF_ENV = "KSPEC_OBS_XPROF"


def parse_xprof(spec: Optional[str]):
    """``"level:3-5"`` -> ("level", 3, 5); ``"level:3"`` -> ("level", 3, 3);
    ``"level"`` -> ("level", 0, inf).  None/empty -> None."""
    if not spec:
        return None
    kind, _, rng = spec.partition(":")
    kind = kind.strip()
    if not kind:
        raise ValueError(f"{XPROF_ENV}={spec!r}: empty span kind")
    if not rng:
        return kind, 0, float("inf")
    lo, sep, hi = rng.partition("-")
    try:
        lo_i = int(lo)
        hi_i = int(hi) if sep else lo_i
    except ValueError:
        raise ValueError(
            f"{XPROF_ENV}={spec!r}: range must be '<lo>[-<hi>]'"
        )
    return kind, lo_i, hi_i


class _SpanCM:
    """Context manager for one span (returned by SpanTracer.span)."""

    def __init__(self, tracer: "SpanTracer", kind: str, attrs: dict):
        self.tracer = tracer
        self.kind = kind
        self.attrs = attrs
        self.span_id = None
        self.t0 = None

    def __enter__(self):
        self.span_id, self.t0 = self.tracer._enter(self.kind, self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.tracer._exit(self.kind, self.span_id, self.t0, self.attrs,
                          error=exc_type.__name__ if exc_type else None)
        return False


class _NullCM:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CM = _NullCM()


class SpanTracer:
    def __init__(self, path: str, run_id: str):
        self.path = path
        self.run_id = run_id
        self._fd = None
        self._seq = 0
        # one tracer may be shared by concurrent in-process jobs (the
        # serving daemon's worker threads): the fd open / seq allocation /
        # close races are guarded here, and each record is a SINGLE
        # os.write on the O_APPEND fd — lines interleave whole, never torn.
        # The NESTING stack is per-thread (not merely locked): a shared
        # stack would attribute thread A's span to thread B's open parent,
        # which is nesting that never happened
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._xprof = parse_xprof(os.environ.get(XPROF_ENV))
        self._xprof_dir = os.path.join(os.path.dirname(path), "xprof")
        self._xprof_live = False

    # --- untearable append ------------------------------------------------
    def _write(self, rec: dict) -> None:
        payload = (json.dumps(rec) + "\n").encode()
        with self._lock:
            if self._fd is None:
                self._fd = os.open(
                    self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
                )
            os.write(self._fd, payload)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    # --- span protocol ----------------------------------------------------
    @property
    def _stack(self) -> list:
        """This thread's open-span-id stack (parent attribution)."""
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _next_id(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _enter(self, kind: str, attrs: dict):
        span_id = self._next_id()
        self._stack.append(span_id)
        self.xprof_maybe_start(kind, attrs.get("depth"))
        return span_id, time.time()

    def _exit(self, kind, span_id, t0, attrs, error=None):
        self.xprof_maybe_stop(kind)
        if self._stack and self._stack[-1] == span_id:
            self._stack.pop()
        parent = self._stack[-1] if self._stack else None
        t1 = time.time()
        rec = heartbeat_record(
            "span",
            t=t1,
            run_id=self.run_id,
            ph="E",
            span=kind,
            span_id=span_id,
            parent_id=parent,
            t0=round(t0, 3),
            ms=round((t1 - t0) * 1e3, 1),
            **attrs,
        )
        if error is not None:
            rec["error"] = error
        self._write(rec)

    def span(self, kind: str, **attrs) -> _SpanCM:
        return _SpanCM(self, kind, attrs)

    def emit_span(self, kind: str, t0: float, t1: float, **attrs) -> None:
        """Record an already-completed span from explicit timestamps — the
        zero-intrusion form for engine hot loops that already keep their
        own timers (no reindentation, no context manager overhead)."""
        parent = self._stack[-1] if self._stack else None
        self._write(
            heartbeat_record(
                "span",
                t=t1,
                run_id=self.run_id,
                ph="E",
                span=kind,
                span_id=self._next_id(),
                parent_id=parent,
                t0=round(t0, 3),
                ms=round((t1 - t0) * 1e3, 1),
                **attrs,
            )
        )

    def begin(self, kind: str, **attrs) -> None:
        """Emit a begin marker (ph=B) — crash forensics for long-lived
        spans: a 'B' with no matching 'E' pins where the run died."""
        self._write(
            heartbeat_record(
                "span",
                run_id=self.run_id,
                ph="B",
                span=kind,
                span_id=self._next_id(),
                **attrs,
            )
        )
        self.xprof_maybe_start(kind, attrs.get("depth"))

    def end(self, kind: str, t0: float, **attrs) -> None:
        """Close a begin-marked span by explicit start time (pairs with
        `begin`; the engines' level loop uses begin/end because wrapping
        the whole level body in a context manager is not practical)."""
        self.xprof_maybe_stop(kind)
        self.emit_span(kind, t0, time.time(), **attrs)

    def event(self, kind: str, **attrs) -> None:
        self._write(
            heartbeat_record("event", run_id=self.run_id, event=kind, **attrs)
        )

    # --- optional jax.profiler windows -------------------------------------
    def xprof_maybe_start(self, kind: str, depth) -> None:
        if self._xprof is None or self._xprof_live:
            return
        want_kind, lo, hi = self._xprof
        if kind != want_kind:
            return
        if depth is not None and not (lo <= depth <= hi):
            return
        try:
            import jax

            os.makedirs(self._xprof_dir, exist_ok=True)
            jax.profiler.start_trace(self._xprof_dir)
            self._xprof_live = True
            self.event("xprof-start", span=kind, depth=depth,
                       dir=self._xprof_dir)
        except Exception as e:  # profiling is best-effort, never a failure
            self._xprof = None  # don't retry every span
            print(f"[obs] {XPROF_ENV} window failed to start: {e}",
                  file=sys.stderr)

    def xprof_maybe_stop(self, kind: str) -> None:
        if not self._xprof_live or self._xprof is None:
            return
        if kind != self._xprof[0]:
            return
        self._xprof_stop(kind)

    def xprof_force_stop(self) -> None:
        """Flush any still-open window — a verdict/cutoff `break` exits
        the level loop without the span end that would close it."""
        if self._xprof_live and self._xprof is not None:
            self._xprof_stop(self._xprof[0])

    def _xprof_stop(self, kind: str) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._xprof_live = False
        self.event("xprof-stop", span=kind)


# --- module-level current tracer (deep call sites, zero plumbing) ---------
#
# Thread-LOCAL, not process-global: the serving daemon (service/daemon.py)
# runs multiple jobs in one process, each with its own RunContext — a
# global would let job B's activate() cross-stamp job A's spans with B's
# run_id.  Each thread sees only the tracer it activated; single-threaded
# callers (the CLI engines) behave exactly as before.
_active = threading.local()


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    _active.tracer = tracer


def current_tracer() -> Optional[SpanTracer]:
    return getattr(_active, "tracer", None)


def span(kind: str, **attrs):
    """Span context manager on the active tracer; no-op when none."""
    cur = current_tracer()
    return cur.span(kind, **attrs) if cur is not None else _NULL_CM


def event(kind: str, **attrs) -> None:
    """Point event on the active tracer; no-op when none."""
    cur = current_tracer()
    if cur is not None:
        cur.event(kind, **attrs)


def read_jsonl_tolerant(path: str) -> list:
    """Parse a JSONL file, skipping torn lines and blanks.

    The O_APPEND writers can tear only the FINAL line — but a supervised
    restart appends past its predecessor's torn tail (one shared
    stats/events file per run directory), so by the time `cli report`
    reads the stream a tear can sit anywhere.  Unparsable lines are
    skipped, never fatal: a report over a crashed run must render from
    whatever survived."""
    out = []
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return out
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue  # torn by a kill; the surrounding records stand alone
    return out
