"""Atomic file publication for the jax-free observability/serving plane.

One tmp + flush + (optional fsync) + ``os.replace`` sequence, shared by
every side-channel publisher that must never expose a torn file: run
manifests (obs/runctx.py), job specs and verdicts (service/queue.py),
route records and host tables (service/router.py), sweep manifests
(sweep/portfolio.py), and the ``metrics.prom`` textfile export
(obs/metrics.py).

This is a deliberate copy of ``storage.atomic.atomic_write``'s sequence:
importing the storage package would pull the native C++ FpSet into
jax-free supervisor parents, so the serving plane keeps its own leaf
module with zero intra-package imports.

``fsync=True`` is for records whose loss would sever a lineage (a power
loss publishing an empty manifest mints a new run_id on reopen).
``fsync=False`` is for scrape artifacts and per-job dirs whose durable
record lives elsewhere — at ~15ms per fsync on CI disks, five fsyncs per
job was the serving warm path's latency floor.

Must stay jax-free (imported by the router/queue/daemon import chain).
"""

from __future__ import annotations

import json
import os


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    """Publish ``text`` at ``path`` atomically (tmp + replace).

    A reader re-opening ``path`` mid-write never sees a torn file; a
    failed write (ENOSPC mid-dump, KeyboardInterrupt) never leaves a
    stray ``.tmp`` behind."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as fh:
            fh.write(text)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj: dict, fsync: bool = True) -> None:
    """Publish ``obj`` as JSON at ``path`` atomically (tmp + replace)."""
    atomic_write_text(path, json.dumps(obj, indent=1, default=str),
                      fsync=fsync)
