"""Atomic file publication for the jax-free observability/serving plane.

One tmp + flush + (optional fsync) + ``os.replace`` + parent-dir fsync
sequence, shared by every side-channel publisher that must never expose
a torn file: run manifests (obs/runctx.py), job specs and verdicts
(service/queue.py), route records and host tables (service/router.py),
sweep manifests (sweep/portfolio.py), and the ``metrics.prom`` textfile
export (obs/metrics.py).

This is a deliberate copy of ``storage.atomic.atomic_write``'s full
sequence — including the parent-directory fsync after the promote, which
this module historically omitted: without it a power loss after the
``os.replace`` but before the directory entry hits disk reverts the
rename, so an *acknowledged* publish (a job the client was told is in
pending/) could silently vanish.  The crashcheck harness
(``resilience/crashcheck``) enumerates exactly that state and keeps this
fixed.  Importing the storage package would pull the native C++ FpSet
into jax-free supervisor parents, so the serving plane keeps its own
leaf; both twins now share their primitives through the stdlib-only
``durable_io`` leaf (the crash-harness interposition point), which keeps
the zero-heavy-import contract intact.

``fsync=True`` is for records whose loss would sever a lineage (a power
loss publishing an empty manifest mints a new run_id on reopen).
``fsync=False`` is for scrape artifacts and per-job dirs whose durable
record lives elsewhere — at ~15ms per fsync on CI disks, five fsyncs per
job was the serving warm path's latency floor.  The parent-dir fsync is
tied to the same flag: a caller that opted out of data durability gets
no rename durability barrier either.

``tmp_nonce`` privatises the tmp name (``path.<nonce>.tmp``) for callers
whose writers race each other to the SAME final path (router route
records, sweep manifests): with the default shared ``path.tmp`` one
racer can replace/unlink the sibling's half-written tmp out from under
it (the PR 16 torn-promote precedent).  Nonce'd names still match the
startup janitor's ``sweep_tmp`` pattern.

Must stay jax-free (imported by the router/queue/daemon import chain).
"""

from __future__ import annotations

import json
import os

from .. import durable_io as _dio


def atomic_write_text(path: str, text: str, fsync: bool = True,
                      tmp_nonce: str = None) -> None:
    """Publish ``text`` at ``path`` atomically (tmp + replace + dir
    fsync).

    A reader re-opening ``path`` mid-write never sees a torn file; a
    failed write (ENOSPC mid-dump, KeyboardInterrupt) never leaves a
    stray ``.tmp`` behind; with ``fsync=True`` the publish survives a
    power loss (data fsync before the promote, directory fsync after)."""
    tmp = path + ".tmp" if tmp_nonce is None else f"{path}.{tmp_nonce}.tmp"
    try:
        with open(tmp, "w") as fh:
            fh.write(text)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        _dio.note_write(tmp, fsynced=fsync)
        _dio.replace(tmp, path)
    except BaseException:
        try:
            _dio.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        _dio.fsync_dir(os.path.dirname(path))


def atomic_write_json(path: str, obj: dict, fsync: bool = True,
                      tmp_nonce: str = None) -> None:
    """Publish ``obj`` as JSON at ``path`` atomically (tmp + replace)."""
    atomic_write_text(path, json.dumps(obj, indent=1, default=str),
                      fsync=fsync, tmp_nonce=tmp_nonce)
