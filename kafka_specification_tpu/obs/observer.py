"""RunObserver: the engines' one window into the obs subsystem.

Both engines used to hand-roll their per-level stats emission
(``heartbeat_record`` + ``append_jsonl``).  That call site is now a thin
shim over this class:

- with only ``stats_path`` (the pre-obs interface), the emitted records
  are **identical** to the historical stream — same envelope, same
  fields, same order, no run_id — so every existing consumer (the
  supervisor's stall detector, ``tail -f | jq``, the banked RUN*_stats
  artifacts) keeps working unchanged (tier-1 test: shim equivalence);
- with a :class:`~.runctx.RunContext`, the same records are additionally
  run_id-stamped, routed to the run directory's ``stats.jsonl``, folded
  into the metrics registry (states/sec, duplicate ratio, per-shard
  imbalance, wall-share counters), snapshotted to ``metrics.jsonl`` +
  ``metrics.prom`` every level, and bracketed by level spans.

Constructing an observer also (de)activates the module-global tracer and
metrics registry: a ``run=None`` engine call always *clears* them, so a
crashed traced run can never leak spans into a later untraced run in the
same process.

Must stay jax-free (the class; engines pass platform strings in).
"""

from __future__ import annotations

import time
from typing import Optional

from ..resilience.heartbeat import append_jsonl, heartbeat_record
from .metrics import set_registry
from .tracer import set_tracer


# metrics export cadence: toy models run thousands of millisecond-scale
# levels, and metrics.prom is an fsync'd whole-file rewrite — snapshot at
# most this often (scrapers poll in tens of seconds; finish() always
# writes the terminal snapshot)
_SNAPSHOT_MIN_INTERVAL_S = 5.0


class RunObserver:
    def __init__(self, run=None, stats_path: Optional[str] = None,
                 engine: str = "bfs"):
        self.run = run
        self.engine = engine
        self._last_snapshot = 0.0
        # legacy stream: exactly where the caller pointed it; the run
        # directory's stats.jsonl is the default only when a run is active
        self.stats_path = stats_path or (run.stats_path if run else None)
        self.active = run is not None
        # stats collection is on iff anyone consumes it (pre-obs semantics:
        # `collect_stats = stats_path is not None`)
        self.collect = self.stats_path is not None or self.active
        if run is not None:
            run.activate()
        else:
            set_tracer(None)
            set_registry(None)

    # --- configuration stamping -------------------------------------------
    def config(self, **fields) -> None:
        if self.run is not None:
            self.run.record_config(engine=self.engine, **fields)

    # --- per-level emission -----------------------------------------------
    def level_begin(self, depth: int, frontier: int) -> None:
        """Begin marker for the level span (crash forensics: a 'B' with no
        matching 'E' pins the level the run died in)."""
        if self.run is not None:
            self.run.tracer.begin("level", depth=depth, frontier=frontier)

    def level(self, **fields) -> dict:
        """Build + route the per-level heartbeat record.

        `fields` is the engine's historical record payload, in its
        historical order.  Returns the record (engines also keep it in
        result.stats['levels'])."""
        if self.run is not None:
            rec = heartbeat_record("level", run_id=self.run.run_id, **fields)
        else:
            rec = heartbeat_record("level", **fields)
        if self.stats_path is not None:
            append_jsonl(self.stats_path, rec)
        if self.run is not None:
            # span t0 back-computed from the record's own wall time (the
            # engines time levels with perf_counter, a different clock)
            t0 = time.time() - fields.get("level_ms", 0.0) / 1e3
            self.run.tracer.end(
                "level", t0, depth=fields.get("depth"),
                new=fields.get("new"), total=fields.get("total"),
            )
            self._fold_metrics(fields)
            now = time.time()
            if now - self._last_snapshot >= _SNAPSHOT_MIN_INTERVAL_S:
                self._last_snapshot = now
                self.run.snapshot_metrics()
        return rec

    def _fold_metrics(self, f: dict) -> None:
        m = self.run.metrics
        new = f.get("new", 0)
        dup = f.get("duplicates", 0)
        en = f.get("enabled_candidates", 0)
        lvl_ms = f.get("level_ms", 0.0)
        m.inc("kspec_levels_total")
        m.inc("kspec_states_total", new)
        m.inc("kspec_duplicates_total", dup)
        m.inc("kspec_enabled_candidates_total", en)
        m.set_gauge("kspec_depth", f.get("depth", 0))
        m.set_gauge("kspec_frontier", f.get("frontier", 0))
        m.set_gauge("kspec_states_distinct", f.get("total", 0))
        m.set_gauge("kspec_duplicate_ratio",
                    round(dup / en, 4) if en else 0.0)
        m.set_gauge("kspec_states_per_sec",
                    round(new / (lvl_ms / 1e3), 1) if lvl_ms else 0.0)
        m.observe("kspec_level_ms", lvl_ms)
        # host-vs-step wall share (single-device engine records both)
        if "step_ms" in f:
            m.inc("kspec_step_ms_total", f["step_ms"])
        if "host_ms" in f:
            m.inc("kspec_host_ms_total", f["host_ms"])
        # per-shard exchange balance (sharded engine)
        shard_new = f.get("shard_new")
        if shard_new:
            for d, v in enumerate(shard_new):
                m.set_gauge("kspec_shard_new", v, shard=d)
            mean = sum(shard_new) / len(shard_new)
            m.set_gauge(
                "kspec_shard_imbalance",
                round(max(shard_new) / mean, 3) if mean else 0.0,
            )
        for key, name in (
            ("shard_frontier", "kspec_shard_frontier"),
            ("shard_duplicates", "kspec_shard_duplicates"),
        ):
            vals = f.get(key)
            if vals:
                for d, v in enumerate(vals):
                    m.set_gauge(name, v, shard=d)

    # --- sub-level spans ---------------------------------------------------
    def chunk_span(self, kind: str, seconds: float, **attrs) -> None:
        """Record a completed chunk-phase span (step / host-assembly /
        dedup-insert / exchange) from the engine's own duration timer —
        no-op without a run."""
        if self.run is not None:
            t1 = time.time()
            self.run.tracer.emit_span(kind, t1 - seconds, t1, **attrs)

    # --- terminal ----------------------------------------------------------
    def abort(self, status: str, **detail) -> None:
        """Terminal manifest update for a non-CheckResult ending — the
        typed RESOURCE_EXHAUSTED clean exit (resilience.resources): the
        manifest's status is what `cli report`'s verdict keys on, and the
        detail (reason / depth / states so far) lands under result."""
        if self.run is not None:
            self.run.finish(status, **detail)

    def finish(self, result) -> None:
        """Fold the terminal CheckResult into metrics + manifest."""
        if self.run is None:
            return
        m = self.run.metrics
        s = result.stats or {}
        m.inc("kspec_transient_retries_total", s.get("transient_retries", 0))
        m.set_gauge("kspec_degradations", len(s.get("degradations", ())))
        spill = s.get("spill")
        spills = spill if isinstance(spill, list) else [spill]
        for d, sp in enumerate(spills):
            if not sp:
                continue
            labels = {"shard": d} if isinstance(spill, list) else {}
            m.set_gauge("kspec_spill_runs", sp.get("runs", 0), **labels)
            m.set_gauge("kspec_spill_hot_fps", sp.get("hot", 0), **labels)
            m.set_gauge("kspec_spill_disk_fps", sp.get("disk", 0), **labels)
            m.set_gauge("kspec_spill_spills", sp.get("spills", 0), **labels)
            m.set_gauge("kspec_spill_merges", sp.get("merges", 0), **labels)
            bt = sp.get("bloom_totals")
            if bt:
                m.inc("kspec_bloom_maybe_total", bt["bloom_maybe"])
                m.inc(
                    "kspec_bloom_filtered_total",
                    bt["probes"] - bt["bloom_maybe"],
                )
                m.inc("kspec_bloom_hits_total", bt["hits"])
        status = "violation" if result.violation is not None else "complete"
        summary = dict(
            model=result.model,
            distinct_states=result.total,
            diameter=result.diameter,
            seconds=round(result.seconds, 3),
            states_per_sec=round(result.states_per_sec, 1),
        )
        if result.violation is not None:
            summary["violation"] = {
                "invariant": result.violation.invariant,
                "depth": result.violation.depth,
                "trace_len": len(result.violation.trace),
            }
        self.run.finish(status, **summary)

    def close(self) -> None:
        if self.run is not None:
            self.run.deactivate()
