"""`cli report <run-dir>`: render a run directory into a human summary.

Works on any run directory — completed, still live, or crashed mid-level:
every input is optional and every JSONL stream is read torn-final-line
tolerantly (the only tear the O_APPEND writers can leave).  Never imports
jax: a report must render on a box whose accelerator tunnel is wedged,
which is exactly when you want it most.

Sections:
  header     run id / module / engine / status verdict
  levels     per-level table + states/sec sparkline (TLC's live coverage
             statistics, after the fact and correlated by run)
  actions    cumulative action-enablement histogram (TLC action coverage)
  spill      disk-tier accounting (runs/spills/merges/bloom gating)
  timeline   restarts, stall-kills, checkpoint fallbacks, retries,
             degradations — supervisor events + obs events, interleaved
  ETA        frontier growth-rate fit over the recent levels
  verdict    complete / violation / live / stalled / crashed — the stall
             rule is the supervisor's own (no heartbeat growth past the
             stall timeout), so `cli report` and the sentry always agree
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Optional

from .tracer import read_jsonl_tolerant

DEFAULT_STALL_TIMEOUT = 1800.0  # the supervisor's default
_SPARK = "▁▂▃▄▅▆▇█"
_EVENT_KINDS = (
    "retry",
    "compile-fallback",
    "chunk-degrade",
    "checkpoint-fallback",
    "elastic-reshard",
    "resource-pressure",
    "reclaim",
    "resource-exhausted",
    "integrity-violation",
    "pipeline-fallback",
    "xprof-start",
    "xprof-stop",
)


def load_run(run_dir: str) -> dict:
    """Collect everything a run directory holds, tolerating absences."""
    run_dir = os.path.normpath(run_dir)

    def maybe_json(name):
        p = os.path.join(run_dir, name)
        if os.path.isfile(p):
            try:
                with open(p) as fh:
                    return json.load(fh)
            except ValueError:
                return None  # torn manifest: the report still renders
        return None

    def jsonl(name):
        return read_jsonl_tolerant(os.path.join(run_dir, name))

    spans = jsonl("spans.jsonl")
    metrics = jsonl("metrics.jsonl")
    # per-process shard heartbeats (parallel/sharded.py writes one file
    # per process under <run-dir>/shards/): the only stream that tells a
    # multiprocess run's processes apart after the fact
    shard_streams = []
    shard_dir = os.path.join(run_dir, "shards")
    if os.path.isdir(shard_dir):
        for name in sorted(os.listdir(shard_dir)):
            if name.startswith("proc") and name.endswith(".jsonl"):
                recs = read_jsonl_tolerant(os.path.join(shard_dir, name))
                recs = [r for r in recs
                        if r.get("kind") == "shard-heartbeat"]
                if recs:
                    shard_streams.append(recs)
    return {
        "dir": run_dir,
        "manifest": maybe_json("manifest.json") or {},
        "levels": [r for r in jsonl("stats.jsonl") if r.get("kind") == "level"],
        "events": jsonl("events.jsonl"),
        "spans": [s for s in spans if s.get("kind") == "span"],
        "obs_events": [s for s in spans if s.get("kind") == "event"],
        "metrics": metrics[-1] if metrics else None,
        # full snapshot history: the resource-pressure timeline reads the
        # disk/RSS gauges ACROSS snapshots, not just the last one
        "metrics_history": metrics,
        "shard_heartbeats": shard_streams,
    }


def _pid_alive(pid) -> Optional[bool]:
    if not pid:
        return None
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except (OSError, ValueError):
        return None  # permission / foreign host: unknowable


def verdict(data: dict, now: Optional[float] = None) -> dict:
    """-> {status, detail}: the stall rule is the supervisor's (heartbeat
    growth within the stall timeout), so report and sentry agree."""
    man = data["manifest"]
    status = man.get("status")
    if status in ("complete", "violation", "error", "resource-exhausted",
                  "integrity-violation"):
        # resource-exhausted / integrity-violation are TERMINAL, not
        # crashes: the run exited typed (75 / 76); the detail says what
        # ran out or which integrity check tripped
        return {"status": status, "detail": man.get("result", {})}
    now = time.time() if now is None else now
    beats = [r.get("unix") for r in data["levels"] if r.get("unix")]
    beats += [r.get("unix") for r in data["spans"] if r.get("unix")]
    beats += [r.get("unix") for r in data["events"] if r.get("unix")]
    for stream in data.get("shard_heartbeats", ()):
        beats += [r.get("unix") for r in stream if r.get("unix")]
    last = max(beats) if beats else man.get("unix") or man.get("created_unix")
    age = (now - last) if last else None
    timeout = float(
        (man.get("config") or {}).get("stall_timeout") or DEFAULT_STALL_TIMEOUT
    )
    # a supervisor give-up is terminal ONLY for the current attempt chain:
    # reopening the run dir (a new `cli check --run-dir` on it) appends a
    # fresh open/reopen lineage entry, and give-ups older than that must
    # not shadow the live run
    last_open = max(
        (e.get("unix", 0) for e in man.get("lineage", ())
         if e.get("event") in ("open", "reopen")),
        default=0,
    )
    for ev in reversed(data["events"]):
        if ev.get("event") == "give-up" and ev.get("unix", 0) >= last_open:
            return {
                "status": "crashed",
                "detail": {"supervisor": "gave up", "last_heartbeat_age_s":
                           round(age, 1) if age is not None else None},
            }
    alive = _pid_alive(man.get("pid"))
    if alive is False:
        return {
            "status": "crashed",
            "detail": {
                "pid": man.get("pid"),
                "last_heartbeat_age_s": round(age, 1) if age else None,
            },
        }
    if age is not None and age > timeout:
        return {
            "status": "stalled",
            "detail": {
                "last_heartbeat_age_s": round(age, 1),
                "stall_timeout_s": timeout,
            },
        }
    return {
        "status": "live",
        "detail": {"last_heartbeat_age_s": round(age, 1) if age is not None
                   else None},
    }


def _shard_proc_summary(data: dict) -> list:
    """One row per process of a (multi)process sharded run, from its
    shard-heartbeat stream: pid, owned shards, last completed level."""
    procs = []
    for stream in data.get("shard_heartbeats", ()):
        last = stream[-1]
        procs.append({
            "proc": last.get("proc"),
            "pid": last.get("pid"),
            "shards": last.get("shards"),
            "last_depth": max(
                (r.get("depth") for r in stream
                 if r.get("depth") is not None),
                default=None,
            ),
            "last_unix": last.get("unix"),
            "alive": _pid_alive(last.get("pid")),
            "finished": any(r.get("event") == "finish" for r in stream),
        })
    return procs


def _died_shards(procs: list) -> list:
    """Which process(es) a died-mid-level verdict points at.

    Preference order: known-dead pids that never finished; else any
    unfinished process.  Among those, the one(s) that stopped a level
    behind the rest died first (a lockstep fleet cannot advance past a
    dead peer, so the laggard is the culprit); a level tie falls back to
    the stalest heartbeat."""
    cands = [p for p in procs if p["alive"] is False and not p["finished"]]
    if not cands:
        cands = [p for p in procs if not p["finished"]]
    if not cands:
        return []
    lo = min((p["last_depth"] or 0) for p in cands)
    behind = [p for p in cands if (p["last_depth"] or 0) == lo]
    if len(behind) < len(cands) or len(cands) == 1:
        return behind
    t = min((p["last_unix"] or 0) for p in cands)
    return [p for p in cands if (p["last_unix"] or 0) == t]


def eta(levels: list, window: int = 5) -> dict:
    """Frontier growth-rate fit: log-linear least squares on the per-level
    new-state counts over the last `window` levels.  A decaying frontier
    (ratio < 1) extrapolates the geometric tail into a finite remaining
    count and, via the recent throughput, a time estimate; a flat or
    growing frontier is honestly unbounded (BFS cannot know its horizon).
    """
    pts = [(r["depth"], r["new"]) for r in levels
           if r.get("new", 0) > 0 and "depth" in r]
    if len(pts) < 3:
        return {"status": "insufficient-data"}
    pts = pts[-window:]
    xs = [p[0] for p in pts]
    ys = [math.log(p[1]) for p in pts]
    n = len(pts)
    mx, my = sum(xs) / n, sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / max(denom, 1e-12)
    ratio = math.exp(slope)
    recent = levels[-window:]
    wall_ms = sum(r.get("level_ms", 0.0) for r in recent)
    new_sum = sum(r.get("new", 0) for r in recent)
    rate = new_sum / (wall_ms / 1e3) if wall_ms else None
    out = {"status": "fit", "growth_ratio": round(ratio, 3),
           "recent_states_per_sec": round(rate, 1) if rate else None}
    if ratio < 0.999:
        remaining = pts[-1][1] * ratio / (1.0 - ratio)
        out["est_remaining_states"] = int(remaining)
        # levels until the geometric tail drops below one new state
        out["est_remaining_levels"] = (
            max(1, int(math.ceil(-math.log(pts[-1][1]) / math.log(ratio))))
            if pts[-1][1] > 1
            else 1
        )
        if rate:
            # THE shared flat-throughput estimator (sweep/cost.py): the
            # per-run ETA and the sweep cost model's per-point wall
            # predictions compute remaining/rate in exactly one place,
            # so the two prediction paths cannot drift (same rounding,
            # same None-handling).  Output shape unchanged.
            from ..sweep.cost import flat_time_estimate

            out["eta_seconds"] = flat_time_estimate(remaining, rate)
    else:
        out["note"] = "frontier not yet decaying; ETA unbounded"
    return out


def _spark(vals: list) -> str:
    if not vals:
        return ""
    hi = max(vals) or 1
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int(v / hi * (len(_SPARK) - 1)))] for v in vals)


def _fmt_dur(s: Optional[float]) -> str:
    if s is None:
        return "?"
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.1f}m"
    return f"{s / 3600:.1f}h"


def report_data(run_dir: str, now: Optional[float] = None) -> dict:
    """The machine-readable report (cli report --json)."""
    data = load_run(run_dir)
    levels = data["levels"]
    man = data["manifest"]
    actions: dict = {}
    for r in levels:
        for name, c in (r.get("action_enablement") or {}).items():
            actions[name] = actions.get(name, 0) + int(c)
    # spill accounting: last metrics snapshot (finish-time gauges when the
    # run completed, live counters either way) + span aggregates
    snap = data["metrics"] or {}
    spill = {
        k: v
        for src in ("gauges", "counters")
        for k, v in snap.get(src, {}).items()
        if k.startswith(("kspec_spill_", "kspec_bloom_"))
    }
    span_agg: dict = {}
    for s in data["spans"]:
        if s.get("ph") != "E":
            continue
        k = s.get("span")
        a = span_agg.setdefault(k, {"count": 0, "ms": 0.0})
        a["count"] += 1
        a["ms"] += s.get("ms", 0.0)
    timeline = []
    for ev in data["events"]:
        if ev.get("kind") == "supervisor":
            timeline.append(ev)
    for ev in data["obs_events"]:
        if ev.get("event") in _EVENT_KINDS:
            timeline.append(ev)
    timeline.sort(key=lambda e: e.get("unix", 0))
    # unclosed level begin marker = died mid-level
    open_level = None
    closed = {s.get("depth") for s in data["spans"]
              if s.get("span") == "level" and s.get("ph") == "E"}
    for s in data["spans"]:
        if s.get("span") == "level" and s.get("ph") == "B" \
                and s.get("depth") not in closed:
            open_level = s.get("depth")
    shard_procs = _shard_proc_summary(data)
    resource = _resource_pressure(data)
    vd = verdict(data, now=now)
    died = (
        _died_shards(shard_procs)
        if vd["status"] in ("crashed", "stalled")
        else []
    )
    return {
        "run_id": man.get("run_id") or os.path.basename(data["dir"]),
        "dir": data["dir"],
        "manifest": man,
        "verdict": vd,
        "levels": levels,
        "actions": actions,
        "spill": spill,
        "spans": span_agg,
        "timeline": timeline,
        "eta": eta(levels),
        "open_level": open_level,
        "shard_procs": shard_procs,
        "died_shards": died,
        "resource": resource,
        "integrity": _integrity(data),
        "overlap": _overlap(data),
        "launches": _launches(data),
        "host_probe": _host_probe(data),
    }


def _integrity(data: dict) -> dict:
    """Integrity beat (resilience.integrity): how many always-on checks
    and shadow samples ran, and any violation events."""
    snap = data.get("metrics") or {}
    counters = snap.get("counters") or {}
    return {
        "checks": counters.get("kspec_integrity_checks_total", 0),
        "shadow_samples": counters.get("kspec_integrity_shadow_total", 0),
        "violations": counters.get("kspec_integrity_violations_total", 0),
        "events": [
            e
            for e in data["obs_events"]
            if e.get("event") == "integrity-violation"
        ],
    }


def _launches(data: dict) -> dict:
    """Launches-per-level beat: the `kspec_successor_launches_level`
    gauge history (metrics snapshots) + the per-chunk `step` span
    launch counts.  <=2/level is the device-resident pipeline's launch
    contract; the fused path shows 2x chunks, legacy O(actions)x chunks
    — the emitted stats stream stays record-for-record historical, so
    this beat reads the gauge/span side channels only.  The sharded
    twin `kspec_shard_launches_level` counts dispatched collective-
    bearing programs per level (= launches PER SHARD): O(1)/level under
    the sharded device pipeline vs O(chunks) per-chunk."""
    series = []
    shard_series = []
    for snap in data.get("metrics_history") or ():
        g = snap.get("gauges") or {}
        v = g.get("kspec_successor_launches_level")
        if v is not None:
            series.append(v)
        sv = g.get("kspec_shard_launches_level")
        if sv is not None:
            shard_series.append(sv)
    last = (data.get("metrics") or {}).get("gauges") or {}
    out = {
        "series": series,
        "last": last.get("kspec_successor_launches_level"),
        "max": max(series) if series else None,
        "shard_series": shard_series,
        "shard_last": last.get("kspec_shard_launches_level"),
        "shard_max": max(shard_series) if shard_series else None,
    }
    out["present"] = (
        bool(series) or out["last"] is not None
        or bool(shard_series) or out["shard_last"] is not None
    )
    return out


def _host_probe(data: dict) -> dict:
    """Deferred batched host-probe beat: the `kspec_host_probe_ms`
    gauge history (metrics snapshots).  Set only by the host-backend
    device-resident pipelines — ONE batched FpSet / tiered-run probe
    per level — so its presence is itself the proof the deferred path
    engaged; the value is the per-level wall of that one call.  Reads
    the gauge side channel only (the emitted stats stream stays
    record-for-record historical, like the launch counters)."""
    series = []
    for snap in data.get("metrics_history") or ():
        v = (snap.get("gauges") or {}).get("kspec_host_probe_ms")
        if v is not None:
            series.append(v)
    last = ((data.get("metrics") or {}).get("gauges") or {}).get(
        "kspec_host_probe_ms"
    )
    return {
        "series": series,
        "last": last,
        "max": max(series) if series else None,
        "present": bool(series) or last is not None,
    }


def _overlap(data: dict) -> dict:
    """Async-overlap beat (KSPEC_OVERLAP, docs/engine.md § Async
    execution): how much storage/checkpoint/exchange wall hid behind
    device compute.  `kspec_overlap_efficiency` is the per-level gauge
    (1.0 = every background-I/O second overlapped; snapshots give its
    history), the io counters are run totals, and `exposed_io_stalled`
    is the machine-readable acceptance signal for ROADMAP item 2's
    "storage I/O fully hidden": True when more exposed than hidden I/O
    wall accumulated — the engine is stalling on I/O it should hide."""
    last = data.get("metrics") or {}
    counters = last.get("counters") or {}
    gauges = last.get("gauges") or {}
    series = []
    for snap in data.get("metrics_history") or ():
        v = (snap.get("gauges") or {}).get("kspec_overlap_efficiency")
        if v is not None:
            series.append(v)
    hidden = counters.get("kspec_io_hidden_ms_total", 0)
    exposed = counters.get("kspec_io_exposed_ms_total", 0)
    out = {
        "efficiency": gauges.get("kspec_overlap_efficiency"),
        "series": series,
        "io_hidden_ms": hidden,
        "io_exposed_ms": exposed,
        "exchange_bytes_level": gauges.get("kspec_exchange_bytes_level"),
        "exchange_compression_ratio": gauges.get(
            "kspec_exchange_compression_ratio"
        ),
        "exposed_io_stalled": bool(
            (hidden + exposed) > 0 and exposed > hidden
        ),
    }
    out["present"] = bool(
        series
        or hidden
        or exposed
        or out["efficiency"] is not None
        or out["exchange_compression_ratio"] is not None
    )
    return out


def _resource_pressure(data: dict) -> dict:
    """Disk/RSS pressure timeline (resilience.resources): gauge history
    across metric snapshots + reclaim / exhaustion events."""
    series: dict = {}
    for snap in data.get("metrics_history") or ():
        for key in (
            "kspec_disk_used_bytes",
            "kspec_rss_bytes",
        ):
            v = (snap.get("gauges") or {}).get(key)
            if v is not None:
                series.setdefault(key, []).append(v)
    last = data.get("metrics") or {}
    gauges = last.get("gauges") or {}
    events = [
        e
        for e in data["obs_events"]
        if e.get("event") in ("resource-pressure", "reclaim",
                              "resource-exhausted", "chunk-degrade")
    ]
    out = {
        "disk_used": gauges.get("kspec_disk_used_bytes"),
        "disk_budget": gauges.get("kspec_disk_budget_bytes"),
        "rss": gauges.get("kspec_rss_bytes"),
        "rss_budget": gauges.get("kspec_rss_budget_bytes"),
        "series": series,
        "events": events,
        "reclaims": (last.get("counters") or {}).get(
            "kspec_reclaims_total", 0
        ),
    }
    out["present"] = bool(
        events
        or out["disk_budget"]
        or out["rss_budget"]
        or any(series.values())
    )
    return out


def _last_level_record(stats_path: str, tail_bytes: int = 65536) -> dict:
    """Last "level" record of a stats.jsonl, reading only a bounded tail
    of the file — the run index must stay O(runs), not O(levels), and a
    long run's stats stream is thousands of lines.  The first line of the
    tail window may be torn by the seek (and the writer may have torn the
    final line mid-crash); both parse-fail and are skipped."""
    try:
        with open(stats_path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - tail_bytes))
            lines = fh.read().splitlines()
    except OSError:
        return {}
    for raw in reversed(lines):
        try:
            rec = json.loads(raw)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("kind") == "level":
            return rec
    return {}


def list_runs(root: str, limit: int = 20) -> list:
    """Index the run directories under `root`, newest first — the
    operator's ls once a serving daemon multiplies run dirs.  Each row is
    built from the manifest + last stats line only (no full report load:
    the index must stay O(runs), not O(levels))."""
    rows = []
    try:
        names = os.listdir(root)
    except OSError:
        return rows
    for name in names:
        d = os.path.join(root, name)
        man_path = os.path.join(d, "manifest.json")
        if not os.path.isfile(man_path):
            continue
        try:
            with open(man_path) as fh:
                man = json.load(fh)
        except (OSError, ValueError):
            man = {}
        cfg = man.get("config") or {}
        result = man.get("result") or {}
        last_level = _last_level_record(os.path.join(d, "stats.jsonl"))
        status = man.get("status", "?")
        if status == "running":
            # refine cheaply: a dead pid means crashed, not live
            if _pid_alive(man.get("pid")) is False:
                status = "crashed"
        try:
            mtime = os.path.getmtime(man_path)
        except OSError:
            mtime = 0
        rows.append({
            "run_id": man.get("run_id") or name,
            "dir": d,
            "status": status,
            "module": cfg.get("module") or cfg.get("model"),
            "engine": cfg.get("engine"),
            "service": (cfg.get("service") or {}).get("job_id"),
            "states": result.get("distinct_states")
            or last_level.get("total"),
            "states_per_sec": result.get("states_per_sec"),
            "depth": result.get("diameter") or last_level.get("depth"),
            "created": man.get("created"),
            "mtime": mtime,
        })
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows[:limit]


def render_run_index(root: str, rows: list) -> str:
    if not rows:
        return f"no runs under {root}"
    out = [f"Runs under {root} ({len(rows)} most recent):"]
    out.append(
        f"  {'run_id':<28} {'status':<12} {'module':<22} "
        f"{'states':>12} {'k/s':>8}  job"
    )
    for r in rows:
        sps = r.get("states_per_sec")
        out.append(
            f"  {str(r['run_id'])[:28]:<28} {str(r['status'])[:12]:<12} "
            f"{str(r.get('module') or '?')[:22]:<22} "
            f"{r.get('states') if r.get('states') is not None else '?':>12} "
            f"{(sps / 1e3 if sps else 0.0):>8.1f}  "
            f"{r.get('service') or ''}"
        )
    out.append("  (render one with `cli report <dir>` or `--latest`)")
    return "\n".join(out)


def _parse_prom(path: str) -> dict:
    """Parse one Prometheus textfile export into ``{key: value}``.

    The key is the metric name plus its labels with the daemon-identity
    labels (``run_id``, ``instance``, ``host``) stripped: every daemon
    stamps its own identity so scraped series never collide, but a
    cross-daemon rollup must sum ACROSS restarts and instances, not
    treat each incarnation as a new series.
    Histogram series are skipped — the rollup wants counters/gauges."""
    out: dict = {}
    try:
        with open(path) as fh:
            lines = fh.read().splitlines()
    except OSError:
        return out
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        try:
            key, val = ln.rsplit(" ", 1)
            value = float(val)
        except ValueError:
            continue
        base, _, labels = key.partition("{")
        if base.endswith(("_bucket", "_sum", "_count")):
            continue
        kept = [
            part for part in labels.rstrip("}").split(",")
            if part and not part.startswith(
                ("run_id=", "instance=", "host=")
            )
        ]
        if kept:
            out["{}{{{}}}".format(base, ",".join(sorted(kept)))] = value
        else:
            out[base] = value
    return out


# gauges describe the ONE shared queue every daemon of a host sees, so a
# per-host rollup takes the max across daemons instead of summing
_ROLLUP_GAUGES = (
    "kspec_svc_queue_pending",
    "kspec_svc_queue_claimed",
)


def host_metrics_rollup(service_dir: str) -> dict:
    """Sum every daemon's ``metrics*.prom`` under one host's service dir
    (counters summed, shared-queue gauges maxed) — the per-host row of
    the router report."""
    try:
        names = sorted(
            n for n in os.listdir(service_dir)
            if n.startswith("metrics") and n.endswith(".prom")
        )
    except OSError:
        names = []
    rolled: dict = {}
    for name in names:
        for key, value in _parse_prom(
            os.path.join(service_dir, name)
        ).items():
            base = key.partition("{")[0]
            if base in _ROLLUP_GAUGES:
                rolled[key] = max(rolled.get(key, 0.0), value)
            else:
                rolled[key] = rolled.get(key, 0.0) + value
    return rolled


def router_report_data(router_dir: str) -> dict:
    """The cross-host rollup for a router directory: per-host health +
    queue depths (the router's own view) joined with each host's summed
    daemon metrics, plus fleet-wide totals and the router event tally.
    Jax-free like everything in obs."""
    from ..service.router import Router

    router = Router(router_dir)
    data = router.overview()
    totals: dict = {}
    for h in data["hosts"]:
        rolled = host_metrics_rollup(os.path.join(h["dir"], "service"))
        h["metrics"] = rolled
        for key, value in rolled.items():
            # summing is right even for the queue gauges here: across
            # HOSTS they describe distinct queues
            totals[key] = totals.get(key, 0.0) + value
    data["totals"] = totals
    events: dict = {}
    for rec in read_jsonl_tolerant(router.events_path):
        kind = rec.get("event")  # records are kind="router", event=<what>
        if kind:
            events[kind] = events.get(kind, 0) + 1
    data["events"] = events
    return data


def render_router_report(data: dict) -> str:
    out = [
        f"Router {data['dir']}: {len(data['hosts'])} hosts, "
        f"{data['routes']} routed jobs, dead after "
        f"{data['dead_after_s']}s (+{data['clock_skew_s']}s skew "
        "allowance)"
    ]
    for h in data["hosts"]:
        age = h["hb_age_s"]
        m = h.get("metrics") or {}
        jobs = sum(
            v for k, v in m.items()
            if k.startswith("kspec_svc_jobs_total")
        )
        hits = m.get("kspec_svc_state_cache_hits_total", 0)
        falls = m.get("kspec_svc_state_cache_fallbacks_total", 0)
        out.append(
            f"  host{h['host']} [{h['state']:>6}] {h['dir']}: "
            f"{h['pending']} pending, {h['claimed']} in flight, "
            f"{jobs:.0f} verdicts, cache {hits:.0f} hits/"
            f"{falls:.0f} fallbacks, heartbeat "
            + ("never" if age is None else f"{age:.1f}s ago")
        )
    ev = data.get("events") or {}
    if ev:
        out.append(
            "  router events: "
            + ", ".join(f"{k}={ev[k]}" for k in sorted(ev))
        )
    t = data.get("totals") or {}
    done = sum(
        v for k, v in t.items() if k.startswith("kspec_svc_jobs_total")
    )
    out.append(
        f"  fleet totals: {done:.0f} verdicts, "
        f"{t.get('kspec_svc_state_cache_hits_total', 0):.0f} cache hits, "
        f"{t.get('kspec_svc_takeovers_total', 0):.0f} takeovers"
    )
    return "\n".join(out)


def sweep_report_data(sweep_dir: str) -> dict:
    """The sweep rollup for a sweep directory (``sweep.json``,
    kspec-sweep/1): coverage, the per-invariant minimal-violating-config
    frontier, scaling-law curves (states vs axis value), and estimator
    accuracy.  Jax-free like everything in obs."""
    from ..sweep.bisect import frontier_from_manifest
    from ..sweep.portfolio import load_manifest

    man = load_manifest(sweep_dir)
    points = man.get("points", {})
    counts = {"done": 0, "skipped": 0, "error": 0, "pending": 0,
              "submitted": 0, "hit": 0, "seeded": 0, "violations": 0}
    skipped_rows = []
    residuals = []
    ratios = []
    for row in points.values():
        st = row.get("status", "pending")
        counts[st] = counts.get(st, 0) + 1
        cache = row.get("cache") or {}
        if cache.get("state_cache") == "hit":
            counts["hit"] += 1
        elif cache.get("state_cache") == "seed":
            counts["seeded"] += 1
        if (row.get("verdict") or {}).get("violation"):
            counts["violations"] += 1
        if st == "skipped":
            skipped_rows.append({
                "point_id": row.get("point_id"),
                "coords": row.get("coords"),
                "skip": row.get("skip"),
            })
        if row.get("residual") is not None:
            residuals.append(float(row["residual"]))
            pred = (row.get("predicted") or {}).get("states")
            act = (row.get("actual") or {}).get("states")
            if pred and act:
                ratios.append(act / pred)
    # scaling laws: for each axis, median states among DONE clean rows
    # per axis value (in declared order) — the states-vs-config-size
    # curve the lattice exists to measure
    curves: dict = {}
    axis_order: dict = {}
    for sheet in (man.get("lattice") or {}).get("sheets", []):
        for axis in sheet.get("axes", []):
            axis_order.setdefault(axis["name"], list(axis["values"]))
    for name, values in axis_order.items():
        per_value: dict = {}
        for row in points.values():
            v = row.get("verdict") or {}
            if row.get("status") != "done" or v.get("violation"):
                continue
            if v.get("distinct_states") is None:
                continue
            for cname, cval in row.get("coords", []):
                if cname == name:
                    key = json.dumps(cval)
                    per_value.setdefault(key, []).append(
                        int(v["distinct_states"])
                    )
        curve = []
        for val in values:
            samples = sorted(per_value.get(json.dumps(val), []))
            if samples:
                curve.append({
                    "value": val,
                    "median_states": samples[len(samples) // 2],
                    "n": len(samples),
                })
        if len(curve) >= 2:
            curves[name] = curve
    acc = None
    if residuals:
        mean = sum(residuals) / len(residuals)
        acc = {
            "n": len(residuals),
            "mean_log_residual": round(mean, 3),
            "mean_abs_log_residual": round(
                sum(abs(r) for r in residuals) / len(residuals), 3
            ),
            # the operator-facing phrasing: actual = predicted * factor
            "median_actual_over_predicted": round(
                sorted(ratios)[len(ratios) // 2], 2
            ) if ratios else None,
        }
    return {
        "dir": sweep_dir,
        "schema": man.get("schema"),
        "sweep_id": man.get("sweep_id"),
        "name": man.get("name"),
        "points": len(points),
        "counts": counts,
        "skipped": skipped_rows,
        "frontiers": {
            inv: [
                {
                    "point_id": r.get("point_id"),
                    "coords": r.get("coords"),
                    "indices": r.get("_indices"),
                    "depth": (
                        (r.get("verdict") or {}).get("violation") or {}
                    ).get("depth"),
                }
                for r in rows
            ]
            for inv, rows in frontier_from_manifest(man).items()
        },
        "curves": curves,
        "estimator": acc,
        "cost_model": man.get("cost_model"),
    }


def render_sweep_report(data: dict) -> str:
    c = data["counts"]
    out = [
        f"Sweep {data['name']} ({data['sweep_id']}) — {data['points']} "
        f"points: {c['done']} done ({c['hit']} cache hits, "
        f"{c['seeded']} seeded), {c['skipped']} skipped, "
        f"{c['error']} errors, {c['pending'] + c['submitted']} pending, "
        f"{c['violations']} violations"
    ]
    if data["skipped"]:
        out.append("  skipped (statically vacuous — auditable, typed):")
        for row in data["skipped"][:8]:
            finds = (row.get("skip") or {}).get("findings") or []
            acts = ", ".join(
                f.get("target", "?") for f in finds[:3]
            )
            out.append(
                f"    {dict(row.get('coords') or [])}: "
                f"skipped: vacuous [{acts}]"
            )
        if len(data["skipped"]) > 8:
            out.append(f"    ... and {len(data['skipped']) - 8} more")
    for inv, rows in sorted((data.get("frontiers") or {}).items()):
        out.append(f"  minimal violating configs — {inv}:")
        for r in rows:
            out.append(
                f"    {dict(r.get('coords') or [])}"
                + (
                    f" (violates at depth {r['depth']})"
                    if r.get("depth") is not None
                    else ""
                )
            )
    for name, curve in sorted((data.get("curves") or {}).items()):
        states = [pt["median_states"] for pt in curve]
        out.append(
            f"  scaling law — states vs {name}: "
            f"{_spark(states)}  "
            + " ".join(
                f"{pt['value']}→{pt['median_states']}" for pt in curve
            )
        )
    acc = data.get("estimator")
    if acc:
        out.append(
            f"  estimator: {acc['n']} residuals, mean log error "
            f"{acc['mean_log_residual']:+.3f} (abs "
            f"{acc['mean_abs_log_residual']:.3f}), median actual/"
            f"predicted {acc['median_actual_over_predicted']}"
        )
    cm = data.get("cost_model") or {}
    if cm.get("n_records"):
        out.append(
            f"  cost model: fit over {cm['n_records']} corpus records, "
            f"throughput {cm.get('states_per_sec')}/s, recalibration "
            f"shift {cm.get('residual_shift', 0):+.3f}"
        )
    return "\n".join(out)


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024:
            return f"{n:,.0f}{unit}" if unit == "B" else f"{n:,.1f}{unit}"
        n /= 1024
    return f"{n:,.1f}TiB"


def render_report(run_dir: str, now: Optional[float] = None,
                  max_rows: int = 40) -> str:
    r = report_data(run_dir, now=now)
    man, levels = r["manifest"], r["levels"]
    cfg = man.get("config") or {}
    out = []
    v = r["verdict"]
    out.append(f"Run {r['run_id']}  [{v['status'].upper()}]")
    svc = cfg.get("service") or {}
    if svc:
        # checking-as-a-service run: which job/tenant this run served and
        # whether it rode the warm compile cache / a batched group
        out.append(
            "  service: job "
            + str(svc.get("job_id", "?"))
            + f"  tenant {svc.get('tenant', '?')}"
            + (
                f"  batched x{svc['group_size']}"
                if svc.get("group_size", 1) > 1
                else ""
            )
            + (
                "  compile-cache HIT"
                if svc.get("cache_hit")
                else "  compile-cache miss (cold shape)"
            )
            + (
                f"  leader run {svc['leader_run_id']}"
                if svc.get("leader_run_id")
                else ""
            )
            + (
                "  state-cache SEED"
                if svc.get("state_cache_seed")
                else ""
            )
        )
        if svc.get("takeover"):
            # lease takeover: this run serves a job a DIFFERENT daemon
            # claimed first and abandoned (died or wedged); the janitor
            # attribution rides the job spec into the run manifest
            t = svc["takeover"]
            out.append(
                "  takeover: requeued from pid "
                + str(t.get("from_pid", "?"))
                + f" ({t.get('reason', '?')})"
                + f" by janitor pid {t.get('by_pid', '?')}"
            )
    bits = [
        f"module={cfg.get('module') or cfg.get('model') or '?'}",
        f"engine={cfg.get('engine', '?')}",
    ]
    if cfg.get("platform"):
        bits.append(f"platform={cfg['platform']}")
    if man.get("git"):
        bits.append(f"git={man['git']}")
    if cfg.get("mem_budget"):
        bits.append(f"mem_budget={cfg['mem_budget']}")
    restarts = sum(
        1 for e in r["timeline"]
        if e.get("kind") == "supervisor" and e.get("event") == "restart"
    )
    if restarts:
        bits.append(f"restarts={restarts}")
    out.append("  " + "  ".join(bits))
    if v["detail"]:
        out.append("  " + json.dumps(v["detail"], default=str))
    if v["status"] == "resource-exhausted":
        # the verdict beat: this run did NOT crash — it checkpointed and
        # exited typed (exit code 75) because it ran out of something;
        # tell the operator exactly what to do next
        d = v["detail"] or {}
        out.append(
            f"  RESOURCE EXHAUSTED: {d.get('reason', '?')} at level "
            f"{d.get('depth', '?')} after {d.get('distinct_states', '?')} "
            f"distinct states — clean typed exit, checkpoint intact."
        )
        out.append(
            "  next: free space (or raise --disk-budget), confirm with "
            "`cli verify-checkpoint`, then re-run the same command to "
            "resume — or supervise with --reclaim for one automatic "
            "prune-and-retry."
        )
    if v["status"] == "integrity-violation":
        # the verdict beat: a state-integrity check tripped (exit code
        # 76) — the run's data, not its progress, was the problem
        d = v["detail"] or {}
        out.append(
            f"  INTEGRITY VIOLATION: site {d.get('site', '?')} at level "
            f"{d.get('depth', '?')} after {d.get('distinct_states', '?')} "
            f"distinct states — silent corruption detected, typed exit."
        )
        out.append(
            "  next: `cli verify-checkpoint` shows which generations are "
            "chain-verified; re-running resumes from the newest one "
            "(corrupted generations are skipped automatically).  "
            "Recurring violations on one host suggest failing "
            "hardware — re-run the single-device engine with "
            "`--integrity-shadow 1.0` to localize."
        )
    integ = r.get("integrity") or {}
    if integ.get("checks") or integ.get("shadow_samples") \
            or integ.get("violations"):
        out.append(
            f"  integrity: {integ.get('checks', 0)} checks, "
            f"{integ.get('shadow_samples', 0)} shadow samples, "
            f"{integ.get('violations', 0)} violations"
        )
    ov = r.get("overlap") or {}
    if ov.get("present"):
        eff = ov.get("efficiency")
        bits = []
        if eff is not None:
            bits.append(f"overlap efficiency {eff:.0%}"
                        + (" " + _spark(ov["series"])
                           if ov.get("series") else ""))
        bits.append(
            f"I/O hidden {ov.get('io_hidden_ms', 0):.0f}ms / exposed "
            f"{ov.get('io_exposed_ms', 0):.0f}ms"
        )
        if ov.get("exchange_compression_ratio"):
            bits.append(
                f"exchange compressed {ov['exchange_compression_ratio']}x"
            )
        out.append("  overlap: " + "  ".join(bits))
        if ov.get("exposed_io_stalled"):
            # the exposed-I/O stall beat: ROADMAP item 2's acceptance
            # ("storage I/O fully hidden") made machine-readable — more
            # I/O wall was exposed on the critical path than hidden
            out.append(
                "  EXPOSED-I/O STALL: more storage/checkpoint wall "
                "landed on the critical path than was hidden behind "
                "compute — check --overlap is on, and whether the "
                "spill disk or checkpoint cadence is outrunning the "
                "per-level compute budget."
            )
    ln = r.get("launches") or {}
    if ln.get("present"):
        # launches/level beat: the device-resident pipeline's contract
        # is <=2 per level; fused shows 2x chunks, legacy O(actions)x
        bits = []
        if ln.get("last") is not None or ln.get("series"):
            bits.append(f"successor launches/level last {ln.get('last')}")
            if ln.get("series"):
                bits.append(f"max {ln['max']} " + _spark(ln["series"]))
        if ln.get("shard_last") is not None or ln.get("shard_series"):
            # sharded twin: dispatched collective-bearing programs per
            # level = launches PER SHARD (O(1) under --pipeline device)
            bits.append(
                f"launches/level/shard last {ln.get('shard_last')}"
            )
            if ln.get("shard_series"):
                bits.append(
                    f"max {ln['shard_max']} " + _spark(ln["shard_series"])
                )
        out.append("  launches: " + "  ".join(bits))
    hp = r.get("host_probe") or {}
    if hp.get("present"):
        # probe-ms/level beat, next to the launches sparkline: the
        # deferred-probe device path's host-sync wall — ONE batched
        # FpSet/tiered-run call per level on the host backend
        bits = [f"host-probe ms/level last {hp.get('last')}"]
        if hp.get("series"):
            bits.append(f"max {hp['max']} " + _spark(hp["series"]))
        out.append("  probe: " + "  ".join(bits))
    if r["open_level"] is not None and v["status"] in ("crashed", "stalled"):
        out.append(f"  died mid-level: level {r['open_level']} began but "
                   f"never completed")
    if r["died_shards"] and v["status"] in ("crashed", "stalled"):
        # multiprocess attribution: WHICH process took the run down (its
        # peers wedge in the next collective, so the laggard is causal)
        for p in r["died_shards"]:
            shards = p.get("shards") or []
            out.append(
                "  attributed to shard(s) "
                + ",".join(str(s) for s in shards)
                + f" (process {p['proc']}, pid {p['pid']}"
                + (", pid dead" if p["alive"] is False else "")
                + f", last completed level {p['last_depth']})"
            )
    if r["shard_procs"] and len(r["shard_procs"]) > 1:
        depths = [p["last_depth"] for p in r["shard_procs"]]
        out.append(
            f"  processes: {len(r['shard_procs'])}; last completed level "
            f"per process {depths}"
        )
    # --- levels table -----------------------------------------------------
    if levels:
        out.append("")
        out.append("Per-level throughput "
                   f"({len(levels)} levels recorded):")
        out.append(
            f"  {'depth':>5} {'frontier':>10} {'new':>10} {'dup%':>6} "
            f"{'wall':>8} {'kstates/s':>10}"
        )
        rows = levels if len(levels) <= max_rows else (
            levels[: max_rows // 2] + [None] + levels[-max_rows // 2:]
        )
        for rec in rows:
            if rec is None:
                out.append(f"  {'...':>5}")
                continue
            en = rec.get("enabled_candidates", 0)
            dup = rec.get("duplicates", 0)
            ms = rec.get("level_ms", 0.0)
            sps = rec.get("new", 0) / (ms / 1e3) if ms else 0.0
            out.append(
                f"  {rec.get('depth', '?'):>5} {rec.get('frontier', 0):>10,}"
                f" {rec.get('new', 0):>10,}"
                f" {100.0 * dup / en if en else 0.0:>5.1f}%"
                f" {_fmt_dur(ms / 1e3):>8} {sps / 1e3:>10.1f}"
            )
        sps_curve = [
            rec.get("new", 0) / (rec.get("level_ms", 0) / 1e3)
            if rec.get("level_ms") else 0.0
            for rec in levels
        ]
        out.append(f"  states/sec  {_spark(sps_curve)}")
        out.append(f"  new/level   "
                   f"{_spark([rec.get('new', 0) for rec in levels])}")
        total = levels[-1].get("total")
        if total:
            out.append(f"  total distinct so far: {total:,}")
        shard_new = levels[-1].get("shard_new")
        if shard_new:
            mean = sum(shard_new) / len(shard_new)
            imb = max(shard_new) / mean if mean else 0.0
            out.append(
                f"  shards: {len(shard_new)}; last-level new per shard "
                f"{_spark(shard_new)} (imbalance max/mean {imb:.2f})"
            )
    else:
        out.append("")
        out.append("No per-level stats recorded (yet).")
    # --- action enablement ------------------------------------------------
    if r["actions"]:
        out.append("")
        out.append("Action enablement (cumulative successors per action):")
        tot = sum(r["actions"].values()) or 1
        width = max(len(n) for n in r["actions"])
        for name, c in sorted(r["actions"].items(), key=lambda kv: -kv[1]):
            out.append(f"  {name:<{width}} {c:>12,}  {100.0 * c / tot:>5.1f}%")
    # --- spill accounting -------------------------------------------------
    if r["spill"] or any(k.startswith("spill-") for k in r["spans"]):
        out.append("")
        out.append("Disk-tier (spill) accounting:")
        for k in sorted(r["spill"]):
            out.append(f"  {k} = {r['spill'][k]}")
        for k in ("spill-run-write", "spill-merge"):
            if k in r["spans"]:
                a = r["spans"][k]
                out.append(
                    f"  {k}: {a['count']}x, {_fmt_dur(a['ms'] / 1e3)} total"
                )
    # --- resource pressure ------------------------------------------------
    res = r.get("resource") or {}
    if res.get("present"):
        out.append("")
        out.append("Resource pressure (disk / RSS gauges, "
                   "reclaim + exhaustion events):")
        if res.get("disk_budget"):
            used, bud = res.get("disk_used"), res["disk_budget"]
            pct = 100.0 * used / bud if used is not None and bud else 0.0
            out.append(
                f"  disk  {_fmt_bytes(used)} / {_fmt_bytes(bud)} budget "
                f"({pct:.0f}%)  {_spark(res['series'].get('kspec_disk_used_bytes', []))}"
            )
        elif res["series"].get("kspec_disk_used_bytes"):
            out.append(
                f"  disk  {_fmt_bytes(res.get('disk_used'))} used "
                f"(no budget)  "
                f"{_spark(res['series'].get('kspec_disk_used_bytes', []))}"
            )
        if res.get("rss") is not None:
            bud = res.get("rss_budget")
            out.append(
                f"  rss   {_fmt_bytes(res['rss'])}"
                + (f" / {_fmt_bytes(bud)} budget" if bud else "")
                + f"  {_spark(res['series'].get('kspec_rss_bytes', []))}"
            )
        if res.get("reclaims"):
            out.append(f"  reclaims: {res['reclaims']}")
        for ev in res.get("events", [])[-8:]:
            extra = {
                k: v2
                for k, v2 in ev.items()
                if k not in ("kind", "ts", "unix", "event", "run_id")
            }
            out.append(f"  {ev.get('ts', '?')}  {ev.get('event')}  "
                       f"{json.dumps(extra, default=str)}")
    # --- timeline ---------------------------------------------------------
    if r["timeline"]:
        out.append("")
        out.append("Restart / fallback timeline:")
        for ev in r["timeline"][-20:]:
            what = ev.get("event", "?")
            extra = {
                k: v
                for k, v in ev.items()
                if k not in ("kind", "ts", "unix", "event", "run_id", "cmd")
            }
            out.append(f"  {ev.get('ts', '?')}  {what}  "
                       f"{json.dumps(extra, default=str)}")
    # --- ETA --------------------------------------------------------------
    e = r["eta"]
    out.append("")
    if v["status"] in ("complete", "violation"):
        res = man.get("result") or {}
        out.append(
            f"ETA: run finished — {res.get('distinct_states', '?')} states, "
            f"diameter {res.get('diameter', '?')}, "
            f"{_fmt_dur(res.get('seconds'))}"
        )
    elif e.get("status") == "fit":
        if "eta_seconds" in e:
            out.append(
                f"ETA: frontier decaying x{e['growth_ratio']}/level — "
                f"~{e['est_remaining_states']:,} states remain, "
                f"~{_fmt_dur(e['eta_seconds'])} at "
                f"{e['recent_states_per_sec']:,.0f} states/sec"
            )
        else:
            out.append(
                f"ETA: frontier growth x{e['growth_ratio']}/level — "
                f"unbounded (sustaining "
                f"{e.get('recent_states_per_sec') or 0:,.0f} states/sec)"
            )
    else:
        out.append("ETA: insufficient data (needs >= 3 levels of stats)")
    out.append(f"Stall verdict: {v['status']}")
    return "\n".join(out)
