"""Unified telemetry: run manifests, span tracing, metrics, run reports.

The checker grew three instrumentation dialects ad hoc — per-level stats
JSONL (engine/bfs), heartbeat envelopes (resilience + tpu_sentry), and
supervisor/ladder event logs — none correlated by run, none aggregated;
the 10.7 h half-billion-state run was monitored by tailing raw logs.
This package makes observability a subsystem instead of a side effect:

- :class:`RunContext` (obs/runctx) — a run_id + run directory
  (``runs/<run_id>/``) holding a ``manifest.json`` (config, engine, git,
  knobs, checkpoint lineage across resumes, terminal status) and all the
  artifacts that previously scattered across the repo root;
- :class:`SpanTracer` (obs/tracer) — nested run_id-stamped spans to an
  append-only untearable JSONL, with optional ``jax.profiler`` windows
  attachable to a span kind via ``KSPEC_OBS_XPROF=<kind>:<lo>-<hi>``;
- :class:`MetricsRegistry` (obs/metrics) — counters/gauges/histograms
  exported as JSONL snapshots and an atomically-replaced Prometheus
  textfile for scraping during multi-day runs;
- :func:`render_report` (obs/report) — ``cli report <run-dir>``: per-level
  throughput, action-enablement table, spill accounting, restart/fallback
  timeline, growth-rate ETA, and a stall verdict that uses the
  supervisor's own liveness rule;
- :class:`RunObserver` (obs/observer) — the engines' shim: with only a
  ``stats_path`` it reproduces the historical per-level stream
  record-for-record; with a run context it additionally stamps, traces,
  and aggregates.

The whole package is jax-free at import (supervisor parents must never
touch a possibly-wedged accelerator tunnel); deep call sites in storage/
resilience reach the active tracer/registry through the module-level
``tracer.span/event`` and ``metrics.inc/set_gauge`` helpers, imported
lazily at the call site to keep the obs <-> resilience import graph
acyclic.

Beyond the per-run boundary, :mod:`obs.fleettrace` carries one trace per
*job* across the whole serving fleet (submit -> placement -> claim ->
run -> publish; ``cli trace`` / ``top`` / ``fleet-report``), and
:mod:`obs.atomicio` holds the shared atomic-publication idiom every
side-channel writer (manifests, specs, verdicts, routes, metrics.prom,
sweep manifests) rides.
"""

from . import fleettrace
from .atomicio import atomic_write_json, atomic_write_text
from .metrics import MetricsRegistry
from .observer import RunObserver
from .report import render_report, report_data
from .runctx import RunContext, default_run_dir, new_run_id
from .tracer import SpanTracer, read_jsonl_tolerant

__all__ = [
    "MetricsRegistry",
    "RunContext",
    "RunObserver",
    "SpanTracer",
    "atomic_write_json",
    "atomic_write_text",
    "default_run_dir",
    "fleettrace",
    "new_run_id",
    "read_jsonl_tolerant",
    "render_report",
    "report_data",
]
