"""Violation frontiers: the minimal violating configs, witnessed.

A completed sweep gives every lattice point a verdict.  For each
violated invariant this module answers the question operators actually
ask — *what is the SMALLEST config that breaks it?* — as a frontier:

- **frontier_from_manifest** — the Pareto-minimal violating points per
  invariant over the lattice's axis coordinates (a point is on the
  frontier when no other violating point of the same invariant is ≤ on
  every axis and < on one).  Coordinates compare by their INDEX in the
  axis's declared value order, which is the operator's own "smaller"
  (value lists are expected smallest-first).
- **bisect_line** — classic bisection along one axis line for values
  the sweep did not run (assumes violation is monotone in the axis:
  growing a config never un-breaks an invariant — true for the
  reference corpus's bound-shaped violations, and the cross-check below
  catches the cases where it is not).
- **refine_frontier** — the witness pass: every frontier point's
  in-lattice LOWER neighbors (one step down on one axis) must be
  non-violating for that invariant.  Neighbors the sweep already ran
  are checked from their manifest rows; neighbors it never ran (e.g.
  statically skipped, or off-lattice bisection probes) are ACTUALLY RUN
  through the provided runner — the frontier is witnessed, not guessed.
  A neighbor that turns out to violate demotes its frontier point (the
  neighbor joins the candidate set and the frontier is recomputed).

Jax-free by contract (a runner is a queue/router client).
"""

from __future__ import annotations

import copy
from typing import Optional


def _axis_orders(lattice_rec: dict) -> dict:
    """axis name -> {value-as-key: index in declared order}."""
    orders: dict = {}
    for sheet in lattice_rec.get("sheets", []):
        for axis in sheet.get("axes", []):
            o = orders.setdefault(axis["name"], {})
            for i, v in enumerate(axis["values"]):
                o.setdefault(_vkey(v), i)
    return orders


def _vkey(value):
    return tuple(value) if isinstance(value, list) else value


def _coord_indices(row: dict, orders: dict) -> Optional[tuple]:
    """((axis, index), ...) for one manifest row, None when any coord
    value is not in its axis's declared order (foreign point)."""
    out = []
    for name, value in row.get("coords", []):
        idx = orders.get(name, {}).get(_vkey(value))
        if idx is None:
            return None
        out.append((name, idx))
    return tuple(out)


def _dominates(a: tuple, b: tuple) -> bool:
    """a ≤ b on every shared axis, < on at least one (same axis sets)."""
    da, db = dict(a), dict(b)
    if set(da) != set(db):
        return False
    return all(da[k] <= db[k] for k in da) and any(
        da[k] < db[k] for k in da
    )


def violating_rows(manifest: dict, invariant: Optional[str] = None) -> dict:
    """invariant -> [row, ...] of done rows whose verdict violated it."""
    out: dict = {}
    for row in manifest.get("points", {}).values():
        v = (row.get("verdict") or {}).get("violation")
        if not v:
            continue
        name = v.get("invariant")
        if invariant is not None and name != invariant:
            continue
        out.setdefault(name, []).append(row)
    return out


def frontier_from_manifest(manifest: dict,
                           invariant: Optional[str] = None) -> dict:
    """invariant -> Pareto-minimal violating rows (each annotated with
    ``_indices``, its axis-index coordinates)."""
    orders = _axis_orders(manifest.get("lattice", {}))
    frontiers: dict = {}
    for name, rows in violating_rows(manifest, invariant).items():
        indexed = []
        for row in rows:
            idx = _coord_indices(row, orders)
            if idx is not None:
                indexed.append((idx, row))
        minimal = []
        for idx, row in indexed:
            if any(
                _dominates(other, idx)
                for other, _r in indexed
                if other != idx
            ):
                continue
            r = dict(row)
            r["_indices"] = [[k, i] for k, i in idx]
            minimal.append(r)
        # stable render order: lexicographic in axis-index space
        minimal.sort(key=lambda r: tuple(i for _k, i in r["_indices"]))
        frontiers[name] = minimal
    return frontiers


def bisect_line(values: list, is_violating) -> Optional[int]:
    """Smallest index i in `values` with is_violating(values[i]), by
    bisection under the monotonicity assumption (see module docstring);
    None when even the largest value is clean.  ``is_violating`` is a
    callable(value) -> bool that RUNS the probe (so a B-value axis costs
    O(log B) runs, not B)."""
    lo, hi = 0, len(values) - 1
    if hi < 0 or not is_violating(values[hi]):
        return None
    while lo < hi:
        mid = (lo + hi) // 2
        if is_violating(values[mid]):
            hi = mid
        else:
            lo = mid + 1
    return lo


def lower_neighbors(indices: tuple, orders: dict) -> list:
    """One-step-down neighbors in axis-index space: the configs a
    minimality claim is ABOUT."""
    out = []
    for k, (name, idx) in enumerate(indices):
        if idx == 0:
            continue
        n = list(indices)
        n[k] = (name, idx - 1)
        out.append(tuple(n))
    return out


def refine_frontier(manifest: dict, runner, log=None,
                    invariant: Optional[str] = None,
                    max_probes: int = 64) -> dict:
    """The witness pass.  ``runner(coords) -> verdict-record`` actually
    runs the config at axis coordinates ``((name, value), ...)`` (the
    portfolio's Dispatcher provides one); rows the manifest already
    holds are used as-is.  Returns::

        {invariant: {"frontier": [row...],
                     "witnesses": [{point, neighbor, verdict,
                                    violates}, ...],
                     "demoted": [point_id, ...]}}

    A violating lower neighbor demotes its frontier point: the neighbor
    joins the candidate set and minimality is recomputed — the reported
    frontier is only ever one the witness runs could not shrink."""
    say = log or (lambda _s: None)
    orders = _axis_orders(manifest.get("lattice", {}))
    values_by_axis = {
        name: [v for v, _i in sorted(
            ((val, i) for val, i in o.items()), key=lambda t: t[1]
        )]
        for name, o in orders.items()
    }
    # index rows by axis-index coordinates for neighbor lookup
    by_idx: dict = {}
    for row in manifest.get("points", {}).values():
        idx = _coord_indices(row, orders)
        if idx is not None:
            by_idx[idx] = row
    manifest = copy.deepcopy(manifest)
    out: dict = {}
    probes = 0
    for name, frontier in frontier_from_manifest(
        manifest, invariant
    ).items():
        witnesses: list = []
        demoted: list = []
        queue = list(frontier)
        seen_claims: set = set()
        while queue:
            row = queue.pop(0)
            claim = tuple((k, i) for k, i in row["_indices"])
            if claim in seen_claims:
                continue
            seen_claims.add(claim)
            shrunk = False
            for nb in lower_neighbors(claim, orders):
                nrow = by_idx.get(nb)
                if nrow is not None and nrow.get("verdict"):
                    rec = nrow["verdict"]
                else:
                    if probes >= max_probes:
                        say(
                            f"[bisect] probe budget ({max_probes}) "
                            f"exhausted; {name} frontier partially "
                            "witnessed"
                        )
                        continue
                    probes += 1
                    coords = tuple(
                        (n, values_by_axis[n][i]) for n, i in nb
                    )
                    say(f"[bisect] probing neighbor {dict(coords)}")
                    rec = runner(coords)
                if not rec:
                    # no verdict (no runner wired, probe timed out):
                    # the claim stays UNWITNESSED on this edge — typed
                    # as violates=None, never silently counted clean
                    witnesses.append({
                        "point": row["point_id"],
                        "neighbor": [[n, i] for n, i in nb],
                        "verdict": None,
                        "violates": None,
                    })
                    continue
                v = rec.get("violation")
                violates = bool(v and v.get("invariant") == name)
                witnesses.append({
                    "point": row["point_id"],
                    "neighbor": [[n, i] for n, i in nb],
                    "verdict": {
                        "violation": v,
                        "distinct_states": (rec or {}).get(
                            "distinct_states"
                        ),
                    },
                    "violates": violates,
                })
                if violates:
                    # minimality claim refuted: the neighbor is the new
                    # candidate — chase it down the same way
                    shrunk = True
                    nrec = {
                        "point_id": f"probe:{dict(nb)}",
                        "coords": [
                            [n, values_by_axis[n][i]] for n, i in nb
                        ],
                        "verdict": rec,
                        "_indices": [[n, i] for n, i in nb],
                    }
                    queue.append(nrec)
            if shrunk:
                demoted.append(row["point_id"])
        final = _recompute_minimal(_claims(frontier, witnesses))
        out[name] = {
            "frontier": final,
            "witnesses": witnesses,
            "demoted": demoted,
        }
    return out


def _claims(frontier: list, witnesses: list) -> list:
    """All violating candidates observed during refinement: the original
    frontier plus every violating probe/neighbor."""
    rows = {tuple((k, i) for k, i in r["_indices"]): r for r in frontier}
    for w in witnesses:
        if w["violates"]:
            idx = tuple((n, i) for n, i in w["neighbor"])
            rows.setdefault(idx, {
                "point_id": w["point"] + ":lower",
                "coords": None,
                "verdict": w["verdict"],
                "_indices": [[n, i] for n, i in idx],
            })
    return list(rows.values())


def _recompute_minimal(rows: list) -> list:
    indexed = [
        (tuple((k, i) for k, i in r["_indices"]), r) for r in rows
    ]
    out = []
    for idx, row in indexed:
        if any(
            _dominates(other, idx) for other, _r in indexed if other != idx
        ):
            continue
        out.append(row)
    out.sort(key=lambda r: tuple(i for _k, i in r["_indices"]))
    return out
