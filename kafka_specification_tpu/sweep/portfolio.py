"""Portfolio scheduler: thousands of lattice points, one durable sweep.

The portfolio turns an enumerated lattice into traffic for the serving
plane the previous PRs built, under a cost-model-shaped policy:

- **skip before pay** — points whose shape carries vacuous-action
  findings are marked ``skipped`` (policy ``on_vacuous=skip``) or run
  LAST (``defer``) with the finding attached to the manifest row: the
  skip is typed, machine-readable, auditable — never silent coverage
  loss.
- **cheap points batch** — predicted-cheap points are submitted
  cheapest-first and contiguously per schema shape, so one daemon drain
  claims them together and the scheduler coalesces them into
  service/batch.py vmapped groups (width-capped by the daemon's
  ``max_group``).
- **expensive points run solo** — a point predicted past
  ``solo_threshold_states`` is stamped ``solo`` at submit
  (queue.submit(solo=True)): one huge member must not drag a shared
  exploration out to ITS bounds envelope, and solo runs publish the
  full seedable state-cache artifact.
- **the cache makes repeats incremental** — points are keyed exactly
  like the state-space cache, so a repeat sweep O(verify)-hits every
  completed point and a deeper-bound sweep boundary-seeds; the verdict
  record's ``cache`` stamp is harvested into the manifest row.

Durability (``kspec-sweep/1``).  The manifest — ``sweep.json`` in the
sweep directory, like the router's ``router.json`` — is promoted with
the same tmp-write + atomic-replace idiom every other durable artifact
uses; it tracks every point's status (``pending`` → ``submitted`` →
``done`` | ``skipped`` | ``error``), predicted and actual cost, the
prediction residual, verdict subset and cache stamp.  Job ids are
DETERMINISTIC per (sweep nonce, point id), so a crash-resumed sweep
re-attaches to in-flight jobs and re-submits ONLY points whose job the
queue has never seen — each point runs exactly once per sweep.

Jax-free by contract: the portfolio is a client of the queue/router,
never of the engine.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional

from .. import durable_io as _dio
from ..obs import fleettrace
from ..obs.atomicio import atomic_write_json
from .cost import CostModel, features_from, fit_from_corpus
from .lattice import LatticeSpec, annotate_vacuous, enumerate_points

SWEEP_SCHEMA = "kspec-sweep/1"

#: manifest re-promote cadence while the scheduler loop runs (every
#: harvest also promotes; this bounds staleness on quiet stretches)
_PROMOTE_EVERY_S = 5.0

#: verdict subset a manifest row retains (the full record stays in the
#: service results/ dir, addressed by the row's job_id)
_VERDICT_KEEP = ("model", "distinct_states", "diameter", "violation",
                 "exit_code", "seconds", "states_per_sec")


@dataclass
class SweepConfig:
    sweep_dir: str
    service_dir: Optional[str] = None  # queue dispatch (exactly one of
    router_dir: Optional[str] = None   # service_dir/router_dir is set)
    tenant: str = "sweep"
    max_inflight: int = 64
    #: predicted distinct-states at/past which a point submits solo
    solo_threshold_states: int = 200_000
    wait_timeout_s: float = 900.0
    poll_s: float = 0.05
    state_cache_dir: Optional[str] = None  # cost-model corpus root
    prior_manifests: tuple = ()  # extra corpora for the fit
    #: optional callable() invoked whenever the wait loop is idle —
    #: tests and the single-process bench drive an in-process daemon's
    #: drain_once() here instead of needing a live `cli serve`
    drive: Optional[object] = None


class Dispatcher:
    """One submit/status/result surface over queue or router."""

    def __init__(self, cfg: SweepConfig):
        if bool(cfg.service_dir) == bool(cfg.router_dir):
            raise ValueError("exactly one of service_dir/router_dir")
        if cfg.router_dir:
            from ..service.router import Router

            self.backend = Router(cfg.router_dir)
        else:
            from ..service.queue import JobQueue

            self.backend = JobQueue(cfg.service_dir)
        self.tenant = cfg.tenant

    def submit(self, point, job_id: str, solo: bool) -> dict:
        return self.backend.submit(
            point.cfg_text,
            point.module,
            tenant=self.tenant,
            kernel_source=point.kernel_source,
            max_depth=point.max_depth,
            max_states=point.max_states,
            job_id=job_id,
            solo=solo,
        )

    def status(self, job_id: str) -> dict:
        return self.backend.status(job_id)

    def result(self, job_id: str) -> Optional[dict]:
        return self.backend.result(job_id)

    def max_pending_cap(self) -> Optional[int]:
        """The tenant's admission cap (tenants.json), when budgeted —
        the portfolio throttles BELOW it so sweep traffic never trips
        the submit-side admission control other tenants rely on."""
        try:
            from ..resilience.resources import (
                budget_for_tenant,
                load_tenant_budgets,
            )

            root = getattr(self.backend, "dir", None)
            if root is None:  # router: per-host tenants.json; skip
                return None
            budgets = load_tenant_budgets(
                os.path.join(root, "tenants.json")
            )
            b = budget_for_tenant(budgets, self.tenant)
            return getattr(b, "max_pending", None) if b else None
        except Exception:  # noqa: BLE001 — a cap probe must not fail a sweep
            return None


# --------------------------------------------------------------------------
# manifest
# --------------------------------------------------------------------------


@dataclass
class Manifest:
    path: str
    rec: dict

    @classmethod
    def open_or_create(cls, sweep_dir: str, lattice: LatticeSpec):
        path = os.path.join(sweep_dir, "sweep.json")
        # startup-janitor parity (crashcheck `sweep` scenario): a
        # promote killed mid-tmp-write leaves a nonce'd `.tmp` next to
        # sweep.json; the dir is shared with a possibly-live sweeper, so
        # the sweep is grace-aged like the queue's
        _dio.sweep_tmp(sweep_dir, min_age_s=_dio.TMP_SWEEP_GRACE_S)
        if os.path.isfile(path):
            with open(path) as fh:
                rec = json.load(fh)
            if rec.get("schema") != SWEEP_SCHEMA:
                raise ValueError(
                    f"{path} is not a {SWEEP_SCHEMA} manifest"
                )
            return cls(path, rec)
        os.makedirs(sweep_dir, exist_ok=True)
        rec = {
            "schema": SWEEP_SCHEMA,
            # the nonce makes this SWEEP INSTANCE's job ids unique: a
            # crash-resume reloads it (same ids — exactly-once), while a
            # fresh repeat sweep mints new ids and genuinely re-runs
            # every point through the daemon (where the state cache, not
            # stale results, makes it cheap)
            "sweep_id": f"{lattice.name}-{os.urandom(4).hex()}",
            "name": lattice.name,
            "created_unix": round(time.time(), 3),
            "lattice": lattice.record(),
            "cost_model": None,
            "points": {},
        }
        return cls(path, rec)

    def promote(self) -> None:
        self.rec["updated_unix"] = round(time.time(), 3)
        # a crash-resumed sweeper can race a wedged-but-alive
        # predecessor to this one final path: privatise the tmp (the
        # PR 16 torn-promote precedent) so neither promotes the other's
        # half-written bytes
        atomic_write_json(
            self.path, self.rec,
            tmp_nonce=f"{os.getpid():x}-{os.urandom(4).hex()}",
        )

    def row(self, point_id: str) -> Optional[dict]:
        return self.rec["points"].get(point_id)

    def ensure_row(self, point) -> dict:
        row = self.rec["points"].get(point.point_id)
        if row is None:
            row = dict(point.record())
            row["constants"] = {
                k: list(v) if isinstance(v, tuple) else v
                for k, v in point.key.constants
            }
            row["status"] = "pending"
            row["job_id"] = None
            self.rec["points"][point.point_id] = row
        return row

    def counts(self) -> dict:
        out = {"pending": 0, "submitted": 0, "done": 0, "skipped": 0,
               "error": 0, "hit": 0, "seeded": 0}
        for row in self.rec["points"].values():
            out[row.get("status", "pending")] = (
                out.get(row.get("status", "pending"), 0) + 1
            )
            cache = row.get("cache") or {}
            if cache.get("state_cache") == "hit":
                out["hit"] += 1
            elif cache.get("state_cache") == "seed":
                out["seeded"] += 1
        return out


def job_id_for(sweep_id: str, point_id: str) -> str:
    """Deterministic per (sweep instance, point): the crash-resume key."""
    return f"sw-{sweep_id}-{point_id.replace(':', '-')}"


# --------------------------------------------------------------------------
# the scheduler loop
# --------------------------------------------------------------------------


def _harvest(row: dict, rec: dict, model: CostModel) -> None:
    """Fold one verdict record into its manifest row: verdict subset,
    actual cost, cache stamp, and the prediction residual the next
    sweep's fit learns from."""
    verdict = {k: rec.get(k) for k in _VERDICT_KEEP}
    row["verdict"] = verdict
    row["status"] = (
        "error" if rec.get("exit_code") not in (0, 1) else "done"
    )
    row["cache"] = rec.get("cache")
    states = rec.get("distinct_states")
    row["actual"] = {
        "states": states,
        "seconds": rec.get("seconds"),
    }
    if states is not None and rec.get("violation") is None:
        feats = features_from(
            dict(row.get("constants") or {}),
            max_depth=row.get("max_depth"),
            max_states=row.get("max_states"),
        )
        row["residual"] = round(model.residual(feats, int(states)), 4)


def plan_sweep(lattice: LatticeSpec, cfg: SweepConfig) -> dict:
    """Enumerate + annotate + predict, no dispatch: what `cli sweep
    plan` renders.  -> {points, model, skipped, deferred, runnable}."""
    points = annotate_vacuous(enumerate_points(lattice))
    model = fit_from_corpus(
        state_cache_root=_cache_root(cfg),
        manifests=tuple(cfg.prior_manifests),
    )
    skipped, deferred, runnable = [], [], []
    for p in points:
        if p.vacuous and lattice.on_vacuous == "skip":
            skipped.append(p)
        elif p.vacuous and lattice.on_vacuous == "defer":
            deferred.append(p)
        else:
            runnable.append(p)
    predictions = {p.point_id: model.predict_point(p) for p in points}
    return {
        "points": points,
        "model": model,
        "predictions": predictions,
        "skipped": skipped,
        "deferred": deferred,
        "runnable": runnable,
    }


def _cache_root(cfg: SweepConfig) -> Optional[str]:
    if cfg.state_cache_dir:
        return cfg.state_cache_dir
    if os.environ.get("KSPEC_STATE_CACHE_DIR"):
        return os.environ["KSPEC_STATE_CACHE_DIR"]
    if cfg.service_dir:
        return os.path.join(cfg.service_dir, "state-cache")
    return None


def run_sweep(lattice: LatticeSpec, cfg: SweepConfig,
              log=None) -> dict:
    """Run (or crash-resume) one sweep to completion.  Returns the final
    manifest record.  ``log`` is an optional callable(str) for progress
    lines (the CLI passes print)."""
    say = log or (lambda _s: None)
    dispatch = Dispatcher(cfg)
    plan = plan_sweep(lattice, cfg)
    model: CostModel = plan["model"]
    manifest = Manifest.open_or_create(cfg.sweep_dir, lattice)
    sweep_id = manifest.rec["sweep_id"]
    manifest.rec["cost_model"] = model.to_dict()

    # --- fold the plan into the manifest ---------------------------------
    for p in plan["skipped"]:
        row = manifest.ensure_row(p)
        if row["status"] == "pending":
            row["status"] = "skipped"
            row["skip"] = {"reason": "vacuous", "findings": p.vacuous}
    for p in plan["deferred"]:
        row = manifest.ensure_row(p)
        row.setdefault("skip", {"reason": "vacuous-deferred",
                                "findings": p.vacuous})
    # runnable + deferred all get predictions and (eventually) runs;
    # deferred points sort after every clean point
    to_run = []
    for rank, p in enumerate(plan["runnable"] + plan["deferred"]):
        row = manifest.ensure_row(p)
        pred = plan["predictions"][p.point_id]
        row["predicted"] = pred
        row["solo"] = bool(
            pred["states"] >= cfg.solo_threshold_states
        )
        if row["status"] in ("pending", "submitted"):
            to_run.append((p, row, rank >= len(plan["runnable"])))
    manifest.promote()

    # --- resume: re-attach to jobs the queue already knows ---------------
    outstanding: dict = {}  # job_id -> row
    fresh: list = []
    for p, row, deferred in to_run:
        jid = job_id_for(sweep_id, p.point_id)
        if row["status"] == "submitted":
            st = dispatch.status(jid)
            if st["state"] == "done" and st.get("result"):
                _harvest(row, st["result"], model)
                continue
            if st["state"] in ("pending", "claimed"):
                outstanding[jid] = row  # still in flight: just wait
                continue
            # unknown: the crash hit between manifest promote and queue
            # publish — submit is idempotent on the deterministic id
        fresh.append((p, row, deferred))

    # cheap-first within (clean, deferred): cheap points of one shape
    # land contiguously and coalesce into batched groups; expensive
    # points trail and run solo
    fresh.sort(key=lambda t: (t[2], t[1]["predicted"]["states"],
                              t[0].point_id))

    cap = cfg.max_inflight
    tenant_cap = dispatch.max_pending_cap()
    if tenant_cap:
        cap = max(1, min(cap, int(tenant_cap)))
    say(
        f"[sweep] {lattice.name}: {len(manifest.rec['points'])} points "
        f"({len(fresh)} to submit, {len(outstanding)} in flight, "
        f"cost model over {model.n_records} corpus records)"
    )

    # --- the loop: keep `cap` in flight, harvest as verdicts land --------
    t_promote = time.monotonic()
    deadline = time.monotonic() + cfg.wait_timeout_s
    idx = 0
    try:
        while fresh[idx:] or outstanding:
            while fresh[idx:] and len(outstanding) < cap:
                p, row, _d = fresh[idx]
                idx += 1
                jid = job_id_for(sweep_id, p.point_id)
                spec = dispatch.submit(p, jid, solo=bool(row.get("solo")))
                # portfolio membership is a trace annotation: `cli trace`
                # on any sweep job names its sweep without a side lookup
                fleettrace.emit_event(
                    dispatch.backend.dir, spec.get("trace"),
                    "sweep-member", job_id=jid, sweep_id=sweep_id,
                    point_id=p.point_id, solo=bool(row.get("solo")),
                    predicted_states=(row.get("predicted") or {}).get(
                        "states"
                    ),
                )
                row["status"] = "submitted"
                row["job_id"] = jid
                outstanding[jid] = row
            landed = []
            for jid, row in outstanding.items():
                rec = dispatch.result(jid)
                if rec is not None:
                    _harvest(row, rec, model)
                    landed.append(jid)
            for jid in landed:
                outstanding.pop(jid)
            if landed or time.monotonic() - t_promote > _PROMOTE_EVERY_S:
                manifest.promote()
                t_promote = time.monotonic()
            if not landed:
                if time.monotonic() >= deadline:
                    say(
                        f"[sweep] timeout with {len(outstanding)} points "
                        "in flight (resume with the same sweep dir)"
                    )
                    break
                if cfg.drive is not None:
                    cfg.drive()
                else:
                    time.sleep(cfg.poll_s)
            else:
                deadline = time.monotonic() + cfg.wait_timeout_s
    finally:
        # self-recalibration: the residuals this sweep measured shift
        # the model the NEXT resume/repeat loads from the manifest
        residuals = [
            row["residual"]
            for row in manifest.rec["points"].values()
            if row.get("residual") is not None
        ]
        manifest.rec["cost_model"] = model.recalibrated(
            residuals
        ).to_dict()
        manifest.promote()
    say(f"[sweep] {_counts_line(manifest)}")
    return manifest.rec


def _counts_line(manifest: Manifest) -> str:
    c = manifest.counts()
    return (
        f"done={c['done']} (hit={c['hit']} seeded={c['seeded']}) "
        f"skipped={c['skipped']} error={c['error']} "
        f"pending={c['pending'] + c['submitted']}"
    )


def load_manifest(sweep_dir: str) -> dict:
    path = os.path.join(sweep_dir, "sweep.json")
    with open(path) as fh:
        rec = json.load(fh)
    if rec.get("schema") != SWEEP_SCHEMA:
        raise ValueError(f"{path} is not a {SWEEP_SCHEMA} manifest")
    return rec
