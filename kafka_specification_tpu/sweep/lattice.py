"""Declarative config lattices (``kspec-sweep-lattice/1``).

A lattice names a base TLC .cfg plus AXES — each axis varies one
CONSTANT (ints, or replica-set sizes for model-value sets), one
exploration bound (``max_depth`` / ``max_states``), or the module
itself (model variants; product mixes ride the authored ``Partitions``
constant like any other axis).  Enumeration takes the cartesian
product per sheet and synthesizes each point a complete, standalone
.cfg text — the point IS an ordinary job, bit-identical to what `cli
check` or `cli submit` would run by hand.

Canonical keying.  Every point resolves to the state-space cache's own
:class:`~..service.state_cache.CacheKey` (module, kernel source,
canonical CONSTANTS, resolved ordered invariants, constraints, deadlock
flag, bounds) and its ``point_id`` is that key's content address
(``<base16-base-digest>:<bounds>``).  The sweep therefore keys the SAME
namespace the cache does: a repeat sweep's points are O(verify) hits, a
deeper-bound point finds its shallower sibling's boundary, and two
axis paths that synthesize the same config dedupe to one point.

Static vacuity skip.  Before any exploration is paid for, each distinct
shape runs the jax-free ``kspec analyze`` action passes
(analysis/encoding.analyze_model under the jax stub): a point whose
CONSTANTS statically disable one or more actions (``vacuous-action``
findings — its distinguishing behavior cannot occur) is skipped or
deferred per the lattice's ``on_vacuous`` policy, and the finding
travels with the point so the skip is auditable in the manifest and
``cli sweep report`` — never silent coverage loss.

Jax-free by contract (the analyzer runs models abstractly; in a process
that already imported the real jax, the stub install is a no-op).
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from ..service.state_cache import CacheKey, canonical_constants
from ..utils.cfg import parse_cfg, resolved_invariants

LATTICE_SCHEMA = "kspec-sweep-lattice/1"

#: what to do with a point whose model carries vacuous-action findings
ON_VACUOUS = ("skip", "defer", "run")


@dataclass(frozen=True)
class Axis:
    """One lattice dimension.

    kind:
      ``constant`` — vary CONSTANTS[name]; int values replace an int
        constant directly, and for a model-value-set constant (e.g.
        ``Replicas = {b1, b2}``) an int N means "a set of N values"
        (named from the base set's prefix);
      ``bound``    — vary ``max_depth`` or ``max_states`` (null = unbounded);
      ``module``   — vary the TLA+ module itself (model variants).
    """

    name: str
    values: tuple
    kind: str = "constant"

    def record(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "values": list(self.values)}


@dataclass
class LatticeSheet:
    """One (module, base cfg, axes) product — a lattice may union
    several sheets (e.g. an IdSequence MaxId sweep next to a
    FiniteReplicatedLog brokers x log-size sweep)."""

    module: str
    cfg_text: str
    axes: list
    kernel_source: str = "hand"

    def record(self) -> dict:
        return {
            "module": self.module,
            "cfg_text": self.cfg_text,
            "kernel_source": self.kernel_source,
            "axes": [a.record() for a in self.axes],
        }


@dataclass
class LatticeSpec:
    name: str
    sheets: list
    on_vacuous: str = "skip"
    source_path: Optional[str] = None

    def record(self) -> dict:
        return {
            "schema": LATTICE_SCHEMA,
            "name": self.name,
            "on_vacuous": self.on_vacuous,
            "sheets": [s.record() for s in self.sheets],
        }

    def axis_names(self) -> list:
        seen: list = []
        for s in self.sheets:
            for a in s.axes:
                if a.name not in seen:
                    seen.append(a.name)
        return seen


@dataclass
class LatticePoint:
    """One enumerated config — a complete, standalone unit of work."""

    point_id: str
    module: str
    cfg_text: str
    kernel_source: str
    coords: tuple  # ((axis_name, value), ...) in sheet axis order
    max_depth: Optional[int]
    max_states: Optional[int]
    key: CacheKey
    vacuous: list = field(default_factory=list)  # finding records

    def record(self) -> dict:
        return {
            "point_id": self.point_id,
            "module": self.module,
            "coords": [[n, v] for n, v in self.coords],
            "max_depth": self.max_depth,
            "max_states": self.max_states,
            "base_digest": self.key.base_digest(),
            "kernel_source": self.kernel_source,
        }


# --------------------------------------------------------------------------
# loading
# --------------------------------------------------------------------------


def _axis_from_record(rec: dict) -> Axis:
    kind = rec.get("kind", "constant")
    if "bound" in rec and "name" not in rec:  # {"bound": "max_depth", ...}
        kind, name = "bound", rec["bound"]
    else:
        name = rec.get("name") or rec.get("constant")
        if rec.get("constant"):
            kind = "constant"
        if rec.get("kind"):
            kind = rec["kind"]
    if not name:
        raise ValueError(f"axis needs a name: {rec!r}")
    if kind == "bound" and name not in ("max_depth", "max_states"):
        raise ValueError(f"bound axis must be max_depth|max_states: {name!r}")
    if kind not in ("constant", "bound", "module"):
        raise ValueError(f"unknown axis kind {kind!r}")
    values = rec.get("values")
    if not isinstance(values, list) or not values and values != [None]:
        raise ValueError(f"axis {name!r} needs a non-empty values list")
    return Axis(name=name, values=tuple(
        tuple(v) if isinstance(v, list) else v for v in values
    ), kind=kind)


def _sheet_from_record(rec: dict, base_dir: Path) -> LatticeSheet:
    cfg_text = rec.get("cfg_text")
    if cfg_text is None:
        base = rec.get("base_cfg")
        if base is None:
            raise ValueError("sheet needs cfg_text or base_cfg")
        p = Path(base)
        if not p.is_absolute():
            p = base_dir / p
        cfg_text = p.read_text()
    module = rec.get("module")
    if not module:
        raise ValueError("sheet needs a module")
    axes = [_axis_from_record(a) for a in rec.get("axes", [])]
    ks = rec.get("kernel_source", "hand")
    if ks not in ("auto", "emitted", "hand"):
        raise ValueError(f"bad kernel_source {ks!r}")
    return LatticeSheet(module=module, cfg_text=cfg_text, axes=axes,
                        kernel_source=ks)


def load_lattice(path_or_record) -> LatticeSpec:
    """Load a ``kspec-sweep-lattice/1`` spec from a JSON file path or an
    already-parsed record dict."""
    if isinstance(path_or_record, dict):
        rec, base_dir, src = path_or_record, Path("."), None
    else:
        p = Path(path_or_record)
        rec = json.loads(p.read_text())
        base_dir, src = p.parent, str(p)
    if rec.get("schema") != LATTICE_SCHEMA:
        raise ValueError(
            f"not a {LATTICE_SCHEMA} record (schema={rec.get('schema')!r})"
        )
    sheets_rec = rec.get("sheets")
    if sheets_rec is None:
        # single-sheet shorthand: module/base_cfg/axes at top level
        sheets_rec = [rec]
    sheets = [_sheet_from_record(s, base_dir) for s in sheets_rec]
    if not sheets:
        raise ValueError("lattice has no sheets")
    on_vac = rec.get("on_vacuous", "skip")
    if on_vac not in ON_VACUOUS:
        raise ValueError(f"on_vacuous must be one of {ON_VACUOUS}")
    return LatticeSpec(
        name=rec.get("name") or (sheets[0].module if sheets else "lattice"),
        sheets=sheets,
        on_vacuous=on_vac,
        source_path=src,
    )


# --------------------------------------------------------------------------
# enumeration
# --------------------------------------------------------------------------


def _apply_constant(constants: dict, name: str, value):
    """Override one CONSTANT.  For model-value-set constants an int N
    means a set of N values, named from the base set's prefix (so
    ``Replicas = {b1, b2}`` swept to 3 becomes ``{b1, b2, b3}`` — the
    engine maps names to indices, only the SIZE is semantic)."""
    base = constants.get(name)
    if isinstance(base, list) and isinstance(value, int):
        prefix = "".join(c for c in str(base[0]) if not c.isdigit()) or "v"
        constants[name] = [f"{prefix}{i + 1}" for i in range(value)]
    elif isinstance(value, tuple):
        constants[name] = list(value)
    else:
        constants[name] = value


def _render_cfg(cfg) -> str:
    """Synthesize standalone TLC .cfg text from a parsed config — the
    point's complete unit of work (travels inline in the job spec)."""
    lines = [f"SPECIFICATION {cfg.specification or 'Spec'}", "CONSTANTS"]
    for k, v in cfg.constants.items():
        if isinstance(v, list):
            lines.append(f"    {k} = {{{', '.join(str(x) for x in v)}}}")
        else:
            lines.append(f"    {k} = {v}")
    if cfg.invariants:
        lines.append("INVARIANTS " + " ".join(cfg.invariants))
    if cfg.constraints:
        lines.append("CONSTRAINT " + " ".join(cfg.constraints))
    lines.append(
        f"CHECK_DEADLOCK {'TRUE' if cfg.check_deadlock else 'FALSE'}"
    )
    return "\n".join(lines) + "\n"


def point_key(module: str, cfg, emitted: bool,
              max_depth, max_states) -> CacheKey:
    """The state-cache key this point's job resolves to — EXACTLY
    service/state_cache.key_for_job's resolution, so sweep bookkeeping
    and the cache share one content address."""
    return CacheKey(
        module=module,
        emitted=bool(emitted),
        constants=canonical_constants(cfg.constants),
        invariants=tuple(resolved_invariants(module, cfg)),
        constraints=tuple(cfg.constraints),
        check_deadlock=bool(cfg.check_deadlock),
        max_depth=max_depth,
        max_states=max_states,
    )


def enumerate_points(spec: LatticeSpec) -> list:
    """Cartesian product per sheet, union across sheets, deduped on the
    canonical point_id (two axis paths synthesizing the same config are
    ONE point).  Submit-stable order: sheets in spec order, coordinates
    in row-major axis order."""
    import copy

    out: list = []
    seen: set = set()
    for sheet in spec.sheets:
        base = parse_cfg(sheet.cfg_text)
        axes = sheet.axes or [Axis("_base", (None,), "bound")]
        # kernel_source resolution is static per sheet ("auto" keys as
        # emitted iff the reference checkout has the module — same rule
        # as the daemon's resolve_kernel_source, evaluated lazily only
        # when someone actually asked for auto)
        emitted = _resolve_emitted(sheet.kernel_source, sheet.module)
        for combo in itertools.product(*(a.values for a in axes)):
            cfg = copy.deepcopy(base)
            module = sheet.module
            max_depth = max_states = None
            coords = []
            for axis, value in zip(axes, combo):
                if axis.name == "_base":
                    continue
                coords.append((axis.name, value))
                if axis.kind == "module":
                    module = value
                elif axis.kind == "bound":
                    if axis.name == "max_depth":
                        max_depth = value
                    else:
                        max_states = value
                else:
                    _apply_constant(cfg.constants, axis.name, value)
            key = point_key(module, cfg, emitted, max_depth, max_states)
            pid = f"{key.base_digest()}:{key.bounds_name()}"
            if pid in seen:
                continue
            seen.add(pid)
            out.append(LatticePoint(
                point_id=pid,
                module=module,
                cfg_text=_render_cfg(cfg),
                kernel_source=sheet.kernel_source,
                coords=tuple(coords),
                max_depth=max_depth,
                max_states=max_states,
                key=key,
            ))
    return out


def _resolve_emitted(kernel_source: str, module: str) -> bool:
    if kernel_source == "emitted":
        return True
    if kernel_source == "hand":
        return False
    from ..service.kernel_cache import resolve_kernel_source

    return resolve_kernel_source("auto", module)


# --------------------------------------------------------------------------
# static vacuity (the pre-exploration skip)
# --------------------------------------------------------------------------

#: per-process memo: one abstract-interpretation pass per distinct
#: model shape (module, emitted, constants, constraints) — a lattice
#: whose points differ only in bounds/invariants analyzes each shape once
_VACUOUS_MEMO: dict = {}


def vacuous_findings(module: str, cfg_text: str) -> list:
    """``vacuous-action`` finding records for this (module, CONSTANTS)
    shape, via the jax-free analyzer (analysis/encoding.analyze_model
    under the jax stub; a real already-imported jax is kept).  Returns
    [] when the shape analyzes clean; an UNANALYZABLE shape also returns
    [] — vacuity skipping is an optimization and must never veto a
    point the engine could legitimately run."""
    from ..analysis import install_jax_stub

    cfg = parse_cfg(cfg_text)
    memo_key = (module, canonical_constants(cfg.constants),
                tuple(cfg.constraints))
    hit = _VACUOUS_MEMO.get(memo_key)
    if hit is not None:
        return list(hit)
    install_jax_stub()
    try:
        from ..analysis.encoding import analyze_model
        from ..utils.cfg import build_model

        model = build_model(module, cfg, analysis_gate=False)
        found = [
            f.record() for f in analyze_model(model)
            if f.kind == "vacuous-action" and not f.suppressed
        ]
    except Exception:  # noqa: BLE001 — analysis is advisory here
        found = []
    _VACUOUS_MEMO[memo_key] = found
    return list(found)


def annotate_vacuous(points: list) -> list:
    """Attach vacuous-action findings to each point (memoized per
    shape); returns the same list for chaining."""
    for p in points:
        p.vacuous = vacuous_findings(p.module, p.cfg_text)
    return points
