"""Coverage sweep subsystem: the config lattice as a scheduled portfolio.

The reference corpus is only ever checked at a handful of hand-picked
CONSTANTS, but the protocol's interesting behavior lives on a *lattice*
of configs (brokers x log sizes x MaxId x bounds x product mixes) —
ROADMAP item 2.  This package turns the serving plane the previous PRs
built into a standing workload generator over that lattice:

- :mod:`.lattice` — declarative lattice spec (``kspec-sweep-lattice/1``)
  enumerated into canonical points keyed COMPATIBLY with the state-space
  cache's key schema (service/state_cache.CacheKey), with points whose
  distinguishing actions are statically vacuous under their CONSTANTS
  (``kspec analyze`` findings) skipped/deferred *before* any exploration
  is paid for.
- :mod:`.cost` — a log-linear frontier-growth cost model fit from the
  standing corpus (state-cache entries + banked BENCH/stats records +
  prior sweep manifests), predicting states and wall per point, with
  prediction-vs-actual residuals recorded on every completed point so
  the model self-recalibrates across sweeps.  Also the ONE shared
  flat-throughput time estimator ``cli report``'s ETA delegates to.
- :mod:`.portfolio` — schedules the points under per-tenant budgets
  through the existing queue or router: predicted-cheap points packed
  so the daemon's group planner coalesces them into batched vmapped
  runs, predicted-expensive points marked solo; a durable sweep
  manifest (``kspec-sweep/1``, atomic-promote, crash-resumable) tracks
  every point's verdict + cost.
- :mod:`.bisect` — from lattice verdicts, the minimal-violating-config
  frontier per invariant (Pareto-minimal over axis coordinates),
  refined by actually running the claimed-minimal points' lower
  neighbors until the frontier is witnessed, not guessed.

The whole package is JAX-FREE BY CONTRACT (like the service clients and
the router): planning, dispatch, bisection and reporting run on
operator boxes that never pay the accelerator cold start.  The only
engine work a sweep causes happens inside serving daemons.
"""

from .bisect import (  # noqa: F401
    bisect_line,
    frontier_from_manifest,
    refine_frontier,
)
from .cost import (  # noqa: F401
    CostModel,
    corpus_records,
    fit_from_corpus,
    flat_time_estimate,
)
from .lattice import (  # noqa: F401
    LATTICE_SCHEMA,
    Axis,
    LatticePoint,
    LatticeSpec,
    enumerate_points,
    load_lattice,
    vacuous_findings,
)
from .portfolio import (  # noqa: F401
    SWEEP_SCHEMA,
    Dispatcher,
    Manifest,
    SweepConfig,
    job_id_for,
    load_manifest,
    plan_sweep,
    run_sweep,
)
