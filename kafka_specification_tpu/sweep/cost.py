"""Log-linear frontier-growth cost model over the standing corpus.

BFS state counts over these bounded protocol configs grow roughly
geometrically in the config sizes (the PR 3 per-run ETA fit measures
the same thing *within* one run's levels), so ``log(states)`` is
modeled as linear in per-constant log features::

    log(1 + states)  ~  w0 + sum_name w_name * log(1 + size(name))
                        + w_depth * log(1 + effective_depth_bound)

fit by ridge-regularized least squares (tiny lambda — the corpus can be
a handful of records and the normal equations must stay solvable) over
every completed check the system has banked: state-space cache entries
(the durable corpus PR 14 built), prior sweep manifests, and any
records a caller scrapes from BENCH/stats files.  Wall time is then
``states / throughput`` with throughput the corpus median states/sec —
the same flat-throughput assumption ``cli report``'s ETA has always
made, now in ONE place (:func:`flat_time_estimate`) so the two
prediction paths cannot drift.

Honesty limits (docs/sweep.md): the fit extrapolates geometric growth
from small configs — a config that crosses a structural cliff (a new
action becoming enabled, a product mix) can be off by orders of
magnitude, which is exactly why every completed point records its
prediction-vs-actual residual in the sweep manifest and the model
re-fits over those residuals on the next sweep
(:meth:`CostModel.recalibrated`).  Predictions ORDER the portfolio
(cheap-first packing, expensive-solo) — they never gate correctness.

Jax-free by contract (numpy only).
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

#: ridge regularizer: keeps the normal equations solvable on tiny or
#: collinear corpora without visibly biasing a well-determined fit
_RIDGE = 1e-3

#: fallback throughput when the corpus has no timed records at all
#: (1-core CPU venue floor; any real record replaces it)
_DEFAULT_STATES_PER_SEC = 5_000.0

#: feature cap for unbounded depth: log-features need a finite value
#: for "no bound"; 64 exceeds every corpus diameter observed so far
_UNBOUNDED_DEPTH = 64


def flat_time_estimate(states: Optional[float],
                       states_per_sec: Optional[float]) -> Optional[float]:
    """THE flat-throughput wall estimate (seconds, 1 decimal): used by
    the per-run ETA in ``cli report`` (obs/report.eta) and by the sweep
    cost model's per-point wall predictions, so the two prediction
    paths share one formula by construction."""
    if states is None or not states_per_sec or states_per_sec <= 0:
        return None
    return round(float(states) / float(states_per_sec), 1)


# --------------------------------------------------------------------------
# features
# --------------------------------------------------------------------------


def _size(value) -> Optional[float]:
    """Numeric 'size' of one CONSTANT value: ints count themselves,
    model-value sets count their cardinality, other strings don't
    feature."""
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, (list, tuple)):
        return float(len(value))
    return None


def features_from(constants, max_depth=None, max_states=None) -> dict:
    """name -> log1p(size) feature map for one config.  ``constants``
    is a dict or the canonical ((name, value), ...) tuple form (the
    state-cache key / manifest form)."""
    items = constants.items() if isinstance(constants, dict) else constants
    out: dict = {}
    for name, value in items:
        s = _size(value)
        if s is not None:
            out[f"c:{name}"] = math.log1p(max(0.0, s))
    depth = _UNBOUNDED_DEPTH if max_depth is None else int(max_depth)
    out["b:max_depth"] = math.log1p(max(0, depth))
    if max_states is not None:
        out["b:max_states"] = math.log1p(max(0, int(max_states)))
    return out


# --------------------------------------------------------------------------
# corpus
# --------------------------------------------------------------------------


def corpus_records(state_cache_root: Optional[str] = None,
                   manifests: tuple = (),
                   extra: tuple = ()) -> list:
    """Training records from the standing corpus.  Each record::

        {"features": {...}, "states": int, "seconds": float|None,
         "source": "state-cache"|"sweep-manifest"|...}

    - ``state_cache_root``: every verified-enough entry of the
      persistent state-space cache (service/state_cache.iter_corpus —
      light validation only; a bad entry is skipped, never fatal).
    - ``manifests``: prior ``kspec-sweep/1`` manifest paths — completed
      points carry actuals, which is how the model self-recalibrates
      across sweeps.
    - ``extra``: pre-built record dicts (BENCH scrapes, tests).
    """
    records: list = []
    if state_cache_root:
        from ..service.state_cache import iter_corpus

        for entry in iter_corpus(state_cache_root):
            v = entry.get("verdict") or {}
            states = v.get("distinct_states")
            if states is None or v.get("violation") is not None:
                continue  # a violating run's count stops at the violation
            key = entry.get("key") or {}
            records.append({
                "features": features_from(
                    [tuple(kv) for kv in key.get("constants", [])],
                    max_depth=entry.get("max_depth"),
                    max_states=entry.get("max_states"),
                ),
                "states": int(states),
                "seconds": v.get("seconds"),
                "source": "state-cache",
            })
    for path in manifests:
        try:
            with open(path) as fh:
                man = json.load(fh)
        except (OSError, ValueError):
            continue
        for row in (man.get("points") or {}).values():
            v = row.get("verdict") or {}
            states = v.get("distinct_states")
            if row.get("status") != "done" or states is None:
                continue
            if v.get("violation") is not None:
                continue
            records.append({
                "features": features_from(
                    dict(row.get("constants") or {}),
                    max_depth=row.get("max_depth"),
                    max_states=row.get("max_states"),
                ),
                "states": int(states),
                "seconds": (row.get("actual") or {}).get("seconds"),
                "source": "sweep-manifest",
            })
    records.extend(extra)
    return records


# --------------------------------------------------------------------------
# the model
# --------------------------------------------------------------------------


@dataclass
class CostModel:
    names: list = field(default_factory=list)  # feature names, fit order
    weights: list = field(default_factory=list)
    intercept: float = 0.0
    states_per_sec: float = _DEFAULT_STATES_PER_SEC
    n_records: int = 0
    residual_shift: float = 0.0  # log-space recalibration offset

    # --- fitting ----------------------------------------------------------
    @classmethod
    def fit(cls, records: list) -> "CostModel":
        """Ridge least squares of log1p(states) on the union feature
        set.  An empty corpus yields the honest null model: intercept 0,
        default throughput — predictions are then pure ordering noise
        and the first sweep's residuals immediately recalibrate it."""
        recs = [r for r in records if r.get("states") is not None]
        if not recs:
            return cls()
        names = sorted({n for r in recs for n in r["features"]})
        X = np.ones((len(recs), len(names) + 1))
        for i, r in enumerate(recs):
            for j, n in enumerate(names):
                X[i, 1 + j] = r["features"].get(n, 0.0)
        y = np.array([math.log1p(float(r["states"])) for r in recs])
        d = X.shape[1]
        reg = _RIDGE * np.eye(d)
        reg[0, 0] = 0.0  # never shrink the intercept
        w = np.linalg.solve(X.T @ X + reg, X.T @ y)
        rates = [
            r["states"] / r["seconds"]
            for r in recs
            if r.get("seconds") and r["seconds"] > 0
        ]
        return cls(
            names=list(names),
            weights=[float(v) for v in w[1:]],
            intercept=float(w[0]),
            states_per_sec=(
                float(np.median(rates)) if rates else _DEFAULT_STATES_PER_SEC
            ),
            n_records=len(recs),
        )

    # --- prediction -------------------------------------------------------
    def predict_log_states(self, features: dict) -> float:
        z = self.intercept + self.residual_shift
        for n, w in zip(self.names, self.weights):
            z += w * features.get(n, 0.0)
        return z

    def predict(self, features: dict) -> dict:
        """-> {"states": int, "seconds": float|None} for one feature map
        (see :func:`features_from`)."""
        states = max(1.0, math.expm1(self.predict_log_states(features)))
        return {
            "states": int(round(states)),
            "seconds": flat_time_estimate(states, self.states_per_sec),
        }

    def predict_point(self, point) -> dict:
        """Predict a :class:`~.lattice.LatticePoint` (features from its
        canonical key, so prediction and cache address agree on what the
        config IS)."""
        feats = features_from(
            point.key.constants,
            max_depth=point.max_depth,
            max_states=point.max_states,
        )
        return self.predict(feats)

    # --- recalibration ----------------------------------------------------
    def residual(self, features: dict, actual_states: int) -> float:
        """log-space prediction error for one completed point (positive
        = the point was BIGGER than predicted)."""
        return math.log1p(max(0, int(actual_states))) \
            - self.predict_log_states(features)

    def recalibrated(self, residuals: list) -> "CostModel":
        """A copy shifted by the mean residual — the cheap cross-sweep
        self-recalibration (the full refit happens anyway next sweep,
        when the manifest joins the corpus)."""
        import dataclasses

        if not residuals:
            return self
        return dataclasses.replace(
            self,
            residual_shift=self.residual_shift
            + float(np.mean([float(r) for r in residuals])),
        )

    # --- (de)serialization (rides the sweep manifest) ---------------------
    def to_dict(self) -> dict:
        return {
            "names": list(self.names),
            "weights": list(self.weights),
            "intercept": self.intercept,
            "states_per_sec": round(self.states_per_sec, 1),
            "n_records": self.n_records,
            "residual_shift": self.residual_shift,
        }

    @classmethod
    def from_dict(cls, rec: dict) -> "CostModel":
        return cls(
            names=list(rec.get("names", [])),
            weights=[float(w) for w in rec.get("weights", [])],
            intercept=float(rec.get("intercept", 0.0)),
            states_per_sec=float(
                rec.get("states_per_sec", _DEFAULT_STATES_PER_SEC)
            ),
            n_records=int(rec.get("n_records", 0)),
            residual_shift=float(rec.get("residual_shift", 0.0)),
        )


def fit_from_corpus(state_cache_root: Optional[str] = None,
                    manifests: tuple = ()) -> CostModel:
    """The one-call front door the portfolio and CLI use."""
    if state_cache_root is None:
        state_cache_root = os.environ.get("KSPEC_STATE_CACHE_DIR")
    return CostModel.fit(
        corpus_records(state_cache_root=state_cache_root,
                       manifests=manifests)
    )
