"""Async overlap layer: the worker-thread plumbing that takes storage
I/O, spill-run merges and checkpoint writes off the engines' critical
path (ROADMAP item 2; the GPUexplore overlap levers, PAPERS.md
arXiv:1801.05857).

One knob governs every overlap: ``KSPEC_OVERLAP`` (env) /
``--overlap on|off`` (CLI) / ``check(overlap=...)``.  Default ON;
``off`` restores the exact historical serial behavior and is the
bit-identity oracle the overlap tests compare against
(tests/test_overlap.py).  The four overlaps this module underpins:

1. **double-buffered chunk pipeline** (engine/bfs.py + pipeline.py):
   no thread at all — JAX async dispatch is the worker.  The level loop
   stages at most TWO chunks: chunk k+1's device programs are dispatched
   before chunk k's host commit (fingerprint-set insert, arena assembly,
   digest folds) runs, so the C-speed host work drains behind the
   in-flight update-skeleton launch.
2. **background spill-run merges** (storage/tiered.py): k-way merges run
   on an :class:`AsyncWorker`.  Inputs are immutable sorted runs, so
   lookups keep serving from them until the merged output is atomically
   promoted and *adopted* — all engine-visible mutation stays on the
   submitting thread.
3. **async checkpoint writes** (resilience/checkpoints.py): the engine
   snapshots the (immutable, already-materialized) arrays synchronously
   and a writer thread runs chain verification + checksummed write +
   atomic promote.
4. **sharded exchange overlap + compression** (parallel/sharded.py):
   staged commit around the exchange step plus the bit-packed
   fingerprint payload codec (ops/fpcompress.py).

Error contract: a worker NEVER swallows a failure.  Exceptions
(including injected faults — ``crash@merge:N`` raising
:class:`~.resilience.faults.InjectedCrash`, ``enospc@ckpt:N`` raising
``OSError(ENOSPC)``) are stored on the job and re-raised on the
submitting thread at its next ``wait``/``poll``/``drain`` — so the
typed exit paths (rc-75 resource exits, crash-restart supervision,
exit-76 integrity) fire exactly as in serial mode, at the next join
point.  Jobs propagate the submitter's obs context (tracer + metrics
registry are thread-local), so ``checkpoint-write``/``spill-merge``
spans emitted on a worker land in the same run trace — which is how the
overlap tests prove a write actually overlapped a ``step`` span.

Must stay jax-free (storage and resilience import it).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

OVERLAP_ENV = "KSPEC_OVERLAP"
_OFF = ("0", "off", "false", "no")

#: machine-readable ownership contract (docs/analysis.md; verified by
#: `cli analyze`'s AST pass and, under KSPEC_TSAN=1, asserted on every
#: attribute write at runtime).  This is the docs/engine.md § Async
#: execution prose as data:
#: - AsyncJob results are written by the worker and published by
#:   `done.set()`; immutable afterwards (readers join through wait()).
#: - AsyncWorker queue/accounting state is guarded by `_cv`;
#:   `blocked_s` belongs to the single submitting (engine) thread.
THREAD_CONTRACT = {
    "schema": "kspec-ownership/1",
    "classes": {
        "AsyncJob": {
            "immutable_after_init": ["label", "done"],
            # result/exc/seconds/fn: worker-written, immutable after
            # done.set() — writes happen in AsyncWorker._run, so they
            # are checked under AsyncWorker's worker context
        },
        "AsyncWorker": {
            "lock": "_cv",
            "shared_locked": ["_q", "_inflight", "_failed", "_closed",
                              "busy_s", "jobs_done"],
            "engine_only": ["blocked_s"],
            "immutable_after_init": ["name", "_cv", "_thread"],
            "worker_methods": ["_run"],
        },
    },
}


def overlap_enabled(flag=None) -> bool:
    """Resolve the overlap knob: explicit arg > $KSPEC_OVERLAP > on."""
    if flag is not None:
        if isinstance(flag, str):
            return flag.strip().lower() not in _OFF
        return bool(flag)
    env = os.environ.get(OVERLAP_ENV)
    if env is None or not env.strip():
        return True
    return env.strip().lower() not in _OFF


class AsyncJob:
    """One unit of background work; results/errors read via the worker."""

    __slots__ = ("label", "fn", "done", "result", "exc", "seconds")

    def __init__(self, label: str, fn: Callable):
        self.label = label
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.seconds = 0.0


class AsyncWorker:
    """A single serial daemon worker thread.

    Jobs run strictly in submission order (the engines rely on this:
    checkpoint generations rotate in save order, merge promotes never
    reorder).  Jobs must only produce files/values — every mutation of
    engine-visible state happens on the submitting thread when it adopts
    a completed job's result.  ``busy_s``/``blocked_s`` feed the
    hidden-vs-exposed I/O accounting (obs ``kspec_overlap_efficiency``).
    """

    def __init__(self, name: str):
        self.name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._inflight: Optional[AsyncJob] = None
        self._failed: deque = deque()  # completed jobs with unraised errors
        self._closed = False
        self.busy_s = 0.0  # worker wall spent running jobs (hidden I/O)
        self.blocked_s = 0.0  # submitter wall spent blocked on jobs (exposed)
        self.jobs_done = 0
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # --- submission -------------------------------------------------------
    def submit(self, label: str, fn: Callable) -> AsyncJob:
        """Queue `fn` for the worker; returns the job handle.

        The submitter's thread-local obs context (active tracer + metrics
        registry) is captured here and re-activated around the job, so
        spans/metrics emitted by background I/O land in the same run."""
        from .obs import metrics as _met  # jax-free
        from .obs import tracer as _tr

        tracer = _tr.current_tracer()
        registry = _met.current_registry()
        inner = fn

        def run():
            _tr.set_tracer(tracer)
            _met.set_registry(registry)
            try:
                return inner()
            finally:
                _tr.set_tracer(None)
                _met.set_registry(None)

        job = AsyncJob(label, run)
        with self._cv:
            if self._closed:
                raise RuntimeError(f"AsyncWorker {self.name!r} is closed")
            self._q.append(job)
            self._cv.notify_all()
        return job

    # --- worker loop ------------------------------------------------------
    def _run(self) -> None:
        from .analysis import ownership as _own  # jax-free

        _own.register_worker_thread(self._thread)
        try:
            self._run_loop()
        finally:
            _own.unregister_worker_thread(self._thread)

    def _run_loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                job = self._q.popleft()
                self._inflight = job
            t0 = time.perf_counter()
            try:
                job.result = job.fn()
            except BaseException as e:  # noqa: BLE001 — stored, re-raised
                job.exc = e
            # release the closure NOW: a checkpoint job closes over the
            # full array snapshot (the dominant RSS object at scale), and
            # the engine may not reap the handle until a level later —
            # the promoted file is the durable copy, so holding the
            # in-memory one past completion only inflates peak RSS
            job.fn = None
            job.seconds = time.perf_counter() - t0
            with self._cv:
                self.busy_s += job.seconds
                self.jobs_done += 1
                self._inflight = None
                if job.exc is not None:
                    self._failed.append(job)
                job.done.set()
                self._cv.notify_all()

    # --- joining ----------------------------------------------------------
    def _raise_failed(self, job: AsyncJob) -> None:
        with self._cv:
            try:
                self._failed.remove(job)
            except ValueError:
                pass  # already consumed by a poll
        raise job.exc

    def wait(self, job: AsyncJob):
        """Block for one job; re-raise its error; return its result."""
        t0 = time.perf_counter()
        job.done.wait()
        self.blocked_s += time.perf_counter() - t0
        if job.exc is not None:
            self._raise_failed(job)
        return job.result

    def poll(self) -> None:
        """Non-blocking: re-raise the oldest unraised worker error."""
        with self._cv:
            job = self._failed.popleft() if self._failed else None
        if job is not None:
            raise job.exc

    def pending(self) -> int:
        with self._cv:
            return len(self._q) + (1 if self._inflight is not None else 0)

    def drain(self) -> None:
        """Block until every queued job completed, then raise the first
        stored error (if any) — the engines' durability join point."""
        t0 = time.perf_counter()
        with self._cv:
            while self._q or self._inflight is not None:
                self._cv.wait()
        self.blocked_s += time.perf_counter() - t0
        self.poll()

    def close(self, swallow: bool = True) -> None:
        """Drain + stop the thread.  swallow=True (terminal/error paths)
        discards stored errors instead of raising from cleanup."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=60.0)
        if not swallow:
            self.poll()
        else:
            with self._cv:
                self._failed.clear()

    def stats(self) -> dict:
        with self._cv:
            return {
                "jobs": self.jobs_done,
                "busy_s": round(self.busy_s, 4),
                "blocked_s": round(self.blocked_s, 4),
            }


def close_workers(workers, drain: bool) -> None:
    """Shared engine shutdown: drain=True (clean completion) surfaces
    worker errors; error paths close with swallow (their typed exception
    is already propagating).  None entries are skipped."""
    for w in workers:
        if w is None:
            continue
        if drain:
            w.drain()
        w.close(swallow=True)


def worker_counters(workers) -> tuple:
    """(worker-busy, caller-blocked) seconds across `workers` — the
    hidden-vs-exposed I/O attribution inputs both engines sample per
    level.  None entries are skipped."""
    busy = blocked = 0.0
    for w in workers:
        if w is not None:
            busy += w.busy_s
            blocked += w.blocked_s
    return busy, blocked


# KSPEC_TSAN=1 (test-only): assert THREAD_CONTRACT ownership on every
# attribute write (analysis/ownership.py); zero overhead otherwise
from .analysis.ownership import bind_contract as _bind_contract  # noqa: E402

_bind_contract(globals(), THREAD_CONTRACT)
