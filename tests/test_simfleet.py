"""Deterministic fleet simulation (resilience/simfleet;
docs/resilience.md § Deterministic simulation).

Fast tier (`simfleet` marker).  Pins the load-bearing promises:

- bit-identity: same seed ⇒ byte-identical determinism surface
  (events + verdicts + violations + drain), twice in one process;
- a seeded lease-logic mutant is CAUGHT by the live-claim-stolen
  oracle, ddmin-SHRUNK to a handful of events (the issue's <=25
  acceptance bound), and the banked ``kspec-simfleet/1`` repro
  reproduces under the mutant and reads STALE on the clean tree;
- the KSPEC_CLOCK_SKEW expiry/liveness boundaries are exact to the
  millisecond on both sides (queue lease takeover, router
  classify_host) — driven through the injectable clock, no sleeping;
- the raw-clock lint holds the whole migrated set at zero findings
  and actually fires on a seeded raw-``time.time()`` mutant copy;
- the durable_io fault hook injects failures before the effect and
  restores cleanly.

One slow test soaks 500 seeds against the <120s 1-core budget.
"""

import json
import os
import shutil
import time

import pytest

import kafka_specification_tpu.durable_io as dio
import kafka_specification_tpu.service.queue as qmod
from kafka_specification_tpu.analysis.clock_lint import (
    CLOCK_MIGRATED,
    lint_raw_clock,
)
from kafka_specification_tpu.resilience import simfleet as sf
from kafka_specification_tpu.service.queue import JobQueue
from kafka_specification_tpu.service.router import Router, classify_host
from kafka_specification_tpu.utils import clock as uclock
from kafka_specification_tpu.utils.cli import main as cli_main

pytestmark = pytest.mark.simfleet

ID_CFG = """
SPECIFICATION Spec
CONSTANTS
    MaxId = 6
INVARIANTS TypeOk
CHECK_DEADLOCK FALSE
"""


def _surface(record):
    return {k: record[k]
            for k in ("events", "verdicts", "violations", "drained")}


# --- determinism -----------------------------------------------------------


def test_same_seed_bit_identical():
    a = sf.run_seed(7)
    b = sf.run_seed(7)
    assert a["digest"] == b["digest"]
    # not just the hash: the full surface, byte for byte
    assert json.dumps(_surface(a), sort_keys=True) == \
        json.dumps(_surface(b), sort_keys=True)
    assert a["violations"] == [] and a["drained"]


def test_distinct_seeds_explore_distinct_schedules():
    a = sf.run_seed(1)
    b = sf.run_seed(2)
    assert a["digest"] != b["digest"]
    assert a["schedule"] != b["schedule"]


def test_replay_of_recorded_schedule_matches_generation():
    gen = sf.run_seed(11)
    rec, _ = sf.run_schedule(gen["schedule"], seed=11)
    assert rec["digest"] == gen["digest"]


def test_fast_soak_50_seeds_clean():
    out = sf.sweep_seeds(range(50))
    assert out["runs"] == 50 and out["clean"] == 50
    assert out["violating"] == []


@pytest.mark.slow
def test_soak_500_seeds_clean_under_budget():
    t0 = time.monotonic()
    out = sf.sweep_seeds(range(500))
    elapsed = time.monotonic() - t0
    assert out["runs"] == 500 and out["clean"] == 500, out["violating"][:1]
    assert elapsed < 120.0, f"soak took {elapsed:.1f}s (budget 120s)"


def test_coverage_guided_sweep_queues_derived_seeds():
    out = sf.sweep_seeds(range(3), coverage=True, max_extra=2)
    assert out["runs"] == 5  # 3 requested + 2 derived
    assert out["pair_coverage"] > 0


# --- the mutant loop: catch, shrink, bank, replay, stale -------------------


def _install_lease_mutant(monkeypatch):
    """THE seeded bug: every lease reads as orphaned, so janitors steal
    live claims — the exact regression the allowance exists to stop."""
    monkeypatch.setattr(
        JobQueue, "lease_orphaned",
        lambda self, jid, lease_ttl=None, skew_s=None: True)


def test_lease_mutant_caught_shrunk_and_replayed(tmp_path, monkeypatch):
    _install_lease_mutant(monkeypatch)
    hit = None
    for seed in range(20):
        rec = sf.run_seed(seed)
        steals = [v for v in rec["violations"]
                  if v["oracle"] == "live-claim-stolen"]
        if steals:
            hit = (seed, rec)
            break
    assert hit is not None, "mutant never caught in 20 seeds"
    seed, rec = hit
    small, srec = sf.shrink(rec["schedule"], sf.SimConfig(), seed,
                            "live-claim-stolen")
    assert len(small) <= 25, f"shrunk schedule still {len(small)} events"
    sv = next(v for v in srec["violations"]
              if v["oracle"] == "live-claim-stolen")
    path = str(tmp_path / "repro.json")
    sf.save_repro(path, seed, sf.SimConfig(), sv, small, srec,
                  shrunk_from=len(rec["schedule"]))
    repro = sf.load_repro(path)
    assert repro["schema"] == sf.REPRO_SCHEMA
    # under the mutant the banked repro reproduces, digest and all
    out = sf.replay_repro(repro)
    assert out["reproduced"] and out["digest_match"]
    # on the clean tree the same repro must read STALE, never green
    monkeypatch.undo()
    out = sf.replay_repro(repro)
    assert not out["reproduced"]


def test_shrink_rejects_non_reproducing_schedule():
    clean = sf.run_seed(3)
    assert clean["violations"] == []
    with pytest.raises(ValueError):
        sf.shrink(clean["schedule"], sf.SimConfig(), 3, "live-claim-stolen")


def test_load_repro_rejects_foreign_schema(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"schema": "kspec-sweep/1"}))
    with pytest.raises(ValueError):
        sf.load_repro(str(p))


# --- KSPEC_CLOCK_SKEW boundaries, exact to the millisecond -----------------
#
# Driven through the injectable clock: install a SimClock pinned at a
# known instant, plant stamps at threshold / threshold±1ms, and read
# the decision — no sleeping, no real-clock jitter in the assert.


@pytest.fixture
def simclock():
    clk = sf.SimClock()
    prev = uclock.install(clk)
    try:
        yield clk
    finally:
        uclock.install(prev)


def _plant_lease(q, jid, age):
    with open(q._lease_path(jid), "w") as fh:
        json.dump({"pid": 1, "token": "foreign-host",
                   "lease_unix": round(uclock.now() - age, 3)}, fh)


def test_queue_takeover_skew_boundary_exact_and_1ms(tmp_path, simclock):
    """lease_orphaned expiry: age >= ttl + skew takes over; 1ms inside
    the widened window the live foreigner keeps its claim."""
    q = JobQueue(str(tmp_path / "svc"), skew_s=5.0)
    jid = q.submit(ID_CFG, "IdSequence", kernel_source="hand")["job_id"]
    q.claim_pending()
    ttl, skew = 10.0, 5.0
    _plant_lease(q, jid, ttl + skew)          # exactly at the boundary
    assert q.lease_orphaned(jid, lease_ttl=ttl) is True
    _plant_lease(q, jid, ttl + skew - 0.001)  # 1ms fresh: pid 1 lives
    assert q.lease_orphaned(jid, lease_ttl=ttl) is False
    _plant_lease(q, jid, ttl + skew + 0.001)  # 1ms past: expired
    assert q.lease_orphaned(jid, lease_ttl=ttl) is True
    # an explicit per-call skew override wins over the instance's
    _plant_lease(q, jid, ttl + 1.0)
    assert q.lease_orphaned(jid, lease_ttl=ttl, skew_s=0.0) is True
    assert q.lease_orphaned(jid, lease_ttl=ttl, skew_s=2.0) is False


def _plant_hb(host_dir, unix):
    svc = os.path.join(str(host_dir), "service")
    os.makedirs(svc, exist_ok=True)
    with open(os.path.join(svc, "heartbeat.jsonl"), "a") as fh:
        fh.write(json.dumps({"kind": "service-heartbeat",
                             "unix": round(unix, 3)}) + "\n")


def test_router_liveness_skew_boundary_exact_and_1ms(tmp_path, simclock):
    """host_health/classify_host: hb_age <= dead_after + skew is alive;
    1ms past the widened window the host is dead."""
    dead_after, skew = 2.0, 5.0
    limit = dead_after + skew
    for i, (age, state) in enumerate([
        (limit, "ok"),            # exactly at the boundary: alive
        (limit - 0.001, "ok"),    # 1ms inside
        (limit + 0.001, "dead"),  # 1ms past
    ]):
        h = tmp_path / f"h{i}"
        JobQueue(str(h))
        r = Router(str(tmp_path / f"rt{i}"), hosts=[str(h)],
                   dead_after_s=dead_after, skew_s=skew)
        _plant_hb(h, uclock.now() - age)
        got = r.host_health(0)["state"]
        assert got == state, f"hb_age {age}: {got} != {state}"
    assert classify_host(True, False) == "dead"
    assert classify_host(True, True) == "ok"


# --- raw-clock lint --------------------------------------------------------


def test_clock_lint_zero_findings_on_tree():
    assert lint_raw_clock() == []


def test_clock_lint_covers_the_whole_migrated_plane():
    migrated = set(CLOCK_MIGRATED)
    for mod in ("kafka_specification_tpu/service/queue.py",
                "kafka_specification_tpu/service/router.py",
                "kafka_specification_tpu/service/daemon.py",
                "kafka_specification_tpu/resilience/heartbeat.py",
                "kafka_specification_tpu/resilience/retry.py",
                "kafka_specification_tpu/obs/fleettrace.py",
                "kafka_specification_tpu/resilience/simfleet/kernel.py"):
        assert mod in migrated, f"{mod} missing from CLOCK_MIGRATED"


def _mutant_pkg(tmp_path, body):
    """A trimmed package copy holding one mutated migrated module."""
    root = tmp_path / "kafka_specification_tpu"
    mod = root / "service" / "queue.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(body)
    return str(root)


def test_clock_lint_fires_on_seeded_raw_clock_mutant(tmp_path):
    root = _mutant_pkg(tmp_path, "import time\nstamp = time.time()\n")
    findings = lint_raw_clock(package_root=root)
    assert len(findings) == 1
    f = findings[0]
    assert f["path"].endswith("service/queue.py") and f["line"] == 2
    assert "utils/clock.py" in f["problem"]


def test_clock_lint_reasoned_allow_tag_suppresses(tmp_path):
    root = _mutant_pkg(
        tmp_path,
        "import time\n"
        "# kspec: allow(raw-clock) NTP probe must read the real clock\n"
        "stamp = time.time()\n")
    assert lint_raw_clock(package_root=root) == []


def test_clock_lint_bare_allow_tag_is_a_finding(tmp_path):
    root = _mutant_pkg(
        tmp_path,
        "import time\n"
        "# kspec: allow(raw-clock)\n"
        "stamp = time.time()\n")
    findings = lint_raw_clock(package_root=root)
    assert len(findings) == 1
    assert "no reason" in findings[0]["problem"]


def test_clock_lint_ignores_docstrings_and_comments(tmp_path):
    root = _mutant_pkg(
        tmp_path,
        '"""Uses time.time() internally (docs only)."""\n'
        "# time.sleep(1) would be wrong here\n"
        "x = 1\n")
    assert lint_raw_clock(package_root=root) == []


def test_cli_analyze_reports_raw_clock_high(tmp_path, monkeypatch, capsys):
    """The finding surfaces through `cli analyze` as HIGH raw-clock."""
    root = _mutant_pkg(tmp_path, "import time\nstamp = time.time()\n")
    import kafka_specification_tpu.analysis.clock_lint as cl
    real = cl.lint_raw_clock
    monkeypatch.setattr(cl, "lint_raw_clock",
                        lambda package_root=None: real(package_root=root))
    rc = cli_main(["analyze", "--json"])
    rep = json.loads(capsys.readouterr().out)
    raw = [f for f in rep["findings"] if f["kind"] == "raw-clock"]
    assert rc == 1 and len(raw) == 1
    assert raw[0]["severity"] == "HIGH"


# --- durable_io fault hook -------------------------------------------------


def test_fault_hook_fails_op_before_effect(tmp_path):
    target = str(tmp_path / "x.json")

    def hook(op, path):
        if op == "write":
            raise OSError(5, "injected EIO", path)

    prev = dio.set_fault_hook(hook)
    try:
        with pytest.raises(OSError):
            dio.write_text(target, "{}")
        assert not os.path.exists(target)  # clean-fail: no effect landed
    finally:
        dio.set_fault_hook(prev)
    dio.write_text(target, "{}")  # hook gone: op lands
    assert os.path.exists(target)


# --- cli surface -----------------------------------------------------------


def test_cli_simfleet_run_clean_seeds(tmp_path, capsys):
    rc = cli_main(["simfleet", "run", "--seeds", "3",
                   "--out", str(tmp_path / "repros"), "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0 and rep["ok"]
    assert rep["schema"] == "kspec-simfleet-sweep/1"
    assert rep["runs"] == 3 and rep["clean"] == 3


def test_cli_simfleet_replay_reports_stale_on_clean_tree(
        tmp_path, monkeypatch, capsys):
    _install_lease_mutant(monkeypatch)
    out_dir = str(tmp_path / "repros")
    rc = cli_main(["simfleet", "run", "--seeds", "4",
                   "--out", out_dir, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 1 and rep["violations"]
    banked = rep["violations"][0]
    assert banked["events"] <= 25
    path = banked["path"]
    # still mutated: the repro reproduces and exits 0
    rc = cli_main(["simfleet", "replay", path, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0 and rep["reproduced"]
    # clean tree: STALE, exit 2
    monkeypatch.undo()
    rc = cli_main(["simfleet", "replay", path, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 2 and not rep["reproduced"]


def test_cli_simfleet_replay_trace_renders_waterfall(
        tmp_path, monkeypatch, capsys):
    _install_lease_mutant(monkeypatch)
    out_dir = str(tmp_path / "repros")
    rc = cli_main(["simfleet", "run", "--seeds", "4",
                   "--out", out_dir, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 1
    path = rep["violations"][0]["path"]
    rc = cli_main(["simfleet", "replay", path, "--trace"])
    out = capsys.readouterr().out
    assert rc == 0 and "REPRODUCED" in out
    # the same waterfall `cli trace` renders: a Trace header plus spans
    assert "Trace tr-" in out and "job-submit" in out


# --- real-clock default path unchanged -------------------------------------


def test_system_clock_still_the_default():
    """No sim installed: the shim reads the real clock (the production
    path PR 14/16's e2e suites exercise unmodified)."""
    assert isinstance(uclock.get(), uclock.SystemClock)
    before = time.time()
    got = uclock.now()
    assert abs(got - before) < 5.0
