"""End-to-end engine-vs-oracle checks on the two L2 component models
(SURVEY.md §7 step 4: the minimum end-to-end slice)."""

import pytest

from kafka_specification_tpu.models import finite_replicated_log, id_sequence

from helpers import assert_matches_oracle


@pytest.mark.parametrize("max_id", [0, 3, 10])
def test_id_sequence(max_id):
    model = id_sequence.make_model(max_id)
    oracle = id_sequence.make_oracle(max_id)
    res, ores = assert_matches_oracle(model, oracle)
    # IdSequence is a single chain: 0..MaxId+1 -> MaxId+2 states, diameter MaxId+1
    assert res.total == max_id + 2
    assert res.diameter == max_id + 1
    assert res.ok


@pytest.mark.parametrize(
    "n,l,r",
    [
        (2, 2, 1),
        (2, 2, 2),
        (3, 2, 2),
        (2, 3, 2),
    ],
)
def test_finite_replicated_log(n, l, r):
    model = finite_replicated_log.make_model(n, l, r)
    oracle = finite_replicated_log.make_oracle(n, l, r)
    res, ores = assert_matches_oracle(model, oracle)
    assert res.ok
    # closed form: per-replica log count = sum_{k=0..L} R^k, independent replicas
    per_log = sum(r**k for k in range(l + 1))
    assert res.total == per_log**n


def test_frl_3replicas_logsize4():
    """The BASELINE.json config 'FiniteReplicatedLog (3 replicas, L=4)' at a
    reduced record universe — full cross-check against the oracle."""
    model = finite_replicated_log.make_model(3, 4, 1)
    oracle = finite_replicated_log.make_oracle(3, 4, 1)
    res, _ = assert_matches_oracle(model, oracle)
    assert res.total == 5**3
