"""Sharded device-resident level pipeline (`--pipeline device` +
`--sharded`): per-shard one-dispatch level programs with the exchange
inside the loop.

Pins the PR's contracts:
- bit-identity with the per-chunk sharded path (`pipeline="legacy"`, the
  oracle): counts, levels, duplicate accounting, first-violation rule,
  trace VALUES and digest chains — across violating/clean models, both
  exchange modes, the compressed exchange, and multi-chunk levels;
- O(1) collective-bearing launches per level per shard, span-tracer- and
  gauge-pinned, with a >1-chunk single-dispatch proven and the <=2-launch
  bound holding through the forced level-new-overflow exact re-dispatch;
- cross-pipeline sharded checkpoint resume (sharded-device <->
  sharded-legacy) and an elastic 4->2 reshard under the device pipeline;
- the degradation ladder (non-device backend / injected compile failure
  -> per-chunk, sticky, reason recorded) and loud rejection of unknown
  pipeline names;
- the EXPLICIT mesh-axis layouts (mesh_layouts): every placed tensor
  class carries the named PartitionSpec, asserted on real committed
  arrays and recorded in stats.
"""

import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kafka_specification_tpu.models import finite_replicated_log as frl
from kafka_specification_tpu.models import kip320, variants
from kafka_specification_tpu.models.kafka_replication import Config
from kafka_specification_tpu.obs.runctx import RunContext
from kafka_specification_tpu.parallel.sharded import (
    check_sharded,
    mesh_layouts,
)

pytestmark = pytest.mark.sharded_device

# small gated chunks: the serial sharded path compacts at these sizes,
# so the device program covers the same chunks it mirrors
KW = dict(min_bucket=8, compact_gate=8, chunk_size=64)


def _mk_violating():
    return variants.make_model(
        "KafkaTruncateToHighWatermark", Config(2, 2, 1, 1),
        ("TypeOk", "WeakIsr"),
    )


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("d",))


def _verdict(res):
    v = res.violation
    return (
        res.levels,
        res.total,
        None if v is None else (v.invariant, v.depth, v.state),
    )


def test_sharded_device_bit_identity_violating_model():
    """Counts, levels, first-violation rule and trace VALUES equal the
    per-chunk oracle on the violating workload."""
    ref = check_sharded(_mk_violating(), pipeline="legacy", **KW)
    res = check_sharded(_mk_violating(), pipeline="device", **KW)
    assert res.stats["device"]["levels"] > 0
    assert res.stats["device"]["fallback"] is None
    assert res.stats["pipeline"] == "device"
    assert _verdict(res) == _verdict(ref)
    assert res.violation.trace == ref.violation.trace
    assert res.violation.depth == 8 and res.violation.invariant == "WeakIsr"


@pytest.mark.slow
def test_sharded_device_bit_identity_all_gather():
    """The all_gather exchange mode inside the level loop is exact too."""
    m = kip320.make_model(Config(2, 2, 1, 1))
    ref = check_sharded(m, pipeline="legacy", exchange="all_gather", **KW)
    res = check_sharded(m, pipeline="device", exchange="all_gather", **KW)
    assert res.stats["device"]["levels"] > 0
    assert (res.total, res.levels) == (ref.total, ref.levels) == (277, ref.levels)


@pytest.mark.slow
def test_sharded_device_compressed_exchange(monkeypatch):
    """The PR 10 compression codec rides INSIDE the while_loop:
    bit-identical results, strictly fewer wire bytes than the raw
    layout at the same widths."""
    monkeypatch.setenv("KSPEC_EXCHANGE_COMPRESS", "1")
    m = kip320.make_model(Config(2, 2, 1, 1))
    ref = check_sharded(m, pipeline="legacy", **KW)
    res = check_sharded(m, pipeline="device", **KW)
    assert res.stats["device"]["levels"] > 0
    assert (res.total, res.levels) == (ref.total, ref.levels)
    assert res.stats["exchange_compressed"] is True
    assert 0 < res.stats["exchange_bytes_total"] < \
        res.stats["exchange_raw_bytes_total"]


@pytest.mark.perf
def test_sharded_device_launches_per_level(tmp_path):
    """The O(1)-launches/level/shard contract, span-tracer-verified:
    every level — including MULTI-CHUNK levels — dispatches at most 2
    collective-bearing programs per shard (one steady-state; two only
    on the exact-bound overflow re-dispatch), where the per-chunk path
    dispatches one per chunk.  chunk_size 128 forces several levels of
    FRL(3,3,2) through multiple chunks, so the test proves the
    while_loop really covers the chunk loop AND the exchange."""
    m = frl.make_model(3, 3, 2)
    kw = dict(min_bucket=64, compact_gate=32, chunk_size=128,
              store_trace=False)
    run = RunContext(str(tmp_path / "dev"))
    res = check_sharded(m, pipeline="device", run=run, **kw)
    run.deactivate()
    assert res.ok and res.total == 3375
    assert res.stats["device"]["levels"] > 0
    assert res.stats["device"]["fallback"] is None
    for lvl in res.stats["levels"]:
        assert lvl["shard_launches"] <= 2, lvl
    with open(os.path.join(run.dir, "spans.jsonl")) as fh:
        spans = [json.loads(line) for line in fh]
    lv = [s for s in spans
          if s.get("span") == "exchange-level" and s.get("ph") != "B"]
    assert lv, "no exchange-level spans recorded"
    assert all(s["launches"] <= 2 for s in lv)
    # the multi-chunk proof: at least one single-dispatch span covered
    # more than one serial chunk
    assert any(s.get("chunks", 1) > 1 for s in lv), \
        [s.get("chunks") for s in lv]
    # the per-chunk oracle run shows O(chunks) launches on the same
    # config (and pins bit-identity at this chunking)
    r_leg = check_sharded(m, pipeline="legacy", **kw)
    assert r_leg.levels == res.levels and r_leg.total == res.total


@pytest.mark.slow
def test_sharded_device_ln_overflow_redispatch(monkeypatch):
    """A level-new-set overflow costs exactly one exact-bound
    re-dispatch (<=2 launches/level/shard even then) and stays
    bit-identical: shrink the shared LN ladder so every multi-state
    level overflows."""
    from kafka_specification_tpu.ops import devlevel

    m = kip320.make_model(Config(2, 2, 1, 1))
    ref = check_sharded(m, pipeline="legacy", **KW)
    monkeypatch.setattr(devlevel, "level_new_capacity",
                        lambda T, hw, worst: 8)
    res = check_sharded(m, pipeline="device", stats_path=os.devnull, **KW)
    assert res.stats["device"]["levels"] > 0
    assert (res.total, res.levels) == (ref.total, ref.levels)
    launches = [l["shard_launches"] for l in res.stats["levels"]]
    assert any(n == 2 for n in launches), launches  # re-dispatch happened
    assert all(n <= 2 for n in launches), launches


@pytest.mark.slow
def test_sharded_device_cross_pipeline_resume(tmp_path):
    """A sharded checkpoint written under one pipeline resumes under the
    other, bit-identical on counts, levels AND the digest chain (the
    checkpoint format is pipeline-independent by construction)."""
    m = kip320.make_model(Config(2, 2, 1, 1))
    full = check_sharded(m, pipeline="legacy", **KW)
    chains = {}
    for first, second in (("device", "legacy"), ("legacy", "device")):
        ck = str(tmp_path / f"ck-{first}")
        cut = check_sharded(m, pipeline=first, checkpoint_dir=ck,
                            checkpoint_every=1, max_depth=6, **KW)
        assert cut.diameter == 6
        resumed = check_sharded(m, pipeline=second, checkpoint_dir=ck,
                                checkpoint_every=1, **KW)
        assert resumed.total == full.total
        assert resumed.levels == full.levels
        with np.load(os.path.join(ck, "sharded_checkpoint.npz")) as z:
            chains[(first, second)] = np.array(z["digest_chain"])
    # the two resume orders sealed the identical chain
    a, b = chains.values()
    assert np.array_equal(a, b)


def test_sharded_device_elastic_4_to_2(tmp_path, monkeypatch):
    """Elastic reshard UNDER the device pipeline: a 4-shard device-run
    checkpoint resumed on 2 shards (still --pipeline device) re-buckets
    ownership and completes bit-identical to the oracle."""
    from kafka_specification_tpu.resilience.faults import InjectedCrash

    model = frl.make_model(2, 2, 2)
    kw = dict(min_bucket=8, compact_gate=8)
    golden = check_sharded(model, mesh=_mesh(4), pipeline="legacy", **kw)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", "crash@level:2")
    with pytest.raises(InjectedCrash):
        check_sharded(model, mesh=_mesh(4), pipeline="device",
                      checkpoint_dir=ck, **kw)
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check_sharded(model, mesh=_mesh(2), pipeline="device",
                            checkpoint_dir=ck, **kw)
    assert resumed.ok and resumed.total == 49
    assert _verdict(resumed) == _verdict(golden)


def test_sharded_device_fallback_non_device_backend():
    """The degradation ladder: the device-hash backend (no whole-level
    program) records the sticky fallback reason NAMING the backend and
    the per-chunk path serves the run — results identical to the
    oracle."""
    m = frl.make_model(3, 4, 1)
    ref = check_sharded(m, pipeline="legacy", min_bucket=64,
                        visited_backend="device-hash")
    res = check_sharded(m, pipeline="device", min_bucket=64,
                        visited_backend="device-hash")
    assert res.total == ref.total == 125
    assert res.stats["device"]["levels"] == 0
    assert "device-hash" in res.stats["device"]["fallback"]


@pytest.mark.device_host
def test_sharded_device_host_backend_bit_identity():
    """`--sharded --pipeline device` on the HOST backend (the deferred
    per-shard probe): each shard's level runs as ONE dispatched program
    with NO visited shards on device, and each owner shard's FpSet
    takes one batched insert per level — bit-identical to the per-chunk
    sharded oracle on the violating workload (counts, levels,
    first-violation rule, trace VALUES), device path proven engaged,
    probe attribution recorded."""
    ref = check_sharded(_mk_violating(), pipeline="legacy",
                        visited_backend="host", **KW)
    res = check_sharded(_mk_violating(), pipeline="device",
                        visited_backend="host",
                        stats_path=os.devnull, **KW)
    assert res.stats["device"]["levels"] > 0
    assert res.stats["device"]["fallback"] is None
    assert _verdict(res) == _verdict(ref)
    assert res.violation.trace == ref.violation.trace
    assert res.violation.depth == 8 and \
        res.violation.invariant == "WeakIsr"
    assert any(
        lvl.get("host_probe_ms") is not None
        for lvl in res.stats.get("levels", [])
    )


@pytest.mark.slow
@pytest.mark.device_host
def test_sharded_device_host_backend_clean_model():
    """Deferred per-shard probe on a passing workload (multi-chunk
    levels): counts/levels equal the per-chunk sharded host oracle."""
    m = kip320.make_model(Config(2, 2, 1, 1))
    ref = check_sharded(m, pipeline="legacy", visited_backend="host",
                        **KW)
    res = check_sharded(m, pipeline="device", visited_backend="host",
                        **KW)
    assert res.stats["device"]["levels"] > 0
    assert (res.total, res.levels) == (ref.total, ref.levels) == \
        (277, ref.levels)


@pytest.mark.fault
def test_sharded_device_compile_failure_degrades(monkeypatch):
    """Injected compile-OOM on the level program degrades the run to the
    per-chunk ladder (sticky, reason recorded) with identical results."""
    m = frl.make_model(2, 2, 2)
    kw = dict(min_bucket=8, compact_gate=8)
    ref = check_sharded(m, pipeline="legacy", **kw)
    monkeypatch.setenv("KSPEC_FAULT", "compile_oom")
    res = check_sharded(m, pipeline="device", **kw)
    assert res.total == ref.total and res.levels == ref.levels
    assert res.stats["device"]["levels"] == 0
    assert res.stats["device"]["fallback"] is not None


def test_sharded_unknown_pipeline_rejected():
    """The sharded engine no longer silently ignores --pipeline: a typo
    is rejected loudly naming the valid set (registry contract)."""
    with pytest.raises(ValueError, match="unknown pipeline"):
        check_sharded(frl.make_model(2, 2, 1), pipeline="devcie")


def test_mesh_layouts_are_explicit_and_recorded():
    """The explicit mesh-axis layouts (SNIPPETS.md sharding-rule
    pattern): the named PartitionSpecs are what they claim, committed
    device arrays actually carry them, and the run stats record them."""
    from kafka_specification_tpu.parallel.multihost import put_global

    mesh = _mesh(8)
    L = mesh_layouts(mesh)
    assert L["frontier"].spec == P("d", None)
    assert L["fpset"].spec == P("d", None)
    assert L["fvalid"].spec == P("d")
    assert L["pershard"].spec == P("d")
    assert L["exchange"].spec == P("d", None)
    # a placed per-shard table really carries the named layout
    arr = put_global(np.zeros((8, 64), np.uint32), L["fpset"])
    assert arr.sharding.spec == L["fpset"].spec
    # ... and the engine records the layout map in its stats
    res = check_sharded(frl.make_model(2, 2, 1), min_bucket=32)
    assert res.stats["mesh_layouts"] == {
        k: str(v.spec) for k, v in L.items()
    }


def test_registry_sharded_engine_matrix():
    """Satellite: the per-engine support matrix is the single queryable
    source for which pipelines each engine serves and why a combination
    degrades (jax-free registry)."""
    from kafka_specification_tpu.pipeline_registry import (
        ENGINES,
        engine_support,
        list_pipelines,
    )

    assert ENGINES == ("single-device", "sharded")
    assert engine_support("device", "sharded")["supported"] is True
    assert "level program" in engine_support("device", "sharded")["detail"]
    assert engine_support("fused", "sharded")["supported"] is False
    assert engine_support("legacy", "sharded")["supported"] is True
    with pytest.raises(ValueError, match="unknown engine"):
        engine_support("device", "gpu-cluster")
    for e in list_pipelines():
        assert set(e["engines"]) == set(ENGINES)
        for cell in e["engines"].values():
            assert isinstance(cell["supported"], bool) and cell["detail"]
