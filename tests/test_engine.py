"""Engine behaviors: violation traces, hashed-fingerprint dedup mode,
invariant checking at init, depth cutoffs."""

import numpy as np

from kafka_specification_tpu.engine.bfs import check
from kafka_specification_tpu.models import finite_replicated_log, id_sequence
from kafka_specification_tpu.models.base import Invariant, Model

from helpers import assert_matches_oracle


def _with_invariants(base, invariants):
    return Model(
        name=base.name,
        spec=base.spec,
        init_states=base.init_states,
        actions=base.actions,
        invariants=invariants,
        constraint=base.constraint,
        decode=base.decode,
    )


def test_violation_trace_is_valid_action_path():
    """Falsify an invariant at the end of the IdSequence chain; the
    reconstructed trace must be a valid path init -> violation."""
    max_id = 5
    base = id_sequence.make_model(max_id)
    model = _with_invariants(
        base, [Invariant("BelowBound", lambda s: s["nextId"] <= 3)]
    )
    res = check(model, min_bucket=32)
    assert res.violation is not None
    v = res.violation
    assert v.invariant == "BelowBound"
    assert v.depth == 4 and v.state == 4
    # the trace replays as a real action path: 0 ->NextId-> 1 ... -> 4
    assert [s for _, s in v.trace] == [0, 1, 2, 3, 4]
    assert v.trace[0][0] == "<init>"
    assert all(a == "NextId" for a, _ in v.trace[1:])


def test_violation_at_init():
    base = id_sequence.make_model(3)
    model = _with_invariants(base, [Invariant("NotZero", lambda s: s["nextId"] != 0)])
    res = check(model)
    assert res.violation is not None
    assert res.violation.depth == 0
    assert res.violation.trace == [("<init>", 0)]


def test_hashed_fingerprint_mode_full_bfs():
    """Same model checked in exact64 and forced-hashed dedup mode must agree
    with the oracle state-for-state (exercises murmur3 path through the
    whole sort/member/merge pipeline)."""
    model = finite_replicated_log.make_model(2, 2, 2, force_hashed=True)
    assert not model.spec.exact64
    oracle = finite_replicated_log.make_oracle(2, 2, 2)
    res, _ = assert_matches_oracle(model, oracle)
    assert res.total == 7**2


def test_invariants_checked_on_new_states_each_level():
    """A violation deep in FRL: no log may reach length 2 — found at depth 2."""
    base = finite_replicated_log.make_model(2, 2, 1)
    model = _with_invariants(
        base,
        [Invariant("ShortLogs", lambda s: (s["end"] < 2).all())],
    )
    res = check(model, min_bucket=32)
    assert res.violation is not None
    assert res.violation.invariant == "ShortLogs"
    assert res.violation.depth == 2
    # trace is a valid path of length depth+1
    assert len(res.violation.trace) == 3
    assert res.violation.trace[0][0] == "<init>"


def test_chunked_frontier_matches_golden():
    """Tiny chunk_size forces multi-chunk levels; counts must be identical
    (cross-chunk dedup rides the shared visited set)."""
    model = finite_replicated_log.make_model(3, 4, 2)
    res = check(model, min_bucket=32, chunk_size=32, store_trace=False)
    assert res.ok
    assert res.total == 29791
    assert res.diameter == 12


def test_chunked_violation_depth_stable():
    base = finite_replicated_log.make_model(2, 2, 1)
    model = Model(
        name=base.name,
        spec=base.spec,
        init_states=base.init_states,
        actions=base.actions,
        invariants=[Invariant("ShortLogs", lambda s: (s["end"] < 2).all())],
        decode=base.decode,
    )
    res = check(model, min_bucket=32, chunk_size=32)
    assert res.violation is not None and res.violation.depth == 2
    assert len(res.violation.trace) == 3


def test_multiple_initial_states():
    """TLC enumerates all Init states; the engine must seed BFS with the
    whole (deduplicated) init set and count level 0 accordingly."""
    base = id_sequence.make_model(6)

    def inits():
        return [{"nextId": 0}, {"nextId": 3}, {"nextId": 3}, {"nextId": 5}]

    model = Model(
        name="IdSeq-multi-init",
        spec=base.spec,
        init_states=inits,
        actions=base.actions,
        invariants=base.invariants,
        decode=base.decode,
    )
    res = check(model, min_bucket=32)
    assert res.levels[0] == 3  # deduplicated init set
    # reachable: 0..7 from the three seeds
    assert res.total == 8
    assert res.ok


def test_adaptive_compile_fallback_exact(monkeypatch):
    """An escalated per-action compact program that fails to compile must
    not kill the run: the engine falls back loudly to the uniform path
    and stays exact (XLA:CPU's LLVM has been seen OOMing on the 27-action
    mixed product's escalated step — TODO.md known gap, now handled).

    The escalated state is injected (widths_for returns a per-action
    tuple while adaptation is on) so the test doesn't depend on a model
    dense enough to overflow organically; the organic uniform-overflow ->
    escalate path is covered by tests/test_sharded.py's escalation test
    and the policy unit test."""
    from kafka_specification_tpu.engine import bfs as bfs_mod
    from kafka_specification_tpu.models import finite_replicated_log as frl

    orig_get = bfs_mod._Step.get
    orig_wf = bfs_mod.AdaptiveCompact.widths_for

    def tuple_widths(self, bucket):
        if self.on:  # pre-fallback: pretend a prior chunk escalated
            return tuple(256 for _ in self.actions)
        return orig_wf(self, bucket)

    def failing_get(self, bucket, vcap, *args, **kw):
        if isinstance(kw.get("compact"), (list, tuple)):
            raise RuntimeError("synthetic XLA compile failure")
        return orig_get(self, bucket, vcap, *args, **kw)

    monkeypatch.setattr(bfs_mod.AdaptiveCompact, "widths_for", tuple_widths)
    monkeypatch.setattr(bfs_mod._Step, "get", failing_get)
    model = frl.make_model(2, 2, 2)
    res = check(
        model, store_trace=False, compact_shift=2, visited_backend="host"
    )
    assert res.ok and res.total == 49
    assert res.stats["adaptive_compile_fallback"] is True
    assert res.stats["adaptive_active"] is False
