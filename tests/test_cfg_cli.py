"""TLC .cfg parsing, module registry, CLI, and checkpoint/resume."""

import numpy as np
import pytest

from kafka_specification_tpu.utils.cfg import parse_cfg, build_model
from kafka_specification_tpu.utils.cli import main as cli_main
from kafka_specification_tpu.engine.bfs import check


def test_parse_cfg_full_syntax(tmp_path):
    text = """
\\* comment line
SPECIFICATION Spec
CONSTANTS
    Replicas = {b1, b2, b3}
    LogSize = 2   \\* trailing comment
    MaxRecords = 2
    MaxLeaderEpoch = 2
(* block
   comment *)
INVARIANTS TypeOk WeakIsr
INVARIANT StrongIsr
CONSTRAINT Bounded
CHECK_DEADLOCK FALSE
"""
    cfg = parse_cfg(text)
    assert cfg.constants["Replicas"] == ["b1", "b2", "b3"]
    assert cfg.constants["LogSize"] == 2
    assert cfg.invariants == ["TypeOk", "WeakIsr", "StrongIsr"]
    assert cfg.constraints == ["Bounded"]
    assert cfg.specification == "Spec"
    assert cfg.check_deadlock is False


def test_build_model_registry_covers_all_modules():
    import pathlib

    aliases = {"Kip320Stretch": "Kip320"}  # cfg files not named after a module
    for cfg_file in pathlib.Path("configs").glob("*.cfg"):
        module = aliases.get(cfg_file.stem, cfg_file.stem)
        cfg = parse_cfg(cfg_file)
        model = build_model(module, cfg)
        oracle = build_model(module, cfg, oracle=True)
        assert model.actions and oracle.actions
        # invariant names listed in the .cfg drive the model's predicates
        if cfg.invariants:
            assert [i.name for i in model.invariants] == cfg.invariants


def test_cli_check_and_exit_codes(tmp_path, capsys):
    # IdSequence exhaustive pass -> exit 0
    rc = cli_main(["check", "configs/IdSequence.cfg", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    assert '"distinct_states": 12' in out


def test_cli_simulate_emitted(capsys):
    # random walks over the mechanically emitted IdSequence model; TypeOk
    # holds on every walk -> exit 0
    rc = cli_main(
        ["simulate", "configs/IdSequence.cfg", "--emitted", "--walks", "4",
         "--depth", "6", "--seed", "3"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "no violations" in out


def test_checkpoint_resume(tmp_path):
    from kafka_specification_tpu.models import finite_replicated_log as frl

    ckdir = str(tmp_path / "ck")
    model = frl.make_model(2, 2, 2)
    # run 3 levels, "crash", resume to completion
    partial = check(model, max_depth=3, min_bucket=32, checkpoint_dir=ckdir)
    assert partial.total < 49
    resumed = check(model, min_bucket=32, checkpoint_dir=ckdir)
    assert resumed.total == 49  # 7^2, same as the uncheckpointed golden run
    assert resumed.ok


@pytest.mark.slow  # round-5 fast-suite budget (<=300s): cheaper siblings keep the
# fast-path coverage; this full variant runs in the slow set
def test_stretch_config_builds_product_model():
    """The 5-broker/3-partition stretch workload is expressible via the
    authored Partitions constant and explores correctly under a bound."""
    cfg = parse_cfg("configs/Kip320Stretch.cfg")
    model = build_model("Kip320", cfg)
    assert model.meta["partitions"] == 3
    assert model.spec.num_lanes >= 3 * 9 // 2  # 3 partitions of 5-broker state
    res = check(model, max_states=700, max_depth=2, store_trace=False, min_bucket=64)
    assert res.levels[:3] == [1, 30, 570]  # 3 partitions x 10 controller moves, etc.


def test_validate_emitted_covers_reference_next():
    """`validate --emitted`: the mechanically emitted model's `Name~k` DNF
    branches map back to their source disjuncts and cover the reference
    Next exactly (VERDICT r2 item 7 — the two halves of the fidelity story
    compose).  One module here (emission is ~20s/module); all six L4
    configs are exercised by the CLI run recorded in RESULTS.md."""
    rc = cli_main(["validate", "configs/Kip320.cfg", "--emitted"])
    assert rc == 0
