"""Cross-host routed fleet (service/router.py; docs/service.md
§ Cross-host deployment).

Fast tier (`router` marker).  Units cover the host-fault grammar
(kill@host / partition@host / skew@host), the KSPEC_CLOCK_SKEW lease
allowance, the full-jitter retry envelope, federated state-cache
concurrent-publish races + GC, and the router itself (health taxonomy,
placement, fleet-wide admission, exactly-once dead-host re-routing).
The acceptance e2e runs two in-process "hosts" over one shared cache
namespace under kill@host0:1 + partition@host1 + flip@cache:1 — every
job completes exactly once, verdicts bit-identical to solo cold
answers, including a cross-host chain-verified cache hit served after
the publishing host is dead.  (Real-subprocess host death is covered by
test_fleet's chaos e2e; this one drills the CROSS-host protocol.)
"""

import errno
import json
import os
import random
import threading
import time

import numpy as np
import pytest

from kafka_specification_tpu.resilience.faults import (
    FaultPlan,
    InjectedCrash,
    injected_skew_s,
)
from kafka_specification_tpu.service.daemon import Daemon, ServeConfig
from kafka_specification_tpu.service.queue import (
    JobQueue,
    RETRY_CAP_S,
    clock_skew_s,
    retry_transient,
)
from kafka_specification_tpu.service.router import (
    AdmissionDenied,
    Router,
    classify_host,
)
from kafka_specification_tpu.service.state_cache import (
    CacheHit,
    CacheKey,
    StateSpaceCache,
)
from kafka_specification_tpu.utils.cli import main as cli_main

pytestmark = pytest.mark.router

ID_CFG = """
SPECIFICATION Spec
CONSTANTS
    MaxId = 6
INVARIANTS TypeOk
CHECK_DEADLOCK FALSE
"""

TTW_CFG = """
SPECIFICATION Spec
CONSTANTS
    Replicas = {b1, b2}
    LogSize = 2
    MaxRecords = 1
    MaxLeaderEpoch = 1
INVARIANTS TypeOk
CHECK_DEADLOCK FALSE
"""


def _events(svc, path="service/events.jsonl"):
    try:
        with open(os.path.join(str(svc), path)) as fh:
            return [json.loads(line) for line in fh]
    except OSError:
        return []


def _hb(host_dir, t=None):
    """Stamp one live heartbeat into a host's service dir (what a
    serving daemon does every poll)."""
    svc = os.path.join(str(host_dir), "service")
    os.makedirs(svc, exist_ok=True)
    with open(os.path.join(svc, "heartbeat.jsonl"), "a") as fh:
        fh.write(json.dumps(
            {"kind": "service-heartbeat",
             "unix": round(time.time() if t is None else t, 3)}
        ) + "\n")


def _wait(pred, timeout=20.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


# --- host fault grammar ---------------------------------------------------


def test_host_fault_grammar_parses_and_scopes():
    p = FaultPlan("kill@host0:2,partition@host1:3,skew@host0:-2.5")
    p.set_host(0)
    # skew targets host 0; partition targets host 1 — inert here
    assert p.skew_s() == -2.5
    assert p.host_partition() == 0
    # kill fires on job ordinal 2, not 1, and consumes its budget
    p.host_kill(1, 1)
    with pytest.raises(InjectedCrash):
        p.host_kill(2, 2)
    p.host_kill(2, 2)  # budget spent: a restarted host converges

    p1 = FaultPlan("kill@host0:2,partition@host1:3,skew@host0:-2.5")
    p1.set_host(1)
    assert p1.skew_s() == 0.0
    assert p1.host_partition() == 3  # once...
    assert p1.host_partition() == 0  # ...then 0
    p1.host_kill(1, 10)  # kill targets host 0: silent here

    # without set_host (a non-fleet process) every host fault is inert
    p2 = FaultPlan("kill@host0:1,partition@host0,skew@host0:4")
    assert p2.skew_s() == 0.0
    assert p2.host_partition() == 0
    p2.host_kill(1, 100)


def test_host_fault_typos_rejected_loudly():
    for bad in ("kill@host0", "kill@host:1", "kill@hostx:1",
                "partition@host0:0", "skew@host0", "skew@host0:abc",
                "kill@host0:0"):
        with pytest.raises(ValueError):
            FaultPlan(bad)


def test_faults_registry_lists_host_sites(capsys):
    assert cli_main(["faults", "--json"]) == 0
    entries = json.loads(capsys.readouterr().out)
    grammars = {e["grammar"] for e in entries}
    assert "kill@host<i>:N" in grammars
    assert "partition@host<i>[:N]" in grammars
    assert "skew@host<i>:SECS" in grammars


def test_injected_skew_module_helper(monkeypatch):
    monkeypatch.setenv("KSPEC_FAULT", "skew@host0:-3,skew@host1:7")
    monkeypatch.setenv("KSPEC_HOST_INSTANCE", "0")
    assert injected_skew_s() == -3.0
    monkeypatch.setenv("KSPEC_HOST_INSTANCE", "1")
    assert injected_skew_s() == 7.0
    # no host identity / no plan -> no shift
    monkeypatch.delenv("KSPEC_HOST_INSTANCE")
    assert injected_skew_s() == 0.0
    monkeypatch.setenv("KSPEC_HOST_INSTANCE", "0")
    monkeypatch.delenv("KSPEC_FAULT")
    assert injected_skew_s() == 0.0


# --- clock skew allowance (satellite 1) -----------------------------------


def test_clock_skew_env_default_override_clamp(monkeypatch):
    monkeypatch.delenv("KSPEC_CLOCK_SKEW", raising=False)
    assert clock_skew_s() == 5.0
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "2.5")
    assert clock_skew_s() == 2.5
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "-4")  # clamped: never narrows
    assert clock_skew_s() == 0.0
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "bogus")
    assert clock_skew_s() == 5.0


def test_skew_fault_shifts_lease_stamp(tmp_path, monkeypatch):
    monkeypatch.setenv("KSPEC_FAULT", "skew@host0:-3")
    monkeypatch.setenv("KSPEC_HOST_INSTANCE", "0")
    q = JobQueue(str(tmp_path / "svc"))
    jid = q.submit(ID_CFG, "IdSequence", kernel_source="hand")["job_id"]
    q.claim_pending()
    lease = q.read_lease(jid)
    assert lease is not None
    # the lease stamp reads ~3s behind this process's wall clock
    assert 2.0 < time.time() - lease["lease_unix"] < 4.0


def test_skewed_but_live_claim_never_stolen(tmp_path, monkeypatch):
    """THE skew regression: a live claimer whose clock runs a few
    seconds behind writes lease stamps that LOOK expired to a sibling
    with an aggressive TTL.  The KSPEC_CLOCK_SKEW allowance in lease
    expiry is what keeps its claim un-stolen — drop the allowance and
    the same lease is (wrongly) requeued."""
    q = JobQueue(str(tmp_path / "svc"))
    jid = q.submit(ID_CFG, "IdSequence", kernel_source="hand")["job_id"]
    q.claim_pending()
    # a live foreign claimer (pid 1 never dies) 3s behind our clock
    with open(q._lease_path(jid), "w") as fh:
        json.dump({"pid": 1, "token": "foreign-host",
                   "lease_unix": round(time.time() - 3.0, 3)}, fh)
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "5")
    sibling = JobQueue(str(tmp_path / "svc"))
    assert sibling.requeue_orphans(lease_ttl=1.0) == []
    assert q.status(jid)["state"] == "claimed"
    # same lease, allowance off: the apparent age now exceeds the TTL
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "0")
    assert sibling.requeue_orphans(lease_ttl=1.0) == [jid]
    assert q.status(jid)["state"] == "pending"


def test_router_tolerates_skewed_heartbeats(tmp_path, monkeypatch):
    """A host whose heartbeat stamps run AHEAD or behind by less than
    the allowance still reads as alive; beyond dead_after + allowance it
    is dead."""
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "5")
    h0 = tmp_path / "h0"
    JobQueue(str(h0))
    r = Router(str(tmp_path / "rt"), hosts=[str(h0)], dead_after_s=2.0)
    _hb(h0, t=time.time() - 6.0)  # 6s stale < 2 + 5 allowance
    assert r.host_health(0)["state"] == "ok"
    _hb(h0, t=time.time() + 4.0)  # a fast clock is just as alive
    assert r.host_health(0)["state"] == "ok"
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "0")
    r2 = Router(str(tmp_path / "rt2"), hosts=[str(h0)], dead_after_s=2.0)
    # newest stamp is the +4s one: still fresh even without allowance
    assert r2.host_health(0)["state"] == "ok"


# --- full-jitter retry backoff (satellite 2) ------------------------------


def test_retry_full_jitter_envelope(monkeypatch):
    """The backoff is full jitter: every sleep ~ U[0, min(cap, base*2^i)]
    — deterministic under a seeded rng, never above the envelope, and
    actually jittered (not the old fixed ladder)."""
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))

    def always():
        raise OSError(errno.EAGAIN, "again")

    base, attempts = 0.05, 6
    with pytest.raises(OSError):
        retry_transient(always, attempts=attempts, base=base,
                        rng=random.Random(42))
    assert len(sleeps) == attempts - 1
    for i, s in enumerate(sleeps):
        assert 0.0 <= s <= min(RETRY_CAP_S, base * (2.0 ** i)), (i, s)
    # same seed -> same schedule (tests can pin retry timing exactly)
    replay = []
    monkeypatch.setattr(time, "sleep", lambda s: replay.append(s))
    with pytest.raises(OSError):
        retry_transient(always, attempts=attempts, base=base,
                        rng=random.Random(42))
    assert replay == sleeps
    # different seed -> different schedule: the jitter is real
    other = []
    monkeypatch.setattr(time, "sleep", lambda s: other.append(s))
    with pytest.raises(OSError):
        retry_transient(always, attempts=attempts, base=base,
                        rng=random.Random(7))
    assert other != sleeps


# --- federated state cache: concurrent same-key publishes (satellite 3) ---


def _entry_key(max_depth=2):
    return CacheKey("M", False, (("MaxId", 6),), ("TypeOk",), (), False,
                    max_depth=max_depth)


def _publish_toy(cache, key, seed, n_levels=3):
    rng = np.random.RandomState(seed)
    counts = [1, 3, 5][:n_levels]
    rows = [rng.randint(0, 50, size=(n, 2)).astype(np.uint32)
            for n in counts]
    verdict = {"model": "M", "distinct_states": sum(counts),
               "diameter": n_levels - 1, "levels": counts,
               "violation": None, "exit_code": 0,
               "states_per_sec": 1.0, "seconds": 0.1}
    assert cache.publish(key, verdict, exact64=True, lanes=2,
                         level_rows=rows, diameter=n_levels - 1)
    return verdict


def test_concurrent_same_key_publish_last_promote_wins(tmp_path):
    """Two publishers (two hosts of a federation) race the same key:
    whichever entry.json promote lands last wins, the surviving entry
    chain-verifies, and the loser's nonce-named artifacts are invisible
    to readers and collected by GC."""
    events = []
    c = StateSpaceCache(str(tmp_path / "sc"),
                        event=lambda k, **f: events.append((k, f)))
    key = _entry_key()
    barrier = threading.Barrier(2)
    errs = []

    def publisher(seed):
        try:
            barrier.wait(timeout=10)
            _publish_toy(c, key, seed)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=publisher, args=(s,)) for s in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs
    # the surviving entry chain-verifies end to end
    hit = c.lookup(key)
    assert isinstance(hit, CacheHit)
    assert hit.verdict["distinct_states"] == 9
    # exactly one entry's artifacts referenced; GC (grace 0: the race is
    # over) removes the loser's files, never the winner's
    d = c._entry_dir(key)
    art = json.load(open(os.path.join(d, "entry.json")))["artifact"]
    referenced = {art["visited"]["name"], art["boundary"]["name"]}
    collected = set(c.collect_garbage(key, grace_s=0.0))
    assert not (collected & referenced)
    left = {f for f in os.listdir(d)
            if f.endswith((".run", ".npy"))}
    assert left == referenced
    # the winner still verifies after the sweep
    assert isinstance(c.lookup(key), CacheHit)


def test_reader_mid_race_verified_hit_or_typed_fallback(tmp_path):
    """A reader racing publishers gets a chain-verified hit or a typed
    miss/fallback — never a torn artifact surfaced as an answer."""
    events = []
    c = StateSpaceCache(str(tmp_path / "sc"),
                        event=lambda k, **f: events.append((k, f)))
    key = _entry_key()
    stop = threading.Event()
    errs = []

    def hammer(seed):
        try:
            while not stop.is_set():
                _publish_toy(c, key, seed)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    writers = [threading.Thread(target=hammer, args=(s,))
               for s in (0, 1)]
    for t in writers:
        t.start()
    try:
        verified = 0
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            out = c.lookup(key)  # must never raise or return garbage
            if isinstance(out, CacheHit):
                assert out.verdict["distinct_states"] == 9
                verified += 1
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=30)
    assert not errs
    assert verified  # the race window actually served verified hits


def test_gc_grace_protects_concurrent_publishers(tmp_path):
    c = StateSpaceCache(str(tmp_path / "sc"))
    key = _entry_key()
    _publish_toy(c, key, 0)
    d = c._entry_dir(key)
    # a concurrent publisher's half-written artifact, seconds old
    in_flight = os.path.join(d, "visited-dead-beef.run")
    with open(in_flight, "wb") as fh:
        fh.write(b"partial")
    assert c.collect_garbage(key, grace_s=120.0) == []
    assert os.path.exists(in_flight)
    # past the grace it is garbage (its publisher died mid-flight)
    old = time.time() - 600
    os.utime(in_flight, (old, old))
    assert c.collect_garbage(key, grace_s=120.0) == [
        "visited-dead-beef.run"
    ]
    assert not os.path.exists(in_flight)


# --- the router: health, placement, admission, re-route -------------------


def test_classify_host_table():
    assert classify_host(False, False) == "unseen"
    assert classify_host(True, True) == "ok"
    assert classify_host(True, False) == "dead"


def _two_hosts(tmp_path, dead_after=2.0):
    h0, h1 = str(tmp_path / "h0"), str(tmp_path / "h1")
    JobQueue(h0)
    JobQueue(h1)
    r = Router(str(tmp_path / "rt"), hosts=[h0, h1],
               dead_after_s=dead_after)
    return r, h0, h1


def test_router_persists_and_rejects_non_router_dir(tmp_path):
    r, h0, h1 = _two_hosts(tmp_path)
    # reopen without hosts: the persisted config carries them
    r2 = Router(r.dir)
    assert r2.hosts == [h0, h1]
    assert r2.dead_after_s == 2.0
    with pytest.raises(FileNotFoundError):
        Router(str(tmp_path / "h0"))  # a service dir is not a router


def test_placement_prefers_live_then_least_loaded(tmp_path):
    r, h0, h1 = _two_hosts(tmp_path)
    _hb(h1)  # only host 1 has ever heartbeat
    s = r.submit(ID_CFG, "IdSequence", tenant="t",
                 kernel_source="hand")
    assert s["host"] == 1
    _hb(h0)
    # both alive now, host 0 shallower — but the SAME module sticks to
    # its affinity host (the daemons batch same-shape pending jobs into
    # one engine group; co-location is what makes that group large)
    s2 = r.submit(ID_CFG, "IdSequence", tenant="t",
                  kernel_source="hand")
    assert s2["host"] == 1
    # a DIFFERENT module has no affinity yet: least-loaded wins
    s3 = r.submit(TTW_CFG, "KafkaTruncateToHighWatermark", tenant="t",
                  kernel_source="hand")
    assert s3["host"] == 0
    # route records written for all
    assert r.read_route(s["job_id"])["host"] == 1
    assert r.read_route(s2["job_id"])["history"][0]["why"] == "submit"


def test_placement_affinity_releases_on_lag_and_death(tmp_path):
    from kafka_specification_tpu.service.router import AFFINITY_SLACK_JOBS

    r, h0, h1 = _two_hosts(tmp_path)
    _hb(h0)
    _hb(h1)
    assert r.submit(ID_CFG, "IdSequence", tenant="t",
                    kernel_source="hand")["host"] == 0
    # push the affinity host past the slack: the module re-sticks to
    # the least-loaded host instead of deepening the imbalance
    r._affinity["IdSequence"] = 0
    healths = [
        {"host": 0, "state": "ok", "pending": AFFINITY_SLACK_JOBS + 2,
         "claimed": 0},
        {"host": 1, "state": "ok", "pending": 1, "claimed": 0},
    ]
    assert r._choose_host(healths, module="IdSequence") == 1
    assert r._affinity["IdSequence"] == 1
    # an affinity host that leaves the routable pool releases too
    healths = [
        {"host": 0, "state": "ok", "pending": 0, "claimed": 0},
        {"host": 1, "state": "dead", "pending": 0, "claimed": 0},
    ]
    assert r._choose_host(healths, module="IdSequence") == 0
    assert r._affinity["IdSequence"] == 0


def test_fleet_wide_admission(tmp_path):
    r, h0, h1 = _two_hosts(tmp_path)
    _hb(h0)
    _hb(h1)
    with open(r.tenants_path, "w") as fh:
        json.dump({"capped": {"max_pending": 2}}, fh)
    # the cap counts pending across BOTH hosts, not per host
    r.submit(ID_CFG, "IdSequence", tenant="capped", kernel_source="hand")
    r.submit(ID_CFG, "IdSequence", tenant="capped", kernel_source="hand")
    with pytest.raises(AdmissionDenied):
        r.submit(ID_CFG, "IdSequence", tenant="capped",
                 kernel_source="hand")
    # other tenants unaffected
    r.submit(ID_CFG, "IdSequence", tenant="other", kernel_source="hand")


def test_dead_host_pending_rerouted_exactly_once(tmp_path):
    r, h0, h1 = _two_hosts(tmp_path)
    _hb(h0)
    _hb(h1)
    jid = r.submit(ID_CFG, "IdSequence", tenant="t",
                   kernel_source="hand", host=0)["job_id"]
    # host 0 goes quiet past the threshold; host 1 stays fresh.  The
    # stale STAMP is what matters: freshness reads the heartbeat's own
    # `unix` field, never file mtime (mtime would dodge the skew drill)
    hb = os.path.join(h0, "service", "heartbeat.jsonl")
    with open(hb, "w") as fh:
        fh.write(json.dumps({"kind": "service-heartbeat",
                             "unix": round(time.time() - 60, 3)}) + "\n")
    _hb(h1)
    assert r.host_health(0)["state"] == "dead"
    out = r.sweep()
    assert out["rerouted"] == {0: [jid]}
    q0, q1 = JobQueue(h0, create=False), JobQueue(h1, create=False)
    assert q0.pending_count() == 0
    assert q1.pending_count() == 1
    # attribution: the spec carries the hop, the route record the path
    spec = json.load(open(q1._job_path("pending", jid)))
    assert spec["reroutes"][0]["from_host"] == 0
    assert spec["reroutes"][0]["to_host"] == 1
    assert spec["reroutes"][0]["reason"] == "host-dead"
    rec = r.read_route(jid)
    assert rec["host"] == 1
    assert [h["why"] for h in rec["history"]] == [
        "submit", "reroute:host-dead"
    ]
    # idempotent: a second sweep finds nothing to move
    assert r.sweep()["rerouted"] == {}
    # tenant admission markers moved with the job
    assert q1.pending_for_tenant("t") == 1
    assert q0.pending_for_tenant("t") == 0


def test_reroute_retires_verdict_bearing_pending_in_place(tmp_path):
    """A pending file whose verdict already published (the takeover
    protocol's exactly-once edge) is retired to done/ on the dead host,
    never re-routed into a duplicate run."""
    r, h0, h1 = _two_hosts(tmp_path)
    q0 = JobQueue(h0, create=False)
    jid = r.submit(ID_CFG, "IdSequence", tenant="t",
                   kernel_source="hand", host=0)["job_id"]
    os.makedirs(os.path.dirname(q0.result_path(jid)), exist_ok=True)
    with open(q0.result_path(jid), "w") as fh:
        json.dump({"schema": "kspec-verdict/1", "job_id": jid,
                   "status": "complete", "exit_code": 0}, fh)
    _hb(h1)
    # unseen hosts are never swept (they may simply not have started
    # yet): forge a stale heartbeat so host 0 reads dead, not unseen
    _hb(h0, t=time.time() - 60)
    out = r.sweep()
    assert out["rerouted"] == {}
    assert q0.status(jid)["state"] == "done"
    assert JobQueue(h1, create=False).pending_count() == 0


def test_adopt_stale_reroutes(tmp_path):
    """A router that dies mid-re-route leaves a private .reroute-<pid>
    file; the next sweep adopts it — republishing when the copy never
    landed, retiring when it did (stamped intent decides)."""
    r, h0, h1 = _two_hosts(tmp_path)
    q0, q1 = JobQueue(h0, create=False), JobQueue(h1, create=False)
    jid = r.submit(ID_CFG, "IdSequence", tenant="t",
                   kernel_source="hand", host=0)["job_id"]
    src = q0._job_path("pending", jid)
    spec = json.load(open(src))
    spec["reroutes"] = [{"from_host": 0, "to_host": 1,
                         "by_pid": 999999999, "reason": "host-dead",
                         "at": time.time()}]
    private = src + ".reroute-999999999"  # a dead router's pid
    with open(private, "w") as fh:
        json.dump(spec, fh)
    os.unlink(src)
    # case 1: the copy never landed -> adopted back to pending on host 0
    _hb(h0)
    _hb(h1)
    r.sweep()
    assert q0.status(jid)["state"] == "pending"
    # case 2: the copy DID land on the target -> the private file is
    # retired, no duplicate pending left behind
    os.rename(src, private)
    with open(q1._job_path("pending", jid), "w") as fh:
        json.dump(spec, fh)
    r.sweep()
    assert not os.path.exists(private)
    assert q0.status(jid)["state"] != "pending"
    assert q1.status(jid)["state"] == "pending"


def test_router_cli_surface(tmp_path, capsys, monkeypatch):
    """`cli route` + `submit/status/result --router` stay jax-free and
    speak the same records as the library (the tenant contract)."""
    monkeypatch.chdir(tmp_path)
    h0, h1 = str(tmp_path / "h0"), str(tmp_path / "h1")
    JobQueue(h0)
    JobQueue(h1)
    assert cli_main(["route", "rt", "--hosts", h0, h1,
                     "--dead-after", "2", "--status"]) == 0
    assert "2 hosts" in capsys.readouterr().out
    cfg = tmp_path / "id.cfg"
    cfg.write_text(ID_CFG)
    _hb(h0)
    _hb(h1)
    assert cli_main(["submit", str(cfg), "--module", "IdSequence",
                     "--router", "rt", "--json"]) == 0
    sub = json.loads(capsys.readouterr().out)
    assert sub["service_dir"] in (h0, h1)
    assert cli_main(["status", sub["job_id"], "--router", "rt",
                     "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["state"] == "pending" and st["host"] == sub["host"]
    # a sweep pass via the CLI
    assert cli_main(["route", "rt", "--once"]) == 0
    assert "0 claims taken over" in capsys.readouterr().out
    # fleet-wide admission denial exits 2 like the single-dir client
    with open(os.path.join("rt", "tenants.json"), "w") as fh:
        json.dump({"default": {"max_pending": 1}}, fh)
    assert cli_main(["submit", str(cfg), "--module", "IdSequence",
                     "--router", "rt"]) == 2
    assert "max_pending" in capsys.readouterr().err
    # verdict resolution: finish the job on its host, read via router
    q = JobQueue(sub["service_dir"], create=False)
    q.claim_pending()
    q.finish(sub["job_id"], {"schema": "kspec-verdict/1",
                             "job_id": sub["job_id"],
                             "status": "complete", "exit_code": 0})
    assert cli_main(["result", sub["job_id"], "--router", "rt",
                     "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["status"] == "complete"
    # `cli report <router_dir>` renders the cross-host rollup
    assert cli_main(["report", "rt"]) == 0
    assert "Router rt" in capsys.readouterr().out


# --- partition fault through the daemon (in-process) ----------------------


def test_partition_fault_degrades_defers_then_heals(tmp_path, monkeypatch):
    """partition@host0:1 on a serving daemon: the in-window job's cache
    consult degrades to a typed cold run, its publish is deferred, and
    the heal re-publishes — after which the entry serves hits."""
    monkeypatch.setenv("KSPEC_FAULT", "partition@host0:1")
    monkeypatch.setenv("KSPEC_HOST_INSTANCE", "0")
    svc = tmp_path / "svc"
    q = JobQueue(str(svc))
    d = Daemon(ServeConfig(service_dir=str(svc), linger_s=0.0,
                           min_bucket=32))
    j1 = q.submit(ID_CFG, "IdSequence", kernel_source="hand")["job_id"]
    assert d.drain_once() == 1
    r1 = q.result(j1)
    assert r1["status"] == "complete"
    assert r1["distinct_states"] == 8
    assert r1.get("cache") is None  # partitioned: cold, not a hit
    ev = _events(svc)
    assert any(e.get("event") == "cache-partition-injected"
               for e in ev)
    assert any(e.get("event") == "cache-fallback"
               and e.get("reason") == "partition" for e in ev)
    assert any(e.get("event") == "cache-publish-deferred" for e in ev)
    heal = [e for e in ev if e.get("event") == "cache-partition-heal"]
    assert heal and heal[0]["republished"] == 1
    # durable marker: a restarted daemon does NOT re-partition
    assert os.path.exists(os.path.join(
        str(svc), "service", "faults-fired", "partition-daemon0"
    ))
    d2 = Daemon(ServeConfig(service_dir=str(svc), linger_s=0.0,
                            min_bucket=32))
    j2 = q.submit(ID_CFG, "IdSequence", kernel_source="hand")["job_id"]
    assert d2.drain_once() == 1
    r2 = q.result(j2)
    # the healed re-publish serves: chain-verified hit, same answer
    assert r2["cache"]["state_cache"] == "hit"
    assert r2["distinct_states"] == 8


# --- the two-host chaos e2e (acceptance) ----------------------------------


def test_cross_host_chaos_e2e(tmp_path, monkeypatch, capsys):
    """Two 'hosts' (separate service dirs + daemons, one shared cache
    namespace, one router) under kill@host0:1 + partition@host1:1 +
    flip@cache:1 + skew@host1:-0.75 — one composed plan string drives
    the whole drill:

    - host 0's daemon is killed mid-job-1; its 'restart' converges
      (durable fired-marker), the claim returns via lease-expiry
      takeover, and the verdict publishes exactly once, cold.
    - that publish is bit-flipped (flip@cache): job 2 on host 0 rejects
      the corrupt entry with a typed fallback, recomputes cold
      bit-identically, and re-publishes clean.
    - host 1's first job lands inside its partition window: typed
      'partition' fallback, deferred publish, heal re-publish.
    - host 0 then DIES for good.  A fresh TTW job routes to host 1 and
      is served as a cross-host chain-verified cache hit of the entry
      host 0 published — after host 0's publisher is gone.
    - a job stranded pending on dead host 0 is re-routed to host 1 by
      the sweep, exactly once, with attribution.
    - host 1's wall clock runs 0.75 s BEHIND the submitter's
      (skew@host1:-0.75): every job still reassembles into one coherent
      fleet trace with non-negative normalized stage durations.
    """
    monkeypatch.setenv("KSPEC_CLAIM_LEASE_TTL", "1")
    monkeypatch.setenv("KSPEC_CLOCK_SKEW", "0.5")
    monkeypatch.setenv(
        "KSPEC_FAULT",
        "kill@host0:1,partition@host1:1,flip@cache:1,skew@host1:-0.75",
    )
    import kafka_specification_tpu.service.state_cache as sc_mod
    sc_mod._publish_ordinal["n"] = 0  # per-process ordinal: pin for test
    h0, h1 = str(tmp_path / "h0"), str(tmp_path / "h1")
    cache_dir = str(tmp_path / "shared-cache")
    q0, q1 = JobQueue(h0), JobQueue(h1)
    router = Router(str(tmp_path / "rt"), hosts=[h0, h1],
                    dead_after_s=2.0)

    def make_daemon(host, svc):
        monkeypatch.setenv("KSPEC_HOST_INSTANCE", str(host))
        return Daemon(ServeConfig(service_dir=svc, linger_s=0.0,
                                  min_bucket=32,
                                  state_cache_dir=cache_dir))

    # phase 1: job 1 -> host 0; the kill fires before any verdict
    d0 = make_daemon(0, h0)
    _hb(h0)
    _hb(h1)
    j1 = router.submit(TTW_CFG, "KafkaTruncateToHighWatermark",
                       kernel_source="hand", host=0)["job_id"]
    with pytest.raises(InjectedCrash):
        d0.drain_once()
    assert q0.result(j1) is None  # died before deriving a verdict
    assert q0.status(j1)["state"] == "claimed"  # the orphaned claim
    # the 'restarted' daemon converges (durable kill marker) and its
    # janitor takes the expired claim over — exactly-once via the
    # takeover protocol
    d0b = make_daemon(0, h0)
    time.sleep(1.6)  # ttl 1s + skew 0.5s: the lease is now expired
    assert q0.requeue_orphans() == [j1]  # the startup janitor's takeover
    assert d0b.drain_once() == 1
    r1 = q0.result(j1)
    assert r1["status"] == "complete"
    assert r1["distinct_states"] == 353  # bit-identical to solo cold
    assert r1["takeover"]["reason"] in ("lease-expired", "dead-pid")
    assert os.path.exists(os.path.join(
        h0, "service", "faults-fired", "kill-daemon0"))

    # phase 2: job 2 -> host 0.  flip@cache corrupted d0b's publish of
    # job 1, so the lookup must reject it and recompute cold.
    j2 = router.submit(TTW_CFG, "KafkaTruncateToHighWatermark",
                       kernel_source="hand", host=0)["job_id"]
    assert d0b.drain_once() == 1
    r2 = q0.result(j2)
    assert r2["status"] == "complete"
    assert r2.get("cache") is None  # corrupt entry -> cold, not a hit
    for k in ("distinct_states", "diameter", "levels", "violation",
              "exit_code"):
        assert r2[k] == r1[k], k
    assert any(e.get("event") == "cache-fallback"
               and "artifact-corrupt" in str(e.get("reason"))
               for e in _events(h0))

    # phase 3: host 1's first job runs inside its partition window
    d1 = make_daemon(1, h1)
    jx = router.submit(ID_CFG, "IdSequence", kernel_source="hand",
                       host=1)["job_id"]
    assert d1.drain_once() == 1
    rx = q1.result(jx)
    assert rx["status"] == "complete"
    assert rx["distinct_states"] == 8
    ev1 = _events(h1)
    assert any(e.get("event") == "cache-fallback"
               and e.get("reason") == "partition" for e in ev1)
    assert any(e.get("event") == "cache-partition-heal" for e in ev1)

    # phase 4: host 0 dies for good — heartbeats stop, the router sees
    # it dead, and a fresh TTW job placed by HEALTH lands on host 1,
    # served as a cross-host chain-verified hit of host 0's entry
    # (published by a process that no longer exists).
    d0 = d0b = None  # the host-0 daemons are gone
    _hb(h1)
    assert _wait(lambda: router.host_health(0)["state"] == "dead",
                 timeout=30, poll=0.25)
    assert router.host_health(1)["state"] == "ok"
    j3 = router.submit(TTW_CFG, "KafkaTruncateToHighWatermark",
                       kernel_source="hand")["job_id"]
    assert router.read_route(j3)["host"] == 1
    assert d1.drain_once() == 1
    r3 = q1.result(j3)
    assert r3["status"] == "complete"
    assert r3["cache"]["state_cache"] == "hit"  # THE cross-host hit
    for k in ("distinct_states", "diameter", "levels", "violation",
              "exit_code"):
        assert r3[k] == r1[k], k
    assert any(e.get("event") == "state-cache-hit"
               for e in _events(h1))

    # phase 5: a job stranded pending on the dead host re-routes to the
    # survivor, exactly once, and completes there
    j4 = router.submit(ID_CFG, "IdSequence", kernel_source="hand",
                       host=0)["job_id"]
    out = router.sweep()
    assert out["rerouted"] == {0: [j4]}
    assert d1.drain_once() == 1
    r4 = router.result(j4)
    assert r4["status"] == "complete"
    assert r4["distinct_states"] == 8
    assert [h["why"] for h in router.read_route(j4)["history"]] == [
        "submit", "reroute:host-dead"
    ]

    # exactly once, everywhere: both queues drained, one verdict per
    # job, every verdict bit-identical to the solo cold answer
    for q in (q0, q1):
        ov = q.overview()
        assert ov["counts"]["pending"] == 0
        assert ov["counts"]["claimed"] == 0
    assert q0.overview()["counts"]["done"] == 2  # j1, j2
    assert q1.overview()["counts"]["done"] == 3  # jx, j3, j4
    for jid, states in ((j1, 353), (j2, 353), (jx, 8), (j3, 353),
                        (j4, 8)):
        homes = [q for q in (q0, q1) if q.result(jid) is not None]
        assert len(homes) == 1, jid
        rec = homes[0].result(jid)
        assert rec["status"] == "complete" and rec["exit_code"] == 0
        assert rec["distinct_states"] == states
    # and the router can render the aftermath (jax-free rollup)
    from kafka_specification_tpu.obs.report import router_report_data

    data = router_report_data(router.dir)
    assert {h["state"] for h in data["hosts"]} == {"dead", "ok"}
    assert data["events"].get("route-reroute") == 1

    # --- one coherent fleet trace per job, across hosts and deaths ----
    from kafka_specification_tpu.obs import fleettrace as ft

    roots = [router.dir, h0, h1]
    # j4: submitted to the dead host, re-routed, completed on host 1 —
    # ONE trace: submit root + placement + the re-route as a typed
    # annotation + claim + run + publish, every normalized stage >= 0
    # even though host 1's clock ran 0.75 s behind the submitter's
    t4 = ft.assemble(ft.load_trace(roots, j4), job_id=j4)
    kinds4 = [s["span"] for s in t4["spans"]]
    for k in ("job-submit", "route-place", "queue-claim",
              "verdict-publish"):
        assert k in kinds4, (k, kinds4)
    # the survivor served the re-routed job from the state cache (jx's
    # healed publish): its run stage is a chain-verified cache-lookup
    # hit, not an svc-run engine window — the trace says exactly that
    lk4 = [s for s in t4["spans"] if s["span"] == "cache-lookup"]
    assert lk4 and lk4[-1]["outcome"] == "hit", kinds4
    assert t4["complete"]
    assert [e["event"] for e in t4["events"]] == ["route-reroute"]
    rr = t4["events"][0]
    assert (rr["from_host"], rr["to_host"]) == (0, 1)
    assert rr["reason"] == "host-dead"
    bad = {k: v for k, v in t4["stages"].items()
           if v is not None and v < 0}
    assert not bad, f"negative normalized stage durations: {bad}"
    assert t4["stages"]["queue-wait"] is not None
    assert t4["stages"]["publish"] is not None
    # both clock domains (submitter/host-1 process switched identity
    # mid-test) contributed spans to the one trace file set
    assert t4["duration_ms"] is not None and t4["duration_ms"] >= 0

    # j1: killed mid-job on host 0 — the dead incarnation's partial
    # spans (a claim with no run) coexist in the SAME trace with the
    # takeover incarnation's completion; the takeover is an annotation
    tj1 = ft.assemble(ft.load_trace(roots, j1), job_id=j1)
    claims = [s for s in tj1["spans"] if s["span"] == "queue-claim"]
    assert len(claims) >= 2, "expected dead + takeover claim spans"
    assert sum(1 for s in tj1["spans"] if s["span"] == "svc-run") == 1
    assert [e["event"] for e in tj1["events"]] == ["queue-requeue"]
    assert tj1["events"][0]["reason"] in ("lease-expired", "dead-pid")
    assert tj1["complete"]
    neg1 = {k: v for k, v in tj1["stages"].items()
            if v is not None and v < 0}
    assert not neg1, f"negative normalized stage durations: {neg1}"

    # the operator CLI renders the aftermath from disk alone (jax-free)
    assert cli_main(["trace", j4, "--router", router.dir]) == 0
    out = capsys.readouterr().out
    assert "verdict-publish" in out and "route-reroute" in out
    assert cli_main(["fleet-report", "--router", router.dir,
                     "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["completed"] >= 5  # every job's trace reached a verdict
    assert rep["stages"]["publish"]["p50_ms"] is not None
    assert rep["cache"]["hit"] >= 1  # phase 4's cross-host hit
