"""Property-based tests (Hypothesis) for the codec and dedup primitives —
the per-operator layer of the test strategy (SURVEY.md §4: kernels vs a slow
reference, property-based)."""

import numpy as np
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from kafka_specification_tpu.ops import dedup
from kafka_specification_tpu.ops.packing import Field, StateSpec


@st.composite
def spec_and_states(draw):
    n_fields = draw(st.integers(1, 4))
    fields = []
    for i in range(n_fields):
        lo = draw(st.integers(-8, 4))
        hi = lo + draw(st.integers(0, 40))
        shape = draw(
            st.sampled_from([(), (draw(st.integers(1, 4)),), (2, draw(st.integers(1, 3)))])
        )
        fields.append(Field(f"f{i}", shape, lo, hi))
    spec = StateSpec(fields)
    rng_seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(rng_seed)
    states = [
        {
            f.name: rng.integers(f.lo, f.hi + 1, size=f.shape).astype(np.int32)
            for f in fields
        }
        for _ in range(draw(st.integers(1, 8)))
    ]
    return spec, states


@settings(max_examples=25, deadline=None)
@given(spec_and_states())
def test_pack_unpack_roundtrip_property(sas):
    spec, states = sas
    for s in states:
        out = spec.unpack(spec.pack(s))
        for k, v in s.items():
            np.testing.assert_array_equal(np.asarray(out[k]), v)


@settings(max_examples=25, deadline=None)
@given(spec_and_states())
def test_pack_injective_property(sas):
    """Distinct states pack to distinct lane vectors (canonical encoding)."""
    spec, states = sas
    packs = {}
    for s in states:
        key = tuple(np.asarray(spec.pack(s)).tolist())
        canon = tuple(np.asarray(s[f.name]).tobytes() for f in spec.fields)
        assert packs.setdefault(key, canon) == canon


@settings(max_examples=30, deadline=None)
@given(
    st.lists(st.integers(0, 2**32 - 2), min_size=0, max_size=60, unique=True),
    st.lists(st.integers(0, 2**32 - 2), min_size=0, max_size=60, unique=True),
)
def test_merge_ranked_equals_sorted_union(visited_vals, new_vals):
    """merge_ranked(visited, new) == sorted(visited | new) for disjoint sets,
    against a plain numpy reference."""
    visited = np.array(sorted(set(visited_vals) - set(new_vals)), np.uint32)
    new = np.array(sorted(set(new_vals) - set(visited_vals)), np.uint32)
    vn, nn = len(visited), len(new)
    cap = 1 << max(3, (vn + nn).bit_length())
    SENT = np.uint32(0xFFFFFFFF)

    vhi = np.full(cap, SENT)
    vlo = np.full(cap, SENT)
    # use value as lo, a pseudo hi derived deterministically (here: value >> 16)
    vhi[:vn] = visited >> np.uint32(16)
    vlo[:vn] = visited
    order = np.lexsort((vlo[:vn], vhi[:vn]))
    vhi[:vn], vlo[:vn] = vhi[:vn][order], vlo[:vn][order]

    M = max(8, 1 << max(0, (nn - 1)).bit_length())
    nhi = np.full(M, SENT)
    nlo = np.full(M, SENT)
    nhi[:nn] = new >> np.uint32(16)
    nlo[:nn] = new
    norder = np.lexsort((nlo[:nn], nhi[:nn]))
    nhi[:nn], nlo[:nn] = nhi[:nn][norder], nlo[:nn][norder]

    _, rank = dedup.rank_sorted(
        jnp.asarray(vhi), jnp.asarray(vlo), jnp.int32(vn),
        jnp.asarray(nhi), jnp.asarray(nlo),
    )
    mhi, mlo, mn = dedup.merge_ranked(
        jnp.asarray(vhi), jnp.asarray(vlo), jnp.int32(vn),
        jnp.asarray(nhi), jnp.asarray(nlo), rank, jnp.int32(nn), cap,
    )
    mhi, mlo = np.asarray(mhi), np.asarray(mlo)
    assert int(mn) == vn + nn
    want = np.array(
        sorted(
            [(int(v >> np.uint32(16)), int(v)) for v in visited]
            + [(int(v >> np.uint32(16)), int(v)) for v in new]
        ),
        dtype=np.int64,
    ).reshape(-1, 2)
    got = np.stack([mhi[: vn + nn], mlo[: vn + nn]], axis=1).astype(np.int64)
    np.testing.assert_array_equal(got, want)
    assert (mhi[vn + nn :] == SENT).all() and (mlo[vn + nn :] == SENT).all()
