"""Async level-pipelined execution (KSPEC_OVERLAP; overlap.py,
docs/engine.md § Async execution).

Pins the PR 10 contract: overlap-on is BIT-IDENTICAL to overlap-off —
level counts, duplicate accounting, first-violation rule, trace values
and digest chains — across the model x backend x disk-tier x resume
matrix on both engines; the two-slot staging queue is structurally
bounded; background I/O actually overlaps device compute (span
evidence); faults firing on the worker threads (crash@merge, enospc@
ckpt, flip@spill) still produce the typed exits, a chain-verified
checkpoint, and bit-identical resume; the compressed exchange
round-trips exactly and stays inside the fabric-integrity boundary;
and a reclaim quiesces the merge worker before touching its files
(the PR 10 small fix).
"""

import os
import time

import numpy as np
import pytest
from jax.sharding import Mesh

import jax

from kafka_specification_tpu.engine.bfs import check
from kafka_specification_tpu.models import finite_replicated_log as frl
from kafka_specification_tpu.models import variants
from kafka_specification_tpu.models.kafka_replication import Config
from kafka_specification_tpu.obs.runctx import RunContext
from kafka_specification_tpu.obs.tracer import read_jsonl_tolerant
from kafka_specification_tpu.ops import fpcompress as fpc
from kafka_specification_tpu.overlap import AsyncWorker, overlap_enabled
from kafka_specification_tpu.parallel.sharded import check_sharded
from kafka_specification_tpu.resilience.checkpoints import (
    verify_checkpoint_dir,
)
from kafka_specification_tpu.resilience.faults import InjectedCrash
from kafka_specification_tpu.resilience.integrity import IntegrityError
from kafka_specification_tpu.resilience.resources import ResourceExhausted

pytestmark = pytest.mark.overlap

TINY = Config(n_replicas=2, log_size=2, max_records=1, max_leader_epoch=1)


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("d",))


def _mk_violating():
    return variants.make_model(
        "KafkaTruncateToHighWatermark", TINY, ("TypeOk", "WeakIsr")
    )


def _verdict(res):
    return (
        res.total,
        res.diameter,
        tuple(res.levels),
        res.ok,
        (res.violation.invariant, res.violation.depth)
        if res.violation
        else None,
    )


def _trace_values(res):
    if res.violation is None:
        return None
    return [(name, repr(st)) for name, st in res.violation.trace]


# --- knob resolution ------------------------------------------------------


def test_overlap_knob_resolution(monkeypatch):
    monkeypatch.delenv("KSPEC_OVERLAP", raising=False)
    assert overlap_enabled(None) is True  # default ON
    assert overlap_enabled("off") is False
    assert overlap_enabled("on") is True
    assert overlap_enabled(False) is False
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    assert overlap_enabled(None) is False
    monkeypatch.setenv("KSPEC_OVERLAP", "on")
    assert overlap_enabled(None) is True


# --- the worker primitive -------------------------------------------------


def test_async_worker_runs_in_order_and_propagates_errors():
    w = AsyncWorker("t-worker")
    seen = []
    jobs = [w.submit(f"j{i}", lambda i=i: seen.append(i)) for i in range(5)]
    w.drain()
    assert seen == [0, 1, 2, 3, 4]

    def boom():
        raise OSError(28, "No space left on device (test)")

    w.submit("boom", boom)
    w.submit("after", lambda: seen.append(99))
    with pytest.raises(OSError):
        w.drain()
    assert seen[-1] == 99  # the failed job never blocks later jobs
    w.drain()  # error raised exactly once
    assert all(j.done.is_set() for j in jobs)
    w.close()


# --- compressed-exchange codec (satellite: round-trip unit) ---------------


def test_fpcompress_roundtrip_jit_matches_numpy():
    rng = np.random.default_rng(7)
    import jax.numpy as jnp

    for W, n in [(64, 0), (64, 17), (128, 1), (128, 60), (256, 100),
                 (512, 200)]:
        vals = np.sort(
            rng.integers(0, 2**64 - 2, size=n, dtype=np.uint64)
        )
        if n > 3:
            vals[2] = vals[1]  # duplicate fingerprints must survive
            vals = np.sort(vals)
        full = np.concatenate(
            [vals, np.full(W - n, np.uint64(0xFFFFFFFFFFFFFFFF))]
        )
        hi = (full >> np.uint64(32)).astype(np.uint32)
        lo = (full & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        NW = fpc.default_stream_words(W)
        words, hdr, ovf = jax.jit(
            lambda h, l, c: fpc.pack_sorted(h, l, c, NW)
        )(jnp.asarray(hi), jnp.asarray(lo), jnp.int32(n))
        words, hdr, ovf = np.asarray(words), np.asarray(hdr), bool(ovf)
        wn, hn, on = fpc.pack_np(hi, lo, n, NW)
        assert np.array_equal(words, wn) and np.array_equal(hdr, hn)
        assert ovf == on
        assert not ovf, (W, n)
        h2, l2 = jax.jit(lambda w, h: fpc.unpack_sorted(w, h, W))(
            jnp.asarray(words), jnp.asarray(hdr)
        )
        assert np.array_equal(np.asarray(h2), hi)
        assert np.array_equal(np.asarray(l2), lo)
        h3, l3 = fpc.unpack_np(words, hdr, W)
        assert np.array_equal(h3, hi) and np.array_equal(l3, lo)
        # the wire actually shrinks: stream+header vs raw hi/lo lanes
        assert fpc.packed_bytes(W, NW) < fpc.raw_bytes(W)


def test_fpcompress_overflow_flag_on_dense_bucket():
    rng = np.random.default_rng(3)
    W = 128
    vals = np.sort(rng.integers(0, 2**64 - 2, size=W, dtype=np.uint64))
    hi = (vals >> np.uint64(32)).astype(np.uint32)
    lo = (vals & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    _w, _h, ovf = fpc.pack_np(hi, lo, W, fpc.default_stream_words(W))
    assert ovf  # a full bucket of random fps cannot fit 1 word/slot


# --- bit-identity matrix (the tentpole contract) --------------------------


@pytest.mark.parametrize("backend", ["device", "device-hash", "host"])
def test_overlap_bit_identity_backends(monkeypatch, backend):
    mk = lambda: frl.make_model(2, 2, 2)  # noqa: E731
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    base = check(mk(), min_bucket=32, chunk_size=64,
                 visited_backend=backend)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    on = check(mk(), min_bucket=32, chunk_size=64,
               visited_backend=backend)
    assert _verdict(on) == _verdict(base)
    assert on.stats["overlap"]["enabled"]
    assert not base.stats["overlap"]["enabled"]


def test_overlap_bit_identity_violation_trace(monkeypatch):
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    base = check(_mk_violating(), min_bucket=32, chunk_size=64)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    on = check(_mk_violating(), min_bucket=32, chunk_size=64)
    assert not base.ok and _verdict(on) == _verdict(base)
    assert _trace_values(on) == _trace_values(base)


def test_overlap_bit_identity_disk_tier_and_chains(monkeypatch, tmp_path):
    """Forced-spill disk tier + checkpoints: counts AND the stamped
    digest chains must match across the knob."""
    import numpy.testing as npt

    from kafka_specification_tpu.resilience.checkpoints import verify_file

    chains = {}
    for flag, sub in (("0", "off"), ("1", "on")):
        monkeypatch.setenv("KSPEC_OVERLAP", flag)
        ck = str(tmp_path / f"ck-{sub}")
        res = check(
            frl.make_model(2, 2, 2),
            min_bucket=32,
            chunk_size=64,
            mem_budget=256,
            store="disk",
            checkpoint_dir=ck,
        )
        chains[sub] = (
            _verdict(res),
            verify_file(os.path.join(ck, "bfs_checkpoint.npz"))[
                "digest_chain"
            ],
        )
        assert verify_checkpoint_dir(ck)["ok"]
    assert chains["on"][0] == chains["off"][0]
    npt.assert_array_equal(chains["on"][1], chains["off"][1])


def test_overlap_resume_across_knob(monkeypatch, tmp_path):
    """A checkpoint written with overlap ON resumes bit-identically with
    overlap OFF (and vice versa) — the knob is execution strategy, not
    state."""
    mk = lambda: frl.make_model(2, 2, 2)  # noqa: E731
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    golden = check(mk(), min_bucket=32)
    for first, second in (("1", "0"), ("0", "1")):
        ck = str(tmp_path / f"ck-{first}{second}")
        monkeypatch.setenv("KSPEC_OVERLAP", first)
        check(mk(), min_bucket=32, checkpoint_dir=ck, max_depth=3)
        monkeypatch.setenv("KSPEC_OVERLAP", second)
        res = check(mk(), min_bucket=32, checkpoint_dir=ck)
        assert _verdict(res)[:3] == _verdict(golden)[:3]


def test_overlap_bit_identity_sharded_compressed(monkeypatch):
    """Sharded engine: overlap ON (staged commit + compressed exchange)
    vs OFF (raw exchange) — counts AND trace values identical, and the
    compressed wire moved >= 2x fewer bytes."""
    mk = _mk_violating
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    base = check_sharded(mk(), mesh=_mesh(4), min_bucket=64)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    # the codec defaults off on the virtual CPU mesh (no wire to save);
    # force it on — measuring/pinning it IS the point here
    monkeypatch.setenv("KSPEC_EXCHANGE_COMPRESS", "1")
    on = check_sharded(mk(), mesh=_mesh(4), min_bucket=64)
    assert _verdict(on) == _verdict(base)
    assert _trace_values(on) == _trace_values(base)
    assert on.stats["exchange_compressed"]
    assert not base.stats["exchange_compressed"]
    sent = on.stats["exchange_bytes_total"]
    raw = on.stats["exchange_raw_bytes_total"]
    assert raw and sent and raw / sent >= 2.0, (sent, raw)


def test_overlap_bit_identity_sharded_host_backend(monkeypatch):
    mk = lambda: frl.make_model(2, 2, 2)  # noqa: E731
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    base = check_sharded(mk(), mesh=_mesh(2), min_bucket=64,
                         visited_backend="host")
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    on = check_sharded(mk(), mesh=_mesh(2), min_bucket=64,
                       visited_backend="host")
    assert _verdict(on) == _verdict(base)
    assert on.stats["overlap"]["staged_chunks_peak"] <= 2


# --- staging bounds + span evidence (satellite: test coverage) ------------


@pytest.mark.perf
def test_two_slot_pipeline_never_holds_more_than_two_chunks(monkeypatch):
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    # frl(2,2,3) levels reach 81 rows: chunk 32 -> multiple chunks/level
    res = check(frl.make_model(2, 2, 3), min_bucket=32, chunk_size=32)
    ov = res.stats["overlap"]
    assert ov["enabled"]
    # multiple chunks per level -> both slots used, and the structural
    # bound holds
    assert ov["staged_chunks_peak"] == 2
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    res2 = check(frl.make_model(2, 2, 3), min_bucket=32, chunk_size=32)
    assert res2.stats["overlap"]["staged_chunks_peak"] <= 1


@pytest.mark.perf
def test_checkpoint_write_span_overlaps_step_span(tmp_path, monkeypatch):
    """The async checkpoint's write span (emitted on the writer thread,
    obs context propagated) must overlap some chunk `step` span in wall
    time — the direct evidence a write ran behind device compute.  The
    write is slowed so the overlap window cannot vanish into scheduling
    noise on a loaded CI box (everything here is warm and sub-ms)."""
    orig_savez = np.savez

    def slow_savez(*a, **kw):
        time.sleep(0.05)
        return orig_savez(*a, **kw)

    monkeypatch.setattr(np, "savez", slow_savez)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    run = RunContext(str(tmp_path / "run"))
    res = check(
        frl.make_model(2, 2, 3),
        min_bucket=32,
        chunk_size=64,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=1,
        run=run,
    )
    assert res.total > 0
    spans = read_jsonl_tolerant(run.spans_path)

    def _ivals(kind):
        return [
            (s["t0"], s["t0"] + s["ms"] / 1e3)
            for s in spans
            if s.get("span") == kind and s.get("ph") == "E"
        ]

    steps = _ivals("step")
    writes = _ivals("checkpoint-write")
    assert steps and writes, "expected step and checkpoint-write spans"
    overlapped = any(
        w0 < s1 and s0 < w1 for (w0, w1) in writes for (s0, s1) in steps
    )
    assert overlapped, (
        "no checkpoint-write span overlapped a step span — the async "
        "writer is not off the critical path"
    )


# --- fault matrix on the async paths (satellite) --------------------------


def _spilling_kwargs(ck):
    return dict(
        min_bucket=32,
        chunk_size=64,
        mem_budget=128,
        store="disk",
        checkpoint_dir=ck,
    )


def test_crash_at_merge_fires_on_worker_and_resumes(monkeypatch, tmp_path):
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    golden = check(frl.make_model(2, 2, 2), min_bucket=32, chunk_size=64)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_SPILL_RUNS_PER_MERGE", "2")
    monkeypatch.setenv("KSPEC_FAULT", "crash@merge:1")
    with pytest.raises(InjectedCrash):
        check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    assert verify_checkpoint_dir(ck)["ok"]
    monkeypatch.delenv("KSPEC_FAULT")
    res = check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    assert _verdict(res)[:3] == _verdict(golden)[:3]


def test_enospc_at_ckpt_async_still_typed_exit_75(monkeypatch, tmp_path):
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    golden = check(frl.make_model(2, 2, 2), min_bucket=32, chunk_size=64)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_FAULT", "enospc@ckpt:2")
    with pytest.raises(ResourceExhausted) as ei:
        check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    assert ei.value.reason == "enospc"
    # the failed write cleaned its tmp; the promoted state verifies
    assert verify_checkpoint_dir(ck)["ok"]
    monkeypatch.delenv("KSPEC_FAULT")
    res = check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    assert _verdict(res)[:3] == _verdict(golden)[:3]


def test_flip_at_spill_detected_with_background_merges(
    monkeypatch, tmp_path
):
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    golden = check(frl.make_model(2, 2, 2), min_bucket=32, chunk_size=64)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_FAULT", "flip@spill:1")
    with pytest.raises(IntegrityError):
        check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    monkeypatch.delenv("KSPEC_FAULT")
    res = check(frl.make_model(2, 2, 2), **_spilling_kwargs(ck))
    assert _verdict(res)[:3] == _verdict(golden)[:3]


def test_compressed_overflow_at_full_width_falls_back_to_raw(monkeypatch):
    """Review regression: the raw exchange cannot overflow at W == T,
    but the codec's stream/row budgets can — once the width ladder tops
    out, the chunk must fall back to the RAW wire (bit-identically)
    instead of committing a truncated payload.  A starved stream budget
    forces the codec to overflow at EVERY width."""
    monkeypatch.setattr(fpc, "default_stream_words", lambda w: fpc.BLK)
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    base = check_sharded(frl.make_model(2, 2, 3), mesh=_mesh(2),
                         min_bucket=64)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_EXCHANGE_COMPRESS", "1")
    on = check_sharded(frl.make_model(2, 2, 3), mesh=_mesh(2),
                       min_bucket=64)
    assert _verdict(on) == _verdict(base)
    # the codec was requested but every real chunk fell back: the wire
    # accounting must reflect raw-dominated traffic, not claim savings
    assert on.stats["exchange_bytes_total"] >= \
        0.5 * on.stats["exchange_raw_bytes_total"]


def test_sharded_flip_exchange_detected_through_compression(monkeypatch):
    """flip@exchange must still trip the framing digests when the wire
    is compressed — the digests frame the DECODED payload."""
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_EXCHANGE_COMPRESS", "1")
    monkeypatch.setenv("KSPEC_FAULT", "flip@exchange:2")
    with pytest.raises(IntegrityError) as ei:
        check_sharded(frl.make_model(2, 2, 2), mesh=_mesh(2),
                      min_bucket=64)
    assert ei.value.site == "exchange"


def test_sharded_crash_merge_on_worker_resumes(monkeypatch, tmp_path):
    monkeypatch.setenv("KSPEC_OVERLAP", "0")
    golden = check_sharded(frl.make_model(2, 2, 2), mesh=_mesh(2),
                           min_bucket=64)
    ck = str(tmp_path / "ck")
    kwargs = dict(
        mesh=_mesh(2),
        min_bucket=64,
        mem_budget=128,
        store="disk",
        checkpoint_dir=ck,
        spill_dir=str(tmp_path / "spill"),
    )
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    monkeypatch.setenv("KSPEC_SPILL_RUNS_PER_MERGE", "2")
    monkeypatch.setenv("KSPEC_FAULT", "crash@merge:1")
    with pytest.raises(InjectedCrash):
        check_sharded(frl.make_model(2, 2, 2), **kwargs)
    monkeypatch.delenv("KSPEC_FAULT")
    res = check_sharded(frl.make_model(2, 2, 2), **kwargs)
    assert _verdict(res)[:3] == _verdict(golden)[:3]


# --- background merges + the reclaim race (satellite: small fix) ----------


def test_background_merge_bit_identical_membership(tmp_path):
    from kafka_specification_tpu.storage.tiered import TieredFpSet

    rng = np.random.default_rng(11)
    fps = rng.integers(1, 2**63, size=6000, dtype=np.uint64)
    w = AsyncWorker("t-merge")
    ts = TieredFpSet(
        str(tmp_path / "async"), mem_budget=16 * 200,
        runs_per_merge=2, merge_worker=w,
    )
    ref = TieredFpSet(
        str(tmp_path / "sync"), mem_budget=16 * 200, runs_per_merge=2
    )
    for i in range(0, fps.size, 500):
        batch = fps[i : i + 500]
        assert np.array_equal(ts.insert(batch), ref.insert(batch))
    ts.quiesce()
    assert len(ts) == len(ref)
    probe = np.concatenate([fps[:100], np.array([7, 8, 9], np.uint64)])
    assert np.array_equal(ts.contains(probe), ref.contains(probe))
    assert ts.merges > 0
    w.close()


def test_reclaim_quiesces_merge_worker_first(tmp_path, monkeypatch):
    """PR 10 small fix: an eager reclaim merge / tmp sweep while a
    background merge is mid-write must quiesce the worker first — the
    in-flight merge's tmp is live work, and a racing second merge over
    the same inputs would double-schedule them on the deletion
    barrier."""
    from kafka_specification_tpu.storage import runs as runs_mod
    from kafka_specification_tpu.storage.tiered import TieredFpSet

    real_merge = runs_mod.merge_runs
    started = []

    def slow_merge(rs, path, block=1 << 20, crash_hook=None):
        started.append(path)
        time.sleep(0.4)  # hold the merge mid-flight
        return real_merge(rs, path, block=block, crash_hook=crash_hook)

    monkeypatch.setattr(
        "kafka_specification_tpu.storage.tiered.merge_runs", slow_merge
    )
    rng = np.random.default_rng(5)
    w = AsyncWorker("t-reclaim")
    ts = TieredFpSet(
        str(tmp_path / "t"), mem_budget=16 * 50,
        runs_per_merge=2, merge_worker=w,
    )
    fps = rng.integers(1, 2**63, size=400, dtype=np.uint64)
    for i in range(0, fps.size, 50):
        ts.insert(fps[i : i + 50])
    assert started, "background merge should have started"
    # the reclaim path: sync merge must quiesce (adopt) first
    ts.merge()
    assert ts._merge_job is None
    pending = [p for _n, p in ts.deleter.pending]
    assert len(pending) == len(set(pending)), (
        "merge inputs double-scheduled on the deletion barrier"
    )
    assert np.all(ts.contains(fps))
    w.close()


def test_report_overlap_beat_and_exposed_io_stall(tmp_path, monkeypatch):
    """`cli report`'s overlap beat (satellite): the efficiency gauge
    renders, and a run whose exposed I/O dominates gets the
    machine-readable EXPOSED-I/O STALL verdict line."""
    from kafka_specification_tpu.obs.report import _overlap, render_report

    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    run = RunContext(str(tmp_path / "run"))
    check(
        frl.make_model(2, 2, 3), min_bucket=32, chunk_size=64,
        mem_budget=128, store="disk",
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1, run=run,
    )
    text = render_report(run.dir)
    assert "overlap" in text and "I/O hidden" in text
    # synthetic exposed-dominated data -> the stall beat fires
    stalled = _overlap(
        {
            "metrics": {
                "counters": {
                    "kspec_io_hidden_ms_total": 10,
                    "kspec_io_exposed_ms_total": 500,
                },
                "gauges": {"kspec_overlap_efficiency": 0.02},
            },
            "metrics_history": [],
        }
    )
    assert stalled["exposed_io_stalled"] is True
    healthy = _overlap(
        {
            "metrics": {
                "counters": {
                    "kspec_io_hidden_ms_total": 500,
                    "kspec_io_exposed_ms_total": 10,
                },
                "gauges": {"kspec_overlap_efficiency": 0.98},
            },
            "metrics_history": [],
        }
    )
    assert healthy["exposed_io_stalled"] is False


def test_overlap_run_clean_without_checkpointing(monkeypatch):
    # overlap on, nothing to overlap with (no disk tier, no checkpoints)
    monkeypatch.setenv("KSPEC_OVERLAP", "1")
    res = check(frl.make_model(2, 2, 2), min_bucket=32)
    assert res.ok and res.stats["overlap"]["enabled"]
