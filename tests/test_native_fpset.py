"""Native C++ FpSet and the engine's host visited-set backend."""

import numpy as np

from kafka_specification_tpu.native import FpSet, native_available
from kafka_specification_tpu.engine.bfs import check
from kafka_specification_tpu.models import finite_replicated_log as frl
from kafka_specification_tpu.models import variants
from kafka_specification_tpu.models.kafka_replication import Config


def test_native_compiles():
    assert native_available(), "g++ toolchain expected in this image"


def test_fpset_insert_contains_dump():
    s = FpSet(initial_capacity=64)
    rng = np.random.default_rng(7)
    a = rng.integers(1, 2**63, size=10_000, dtype=np.uint64)
    uniq = np.unique(a)
    mask1 = s.insert(a)
    # first occurrence of each value reports new
    assert mask1.sum() == uniq.shape[0]
    assert len(s) == uniq.shape[0]
    mask2 = s.insert(a)
    assert not mask2.any()
    assert s.contains(a).all()
    missing = rng.integers(2**63, 2**64 - 1, size=100, dtype=np.uint64)
    present = s.contains(missing)
    assert present.sum() == np.isin(missing, uniq).sum()
    dumped = np.sort(s.dump())
    np.testing.assert_array_equal(dumped, uniq)


def test_fpset_growth_preserves_members():
    s = FpSet(initial_capacity=64)
    a = np.arange(1, 50_000, dtype=np.uint64)
    s.insert(a)
    assert len(s) == a.shape[0]
    assert s.contains(a).all()


def test_fpset_zero_is_distinct():
    """Fingerprint value 0 is a real member (exact-mode fps ARE states) and
    must be distinct from 1."""
    s = FpSet()
    m = s.insert(np.array([0, 1, 0], dtype=np.uint64))
    assert m.tolist() == [True, True, False]
    assert len(s) == 2
    assert s.contains(np.array([0, 1, 2], dtype=np.uint64)).tolist() == [
        True,
        True,
        False,
    ]
    assert sorted(s.dump().tolist()) == [0, 1]


def test_host_backend_matches_device_counts():
    model = frl.make_model(3, 4, 2)
    res = check(model, min_bucket=64, visited_backend="host", store_trace=False)
    assert res.ok
    assert res.total == 29791  # = 31^3, same as device backend / oracle
    assert res.stats["visited_backend"] == "host"
    assert res.stats["host_fpset_size"] == 29791


def test_host_backend_violation_with_trace():
    m = variants.make_model(
        "KafkaTruncateToHighWatermark", Config(2, 2, 1, 1), ("TypeOk", "WeakIsr")
    )
    res = check(m, min_bucket=32, visited_backend="host")
    assert res.violation is not None
    assert res.violation.invariant == "WeakIsr"
    assert res.violation.depth == 8
    assert len(res.violation.trace) == 9  # full parent-pointer path survives


def test_host_backend_exact64_zero_fingerprint():
    """Regression: exact-mode fingerprints are packed states, so u64 value 0
    (e.g. IdSequence nextId=0) is a real state that must not be conflated
    with value 1 (review finding: the old fp==0 remap truncated the search
    to total=1)."""
    from kafka_specification_tpu.models import id_sequence

    res = check(id_sequence.make_model(5), min_bucket=32, visited_backend="host")
    assert res.total == 7
    assert res.diameter == 6


def test_host_backend_with_checkpoint_and_chunking(tmp_path):
    """Flag-interaction matrix: host FpSet dedup + checkpoint/resume +
    multi-chunk levels must compose (the checkpoint stores the dumped
    fingerprint set)."""
    ckdir = str(tmp_path / "ck")
    model = frl.make_model(3, 4, 2)
    partial = check(
        model, max_depth=5, min_bucket=32, chunk_size=64,
        visited_backend="host", checkpoint_dir=ckdir,
    )
    assert partial.total < 29791
    import os

    assert os.path.exists(os.path.join(ckdir, "bfs_checkpoint.npz"))
    resumed = check(
        model, min_bucket=32, chunk_size=64,
        visited_backend="host", checkpoint_dir=ckdir,
    )
    assert resumed.ok
    assert resumed.total == 29791
    assert resumed.diameter == 12  # level bookkeeping restored across resume
    assert resumed.stats["host_fpset_size"] == 29791


def test_host_backend_compact_shift_path():
    """The host-dedup fast path with two-phase compaction active (bucket >=
    4096 enables compact_shift): the squeeze-to-T buffer, its overflow
    wiring and the no-sort fingerprint handoff must reproduce the golden
    count.  This is the profiled bench configuration on CPU (the other
    host-backend tests use tiny buckets where shift stays 0)."""
    res = check(
        frl.make_model(3, 4, 2),
        min_bucket=4096,
        visited_backend="host",
    )
    assert res.ok
    assert res.total == 29791  # 31^3 closed-form golden count (RESULTS.md)
    assert res.diameter == 12
    assert res.stats["host_fpset_size"] == 29791


def test_host_arena_trace_replays_across_chunks():
    """Regression for the fused C insert+compact level assembly (round 5):
    parent indices are globalized inside the C pass (parent_base), so a
    multi-chunk level must still yield a trace that replays through the
    oracle transition relation."""
    m = variants.make_model(
        "KafkaTruncateToHighWatermark", Config(2, 2, 1, 1), ("TypeOk", "WeakIsr")
    )
    # chunk_size far below level sizes forces many parent_base offsets
    res = check(m, min_bucket=32, chunk_size=32, visited_backend="host")
    v = res.violation
    assert v is not None and v.invariant == "WeakIsr" and v.depth == 8
    o = variants.make_oracle(
        "KafkaTruncateToHighWatermark", Config(2, 2, 1, 1), ("TypeOk",)
    )
    actions = {a.name: a for a in o.actions}
    cur = o.init_states()[0]
    assert v.trace[0] == ("<init>", cur)
    for name, nxt in v.trace[1:]:
        assert nxt in set(actions[name].successors(cur)), name
        cur = nxt
