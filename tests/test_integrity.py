"""End-to-end state-integrity defense (resilience.integrity).

Every `flip@` injection site must be DETECTED by the always-on layer,
exit typed (IntegrityError -> CLI exit 76) with the run manifest stamped
`integrity-violation`, and a restart must complete bit-identically from
the newest chain-verified checkpoint generation — on both engines,
including a shard-scoped case.  Plus: the offline `cli verify-checkpoint`
must flag a corrupted generation whose per-array CRCs still pass, the
digest chain must be engine/pipeline/layout-invariant, and shadow
re-execution must be clean on healthy runs and catch injected
divergence.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
from jax.sharding import Mesh

import jax

from kafka_specification_tpu.engine.bfs import check
from kafka_specification_tpu.models import finite_replicated_log as frl
from kafka_specification_tpu.models import variants
from kafka_specification_tpu.models.kafka_replication import Config
from kafka_specification_tpu.parallel.sharded import check_sharded
from kafka_specification_tpu.resilience import integrity
from kafka_specification_tpu.resilience.checkpoints import (
    CheckpointStore,
    build_manifest,
    verify_checkpoint_dir,
    verify_file,
)
from kafka_specification_tpu.resilience.faults import FaultPlan, list_faults
from kafka_specification_tpu.resilience.integrity import (
    EXIT_INTEGRITY,
    IntegrityError,
    LevelDigestChain,
    checkpoint_chain_errors,
    digest_fps,
    fingerprint_rows,
    pair_u64,
)

pytestmark = pytest.mark.integrity

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY = Config(n_replicas=2, log_size=2, max_records=1, max_leader_epoch=1)


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("d",))


def _mk_violating():
    return variants.make_model(
        "KafkaTruncateToHighWatermark", TINY, ("TypeOk", "WeakIsr")
    )


def _verdict(res):
    return (
        res.total,
        res.diameter,
        tuple(res.levels),
        res.ok,
        (res.violation.invariant, res.violation.depth)
        if res.violation
        else None,
    )


# --- the numpy fingerprint twin ------------------------------------------


def test_numpy_fingerprint_matches_jax():
    """fingerprint_rows must be bit-exact with the jax kernel (hashed and
    exact modes) — it is what the digest fold, the frontier verify and
    the shadow host-oracle trust."""
    from kafka_specification_tpu.ops.fingerprint import fingerprint_lanes

    rng = np.random.default_rng(7)
    for k in (1, 2, 5, 9):
        rows = rng.integers(0, 2**32, size=(257, k), dtype=np.uint32)
        hi, lo = fingerprint_lanes(jax.numpy.asarray(rows), False)
        assert np.array_equal(
            pair_u64(np.asarray(hi), np.asarray(lo)),
            fingerprint_rows(rows, False),
        )
    for k in (1, 2):
        rows = rng.integers(0, 2**32, size=(64, k), dtype=np.uint32)
        hi, lo = fingerprint_lanes(jax.numpy.asarray(rows), True)
        assert np.array_equal(
            pair_u64(np.asarray(hi), np.asarray(lo)),
            fingerprint_rows(rows, True),
        )


# --- chain algebra --------------------------------------------------------


def test_digest_chain_order_invariant_and_roundtrips():
    fps = (np.arange(50, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15))
    a = LevelDigestChain()
    a.fold(fps[:20])
    a.fold(fps[20:])
    a.seal(0, 50)
    b = LevelDigestChain()
    for chunk in np.array_split(fps[::-1], 7):  # any order, any chunking
        b.fold(chunk)
    b.seal(0, 50)
    assert a.entries == b.entries
    c = LevelDigestChain.from_array(a.to_array())
    assert c.entries == a.entries
    # count disagreement between accounting and folded multiset is itself
    # a violation
    d = LevelDigestChain()
    d.fold(fps[:10])
    with pytest.raises(IntegrityError):
        d.seal(0, 11)


def test_device_digest_fold_bit_exact_incl_carry_saturation():
    """The device pipeline's in-jit (count, xor, sum) fold
    (ops/devlevel.py) must be bit-exact with digest_fps — including the
    limb-carry saturation case a full 65536-row block of 0xFFFF limbs
    produces (regression: the raw uint32 block sum + accumulator +
    carry could reach exactly 2^32 and silently drop a carry)."""
    import jax.numpy as jnp

    from kafka_specification_tpu.ops import devlevel as dl

    T = 131072
    hi = np.zeros(T, np.uint32)
    lo = np.concatenate([
        np.full(65536, 0xFFFFFFFF, np.uint32),
        np.full(65536, 0xFFFF0001, np.uint32),
    ])
    cases = [
        (hi, lo, np.ones(T, bool)),
        (np.full(300, 0xFFFFFFFF, np.uint32),
         np.full(300, 0xFFFFFFFF, np.uint32),
         np.arange(300) < 123),
    ]
    rng = np.random.default_rng(11)
    for _ in range(3):
        n = int(rng.integers(1, 1 << 17))
        cases.append((
            rng.integers(0, 2**32, n, dtype=np.uint32),
            rng.integers(0, 2**32, n, dtype=np.uint32),
            rng.random(n) < 0.6,
        ))
    for h, l, v in cases:
        acc = dl.zero_digest()
        mid = len(h) // 2  # two folds exercise combine_digest too
        for sl in (slice(0, mid), slice(mid, None)):
            acc = dl.combine_digest(acc, dl.masked_digest(
                jnp.asarray(h[sl]), jnp.asarray(l[sl]),
                jnp.asarray(v[sl]),
            ))
        assert dl.digest_ints(acc) == integrity.digest_fps(
            integrity.pair_u64(h[v], l[v])
        )


def test_chain_validator_flags_tampered_arrays():
    chain = LevelDigestChain()
    for d, n in enumerate((1, 4, 12)):
        chain.fold(np.arange(n, dtype=np.uint64) + np.uint64(1000 * d))
        chain.seal(d, n)
    arrays = {
        "digest_chain": chain.to_array(),
        "levels": np.asarray([1, 4, 12]),
        "total": 17,
    }
    assert checkpoint_chain_errors(arrays) == []
    bad = dict(arrays, levels=np.asarray([1, 5, 11]))
    assert checkpoint_chain_errors(bad)
    tampered = arrays["digest_chain"].copy()
    tampered[1, 1] ^= np.uint64(1)
    assert checkpoint_chain_errors(dict(arrays, digest_chain=tampered))
    assert checkpoint_chain_errors(dict(arrays, total=18))
    # fpset cumulative digest: the stored visited multiset must match
    fps = np.concatenate(
        [np.arange(n, dtype=np.uint64) + np.uint64(1000 * d)
         for d, n in enumerate((1, 4, 12))]
    )
    ok = dict(arrays, host_fps=fps)
    assert checkpoint_chain_errors(ok) == []
    flipped = fps.copy()
    flipped[3] ^= np.uint64(1 << 17)
    assert checkpoint_chain_errors(dict(arrays, host_fps=flipped))


# --- fault grammar + registry (satellite) ---------------------------------


def test_flip_grammar_parses_and_scopes():
    p = FaultPlan(
        "flip@frontier:3,flip@shard2:exchange:4,flip@spill:1,"
        "flip@ckpt:2,flip@fpset:5"
    )
    assert [(s.kind, s.point, s.arg, s.shard) for s in p.specs] == [
        ("flip", "frontier", 3, None),
        ("flip", "exchange", 4, 2),
        ("flip", "spill", 1, None),
        ("flip", "ckpt", 2, None),
        ("flip", "fpset", 5, None),
    ]


def test_unknown_site_rejected_loudly_with_valid_sites():
    """A typo'd SITE (not just a typo'd kind) must fail at parse with an
    actionable message naming the valid sites (satellite fix)."""
    for bad, expect in (
        ("crash@lvl:3", "level, ckpt, merge"),
        ("flip@frntier:2", "frontier, fpset, exchange, spill, ckpt"),
        ("enospc@frontier:1", "spill, ckpt, merge, plog"),
        ("stall@ckpt:1", "level"),
    ):
        with pytest.raises(ValueError) as ei:
            FaultPlan(bad)
        assert expect in str(ei.value), (bad, str(ei.value))
        assert "faults --list" in str(ei.value)
    with pytest.raises(ValueError) as ei:
        FaultPlan("bogus@level:1")
    assert "known kinds" in str(ei.value)


def test_fault_registry_enumerates_every_kind():
    entries = list_faults()
    kinds = {e["kind"] for e in entries}
    assert kinds == {
        "crash", "corrupt_ckpt", "compile_oom", "transient_device_err",
        "enospc", "stall", "flip", "kill", "partition", "skew",
    }
    flip = next(e for e in entries if e["kind"] == "flip")
    assert set(flip["sites"]) == {
        "frontier", "fpset", "exchange", "spill", "ckpt", "cache"
    }


def test_cli_faults_list_is_jax_free_registry_dump(capsys):
    from kafka_specification_tpu.utils.cli import main as cli_main

    assert cli_main(["faults", "--json"]) == 0
    entries = json.loads(capsys.readouterr().out)
    assert {e["kind"] for e in entries} >= {"flip", "crash", "enospc"}
    assert cli_main(["faults"]) == 0
    out = capsys.readouterr().out
    assert "flip@frontier|fpset|exchange|spill|ckpt|cache:N" in out


def test_flip_deferral_and_resume_relief():
    p = FaultPlan("flip@frontier:3")
    assert not p.flip("frontier", 2)
    assert not p.flip("frontier", 3, ckpt_depth=2)  # not durable: defer
    assert p.flip("frontier", 3, ckpt_depth=3)
    assert not p.flip("frontier", 4, ckpt_depth=4)  # budget spent
    p2 = FaultPlan("flip@frontier:3")
    p2.set_start_depth(3)  # resumed at/past target: counts as fired
    assert not p2.flip("frontier", 3, ckpt_depth=3)
    p3 = FaultPlan("flip@spill:2")
    assert not p3.flip("spill", 1)
    assert p3.flip("spill", 2)


# --- the fault matrix: every site detected, typed, recovered --------------


@pytest.mark.parametrize(
    "site,backend",
    [
        ("frontier", "device"),
        ("fpset", "device"),
        ("fpset", "host"),
        ("fpset", "device-hash"),
        ("ckpt", "device"),
    ],
)
def test_flip_detected_and_recovered_single_device(
    tmp_path, monkeypatch, site, backend
):
    """Single-device matrix: flip injected -> typed IntegrityError; a
    restart (fault cleared, as after the one-shot fired) resumes from the
    newest chain-verified generation bit-identically."""
    model = frl.make_model(2, 2, 2)
    golden = _verdict(check(model, min_bucket=32, store_trace=False))
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", f"flip@{site}:2")
    with pytest.raises(IntegrityError) as ei:
        check(model, min_bucket=32, checkpoint_dir=ck,
              visited_backend=backend)
    assert ei.value.site in (site, "ckpt", "fpset", "frontier")
    monkeypatch.delenv("KSPEC_FAULT")
    rep = verify_checkpoint_dir(ck)
    assert rep["ok"], rep  # a chain-verified generation survives
    resumed = check(model, min_bucket=32, checkpoint_dir=ck,
                    visited_backend=backend)
    assert _verdict(resumed) == golden
    assert resumed.total == 49


def test_flip_spill_detected_on_read_and_recovered(tmp_path, monkeypatch):
    """flip@spill corrupts a promoted run file; the read-side CRC catches
    it at the next lookup; resume falls back past every generation that
    references the corrupt file (the deterministic re-run rewrites it)."""
    model = frl.make_model(2, 2, 2)
    golden = _verdict(check(model, min_bucket=32, store_trace=False))
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", "flip@spill:1")
    with pytest.raises(IntegrityError) as ei:
        check(model, min_bucket=32, checkpoint_dir=ck, mem_budget=256,
              store="disk")
    assert ei.value.site == "storage"
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check(model, min_bucket=32, checkpoint_dir=ck,
                    mem_budget=256, store="disk")
    assert _verdict(resumed) == golden


@pytest.mark.parametrize("site", ["frontier", "exchange", "fpset", "ckpt"])
def test_flip_detected_and_recovered_sharded(tmp_path, monkeypatch, site):
    model = frl.make_model(2, 2, 2)
    golden = _verdict(check_sharded(model, min_bucket=32,
                                    store_trace=False))
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", f"flip@{site}:2")
    with pytest.raises(IntegrityError):
        check_sharded(model, min_bucket=32, checkpoint_dir=ck)
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check_sharded(model, min_bucket=32, checkpoint_dir=ck)
    assert _verdict(resumed) == golden
    assert resumed.total == 49


def test_flip_shard_scoped_targets_one_shard(tmp_path, monkeypatch):
    """The acceptance matrix's shard<d>:-scoped case: the flip lands in
    the targeted shard's buffer and is still detected globally."""
    model = frl.make_model(2, 2, 2)
    golden = _verdict(check_sharded(model, min_bucket=32,
                                    store_trace=False))
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", "flip@shard1:frontier:2")
    with pytest.raises(IntegrityError):
        check_sharded(model, min_bucket=32, checkpoint_dir=ck)
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check_sharded(model, min_bucket=32, checkpoint_dir=ck)
    assert _verdict(resumed) == golden


def test_flip_recovery_preserves_trace_values_both_engines(
    tmp_path, monkeypatch
):
    """Counts AND trace VALUES bit-identical after a flip -> restart, on
    a violating workload (the acceptance criterion's strongest clause).
    The golden is the same storage configuration run fault-free: the
    disk-tier parent log's trace is pinned against ITS OWN fault-free
    twin (disk-vs-RAM trace equivalence is test_storage's concern)."""
    golden = check(_mk_violating(), min_bucket=32, mem_budget=512,
                   store="disk")
    assert golden.violation is not None and golden.violation.depth == 8
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", "flip@frontier:3")
    with pytest.raises(IntegrityError):
        check(_mk_violating(), min_bucket=32, checkpoint_dir=ck,
              mem_budget=512, store="disk")
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check(_mk_violating(), min_bucket=32, checkpoint_dir=ck,
                    mem_budget=512, store="disk")
    assert resumed.violation is not None
    assert resumed.violation.invariant == golden.violation.invariant
    assert resumed.violation.depth == golden.violation.depth
    assert resumed.violation.trace == golden.violation.trace

    sgolden = check_sharded(_mk_violating(), mesh=_mesh(2), min_bucket=32)
    assert sgolden.violation is not None
    sck = str(tmp_path / "sck")
    monkeypatch.setenv("KSPEC_FAULT", "flip@frontier:3")
    with pytest.raises(IntegrityError):
        check_sharded(_mk_violating(), mesh=_mesh(2), min_bucket=32,
                      checkpoint_dir=sck)
    monkeypatch.delenv("KSPEC_FAULT")
    sresumed = check_sharded(_mk_violating(), mesh=_mesh(2), min_bucket=32,
                             checkpoint_dir=sck)
    assert sresumed.violation is not None
    assert sresumed.violation.trace == sgolden.violation.trace


def test_integrity_violation_stamps_manifest_and_metrics(
    tmp_path, monkeypatch
):
    """The obs contract: manifest status `integrity-violation` (what `cli
    report`'s verdict beat keys on) + the violation event + counters."""
    from kafka_specification_tpu.obs import RunContext
    from kafka_specification_tpu.obs.report import report_data

    model = frl.make_model(2, 2, 2)
    run_dir = str(tmp_path / "run")
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_FAULT", "flip@frontier:2")
    run = RunContext(run_dir)
    with pytest.raises(IntegrityError):
        check(model, min_bucket=32, checkpoint_dir=ck, run=run)
    monkeypatch.delenv("KSPEC_FAULT")
    man = json.load(open(os.path.join(run_dir, "manifest.json")))
    assert man["status"] == "integrity-violation"
    assert man["result"]["site"] == "frontier"
    rep = report_data(run_dir)
    assert rep["verdict"]["status"] == "integrity-violation"
    integ = rep["integrity"]
    assert integ["violations"] >= 1
    assert integ["checks"] >= 1
    assert any(
        e.get("event") == "integrity-violation" for e in rep["timeline"]
    )


# --- the offline verifier vs CRC-consistent corruption --------------------


def test_verify_checkpoint_flags_crc_passing_corruption(tmp_path):
    """Hand-craft the corruption class CRCs cannot see: rewrite a
    generation's `levels` with the manifest REBUILT over the corrupt
    content.  verify_file passes; the digest chain flags it; a fresh
    engine resume skips it."""
    model = frl.make_model(2, 2, 2)
    ck = str(tmp_path / "ck")
    res = check(model, min_bucket=32, checkpoint_dir=ck)
    assert res.total == 49
    path = os.path.join(ck, "bfs_checkpoint.npz")
    arrays = verify_file(path)
    arrays["levels"] = np.asarray(arrays["levels"])
    arrays["levels"][2] += 7  # silent content corruption
    man = {"__manifest__": json.dumps(build_manifest(arrays))}
    np.savez(path, **man, **arrays)
    assert verify_file(path) is not None  # the CRC-only check PASSES
    rep = verify_checkpoint_dir(ck)
    gen0 = rep["stores"][0]["generations"][0]
    assert gen0["digest_chain"] == "FAILED"
    assert not gen0["ok"]
    assert rep["ok"]  # an older chain-verified generation still resumes
    resumed = check(model, min_bucket=32, checkpoint_dir=ck)
    assert resumed.total == 49


def test_verify_checkpoint_is_jax_free(tmp_path, monkeypatch):
    """`cli verify-checkpoint` (incl. chain validation) must run with a
    poisoned jax — the operator's box may have a wedged accelerator."""
    model = frl.make_model(2, 2, 2)
    ck = str(tmp_path / "ck")
    check(model, min_bucket=32, checkpoint_dir=ck)
    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from kafka_specification_tpu.utils.cli import main\n"
        f"raise SystemExit(main(['verify-checkpoint', {ck!r}, '--json']))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=_REPO, capture_output=True,
        text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["ok"]
    assert rep["stores"][0]["generations"][0]["digest_chain"] == "ok"


# --- shadow re-execution --------------------------------------------------


def test_shadow_clean_on_healthy_run_and_bit_identical():
    model = frl.make_model(2, 2, 2)
    base = check(model, min_bucket=32)
    shadowed = check(model, min_bucket=32, integrity_shadow=1.0)
    assert _verdict(shadowed) == _verdict(base)
    assert shadowed.violation == base.violation


def test_shadow_host_oracle_catches_corrupted_fingerprints(monkeypatch):
    """Corrupt the committed chunk fingerprints between the kernel and
    the host (the wire the host oracle guards) -> typed shadow violation."""
    from kafka_specification_tpu.engine import pipeline as pl

    orig = pl.FusedPipeline.run_chunk_staged

    def corrupting(self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap):
        vh, vl, n, fin = orig(
            self, piece, fp_n, bucket, depth, vhi, vlo, vn, vcap
        )

        def corrupt_fin():
            outs = fin()
            out_hi = np.array(outs[12])
            nn = int(outs[3])
            if nn:
                out_hi[0] ^= np.uint32(1 << 9)
                return outs[:12] + (out_hi,) + outs[13:]
            return outs

        return vh, vl, n, corrupt_fin

    monkeypatch.setattr(pl.FusedPipeline, "run_chunk_staged", corrupting)
    with pytest.raises(IntegrityError) as ei:
        check(frl.make_model(2, 2, 2), min_bucket=32, integrity_shadow=1.0)
    assert ei.value.site in ("shadow", "chain", "frontier")


def test_shadow_forces_device_pipeline_onto_fused_ladder():
    """Shadow re-execution replays single chunks from their pre-chunk
    visited state — a state the whole-level device program never
    materializes — so --pipeline device with a shadow rate runs the
    fused per-chunk ladder (documented fallback), bit-identical and
    with the legacy cross-exec oracle STILL armed."""
    model = frl.make_model(2, 2, 2)
    base = check(model, min_bucket=32, pipeline="device", compact_gate=32)
    assert base.stats["device"]["levels"] > 0
    shadowed = check(model, min_bucket=32, pipeline="device",
                     compact_gate=32, integrity_shadow=1.0)
    assert shadowed.stats["device"]["levels"] == 0
    assert "shadow" in (shadowed.stats["device"]["fallback"] or "")
    assert _verdict(shadowed) == _verdict(base)


def test_shadow_sampling_is_deterministic():
    assert integrity.sample_chunk(3, 0, 1.0)
    assert not integrity.sample_chunk(3, 0, 0.0)
    picks = [integrity.sample_chunk(d, s, 0.5)
             for d in range(20) for s in (0, 32768)]
    assert picks == [integrity.sample_chunk(d, s, 0.5)
                     for d in range(20) for s in (0, 32768)]
    rate = sum(picks) / len(picks)
    assert 0.2 < rate < 0.8  # sanity: roughly the requested rate


# --- chain invariance across engines / pipelines / layouts ----------------


def _load_chain(ck, name):
    arrays = verify_file(os.path.join(ck, name))
    return np.asarray(arrays["digest_chain"])


def test_chain_identical_across_pipelines_engines_and_layouts(tmp_path):
    """The digest is over the per-level new-state fingerprint MULTISET —
    pinned engine-invariant, pipeline-invariant, and shard-layout-
    invariant (the property that makes cross-engine auditing possible)."""
    model_kw = dict(min_bucket=32, store_trace=False)
    chains = {}
    for tag, kw in (
        ("fused", dict(pipeline="fused")),
        ("legacy", dict(pipeline="legacy")),
        # whole-level device programs fold the digest IN-JIT
        # (ops/devlevel.py) — the accumulator must land bit-identical to
        # every host-folded chain (compact_gate 32 forces the device
        # path to actually engage at this model's tiny buckets)
        ("device", dict(pipeline="device", compact_gate=32)),
        ("host", dict(visited_backend="host")),
        # deferred-probe device path: the chain folds the batched
        # probe's SURVIVORS — must land identical to every other fold
        ("device-host", dict(pipeline="device", visited_backend="host",
                             compact_gate=32)),
    ):
        ck = str(tmp_path / tag)
        check(frl.make_model(2, 2, 2), checkpoint_dir=ck, **model_kw, **kw)
        chains[tag] = _load_chain(ck, "bfs_checkpoint.npz")
    for tag, mesh in (("sh2", _mesh(2)), ("sh4", _mesh(4))):
        ck = str(tmp_path / tag)
        check_sharded(frl.make_model(2, 2, 2), mesh=mesh,
                      checkpoint_dir=ck, **model_kw)
        chains[tag] = _load_chain(ck, "sharded_checkpoint.npz")
    ref = chains.pop("fused")
    for tag, arr in chains.items():
        assert np.array_equal(ref, arr), tag


# --- storage read-side verification (units) -------------------------------


def test_frontier_segments_verify_on_read(tmp_path):
    from kafka_specification_tpu.storage.frontier import (
        FrontierWriter,
        SegmentCorrupt,
    )

    w = FrontierWriter(str(tmp_path), 1, 3, seg_rows=8)
    rows = np.arange(60, dtype=np.uint32).reshape(20, 3)
    w.append(rows)
    reader = w.finalize()
    assert np.array_equal(reader.read_all(), rows)
    # corrupt one segment ON DISK; a FRESH reader (no verified cache)
    # must catch it at first read, without an explicit verify pass
    from kafka_specification_tpu.storage.frontier import FrontierReader

    seg_path = os.path.join(str(tmp_path), reader.man["segments"][1]["name"])
    raw = bytearray(open(seg_path, "rb").read())
    raw[-5] ^= 0x40
    open(seg_path, "wb").write(bytes(raw))
    cold = FrontierReader(str(tmp_path), reader.man, verify=False)
    with pytest.raises(SegmentCorrupt):
        cold.read_all()


def test_spill_run_verifies_on_first_lookup(tmp_path):
    from kafka_specification_tpu.resilience.faults import corrupt_file
    from kafka_specification_tpu.storage.runs import (
        RunCorrupt,
        SortedRun,
        write_run,
    )

    fps = np.sort(
        np.random.default_rng(3).integers(
            0, 2**63, size=500, dtype=np.uint64
        )
    )
    path = os.path.join(str(tmp_path), "run-000000.fps")
    meta = write_run(path, fps, bloom_path=path + ".bloom")
    run = SortedRun(str(tmp_path), meta, verify=False)  # writer's own open
    corrupt_file(path)
    with pytest.raises(RunCorrupt):
        run.contains(fps[:10])


# --- supervised end-to-end (exit 76 -> restart -> converge) ----------------


def test_supervised_flip_restarts_and_converges(tmp_path):
    """scripts/resilient_run.py around a flip fault: attempt 1 exits 76
    (typed, classified `integrity-violation`), the supervisor restarts
    with the SAME env, the checkpoint deferral + resume relief make the
    restart converge, and the final verdict matches a clean run."""
    hb = str(tmp_path / "hb.jsonl")
    ev = str(tmp_path / "events.jsonl")
    logs = str(tmp_path / "logs")
    ck = str(tmp_path / "ck")
    env = dict(os.environ, KSPEC_FAULT="flip@frontier:3")
    rc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "scripts", "resilient_run.py"),
            "--heartbeat", hb, "--events", ev, "--log-dir", logs,
            "--stall-timeout", "300", "--max-restarts", "3",
            "--backoff", "0.05",
            "--",
            sys.executable, "-m", "kafka_specification_tpu.utils.cli",
            "check", os.path.join(_REPO, "configs", "IdSequence.cfg"),
            "--hand", "--cpu", "--json", "--checkpoint", ck,
            "--stats", hb,
        ],
        cwd=_REPO,
        env=env,
        timeout=540,
    ).returncode
    assert rc == 0
    events = [json.loads(l) for l in open(ev).read().splitlines()]
    kinds = [e["event"] for e in events]
    assert "integrity-violation" in kinds  # attempt 1 classified typed
    assert kinds.count("start") == 2 and kinds[-1] == "complete"
    exit76 = [e for e in events if e["event"] == "exit" and e["rc"] == 76]
    assert exit76  # the child really exited with the integrity code
    # final attempt's verdict: the clean IdSequence answer
    final = None
    for name in sorted(os.listdir(logs), reverse=True):
        for line in reversed(
            open(os.path.join(logs, name), errors="replace")
            .read().splitlines()
        ):
            if line.startswith("{"):
                final = json.loads(line)
                break
        if final:
            break
    # kspec-verdict/1 record of the final (clean) attempt: the exhaustive
    # IdSequence answer (configs/IdSequence.cfg)
    assert final and final["exit_code"] == 0
    assert final["violation"] is None
    assert final["distinct_states"] == 12


# --- the untested triple: elastic reshard x disk tier x fused -------------


def test_elastic_reshard_disk_tier_fused_triple(tmp_path, monkeypatch):
    """The satellite matrix corner: a sharded DISK-TIER run crashes, is
    ELASTICALLY resumed (4 -> 2 shards) still on the disk tier, and the
    result — counts AND the level digest chain — is bit-identical to the
    single-device FUSED-pipeline disk-tier run of the same model (every
    pair of the triple was pinned before; this pins all three at once)."""
    model_kw = dict(min_bucket=32, store_trace=False)
    fck = str(tmp_path / "fused_ck")
    golden = check(frl.make_model(2, 2, 2), pipeline="fused",
                   checkpoint_dir=fck, mem_budget=256, store="disk",
                   **model_kw)
    assert golden.total == 49
    sck = str(tmp_path / "sck")
    from kafka_specification_tpu.resilience import InjectedCrash

    monkeypatch.setenv("KSPEC_FAULT", "crash@level:2")
    with pytest.raises(InjectedCrash):
        check_sharded(frl.make_model(2, 2, 2), mesh=_mesh(4),
                      checkpoint_dir=sck, mem_budget=256, store="disk",
                      **model_kw)
    monkeypatch.delenv("KSPEC_FAULT")
    resumed = check_sharded(frl.make_model(2, 2, 2), mesh=_mesh(2),
                            checkpoint_dir=sck, mem_budget=256,
                            store="disk", **model_kw)
    assert _verdict(resumed) == _verdict(golden)
    spilled = [s for s in resumed.stats["spill"] if s]
    assert sum(x["disk"] + x["hot"] for x in spilled) == 49
    assert np.array_equal(
        _load_chain(fck, "bfs_checkpoint.npz"),
        _load_chain(sck, "sharded_checkpoint.npz"),
    )


# --- kill switch ----------------------------------------------------------


def test_kill_switch_disables_layer(tmp_path, monkeypatch):
    """KSPEC_INTEGRITY=0: no chain stamped, flips fly silent (the escape
    hatch contract — and the bench baseline mode)."""
    monkeypatch.setenv("KSPEC_INTEGRITY", "0")
    ck = str(tmp_path / "ck")
    res = check(frl.make_model(2, 2, 2), min_bucket=32, checkpoint_dir=ck)
    assert res.total == 49
    arrays = verify_file(os.path.join(ck, "bfs_checkpoint.npz"))
    assert "digest_chain" not in arrays


def test_exit_code_contract():
    assert EXIT_INTEGRITY == 76  # one past EXIT_RESOURCE_EXHAUSTED (75)


# --- review-pass regressions ----------------------------------------------


def test_pre_integrity_checkpoint_resume_upgrade_path(tmp_path, monkeypatch):
    """A checkpoint written WITHOUT the integrity layer (pre-upgrade /
    kill-switch) resumes under the integrity-enabled build: the rebuilt
    chain is unanchored, so it is NOT stamped into new checkpoints —
    a stamped zero-digest chain would fail the cumulative visited check
    on the next load and permanently reject every post-upgrade
    generation (review-pass regression)."""
    from kafka_specification_tpu.resilience import InjectedCrash

    model = frl.make_model(2, 2, 2)
    golden = _verdict(check(model, min_bucket=32, store_trace=False))
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("KSPEC_INTEGRITY", "0")
    monkeypatch.setenv("KSPEC_FAULT", "crash@level:2")
    with pytest.raises(InjectedCrash):
        check(model, min_bucket=32, checkpoint_dir=ck)
    monkeypatch.delenv("KSPEC_FAULT")
    monkeypatch.delenv("KSPEC_INTEGRITY")  # the upgraded build takes over
    resumed = check(model, min_bucket=32, checkpoint_dir=ck)
    assert _verdict(resumed) == golden
    # post-upgrade generations carry no chain (unanchored) ...
    arrays = verify_file(os.path.join(ck, "bfs_checkpoint.npz"))
    assert "digest_chain" not in arrays
    # ... and every generation still verifies and resumes
    assert verify_checkpoint_dir(ck)["ok"]
    again = check(model, min_bucket=32, checkpoint_dir=ck)
    assert _verdict(again) == golden


def test_merge_refuses_to_launder_corrupt_run(tmp_path):
    """A corrupt-but-not-yet-probed run must fail its content CRC when a
    k-way MERGE streams it — merging first would re-checksum corrupted
    values into a 'valid' merged run and defeat read-side verification
    forever (review-pass regression)."""
    from kafka_specification_tpu.resilience.faults import corrupt_file
    from kafka_specification_tpu.storage.runs import (
        RunCorrupt,
        SortedRun,
        merge_runs,
        write_run,
    )

    rng = np.random.default_rng(11)
    fps = np.sort(rng.integers(0, 2**63, size=1000, dtype=np.uint64))
    runs = []
    for i, part in enumerate((fps[::2], fps[1::2])):
        path = os.path.join(str(tmp_path), f"run-{i:06d}.fps")
        meta = write_run(path, part, bloom_path=path + ".bloom")
        runs.append(SortedRun(str(tmp_path), meta, verify=False))
    corrupt_file(runs[1].path)
    with pytest.raises(RunCorrupt):
        merge_runs(runs, os.path.join(str(tmp_path), "merged.fps"))


def test_cli_rejects_shadow_with_sharded(capsys):
    """--integrity-shadow on --sharded must error, not silently no-op
    (the report's guidance sends operators to this flag)."""
    from kafka_specification_tpu.utils.cli import main as cli_main

    rc = cli_main([
        "check", os.path.join(_REPO, "configs", "IdSequence.cfg"),
        "--sharded", "--integrity-shadow", "1.0", "--hand",
    ])
    assert rc == 2
    assert "single-device only" in capsys.readouterr().err
