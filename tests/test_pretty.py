"""TLA-style counterexample rendering."""

from kafka_specification_tpu.engine.bfs import check
from kafka_specification_tpu.models import async_isr, variants
from kafka_specification_tpu.models.kafka_replication import Config
from kafka_specification_tpu.utils.pretty import render_state, render_trace


def test_render_kafka_trace_round_trip():
    m = variants.make_model(
        "KafkaTruncateToHighWatermark", Config(2, 2, 1, 1), ("TypeOk", "WeakIsr")
    )
    res = check(m, min_bucket=32)
    text = render_trace(m.meta, res.violation.trace)
    assert "State 1: <Initial predicate>" in text
    assert "replicaLog" in text and "quorumState" in text
    assert "leaderEpoch|->" in text
    # one state block per trace step
    import re

    assert len(re.findall(r"^State \d+:", text, re.M)) == len(res.violation.trace)


def test_render_async_isr_state():
    cfg = async_isr.AsyncIsrConfig(2, 1, 1)
    m = async_isr.make_model(cfg)
    decoded = m.decode(
        {k: __import__("numpy").asarray(v) for k, v in async_isr.init_state(cfg).items()}
    )
    text = render_state(m.meta, decoded)
    assert "controllerState" in text and "pendingVersion|->-1" in text


def test_render_unknown_falls_back_to_repr():
    assert render_state({}, (1, 2, 3)).strip() == "(1, 2, 3)"


def test_render_uses_cfg_model_value_names():
    # the .cfg declares `Replicas = {b1, b2}` — the rendered trace must use
    # those names, not positional b0/b1 (TLC echoes the given model values)
    from kafka_specification_tpu.utils.cfg import build_model, parse_cfg

    cfg = parse_cfg(
        "SPECIFICATION Spec\n"
        "CONSTANTS Replicas = {b1, b2}\n"
        "  LogSize = 2\n  MaxRecords = 1\n  MaxLeaderEpoch = 1\n"
        "INVARIANTS TypeOk WeakIsr\n"
    )
    m = build_model("KafkaTruncateToHighWatermark", cfg)
    assert m.meta["replica_names"] == ["b1", "b2"]
    res = check(m, min_bucket=32)
    text = render_trace(m.meta, res.violation.trace)
    assert "b1 :>" in text and "b2 :>" in text
    assert "b0" not in text


def test_render_product_state_per_partition():
    from kafka_specification_tpu.models import kip320
    from kafka_specification_tpu.models.product import product_model
    from kafka_specification_tpu.models.kafka_replication import Config
    import numpy as np

    base = kip320.make_model(Config(2, 2, 1, 1))
    model = product_model(base, 2)
    init = {k: np.asarray(v) for k, v in model.init_states()[0].items()}
    text = render_state(model.meta, model.decode(init))
    assert "partition 0:" in text and "partition 1:" in text
    assert text.count("replicaLog") == 2
